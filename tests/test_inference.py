"""Inference/serving engine tests (ISSUE 12).

Covers:
  * decode-step logits BIT-exact vs the training-path forward on the
    same prefix (fp32, small-contraction regime) and to float roundoff
    at larger sizes (the PR-9 precedent: cross-program reduction
    orders preclude literal bit equality once XLA switches matmul
    kernels at different static shapes);
  * paged attention vs a contiguous-cache dense_attention reference;
  * page alloc/free accounting vs independent byte arithmetic, and
    the `kv_cache` ledger category == pool bytes invariant (the PR-9
    ledger window-bound pattern);
  * the NO-HOST-SYNC guard for a multi-request decode loop: zero
    `jax.device_get`/`jax.effects_barrier` between serving fences,
    exactly ONE device_get per fence;
  * continuous-batching scheduler semantics: admission beyond slot
    count, chunked-prefill interleaving, EOS/max-tokens eviction,
    page reuse — with per-request outputs IDENTICAL to isolated
    single-request runs (cache isolation);
  * int8 weight-only quantization within pinned tolerance of fp32;
  * device-side sampling (top_k=1 == greedy; same-seed determinism);
  * `inference` config-block validation and serving monitor events.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference import (InferenceConfig, InferenceConfigError,
                                     InferenceEngine, PagedKVCache,
                                     Request, ServingLoop)
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config


def _params(model):
    return model.init(jax.random.PRNGKey(0),
                      {"input_ids": np.zeros((1, 8), np.int32)})


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_gpt2_config()
    model = GPT2ForCausalLM(cfg)
    params = _params(model)
    engine = InferenceEngine(cfg, params, {"inference": {
        "max_slots": 4, "prefill_chunk": 16, "sync_every": 4,
        "max_new_tokens": 32,
        "kv_cache": {"num_pages": 120, "page_size": 4}}})
    return cfg, model, params, engine


def _train_logits(model, params, tokens):
    out = model.apply(params, np.asarray(tokens, np.int32)[None, :],
                      True)
    return np.asarray(out)[0, -1]


# ----------------------------------------------------------------------
# decode-logits parity vs the training forward
# ----------------------------------------------------------------------
def test_decode_logits_bitexact_vs_training_forward(setup):
    """fp32, total length <= 12: the decode program and the training
    forward run in the same XLA-CPU kernel regime, so the logits must
    be LITERALLY bit-identical at every generated position — any math
    drift between the serving forward and the training forward shows
    up here as a hard failure."""
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(1)
    prompt = r.randint(0, cfg.vocab_size, size=7).astype(np.int32)
    engine.start_request(0, prompt, max_new=5)
    cur = list(prompt)
    for step in range(5):
        logits = np.asarray(engine.decode_once()[0])
        ref = _train_logits(model, params, cur)
        assert np.array_equal(logits, ref), \
            (step, np.abs(logits - ref).max())
        cur.append(int(logits.argmax()))
    engine.reset()


def test_decode_logits_roundoff_parity_long(setup):
    """Longer sequences (chunked prefill, length past XLA-CPU's
    small-gemm threshold): the same math through differently-shaped
    programs — parity to float roundoff (observed ~2e-7; pinned at
    3e-6), greedy tokens identical."""
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(2)
    prompt = r.randint(0, cfg.vocab_size, size=37).astype(np.int32)
    engine.start_request(0, prompt, max_new=20)
    cur = list(prompt)
    for _ in range(20):
        logits = np.asarray(engine.decode_once()[0])
        ref = _train_logits(model, params, cur)
        np.testing.assert_allclose(logits, ref, atol=3e-6, rtol=0)
        assert logits.argmax() == ref.argmax()
        cur.append(int(logits.argmax()))
    engine.reset()


def test_paged_attention_matches_contiguous_reference():
    """Unit: paged_attention over a zero-padded page window ==
    dense_attention over the contiguous cache (bit-exact in the
    small-kernel regime, float roundoff beyond)."""
    from deepspeed_tpu.inference.engine import paged_attention
    from deepspeed_tpu.ops.transformer.flash_attention import \
        dense_attention
    r = np.random.RandomState(3)
    for t, exact in ((10, True), (48, False)):
        q = r.randn(1, t, 4, 16).astype(np.float32)
        k = r.randn(1, t, 4, 16).astype(np.float32)
        v = r.randn(1, t, 4, 16).astype(np.float32)
        tmax = 64
        kc = np.zeros((1, tmax, 4, 16), np.float32)
        vc = r.randn(1, tmax, 4, 16).astype(np.float32)  # garbage tail
        kc[:, :t] = k
        vc[:, :t] = v
        ref = np.asarray(jax.jit(
            lambda q, k, v: dense_attention(q, k, v, causal=True))(
                q, k, v))
        got = np.asarray(jax.jit(paged_attention)(
            q, jnp.asarray(kc), jnp.asarray(vc),
            np.arange(t, dtype=np.int32)[None, :],
            np.asarray([t - 1], np.int32)))
        if exact:
            assert np.array_equal(ref, got), np.abs(ref - got).max()
        else:
            np.testing.assert_allclose(ref, got, atol=2e-6, rtol=0)


# ----------------------------------------------------------------------
# paged cache accounting vs independent byte arithmetic
# ----------------------------------------------------------------------
def test_page_alloc_free_accounting_vs_byte_arithmetic():
    from deepspeed_tpu.monitor.memory import CAT_KV, MemoryLedger
    ledger = MemoryLedger()
    cache = PagedKVCache(n_layer=2, n_head=4, head_dim=16,
                         num_pages=32, page_size=4, max_slots=4,
                         max_pages_per_slot=8, dtype=np.float32,
                         ledger=ledger)
    # independent arithmetic: one page = 2 (K+V) * L * page * H * D * 4B
    page_bytes = 2 * 2 * 4 * 4 * 16 * 4
    assert cache.page_bytes == page_bytes
    assert cache.pool_bytes == 32 * page_bytes

    def kv_total():
        return ledger.totals()["hbm"].get(CAT_KV, 0)

    # empty cache: the whole pool is 'unallocated' but still resident
    assert kv_total() == cache.pool_bytes

    cache.admit(0, 13, name="a")           # worst case ceil(13/4)=4 pages
    assert cache.allocated_pages(0) == 0   # reservation only
    cache.ensure(0, 6)                     # ceil(6/4)=2 pages assigned
    assert cache.allocated_pages(0) == 2
    assert cache.slot_bytes(0) == 2 * page_bytes
    assert kv_total() == cache.pool_bytes  # invariant: total == pool
    cache.ensure(0, 13)
    assert cache.slot_bytes(0) == 4 * page_bytes
    # the cache-side twins of the tracker's ledger-derived utilization
    # (cross-checked in test_kv_page_utilization_ledger_vs_cache_twins)
    assert cache.pages_in_use() == 4
    assert cache.utilization() == 4 / 31
    # per-request ledger entry matches the arithmetic
    tops = {b["name"]: b["bytes"] for b in ledger.top_buffers(16)
            if b["category"] == CAT_KV}
    assert tops["request.s0.a"] == 4 * page_bytes

    # growth past the reservation must refuse, not corrupt
    with pytest.raises(RuntimeError):
        cache.ensure(0, 17)

    # admission control: 31 allocatable pages, 4 held + reservations
    cache.admit(1, 16, name="b")           # reserves 4 more
    assert cache.free_pages() == 31 - 4
    # a request needing more than the uncommitted remainder is refused
    assert not cache.can_admit(4 * (31 - 4 - 4 + 1))
    assert cache.can_admit(8)

    # free returns every page and closes the ledger entry
    freed = cache.free(0)
    assert freed == 4
    assert cache.free_pages() == 31
    tops = {b["name"] for b in ledger.top_buffers(16)
            if b["category"] == CAT_KV}
    assert "request.s0.a" not in tops
    assert kv_total() == cache.pool_bytes
    # the freed pages are reusable immediately
    cache.ensure(1, 16)
    assert cache.slot_bytes(1) == 4 * page_bytes
    cache.free(1)
    assert cache.free_pages() == 31
    assert (cache.tables == 0).all()


def test_serving_kv_ledger_matches_pool_through_lifecycle(setup):
    from deepspeed_tpu.monitor.memory import CAT_KV
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(4)
    prompt = r.randint(0, cfg.vocab_size, size=11).astype(np.int32)
    engine.start_request(0, prompt, max_new=6)
    cats = engine.monitor.ledger.totals()["hbm"]
    assert cats[CAT_KV] == engine.cache.pool_bytes
    # start_request assigns the worst case up front: ceil((11+6)/4)
    assert engine.cache.slot_bytes(0) == \
        -(-(11 + 6) // 4) * engine.cache.page_bytes
    engine.decode_block(6)
    engine.fetch_state()
    engine.reset()
    assert engine.cache.allocated_bytes() == 0
    assert engine.monitor.ledger.totals()["hbm"][CAT_KV] == \
        engine.cache.pool_bytes


def test_oom_hint_names_kv_cache_num_pages():
    from deepspeed_tpu.monitor.memory import oom_hints
    payload = {"hbm": {"categories": {"kv_cache": 10 * 2**30,
                                      "params": 2 * 2**30},
                       "ledger_bytes": 12 * 2**30,
                       "measured_in_use_per_device": 13 * 2**30,
                       "residual_bytes": 1 * 2**30}}
    hints = " ".join(oom_hints(payload))
    assert "inference.kv_cache.num_pages" in hints


# ----------------------------------------------------------------------
# the no-host-sync guard for the multi-request decode loop
# ----------------------------------------------------------------------
class _SyncCounters:
    """Same instrumentation as test_async_dispatch: count the host-sync
    entry points (`jax.device_get`, `jax.effects_barrier`)."""

    def __init__(self, monkeypatch):
        self.device_get = 0
        self.effects_barrier = 0
        real_get, real_barrier = jax.device_get, jax.effects_barrier

        def counting_get(x):
            self.device_get += 1
            return real_get(x)

        def counting_barrier():
            self.effects_barrier += 1
            return real_barrier()

        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "effects_barrier", counting_barrier)


def test_multi_request_decode_loop_has_zero_host_syncs(setup,
                                                       monkeypatch):
    """The serving acceptance guard: with THREE live requests, decode
    blocks dispatched between fences perform ZERO host<->device syncs,
    and the serving fence costs exactly ONE device_get."""
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(5)
    for slot in range(3):
        prompt = r.randint(0, cfg.vocab_size,
                           size=6 + 3 * slot).astype(np.int32)
        engine.start_request(slot, prompt, max_new=20)
    engine.decode_block(4)     # warm the dispatch path
    counters = _SyncCounters(monkeypatch)
    for _ in range(3):
        engine.decode_block(4)
    assert counters.device_get == 0, \
        f"decode loop called jax.device_get {counters.device_get}x"
    assert counters.effects_barrier == 0
    snap = engine.fetch_state()
    assert counters.device_get == 1, \
        "the serving fence must cost exactly ONE device_get"
    assert snap["n_gen"][:3].min() > 0
    engine.reset()


def test_serving_loop_step_syncs_only_at_fence(setup, monkeypatch):
    """ServingLoop.step (admit -> prefill -> decode block -> fence)
    performs exactly one device_get per iteration — the fence."""
    cfg, model, params, engine = setup
    engine.reset()
    loop = ServingLoop(engine)
    r = np.random.RandomState(6)
    for i in range(3):
        loop.submit(Request(rid=i, tokens=r.randint(
            0, cfg.vocab_size, size=9), max_new_tokens=12))
    import time
    loop._t0 = time.monotonic()
    loop._last_fence_t = loop._now()
    loop.step()    # compile/admission settle
    counters = _SyncCounters(monkeypatch)
    n = 0
    while (loop.queue or loop.live or loop.prefilling) and n < 50:
        loop.step()
        n += 1
    assert n > 0
    assert counters.device_get == n, (counters.device_get, n)
    assert counters.effects_barrier == 0
    engine.reset()


# ----------------------------------------------------------------------
# continuous batching semantics
# ----------------------------------------------------------------------
def test_continuous_batch_matches_isolated_runs(setup):
    """10 requests through 4 slots (forced queueing + page reuse):
    every request's greedy output must be IDENTICAL to serving it
    alone — cache pages are isolated per request and recycling a page
    never leaks another request's KV."""
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(7)
    reqs = [(i, r.randint(0, cfg.vocab_size,
                          size=int(r.randint(3, 30))).astype(np.int32),
             int(r.randint(4, 12))) for i in range(10)]
    loop = ServingLoop(engine)
    res = loop.serve([Request(rid=i, tokens=t.copy(), max_new_tokens=m)
                      for i, t, m in reqs])
    assert len(res) == 10
    batched = {q.rid: q.out_tokens.tolist() for q in res}
    engine.reset()
    for i, t, m in reqs:
        alone = ServingLoop(engine).serve(
            [Request(rid=i, tokens=t.copy(), max_new_tokens=m)])[0]
        assert alone.out_tokens.tolist() == batched[i], i
    # everything came back: pages all free, ledger back to pool-only
    assert engine.cache.free_pages() == engine.cache.num_pages - 1


def test_chunked_prefill_interleaves_with_decode(setup):
    """A long prompt (3 chunks) admitted while another request decodes:
    the decoding request keeps generating between the chunks (its
    token count advances before the long prompt goes live), and the
    long request's output still matches its isolated run."""
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(8)
    short = r.randint(0, cfg.vocab_size, size=4).astype(np.int32)
    long_p = r.randint(0, cfg.vocab_size, size=40).astype(np.int32)
    loop = ServingLoop(engine)
    loop.submit(Request(rid="short", tokens=short, max_new_tokens=24))
    loop.submit(Request(rid="long", tokens=long_p, max_new_tokens=6))
    import time
    loop._t0 = time.monotonic()
    loop._last_fence_t = loop._now()
    # drive manually: after the first step the short request is live;
    # the long one is still prefilling (40 tokens / 16-chunk > 1 turn)
    loop.step()
    assert "long" in {q.rid for q, _ in loop.prefilling.values()} or \
        any(q.rid == "long" for q in loop.live.values())
    interleaved = False
    for _ in range(60):
        if not (loop.queue or loop.live or loop.prefilling):
            break
        was_prefilling = any(q.rid == "long"
                             for q, _ in loop.prefilling.values())
        short_live = any(q.rid == "short" for q in loop.live.values())
        if was_prefilling and short_live and \
                int(loop._last_n_gen[list(loop.live)[0]]) > 0:
            interleaved = True
        loop.step()
    assert interleaved, \
        "the short request never decoded while the long one prefilled"
    out = {q.rid: q.out_tokens.tolist() for q in loop.results}
    engine.reset()
    ref = ServingLoop(engine).serve(
        [Request(rid="long", tokens=long_p.copy(), max_new_tokens=6)])[0]
    assert out["long"] == ref.out_tokens.tolist()
    engine.reset()


def test_out_of_order_arrivals_do_not_block_ready_requests(setup):
    """A not-yet-arrived request at the queue head must not block an
    already-arrived one behind it (submission order need not be
    arrival order)."""
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(17)
    loop = ServingLoop(engine)
    loop.submit(Request(rid="late", tokens=r.randint(
        0, cfg.vocab_size, size=5), max_new_tokens=4,
        arrival_time=30.0))
    loop.submit(Request(rid="now", tokens=r.randint(
        0, cfg.vocab_size, size=5), max_new_tokens=4,
        arrival_time=0.0))
    import time
    loop._t0 = time.monotonic()
    loop._last_fence_t = loop._now()
    for _ in range(20):
        loop.step()
        if loop.results:
            break
    assert loop.results and loop.results[0].rid == "now", \
        "the ready request starved behind a future arrival"
    # the future request is still queued, untouched
    assert len(loop.queue) == 1 and loop.queue[0].rid == "late"
    engine.reset()


def test_eos_eviction(setup):
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(9)
    prompt = r.randint(0, cfg.vocab_size, size=8).astype(np.int32)
    # learn what greedy generates, then make the FIRST token the EOS
    probe = ServingLoop(engine).serve(
        [Request(rid="p", tokens=prompt.copy(), max_new_tokens=4)])[0]
    assert probe.finish_reason == "max_tokens"
    eos = int(probe.out_tokens[0])
    engine.reset()
    got = ServingLoop(engine).serve(
        [Request(rid="e", tokens=prompt.copy(), max_new_tokens=10,
                 eos_token_id=eos)])[0]
    assert got.finish_reason == "eos"
    # the EOS token is recorded, and generation stopped right there
    assert got.out_tokens.tolist() == [eos]
    engine.reset()


def test_max_tokens_eviction_and_counts(setup):
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(10)
    res = ServingLoop(engine).serve(
        [Request(rid=i, tokens=r.randint(0, cfg.vocab_size, size=5),
                 max_new_tokens=7) for i in range(2)])
    for q in res:
        assert q.finish_reason == "max_tokens"
        assert len(q.out_tokens) == 7
        assert q.finished_at is not None and q.admitted_at is not None
    engine.reset()


# ----------------------------------------------------------------------
# sampling
# ----------------------------------------------------------------------
def test_topk1_sampling_equals_greedy(setup):
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(11)
    prompt = r.randint(0, cfg.vocab_size, size=9).astype(np.int32)
    greedy = ServingLoop(engine).serve(
        [Request(rid="g", tokens=prompt.copy(), max_new_tokens=8)])[0]
    engine.reset()
    topk1 = ServingLoop(engine).serve(
        [Request(rid="t", tokens=prompt.copy(), max_new_tokens=8,
                 temperature=1.0, top_k=1)])[0]
    assert topk1.out_tokens.tolist() == greedy.out_tokens.tolist()
    engine.reset()


def test_sampling_same_seed_is_deterministic(setup):
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(12)
    prompt = r.randint(0, cfg.vocab_size, size=9).astype(np.int32)

    def run():
        engine.reset()
        return ServingLoop(engine).serve(
            [Request(rid="s", tokens=prompt.copy(), max_new_tokens=8,
                     temperature=0.8, top_k=16)])[0].out_tokens.tolist()

    a = run()
    # the decode program's step counter keeps advancing across resets?
    # no: reset() rebuilds state with step=0, so the stream replays
    b = run()
    assert a == b
    assert all(0 <= t < cfg.vocab_size for t in a)
    engine.reset()


# ----------------------------------------------------------------------
# int8 weight-only quantization
# ----------------------------------------------------------------------
def test_int8_weight_quant_within_pinned_tolerance(setup):
    """The serving quant A/B (the offload-wire parity convention):
    int8 per-block-scale weights must track the fp32 logits within
    the pinned tolerance on the tiny model (measured ~2e-3) and agree
    on the greedy token."""
    cfg, model, params, engine = setup
    engine.reset()
    e8 = InferenceEngine(cfg, params, {"inference": {
        "max_slots": 4, "prefill_chunk": 16, "sync_every": 4,
        "max_new_tokens": 32, "weight_bits": 8,
        "weight_quant_block": 32,
        "kv_cache": {"num_pages": 120, "page_size": 4}}})
    r = np.random.RandomState(13)
    prompt = r.randint(0, cfg.vocab_size, size=12).astype(np.int32)
    engine.start_request(0, prompt, max_new=6)
    e8.start_request(0, prompt, max_new=6)
    for _ in range(3):
        l32 = np.asarray(engine.decode_once()[0])
        l8 = np.asarray(e8.decode_once()[0])
        assert np.abs(l32 - l8).max() < 2e-2, np.abs(l32 - l8).max()
        assert l32.argmax() == l8.argmax()
    engine.reset()


def test_int8_quant_roundtrip_unit():
    from deepspeed_tpu.inference.quant import (int8_matmul,
                                               quantize_kernel_int8)
    r = np.random.RandomState(14)
    w = (r.randn(48, 24) * 0.05).astype(np.float32)
    q, s = quantize_kernel_int8(w, block=16)
    assert q.dtype == np.int8 and q.shape == w.shape
    assert s.shape == (3, 24)
    # dequantised weights within one quantisation step per block
    deq = (q.reshape(3, 16, 24).astype(np.float32) *
           s[:, None, :]).reshape(48, 24)
    assert np.abs(deq - w).max() <= (s.max() / 2) + 1e-8
    x = r.randn(5, 48).astype(np.float32)
    y = np.asarray(int8_matmul(jnp.asarray(x), jnp.asarray(q),
                               jnp.asarray(s), 16, jnp.float32))
    np.testing.assert_allclose(y, x @ deq, atol=1e-4, rtol=1e-4)


# ----------------------------------------------------------------------
# config validation + submit validation
# ----------------------------------------------------------------------
def test_inference_config_validation():
    assert InferenceConfig({}).max_slots == 8
    assert InferenceConfig(None).kv_num_pages == 256
    with pytest.raises(InferenceConfigError):
        InferenceConfig({"inference": "nope"})
    with pytest.raises(InferenceConfigError):
        InferenceConfig({"inference": {"max_slots": 0}})
    with pytest.raises(InferenceConfigError):
        InferenceConfig({"inference": {"weight_bits": 4}})
    with pytest.raises(InferenceConfigError):
        InferenceConfig({"inference": {"kv_cache": {"num_pages": 1}}})
    with pytest.raises(InferenceConfigError):
        InferenceConfig({"inference": {"kv_cache": []}})
    with pytest.raises(InferenceConfigError):
        InferenceConfig({"inference": {"sync_every": -1}})


def test_submit_validation(setup):
    cfg, model, params, engine = setup
    engine.reset()
    loop = ServingLoop(engine)
    with pytest.raises(ValueError, match="empty prompt"):
        loop.submit(Request(rid="x", tokens=np.zeros((0,), np.int32)))
    with pytest.raises(ValueError, match="max_seq_len"):
        loop.submit(Request(rid="x", tokens=np.zeros((120,), np.int32),
                            max_new_tokens=30))
    with pytest.raises(ValueError, match="buffer width"):
        loop.submit(Request(rid="x", tokens=np.zeros((4,), np.int32),
                            max_new_tokens=33))
    with pytest.raises(ValueError, match="top_k_max"):
        loop.submit(Request(rid="x", tokens=np.zeros((4,), np.int32),
                            max_new_tokens=4, temperature=1.0,
                            top_k=500))
    with pytest.raises(ValueError, match="top_k_max"):
        engine.start_request(0, np.zeros((4,), np.int32), max_new=4,
                             top_k=500)
    with pytest.raises(ValueError, match="ring width"):
        engine.start_request(0, np.zeros((4,), np.int32), max_new=33)
    # a request that can NEVER fit the page pool is rejected at
    # submit, not left to starve the queue behind it
    small = InferenceEngine(tiny_gpt2_config(), _params(model),
                            {"inference": {
                                "max_slots": 2, "prefill_chunk": 8,
                                "sync_every": 2, "max_new_tokens": 16,
                                "kv_cache": {"num_pages": 4,
                                             "page_size": 4}}})
    with pytest.raises(ValueError, match="usable pages"):
        ServingLoop(small).submit(
            Request(rid="big", tokens=np.zeros((10,), np.int32),
                    max_new_tokens=10))


def test_duplicate_request_ids_keep_ledger_exact(setup):
    """Two live requests sharing one rid must not collide on the
    ledger key: freeing the first leaves the second's entry intact
    and the kv_cache category total stays == pool bytes."""
    from deepspeed_tpu.monitor.memory import CAT_KV
    cfg, model, params, engine = setup
    engine.reset()
    r = np.random.RandomState(16)
    engine.cache.admit(0, 8, name="user-42")
    engine.cache.admit(1, 8, name="user-42")
    engine.cache.ensure(0, 8)
    engine.cache.ensure(1, 8)
    led = engine.monitor.ledger
    assert led.totals()["hbm"][CAT_KV] == engine.cache.pool_bytes
    engine.cache.free(0)
    # slot 1's entry survives slot 0's free
    tops = {b["name"] for b in led.top_buffers(32)
            if b["category"] == CAT_KV}
    assert "request.s1.user-42" in tops
    assert led.totals()["hbm"][CAT_KV] == engine.cache.pool_bytes
    engine.cache.free(1)
    engine.reset()


def test_config_error_names_dotted_key():
    for bad in ({"weight_bits": "eight"}, {"seed": "abc"},
                {"eos_token_id": "x"}):
        with pytest.raises(InferenceConfigError, match="inference\\."):
            InferenceConfig({"inference": bad})


# ----------------------------------------------------------------------
# serving monitor events
# ----------------------------------------------------------------------
def test_serving_monitor_events_schema(tmp_path):
    cfg = tiny_gpt2_config()
    model = GPT2ForCausalLM(cfg)
    params = _params(model)
    engine = InferenceEngine(cfg, params, {
        "inference": {"max_slots": 2, "prefill_chunk": 8,
                      "sync_every": 4, "max_new_tokens": 16,
                      "kv_cache": {"num_pages": 48, "page_size": 4}},
        "monitor": {"enabled": True, "sinks": ["jsonl"],
                    "output_path": str(tmp_path)}})
    r = np.random.RandomState(15)
    ServingLoop(engine).serve(
        [Request(rid=f"r{i}", tokens=r.randint(0, cfg.vocab_size,
                                               size=6 + i),
                 max_new_tokens=5) for i in range(3)])
    engine.monitor.close()
    events = []
    for root, _, files in os.walk(tmp_path):
        for f in files:
            if f.endswith(".jsonl"):
                with open(os.path.join(root, f)) as fh:
                    events += [json.loads(line) for line in fh]
    kinds = {}
    for e in events:
        kinds.setdefault(e["kind"], []).append(e)
    assert len(kinds.get("request_admitted", [])) == 3
    assert len(kinds.get("request_finished", [])) == 3
    assert kinds.get("decode_batch")
    assert kinds.get("memory"), "memory events must ride serving fences"
    adm = kinds["request_admitted"][0]
    for key in ("request_id", "slot", "prompt_tokens", "max_new_tokens",
                "queue_depth", "queued_ms"):
        assert key in adm, key
    fin = kinds["request_finished"][0]
    for key in ("request_id", "slot", "reason", "prompt_tokens",
                "new_tokens", "queued_ms", "ttft_ms", "wall_ms",
                "tokens_per_sec"):
        assert key in fin, key
    dec = kinds["decode_batch"][0]
    for key in ("iterations", "active_slots", "prefilling_slots",
                "queue_depth", "window_tokens", "tokens_per_sec",
                "kv_pages_in_use", "kv_pages_free"):
        assert key in dec, key
    # the memory event's kv_cache category equals the pool bytes
    mem = kinds["memory"][-1]
    assert mem["hbm"]["categories"]["kv_cache"] == \
        engine.cache.pool_bytes


# ----------------------------------------------------------------------
# serving observability (ISSUE 14): lifecycle tracker, SLO events,
# serving timeline, forensics
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def obs_setup(tmp_path_factory):
    """A monitor-enabled engine (tracker + trace export + jsonl) that
    served one 3-request batch; the exported trace snapshot covers
    exactly that batch."""
    tmp = tmp_path_factory.mktemp("serving_obs")
    cfg = tiny_gpt2_config()
    model = GPT2ForCausalLM(cfg)
    params = _params(model)
    engine = InferenceEngine(cfg, params, {
        "inference": {"max_slots": 4, "prefill_chunk": 8,
                      "sync_every": 4, "max_new_tokens": 16,
                      "kv_cache": {"num_pages": 64, "page_size": 4}},
        "monitor": {"enabled": True, "sinks": ["jsonl"],
                    "output_path": str(tmp),
                    "trace": {"enabled": True}}})
    assert engine.tracker is not None
    r = np.random.RandomState(21)
    results = ServingLoop(engine).serve(
        [Request(rid=f"r{i}",
                 tokens=r.randint(0, cfg.vocab_size, size=5 + 7 * i),
                 max_new_tokens=4 + i) for i in range(3)])
    trace_path = engine.monitor.export_trace()
    # snapshot the event log NOW: later tests drive more serving on
    # the same engine, and the schema assertions below are about THIS
    # batch's totals
    events = _jsonl_events(str(tmp))
    return cfg, engine, results, events, trace_path


def _jsonl_events(root):
    events = []
    for dirpath, _, files in os.walk(root):
        for f in files:
            if f.endswith(".jsonl"):
                with open(os.path.join(dirpath, f)) as fh:
                    events += [json.loads(line) for line in fh]
    return events


def test_tracker_absent_without_monitor(setup):
    """No monitor block -> no tracker (the monitor.flight convention);
    every earlier test in this file runs that way and stays valid."""
    cfg, model, params, engine = setup
    assert engine.monitor.enabled is False
    assert engine.tracker is None


def test_observability_config_validation():
    cfg = InferenceConfig({})
    assert cfg.observability_enabled is True
    assert cfg.slo_ttft_ms == 0.0 and cfg.slo_token_ms == 0.0
    off = InferenceConfig({"inference": {
        "observability": {"enabled": False, "slo_ttft_ms": 250,
                          "slo_token_ms": 20}}})
    assert off.observability_enabled is False
    assert off.slo_ttft_ms == 250.0 and off.slo_token_ms == 20.0
    with pytest.raises(InferenceConfigError, match="observability"):
        InferenceConfig({"inference": {"observability": []}})
    with pytest.raises(InferenceConfigError, match="slo_ttft_ms"):
        InferenceConfig({"inference": {
            "observability": {"slo_ttft_ms": -1}}})
    with pytest.raises(InferenceConfigError, match="slo_token_ms"):
        InferenceConfig({"inference": {
            "observability": {"slo_token_ms": "fast"}}})


def test_latency_histogram_fixed_edges_and_percentiles():
    from deepspeed_tpu.monitor.serving import (HIST_EDGES_MS,
                                               LatencyHistogram)
    # the schema-stability contract: edges are a fixed constant, log
    # spaced at 2^(1/3), and the payload width matches
    assert len(HIST_EDGES_MS) == 61
    for a, b in zip(HIST_EDGES_MS, HIST_EDGES_MS[1:]):
        assert 1.2 < b / a < 1.3
    h = LatencyHistogram()
    assert h.percentile(0.5) is None
    h.record(1.0, count=50)
    h.record(100.0, count=50)
    # bucket resolution: one factor-2^(1/3) bucket of the exact value
    assert 1.0 / 1.3 < h.percentile(0.25) / 1.0 < 1.3
    assert 1.0 / 1.3 < h.percentile(0.99) / 100.0 < 1.3
    # out-of-range values clamp into the end buckets, never lost
    h.record(1e-9)
    h.record(1e9)
    ev = h.to_event()
    assert ev["count"] == 102
    assert len(ev["counts"]) == len(HIST_EDGES_MS)
    assert ev["counts"][0] >= 1 and ev["counts"][-1] >= 1
    for key in ("v", "unit", "count", "sum_ms", "counts"):
        assert key in ev, key


def test_sync_guards_with_observability_enabled(obs_setup, monkeypatch):
    """The ISSUE-12 sync contract re-pinned with serving observability
    ENABLED: decode blocks between fences stay at ZERO host syncs and
    the fence costs exactly ONE device_get — the tracker is host
    arithmetic only."""
    import time
    cfg, engine, _, _, _ = obs_setup
    engine.reset()
    r = np.random.RandomState(22)
    loop = ServingLoop(engine)
    for i in range(3):
        loop.submit(Request(rid=f"g{i}", tokens=r.randint(
            0, cfg.vocab_size, size=6 + 2 * i), max_new_tokens=8))
    loop._t0 = time.monotonic()
    loop._last_fence_t = loop._now()
    loop.step()    # admission/compile settle
    counters = _SyncCounters(monkeypatch)
    n = 0
    while (loop.queue or loop.live or loop.prefilling) and n < 50:
        loop.step()
        n += 1
    assert n > 0
    assert counters.device_get == n, (counters.device_get, n)
    assert counters.effects_barrier == 0
    # engine-level: a decode block dispatches with zero syncs even
    # with the tracker attached
    engine.reset()
    engine.start_request(0, r.randint(0, cfg.vocab_size, size=6),
                         max_new=12)
    engine.decode_block(4)
    counters = _SyncCounters(monkeypatch)
    engine.decode_block(4)
    assert counters.device_get == 0
    assert counters.effects_barrier == 0
    engine.fetch_state()
    assert counters.device_get == 1
    engine.reset()


def test_serving_slo_jsonl_schema_roundtrip(obs_setup):
    """The new event schema through the real sink: `serving_slo` with
    schema-stable histogram payloads, and the extended timing keys on
    the existing serving events."""
    from deepspeed_tpu.monitor.serving import HIST_EDGES_MS
    cfg, engine, results, events, _ = obs_setup
    kinds = {}
    for e in events:
        kinds.setdefault(e["kind"], []).append(e)
    assert kinds.get("serving_slo"), "serving_slo must ride every fence"
    slo = kinds["serving_slo"][-1]
    for key in ("window_ms", "window_tokens", "tokens_per_sec",
                "active_slots", "prefilling_slots", "queue_depth",
                "kv_pages_in_use", "kv_pages_free",
                "kv_page_utilization", "queue_wait_share",
                "ttft_ms", "token_ms", "queue_ms",
                "ttft_p50_ms", "ttft_p99_ms", "token_p50_ms",
                "token_p99_ms", "queue_p50_ms", "queue_p99_ms",
                "finished_eos", "finished_max_tokens",
                "rejected_submit", "admission_deferred",
                "total_tokens", "goodput_tokens", "goodput_fraction"):
        assert key in slo, key
    # the histogram payload is fixed-width (schema-stable): readers
    # can diff bucket-for-bucket across runs
    for hist_key in ("ttft_ms", "token_ms", "queue_ms"):
        hist = slo[hist_key]
        assert len(hist["counts"]) == len(HIST_EDGES_MS)
        assert hist["count"] == sum(hist["counts"])
    # after all three finished: counts + goodput add up
    assert slo["finished_eos"] + slo["finished_max_tokens"] >= 3
    assert slo["total_tokens"] == sum(len(q.out_tokens)
                                      for q in results)
    assert slo["goodput_fraction"] == 1.0   # no SLO targets set
    assert slo["ttft_ms"]["count"] >= 3
    assert slo["token_p99_ms"] >= slo["token_p50_ms"]
    # extended rows on the PR-12 events
    adm = kinds["request_admitted"][0]
    assert adm["kv_pages_reserved"] > 0
    fin = kinds["request_finished"][0]
    for key in ("prefill_ms", "decode_ms", "token_ms"):
        assert key in fin, key
    assert fin["decode_ms"] > 0 and fin["token_ms"] > 0
    assert "window_ms" in kinds["decode_batch"][0]


def test_serving_trace_exports_slot_timeline(obs_setup):
    """The acceptance trace: passes the existing Chrome-trace
    validator, carries >= 1 per-slot request track with the distinct
    slice types, the serving counter tracks, and per-request finish
    instants the summary recomputes from."""
    from test_trace_export import validate_chrome_trace
    from deepspeed_tpu.monitor.trace_export import (
        CAT_SERVE_DECODE, CAT_SERVE_PREFILL, CAT_SERVE_QUEUE,
        CAT_SERVE_REQUEST, load_trace, summarize_trace)
    cfg, engine, results, _events, trace_path = obs_setup
    doc = load_trace(trace_path)
    validate_chrome_trace(doc)
    tracks = {ev["args"]["name"] for ev in doc["traceEvents"]
              if ev["ph"] == "M"}
    assert any(t.startswith("serve/slot") for t in tracks), tracks
    cats = {ev.get("cat") for ev in doc["traceEvents"]}
    for cat in (CAT_SERVE_QUEUE, CAT_SERVE_PREFILL, CAT_SERVE_DECODE,
                CAT_SERVE_REQUEST):
        assert cat in cats, cat
    counter_names = {ev["name"] for ev in doc["traceEvents"]
                     if ev["ph"] == "C"}
    for name in ("queue_depth", "batch_occupancy",
                 "kv_page_utilization", "tokens_per_sec"):
        assert name in counter_names, name
    s = summarize_trace(doc)
    serving = s.get("serving")
    assert serving and serving["requests"] == 3
    assert serving["new_tokens"] == sum(len(q.out_tokens)
                                        for q in results)
    for key in ("queued_ms", "ttft_ms", "token_ms"):
        assert serving[key]["p50"] is not None
        assert serving[key]["p99"] >= serving[key]["p50"]
    assert serving["goodput_fraction"] == 1.0
    # fidelity: summary TTFT p50 within one histogram... no — the
    # summary is exact (recomputed from instants); compare against the
    # scheduler's independent Request stamps instead
    exact = sorted((q.first_token_at - q.admitted_at) * 1e3
                   for q in results)
    assert abs(serving["ttft_ms"]["p50"] - exact[1]) < \
        max(2.0, 0.5 * exact[1])


def test_ds_trace_summary_serving_cli(obs_setup, capsys, tmp_path):
    from deepspeed_tpu.monitor import trace_cli
    cfg, engine, results, _events, trace_path = obs_setup
    assert trace_cli.main(["summary", "--serving", trace_path]) == 0
    out = capsys.readouterr().out
    assert "serving (per-request" in out
    assert "ttft" in out and "token" in out and "queue_wait" in out
    assert "p50_ms" in out and "p99_ms" in out
    # plain summary also prints the serving section when present
    assert trace_cli.main(["summary", trace_path]) == 0
    assert "serving (per-request" in capsys.readouterr().out
    # a serving-less trace reports so (exit 1)
    from deepspeed_tpu.monitor.trace_export import TraceExporter
    ex = TraceExporter()
    ex.complete("t", "e", 1.0, 0.1)
    plain = str(tmp_path / "plain.json")
    ex.write(plain)
    assert trace_cli.main(["summary", "--serving", plain]) == 1
    assert "no serving events" in capsys.readouterr().out


def test_serving_oom_hints_ranking():
    """The serving-aware hint ranking: kv_cache pages vs max_slots vs
    prefill_chunk, ordered by what dominates."""
    from deepspeed_tpu.monitor.serving import serving_oom_hints
    # pool dominates but mostly unallocated -> num_pages first
    payload = {"hbm": {"categories": {"kv_cache": 10 * 2**30,
                                      "params": 2 * 2**30},
                       "ledger_bytes": 12 * 2**30,
                       "measured_in_use_per_device": 13 * 2**30,
                       "residual_bytes": 1 * 2**30}}
    hints = serving_oom_hints(payload, {
        "kv_page_utilization": 0.1, "requests": []})
    assert hints and "inference.kv_cache.num_pages" in hints[0]
    # pool saturated by reservations -> max_slots first
    hints = serving_oom_hints(payload, {
        "kv_page_utilization": 0.95,
        "requests": [{"phase": "decode"}] * 8})
    assert hints and "inference.max_slots" in hints[0]
    # prefill activations dominate the residual -> prefill_chunk named
    payload_resid = {"hbm": {"categories": {"kv_cache": 1 * 2**30},
                             "ledger_bytes": 8 * 2**30,
                             "measured_in_use_per_device": 10 * 2**30,
                             "residual_bytes": 7 * 2**30}}
    hints = serving_oom_hints(payload_resid, {
        "kv_page_utilization": 0.4,
        "requests": [{"phase": "prefill"}]})
    assert any("inference.prefill_chunk" in h for h in hints)
    # no serving signal -> no serving hints (generic oom_hints remain)
    assert serving_oom_hints({}, {}) == []


def test_crash_during_serving_dumps_live_request_table(tmp_path):
    """Subprocess crash-during-serving: an OOM-shaped failure at a
    serving fence must leave a flight dump whose sticky context (and
    crash extra) names exactly the requests that were in flight, with
    the serving-aware OOM hints ranked in."""
    import subprocess
    import sys
    out_dir = str(tmp_path / "mon")
    script = f"""
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
from deepspeed_tpu.inference import InferenceEngine, Request, ServingLoop
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config

cfg = tiny_gpt2_config()
model = GPT2ForCausalLM(cfg)
params = model.init(jax.random.PRNGKey(0),
                    {{"input_ids": np.zeros((1, 8), np.int32)}})
engine = InferenceEngine(cfg, params, {{
    "inference": {{"max_slots": 2, "prefill_chunk": 8, "sync_every": 4,
                   "max_new_tokens": 16,
                   "kv_cache": {{"num_pages": 256, "page_size": 4}}}},
    "monitor": {{"enabled": True, "sinks": ["jsonl"],
                 "output_path": {out_dir!r}}}}})
loop = ServingLoop(engine)
r = np.random.RandomState(0)
for i in range(3):
    loop.submit(Request(rid=f"inflight{{i}}",
                        tokens=r.randint(0, cfg.vocab_size, size=7),
                        max_new_tokens=12))
real = engine.fetch_state
calls = {{"n": 0}}
def oom_fence():
    calls["n"] += 1
    if calls["n"] >= 3:
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: out of memory allocating kv pages")
    return real()
engine.fetch_state = oom_fence
loop.run()
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert proc.returncode != 0      # the crash still propagated
    assert "RESOURCE_EXHAUSTED" in proc.stderr
    from deepspeed_tpu.monitor.flight import list_flight_dumps
    dumps = list_flight_dumps(out_dir)
    assert dumps, (proc.stdout[-1000:], proc.stderr[-1000:])
    # the crash guard's "oom" dump (the armed tracker also leaves an
    # atexit dump when the crashed process exits — both are correct)
    docs = []
    for p in dumps:
        with open(p) as f:
            docs.append(json.load(f))
    ooms = [d for d in docs if d["reason"] == "oom"]
    assert ooms, [d["reason"] for d in docs]
    doc = ooms[-1]
    # the live request table: sticky context AND the crash extra
    for table in (doc["context"]["serving"],
                  doc["extra"]["serving"]):
        rows = table["requests"]
        assert rows, table
        for row in rows:
            assert row["request_id"].startswith("inflight")
            for key in ("slot", "phase", "tokens_emitted",
                        "pages_held"):
                assert key in row, key
    # the serving-aware hint ranking rode the oom extra: the pool is
    # 256 pages for 3 tiny requests -> underutilized -> num_pages
    hints = " ".join(doc["extra"]["oom"]["hints"])
    assert "inference.kv_cache.num_pages" in hints


def test_kv_page_utilization_ledger_vs_cache_twins(obs_setup):
    """The tracker derives KV-page utilization from the memory
    ledger's `kv_cache` category (serving._kv_pages); the cache
    derives it from its own page tables (pages_in_use/utilization).
    Two independent accounting chains — they must agree
    page-for-page."""
    cfg, engine, _, _, _ = obs_setup
    engine.reset()
    cache = engine.cache
    assert engine.tracker._kv_pages() == (0, cache.num_pages - 1, 0.0)
    assert cache.pages_in_use() == 0 and cache.utilization() == 0.0
    cache.admit(0, 12, name="twin")
    cache.ensure(0, 12)
    in_use, free, util = engine.tracker._kv_pages()
    assert in_use == cache.pages_in_use() > 0
    assert free == (cache.num_pages - 1) - in_use
    assert util == pytest.approx(cache.utilization())
    cache.free(0)
    engine.reset()
