"""Engine end-to-end tests: train-loss descent, forward/backward/step API,
ZeRO stages 0-3 equivalence, fp16 loss scaling, grad accumulation, and
checkpoint round-trips (parity targets: ref tests/unit/test_fp16.py,
test_zero.py, test_checkpointing.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from simple_model import SimpleModel, random_dataset
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config


def ds_config(**over):
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 100,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
    }
    cfg.update(over)
    return cfg


def make_batch(bs, dim, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(bs, dim).astype(np.float32)
    w = np.linspace(-1, 1, dim * dim).reshape(dim, dim).astype(np.float32)
    return {"x": x, "y": x @ w}


def train_steps(engine, n, dim=16, bs=16):
    losses = []
    for i in range(n):
        batch = make_batch(bs, dim, seed=i % 4)
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_engine_loss_decreases():
    model = SimpleModel(hidden_dim=16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=ds_config())
    losses = train_steps(engine, 30)
    assert losses[-1] < losses[0] * 0.5


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_match_stage0(stage):
    """All ZeRO stages must produce numerically equivalent training
    (the sharding must be a pure layout change)."""
    def run(stage):
        model = SimpleModel(hidden_dim=16)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.params,
            config=ds_config(zero_optimization={"stage": stage}))
        losses = train_steps(engine, 5)
        final = jax.device_get(engine.fp32_params)
        return losses, final

    losses0, params0 = run(0)
    losses_s, params_s = run(stage)
    # stages differ only by reduction order/layout → tolerance is float32
    # noise, not semantics
    np.testing.assert_allclose(losses0, losses_s, rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(params0),
                    jax.tree_util.tree_leaves(params_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3,
                                   atol=1e-6)


def test_gradient_accumulation_equivalence():
    """gas=4 with micro-bs 4 must match gas=1 with bs 16 (same global
    batch, same data)."""
    def run(gas):
        model = SimpleModel(hidden_dim=8)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.params,
            config=ds_config(train_batch_size=32,
                             gradient_accumulation_steps=gas))
        full = make_batch(32, 8, seed=0)
        for _ in range(3):
            micro_bs = 32 // gas
            for m in range(gas):
                mb = {k: v[m * micro_bs:(m + 1) * micro_bs]
                      for k, v in full.items()}
                loss = engine(mb)
                engine.backward(loss)
                engine.step()
        return jax.device_get(engine.fp32_params)

    p1 = run(1)
    p4 = run(4)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_fp16_dynamic_loss_scale_skips_overflow():
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(
            train_batch_size=16,
            fp16={"enabled": True, "loss_scale": 0,
                  "initial_scale_power": 4, "loss_scale_window": 2,
                  "hysteresis": 1}))
    assert engine.fp16_enabled()
    start_scale = engine.loss_scale()
    assert start_scale == 16.0
    # feed a batch with inf targets -> grads overflow -> step skipped
    bad = {"x": np.full((16, 8), 1e30, np.float32),
           "y": np.zeros((16, 8), np.float32)}
    params_before = jax.device_get(engine.fp32_params)
    loss = engine(bad)
    engine.backward(loss)
    engine.step()
    params_after = jax.device_get(engine.fp32_params)
    for a, b in zip(jax.tree_util.tree_leaves(params_before),
                    jax.tree_util.tree_leaves(params_after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert engine.skipped_steps == 1
    assert engine.loss_scale() == 8.0  # halved


def test_bf16_training():
    model = SimpleModel(hidden_dim=16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(bf16={"enabled": True}))
    losses = train_steps(engine, 20)
    assert losses[-1] < losses[0]
    assert engine.state.params["w"].dtype == jnp.bfloat16
    assert engine.state.master["w"].dtype == jnp.float32


def test_gradient_clipping_applies():
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(train_batch_size=16, gradient_clipping=1e-8,
                         optimizer={"type": "sgd",
                                    "params": {"lr": 1.0}}))
    batch = make_batch(16, 8, seed=0)
    before = jax.device_get(engine.fp32_params)
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    after = jax.device_get(engine.fp32_params)
    # with clip ~0 and sgd, params barely move
    delta = max(np.max(np.abs(np.asarray(a) - np.asarray(b)))
                for a, b in zip(jax.tree_util.tree_leaves(before),
                                jax.tree_util.tree_leaves(after)))
    assert delta < 1e-6


def test_train_batch_fused_path():
    model = SimpleModel(hidden_dim=16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(train_batch_size=32,
                         gradient_accumulation_steps=2))
    losses = []
    for i in range(10):
        full = make_batch(32, 16, seed=i % 2)
        stacked = {k: v.reshape(2, 16, *v.shape[1:]) for k, v in full.items()}
        loss = engine.train_batch(batch=stacked)
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]


def test_scheduler_integration():
    model = SimpleModel(hidden_dim=8)
    engine, _, _, sched = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(
            train_batch_size=16,
            scheduler={"type": "WarmupLR",
                       "params": {"warmup_min_lr": 0.0,
                                  "warmup_max_lr": 0.01,
                                  "warmup_num_steps": 5}}))
    assert sched is not None
    train_steps(engine, 6, dim=8)
    assert engine.get_lr()[0] == pytest.approx(0.01)


def test_gpt2_tiny_trains():
    cfg = tiny_gpt2_config()
    model = GPT2ForCausalLM(cfg)
    rng = jax.random.PRNGKey(0)
    ids = np.asarray(
        jax.random.randint(rng, (8, 32), 0, cfg.vocab_size), np.int32)
    params = model.init(rng, {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=ds_config(train_batch_size=8,
                         optimizer={"type": "Adam",
                                    "params": {"lr": 1e-3}}))
    losses = []
    for i in range(10):
        batch = {"input_ids": ids}
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]  # memorizing a fixed batch


def test_checkpoint_roundtrip(tmp_ckpt_dir):
    model = SimpleModel(hidden_dim=16)
    cfg = ds_config(zero_optimization={"stage": 2})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    train_steps(engine, 5)
    engine.save_checkpoint(tmp_ckpt_dir, client_state={"my_key": 123})
    engine.wait_for_checkpoint()

    model2 = SimpleModel(hidden_dim=16, seed=99)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=model2, model_parameters=model2.params, config=cfg)
    path, client = engine2.load_checkpoint(tmp_ckpt_dir)
    assert path is not None
    assert client["my_key"] == 123
    for a, b in zip(jax.tree_util.tree_leaves(
            jax.device_get(engine.fp32_params)),
            jax.tree_util.tree_leaves(jax.device_get(engine2.fp32_params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # training continues identically
    l1 = train_steps(engine, 3)
    l2 = train_steps(engine2, 3)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_checkpoint_latest_tag(tmp_ckpt_dir):
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(train_batch_size=16))
    train_steps(engine, 2, dim=8)
    engine.save_checkpoint(tmp_ckpt_dir, tag="tag_a")
    engine.save_checkpoint(tmp_ckpt_dir, tag="tag_b")
    engine.wait_for_checkpoint()
    from deepspeed_tpu.runtime.checkpoint import read_latest_tag
    assert read_latest_tag(tmp_ckpt_dir) == "tag_b"


def test_missing_checkpoint_returns_none(tmp_ckpt_dir):
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(train_batch_size=16))
    path, client = engine.load_checkpoint(tmp_ckpt_dir)
    assert path is None


def test_client_optax_optimizer_lr_preserved():
    """A client optax optimizer must keep its own learning rate (a past
    bug forced it to 0.0, silently freezing training)."""
    import optax
    model = SimpleModel(hidden_dim=16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        optimizer=optax.adam(5e-2),
        config={"train_batch_size": 16, "steps_per_print": 100})
    losses = train_steps(engine, 8)
    assert losses[-1] < losses[0] * 0.9, \
        f"client-optimizer training made no progress: {losses}"


def test_bare_flax_model_eval_batch():
    """Bare flax modules (with dropout) must work through eval_batch:
    the adapter forwards `deterministic`."""
    import flax.linen as nn

    class LossModule(nn.Module):
        @nn.compact
        def __call__(self, batch, deterministic: bool = False):
            h = nn.Dense(8)(batch["x"])
            h = nn.Dropout(0.5)(h, deterministic=deterministic)
            pred = nn.Dense(16)(h)
            return jnp.mean((pred - batch["y"]) ** 2)

    model = LossModule()
    batch = make_batch(16, 16, seed=0)
    params = model.init({"params": jax.random.PRNGKey(0)}, batch,
                        deterministic=True)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=ds_config())
    loss = engine.eval_batch(batch)
    assert np.isfinite(float(jax.device_get(loss)))
    # training path (non-deterministic, needs dropout rng) also works
    loss = engine(batch)
    engine.backward(loss)
    engine.step()


def test_checkpoint_restores_lr_scheduler_state(tmp_ckpt_dir):
    """Scheduler state rides the checkpoint (ref
    test_checkpointing.py:406 test_checkpoint_lr_scheduler): a fresh
    engine resumes mid-warmup at the saved iteration, and
    load_lr_scheduler_states=False restarts the schedule."""
    sched = {"scheduler": {"type": "WarmupLR",
                           "params": {"warmup_min_lr": 0.0,
                                      "warmup_max_lr": 1e-2,
                                      "warmup_num_steps": 20}}}
    model = SimpleModel(hidden_dim=16)
    cfg = ds_config(**sched)
    engine, _, _, sch = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    train_steps(engine, 7)
    saved_iter = sch.last_batch_iteration
    saved_lr = sch.get_lr()[0]
    assert 0 < saved_lr < 1e-2    # mid-warmup
    engine.save_checkpoint(tmp_ckpt_dir)
    engine.wait_for_checkpoint()

    model2 = SimpleModel(hidden_dim=16, seed=3)
    engine2, _, _, sch2 = deepspeed_tpu.initialize(
        model=model2, model_parameters=model2.params, config=cfg)
    engine2.load_checkpoint(tmp_ckpt_dir)
    assert sch2.last_batch_iteration == saved_iter
    np.testing.assert_allclose(sch2.get_lr()[0], saved_lr, rtol=1e-9)

    model3 = SimpleModel(hidden_dim=16, seed=4)
    engine3, _, _, sch3 = deepspeed_tpu.initialize(
        model=model3, model_parameters=model3.params, config=cfg)
    engine3.load_checkpoint(tmp_ckpt_dir, load_lr_scheduler_states=False)
    assert sch3.last_batch_iteration != saved_iter or \
        sch3.last_batch_iteration <= 0


@pytest.mark.parametrize("stage", [1, 2])
def test_zero_with_unused_params(stage):
    """ZeRO with a parameter the loss never touches (ref test_zero.py:32
    unbalanced-gradients scenario — in SPMD the analogue is a zero
    gradient, not an absent one): the unused leaf must stay bitwise
    unchanged under Adam (zero grad, zero moments) while training
    descends, and its optimizer state must still shard over data."""
    class ModelWithUnused:
        def __init__(self, dim=16):
            rng = np.random.RandomState(0)
            self.params = {
                "w": jnp.asarray(rng.randn(dim, dim) * 0.1, jnp.float32),
                "b": jnp.zeros((dim,), jnp.float32),
                "unused": jnp.asarray(rng.randn(dim, dim), jnp.float32),
            }

        def loss_fn(self, params, batch, rngs=None, deterministic=False):
            pred = batch["x"].astype(jnp.float32) @ params["w"] + \
                params["b"]
            return jnp.mean((pred - batch["y"].astype(jnp.float32)) ** 2)

    model = ModelWithUnused()
    before = np.asarray(model.params["unused"])
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(zero_optimization={"stage": stage}))
    losses = train_steps(engine, 8)
    assert losses[-1] < losses[0]
    after = np.asarray(jax.device_get(engine.fp32_params["unused"]))
    np.testing.assert_array_equal(before, after)
