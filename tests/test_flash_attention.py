"""Flash-attention kernel numerics vs the dense XLA reference
(parity target: ref tests/unit/test_cuda_forward.py / test_cuda_backward.py
which sweep shapes and compare the fused kernel against a vendored torch
layer). Kernels run in Pallas interpreter mode on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.flash_attention import (
    dense_attention, flash_attention, flash_attention_usable)
from deepspeed_tpu.models.gpt2 import causal_attention_xla


def qkv(b, t, h, d, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, t, h, d), dtype) for _ in range(3)]


def dense_reference(q, k, v, causal):
    if causal:
        return causal_attention_xla(q, k, v)
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("b,t,h,d", [(2, 256, 4, 64), (1, 384, 2, 128)])
def test_forward_matches_dense(b, t, h, d, causal):
    q, k, v = qkv(b, t, h, d)
    ref = dense_reference(q, k, v, causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_dense(causal):
    q, k, v = qkv(1, 256, 2, 64, seed=3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dense_reference(q, k, v, causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=5e-4)


def test_uneven_blocks():
    """block_q != block_k and T not a multiple of the default block."""
    q, k, v = qkv(1, 512, 2, 64, seed=5)
    ref = dense_reference(q, k, v, True)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=256)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_usability_gate():
    q = jnp.zeros((2, 256, 4, 64))
    assert flash_attention_usable(q, True)
    assert not flash_attention_usable(q, False)          # dropout active
    assert not flash_attention_usable(jnp.zeros((2, 100, 4, 64)), True)
    assert not flash_attention_usable(jnp.zeros((2, 256, 4, 48)), True)
    # 128 <= T < 1024 but T % 128 != 0: _fit_block would clamp the tile
    # to T itself, an unaligned lane dim Mosaic rejects on real TPU
    # (advisor r4) — the gate must refuse it
    assert not flash_attention_usable(jnp.zeros((2, 136, 4, 64)), True)
    assert flash_attention_usable(jnp.zeros((2, 640, 4, 64)), True)


def test_jit_and_dtype_preserved():
    q, k, v = qkv(1, 256, 2, 64, dtype=jnp.bfloat16)
    out = jax.jit(lambda a, b, c: flash_attention(a, b, c))(q, k, v)
    assert out.dtype == jnp.bfloat16
    assert out.shape == q.shape


def test_fused_single_tile_backward_parity():
    """Default blocks at T <= _DEFAULT_BLOCK route the backward through
    the fused one-pass kernel (nq == nk == 1) — pin its gradient parity
    against the dense reference (review r4: the path was untested)."""
    B, T, H, D = 2, 256, 4, 64
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)) * 0.3, jnp.bfloat16)
    for causal in (True, False):
        # no explicit blocks: min(_DEFAULT_BLOCK, T) == T == one tile
        gf = jax.grad(lambda q: flash_attention(
            q, k, v, causal=causal).astype(jnp.float32).sum())(q)
        gd = jax.grad(lambda q: dense_attention(
            q, k, v, causal=causal).astype(jnp.float32).sum())(q)
        np.testing.assert_allclose(
            np.asarray(gf, np.float32), np.asarray(gd, np.float32),
            atol=0.02, rtol=0.05)


def test_block_fit_fallback_lengths():
    """T divisible by 512 but not 1024 (1536, 2560) must still ride the
    kernel via the power-of-two block shrink, not fall back to dense or
    assert (review r4)."""
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_usable, _fit_block)
    assert _fit_block(1024, 1536) == 512
    assert _fit_block(1024, 2560) == 512
    assert _fit_block(1024, 384) == 384   # clamp: 384 divides itself
    B, H, D = 1, 2, 64
    for T in (1536, 2560):
        q = jnp.asarray(np.zeros((B, T, H, D)), jnp.bfloat16)
        assert flash_attention_usable(q, no_dropout=True), T
    out = flash_attention(
        jnp.asarray(np.random.default_rng(0).standard_normal(
            (1, 1536, 2, 64)) * 0.3, jnp.bfloat16),
        jnp.asarray(np.zeros((1, 1536, 2, 64)), jnp.bfloat16),
        jnp.asarray(np.zeros((1, 1536, 2, 64)), jnp.bfloat16),
        causal=True)
    assert out.shape == (1, 1536, 2, 64)


# ----------------------------------------------------------------------
# (out, lse) form — the ring-attention partial (VERDICT r4 #4)
# ----------------------------------------------------------------------
def _lse_reference(q, k, v, causal):
    """Dense (out, log2-space lse) reference."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = np.tril(np.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    lse_nat = jax.scipy.special.logsumexp(s, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd",
                     jnp.exp(s - lse_nat).astype(v.dtype), v)
    return out, lse_nat * np.log2(np.e)        # kernel lse is log2-space


@pytest.mark.parametrize("causal", [True, False])
def test_with_lse_forward_matches_dense(causal):
    from deepspeed_tpu.ops.transformer.flash_attention import \
        flash_attention_with_lse
    q, k, v = qkv(1, 256, 2, 64, seed=7)
    out, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                        block_q=128, block_k=128)
    ref_out, ref_lse = _lse_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_with_lse_grads_flow_through_lse(causal):
    """The sharp edge: a loss consuming BOTH outputs must produce the
    same q/k/v grads as the dense reference — the lse cotangent enters
    the backward kernels as a delta shift (flash_attention.py _bwd)."""
    from deepspeed_tpu.ops.transformer.flash_attention import \
        flash_attention_with_lse
    q, k, v = qkv(1, 256, 2, 64, seed=11)

    def loss_flash(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                            block_q=128, block_k=128)
        return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    def loss_ref(q, k, v):
        out, lse = _lse_reference(q, k, v, causal)
        return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=5e-4)
