"""ds_lint — static invariant analyzer tests (ISSUE 11).

Four layers:
  * the SELF-RUN: the analyzer over the whole shipped package must
    report zero non-baselined findings — the analyzer is part of the
    verify loop, the same trick the bench smoke tests use;
  * per-rule fixtures: every rule fires on its true-positive snippet
    (tests/lint_fixtures/tp) and stays silent on its true-negative
    (tests/lint_fixtures/tn);
  * baseline add/expire roundtrip;
  * the HOTSYNC cross-check: the fence-site allowlist must match the
    sync sites the DYNAMIC guard tests pin (test_async_dispatch /
    test_monitor monkeypatch `jax.device_get`/`jax.effects_barrier`
    and count calls) — deleting a fence entry or injecting a
    device_get into a hot function must produce a finding.
"""

import json
import os
import shutil
import types

import pytest

from deepspeed_tpu import analysis
from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis import registry
from deepspeed_tpu.analysis.cli import main as ds_lint_main
from deepspeed_tpu.analysis.rules import ALL_RULES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "deepspeed_tpu")
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")

RULES = ("HOTSYNC", "TRACECTL", "CFGKEY", "EVTSCHEMA", "BROADEXC",
         "LOCKBLOCK")


def fixture_registry():
    """The default contract registry re-pointed at the miniature
    fixture package."""
    reg = types.SimpleNamespace(
        **{k: getattr(registry, k) for k in dir(registry)
           if k.isupper()})
    reg.HOT_ENTRYPOINTS = ("pkg.hot:train_step",)
    reg.FENCE_SITES = ("pkg.hot:fence",)
    reg.ATTR_TYPES = {}
    reg.CONFIG_CONSTANT_MODULES = ("pkg.constants",)
    reg.CONFIG_DOC_FILES = ("docs/MIGRATION.md",)
    reg.EVENT_EMITTER_MODULE_PREFIXES = ("pkg",)
    return reg


def run_fixture(variant, rules=None, root=None):
    root = root or os.path.join(FIXTURES, variant)
    return analysis.run_analysis(
        [os.path.join(root, "pkg")], repo_root=root,
        registry=fixture_registry(), rules=rules)


def rules_of(result):
    return {f.rule for f in result.findings}


# ----------------------------------------------------------------------
# the self-run: the shipped tree lints clean
# ----------------------------------------------------------------------
def test_shipped_tree_lints_clean():
    res = analysis.run_analysis([PKG], repo_root=REPO)
    assert res.errors == [], res.errors
    pretty = [f"{f.location(REPO)} {f.rule} {f.message}"
              for f in res.findings]
    assert res.findings == [], "\n".join(pretty)
    # the deliberate exceptions are annotated, not invisible
    assert len(res.suppressed) >= 30


def test_cli_self_run_exit_zero(capsys):
    assert ds_lint_main([PKG]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_cli_json_output(capsys):
    assert ds_lint_main([PKG, "--json", "--no-baseline"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == []
    assert doc["errors"] == []
    assert doc["suppressed"] >= 30


def test_cli_list_and_explain(capsys):
    assert ds_lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out
    assert ds_lint_main(["--explain", "hotsync"]) == 0
    out = capsys.readouterr().out
    assert "fence" in out.lower()
    assert ds_lint_main(["--explain", "NOPE"]) == 2
    assert ds_lint_main([]) == 2                 # no paths
    assert ds_lint_main([PKG, "--rules", "BOGUS"]) == 2


# ----------------------------------------------------------------------
# per-rule fixtures: TP fires, TN stays silent
# ----------------------------------------------------------------------
@pytest.mark.parametrize("rule", RULES)
def test_rule_fires_on_true_positive(rule):
    res = run_fixture("tp", rules=[rule])
    assert any(f.rule == rule for f in res.findings), \
        f"{rule} produced no finding on its true-positive fixture"


@pytest.mark.parametrize("rule", RULES)
def test_rule_silent_on_true_negative(rule):
    res = run_fixture("tn", rules=[rule])
    got = [f for f in res.findings if f.rule == rule]
    assert got == [], [f"{f.location()} {f.message}" for f in got]


def test_hotsync_fixture_details():
    res = run_fixture("tp", rules=["HOTSYNC"])
    msgs = {f.message.split(" (")[0] for f in res.findings}
    # both the direct sync and the host-conversion form are caught
    assert any("device_get" in m for m in msgs)
    assert any("float()" in m for m in msgs)
    # the declared fence site itself is NOT flagged
    assert not any(f.qualname == "fence" for f in res.findings)


def test_cfgkey_fixture_details():
    res = run_fixture("tp", rules=["CFGKEY"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "literal_key" in msgs          # literal read
    assert "undocumented_key" in msgs     # read but no doc row
    assert "DEAD_KEY" in msgs             # declared but never read


def test_evtschema_fixture_details():
    res = run_fixture("tp", rules=["EVTSCHEMA"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "beta" in msgs                 # emitted, undocumented
    assert "ghost" in msgs                # documented, never emitted


def test_broadexc_annotation_suppresses():
    res = run_fixture("tp", rules=["BROADEXC"])
    # exactly ONE finding (`swallows`); the annotated handler is
    # suppressed and reported as such
    assert [f.qualname for f in res.findings] == ["swallows"]
    assert any(s.qualname == "annotated" for s in res.suppressed)


def test_lockblock_fixture_details():
    res = run_fixture("tp", rules=["LOCKBLOCK"])
    msgs = " | ".join(f.message for f in res.findings)
    assert "fsync" in msgs
    assert "queue" in msgs


# ----------------------------------------------------------------------
# baseline add/expire roundtrip
# ----------------------------------------------------------------------
def test_baseline_roundtrip(tmp_path):
    root = tmp_path / "fx"
    shutil.copytree(os.path.join(FIXTURES, "tp"), root)
    res = run_fixture(None, rules=["BROADEXC"], root=str(root))
    assert len(res.findings) == 1

    # add: baseline the finding -> the tree lints clean
    entries = baseline_mod.build_entries(res.findings, res.index,
                                         str(root))
    bl_path = str(tmp_path / "baseline.json")
    baseline_mod.save(bl_path, entries)
    loaded = baseline_mod.load(bl_path)
    assert loaded == entries

    res2 = run_fixture(None, rules=["BROADEXC"], root=str(root))
    new, baselined, expired = baseline_mod.apply(
        res2.findings, loaded, res2.index, str(root))
    assert new == [] and len(baselined) == 1 and expired == {}

    # expire: fix the offending handler -> the entry is reported stale
    exc_py = root / "pkg" / "exc.py"
    src = exc_py.read_text()
    exc_py.write_text(src.replace(
        "    except Exception:\n        pass          "
        "# BROADEXC finding",
        "    except Exception:\n        raise"))
    res3 = run_fixture(None, rules=["BROADEXC"], root=str(root))
    new, baselined, expired = baseline_mod.apply(
        res3.findings, loaded, res3.index, str(root))
    assert new == [] and baselined == [] and len(expired) == 1


def test_baseline_fingerprint_survives_line_drift(tmp_path):
    root = tmp_path / "fx"
    shutil.copytree(os.path.join(FIXTURES, "tp"), root)
    res = run_fixture(None, rules=["BROADEXC"], root=str(root))
    entries = baseline_mod.build_entries(res.findings, res.index,
                                         str(root))
    # shift the finding down by editing ABOVE it: fingerprint holds
    exc_py = root / "pkg" / "exc.py"
    exc_py.write_text('"""moved."""\n\n\n' + exc_py.read_text())
    res2 = run_fixture(None, rules=["BROADEXC"], root=str(root))
    new, baselined, expired = baseline_mod.apply(
        res2.findings, entries, res2.index, str(root))
    assert new == [] and len(baselined) == 1 and expired == {}


def test_cli_update_baseline_roundtrip(tmp_path, capsys):
    root = tmp_path / "fx"
    shutil.copytree(os.path.join(FIXTURES, "tp"), root)
    # the fixture tree has findings against the DEFAULT registry too
    # (its `pkg` isn't this repo's package) — just verify the CLI
    # mechanics: update writes a file, a later run consumes it
    pkg = str(root / "pkg")
    assert ds_lint_main([pkg, "--update-baseline"]) == 0
    capsys.readouterr()
    bl = os.path.join(str(root), baseline_mod.DEFAULT_BASENAME)
    assert os.path.exists(bl)
    assert ds_lint_main([pkg]) == 0         # all findings baselined
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


# ----------------------------------------------------------------------
# HOTSYNC <-> dynamic guard tests cross-check
# ----------------------------------------------------------------------
def test_registry_entries_all_resolve():
    res = analysis.run_analysis([PKG], repo_root=REPO,
                                rules=["HOTSYNC"])
    # unresolved registry entries surface as findings; clean tree
    # means every declared entry resolves
    assert res.findings == []
    from deepspeed_tpu.analysis import core
    idx = res.index
    for key in registry.HOT_ENTRYPOINTS + registry.FENCE_SITES:
        assert idx.function(key) is not None, f"stale registry: {key}"


def test_fence_sites_cover_the_dynamically_pinned_rendezvous():
    """The dynamic guard tests pin (a) zero per-step syncs and (b)
    exactly one device_get per fence, by monkeypatching jax.device_get
    / jax.effects_barrier. The static twin must (a) treat those names
    as the sync surface and (b) declare exactly the fence path those
    tests allow."""
    guard_src = ""
    for name in ("test_async_dispatch.py", "test_monitor.py"):
        with open(os.path.join(REPO, "tests", name)) as f:
            guard_src += f.read()
    # the names the dynamic counters instrument are in the static
    # sync surface
    assert 'jax, "device_get"' in guard_src
    assert 'jax, "effects_barrier"' in guard_src
    assert {"device_get", "effects_barrier"} <= \
        set(registry.SYNC_CALL_NAMES)
    # the fence path the dynamic tests allow (engine._sync_fence ->
    # Monitor.on_fence -> registry.drain_device) is declared, as is
    # the offload host step the offload guard tests exempt
    declared = set(registry.FENCE_SITES)
    for needed in (
            "deepspeed_tpu.runtime.engine:DeepSpeedEngine._sync_fence",
            "deepspeed_tpu.monitor:Monitor.on_fence",
            "deepspeed_tpu.monitor.registry:"
            "MetricsRegistry.drain_device",
            "deepspeed_tpu.runtime.zero.offload:"
            "ZeroOffloadMixin._offload_take_step"):
        assert needed in declared, needed


def test_every_fence_site_actually_syncs():
    """No stale allowlist entries: each declared fence site must
    reach a sync call — otherwise the entry is dead weight that would
    silently mask a future regression."""
    import ast
    res = analysis.run_analysis([PKG], repo_root=REPO, rules=[])
    idx = res.index
    for key in registry.FENCE_SITES:
        order, _ = idx.reachable([key], stop_keys=(),
                                 attr_types=registry.ATTR_TYPES)
        names = set()
        for fi in order:
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    f = node.func
                    n = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else None)
                    if n:
                        names.add(n)
        assert names & set(registry.SYNC_CALL_NAMES), \
            f"fence site {key} never syncs — stale allowlist entry"


def test_deleting_a_fence_site_produces_findings():
    """Acceptance criterion: remove the engine's declared fence from
    the allowlist and the statically-verified invariant breaks."""
    reg = types.SimpleNamespace(
        **{k: getattr(registry, k) for k in dir(registry)
           if k.isupper()})
    reg.FENCE_SITES = tuple(
        f for f in registry.FENCE_SITES if "_sync_fence" not in f)
    res = analysis.run_analysis([PKG], repo_root=REPO, registry=reg,
                                rules=["HOTSYNC"])
    assert any(f.rule == "HOTSYNC" for f in res.findings), \
        "deleting the _sync_fence allowlist entry produced no finding"


def test_injected_device_get_in_hot_function_is_caught(tmp_path):
    """Acceptance criterion: inject a device_get into a hot function
    in a fixture copy -> finding."""
    root = tmp_path / "fx"
    shutil.copytree(os.path.join(FIXTURES, "tn"), root)
    hot = root / "pkg" / "hot.py"
    src = hot.read_text()
    hot.write_text(src.replace(
        "def helper(x):\n    return x * 2                  "
        "# no sync: clean",
        "def helper(x):\n    return jax.device_get(x)"))
    res = run_fixture(None, rules=["HOTSYNC"], root=str(root))
    assert any("device_get" in f.message for f in res.findings)


# ----------------------------------------------------------------------
# misc analyzer behavior
# ----------------------------------------------------------------------
def test_rule_catalog_is_complete():
    assert set(ALL_RULES) == set(RULES)
    for mod in ALL_RULES.values():
        assert mod.SUMMARY and mod.EXPLAIN


def test_identical_lines_get_distinct_fingerprints(tmp_path):
    """Regression (review finding): two identical violations in one
    function must NOT collapse to one baseline entry — baselining the
    first must not auto-baseline a later-added second one."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    body = ("def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n")
    (pkg / "m.py").write_text(body)
    res = analysis.run_analysis([str(pkg)], repo_root=str(tmp_path),
                                registry=fixture_registry(),
                                rules=["BROADEXC"])
    entries = baseline_mod.build_entries(res.findings, res.index,
                                         str(tmp_path))
    assert len(entries) == 1
    # add an IDENTICAL second violation in the same function
    (pkg / "m.py").write_text(body + ("    try:\n"
                                      "        g()\n"
                                      "    except Exception:\n"
                                      "        pass\n"))
    res2 = analysis.run_analysis([str(pkg)], repo_root=str(tmp_path),
                                 registry=fixture_registry(),
                                 rules=["BROADEXC"])
    assert len(res2.findings) == 2
    new, baselined, expired = baseline_mod.apply(
        res2.findings, entries, res2.index, str(tmp_path))
    assert len(baselined) == 1 and len(new) == 1, \
        "second identical violation was silently auto-baselined"


def test_scoped_run_does_not_expire_or_truncate_baseline(tmp_path,
                                                         capsys):
    """Regression (review finding): linting a sub-path must apply the
    baseline against the whole-package findings — out-of-scope
    entries are neither reported expired nor dropped by a scoped
    --update-baseline."""
    root = tmp_path / "fx"
    shutil.copytree(os.path.join(FIXTURES, "tp"), root)
    pkg = str(root / "pkg")
    assert ds_lint_main([pkg, "--update-baseline"]) == 0
    capsys.readouterr()
    bl = os.path.join(str(root), baseline_mod.DEFAULT_BASENAME)
    full = baseline_mod.load(bl)
    assert len(full) > 1
    # scoped run: exc.py findings are out of scope but must stay
    # baselined, not "expired"
    assert ds_lint_main([os.path.join(pkg, "locks.py")]) == 0
    out = capsys.readouterr().out
    assert "expired" not in out
    # scoped --update-baseline must not truncate the shared file
    assert ds_lint_main([os.path.join(pkg, "locks.py"),
                         "--update-baseline"]) == 0
    capsys.readouterr()
    assert len(baseline_mod.load(bl)) == len(full)


def test_cli_subpath_widens_to_package(capsys):
    """Linting a subdirectory or single file analyzes the whole
    owning package (the rules are package-level contracts) and
    filters findings to the requested scope — no bogus
    registry-resolution findings."""
    assert ds_lint_main([os.path.join(PKG, "monitor")]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out
    assert ds_lint_main(
        [os.path.join(PKG, "runtime", "config.py")]) == 0


def test_broadexc_exc_info_false_does_not_count(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "m.py").write_text(
        "import logging\n"
        "logger = logging.getLogger(__name__)\n\n\n"
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        logger.warning(f'failed: {e}', exc_info=False)\n")
    res = analysis.run_analysis([str(pkg)], repo_root=str(tmp_path),
                                registry=fixture_registry(),
                                rules=["BROADEXC"])
    assert len(res.findings) == 1


def test_pld_params_keep_constructor_defaults():
    """Regression (review finding): enabling PLD without theta must
    keep the ProgressiveLayerDrop constructor default (0.5), not
    substitute PLD_THETA_DEFAULT (1.0 — which makes PLD a no-op)."""
    from deepspeed_tpu.runtime.config import get_pld_params
    assert get_pld_params(
        {"progressive_layer_drop": {"enabled": True}}) == {}
    assert get_pld_params(
        {"progressive_layer_drop":
         {"enabled": True, "theta": 0.9}}) == {"theta": 0.9}


def test_parse_error_reported_not_crash(tmp_path):
    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "__init__.py").write_text("")
    (bad / "broken.py").write_text("def oops(:\n")
    res = analysis.run_analysis([str(bad)], repo_root=str(tmp_path),
                                registry=fixture_registry())
    assert len(res.errors) == 1
    assert "broken.py" in res.errors[0][0]
