"""Test model fixtures (analogue of ref tests/unit/simple_model.py)."""

import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel:
    """Linear + MSE regression; engine-protocol object with loss_fn."""

    def __init__(self, hidden_dim=16, seed=0):
        self.hidden_dim = hidden_dim
        rng = np.random.RandomState(seed)
        self.params = {
            "w": jnp.asarray(rng.randn(hidden_dim, hidden_dim) * 0.1,
                             jnp.float32),
            "b": jnp.zeros((hidden_dim,), jnp.float32),
        }

    def loss_fn(self, params, batch, rngs=None, deterministic=False):
        x, y = batch["x"], batch["y"]
        pred = x.astype(jnp.float32) @ params["w"] + params["b"]
        return jnp.mean((pred - y.astype(jnp.float32)) ** 2)


def random_dataset(total_samples, hidden_dim, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(total_samples, hidden_dim).astype(np.float32)
    w_true = rng.randn(hidden_dim, hidden_dim).astype(np.float32)
    y = x @ w_true
    return [{"x": x[i], "y": y[i]} for i in range(total_samples)]


def random_token_batch(batch_size, seq_len, vocab, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(batch_size, seq_len)).astype(np.int32)
    return {"input_ids": ids}
