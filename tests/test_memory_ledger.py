"""Memory observability (ISSUE 8): ledger, reconciliation, forensics.

Covers:
  * MemoryLedger unit behavior — register/replace/release, dynamic
    entries, per-device byte math on SHARDED arrays (shard_shape
    metadata, no sync), totals/top-buffers, reconcile + the peak
    watermark keeping the attribution snapshot taken AT peak;
  * fence alignment — the memory ledger ON (its default) adds ZERO
    per-step device_get/effects_barrier calls and the fenced window
    still pays exactly ONE device_get per fence (the PR 2/5 guard,
    extended);
  * the `memory` event schema round-tripping through BOTH sinks
    (JSONL parse + native tfevents scalars);
  * Perfetto per-category counter tracks through the Chrome-trace
    schema validator, plus `ds_trace summary`'s memory section;
  * engine registration across modes — bf16 mixed precision, gas>1
    accumulators, ZeRO-Offload host masters/moments + wire
    residual/shadow, checkpoint snapshot double-buffers alive only
    between snapshot and commit;
  * plan-vs-measured — ZeroShardingPolicy.memory_plan vs the live
    ledger vs REAL per-device shard bytes within a pinned tolerance;
  * OOM forensics — classification units and a subprocess run with an
    injected allocator failure whose flight dump names the top ledger
    categories and actionable hints;
  * the see_memory_usage consolidation + host-RSS fallback satellites.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from simple_model import SimpleModel
from deepspeed_tpu.monitor import Monitor, memory as mem
from deepspeed_tpu.monitor.flight import list_flight_dumps
from deepspeed_tpu.monitor.memory import (MemoryLedger, classify_oom,
                                          host_rss_bytes, leaf_nbytes,
                                          oom_hints, plan_vs_measured,
                                          tree_nbytes)
from deepspeed_tpu.monitor.tfevents import read_tfevents
from deepspeed_tpu.monitor.trace_export import summarize_trace
from test_trace_export import validate_chrome_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# helpers (the test_monitor.py engine shape)
# ----------------------------------------------------------------------
def _make_stacked(seed, bs=16, dim=8):
    rng = np.random.RandomState(seed)
    x = rng.randn(bs, dim).astype(np.float32)
    return {"x": x[None], "y": (x * 0.5)[None]}


def _engine(config_over=None, monitor=None):
    model = SimpleModel(hidden_dim=8)
    cfg = {
        "train_batch_size": 16,
        "steps_per_print": 10000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(config_over or {})
    if monitor is not None:
        cfg["monitor"] = monitor
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    return engine


# ----------------------------------------------------------------------
# byte arithmetic
# ----------------------------------------------------------------------
def test_leaf_nbytes_shapes_and_dtypes():
    assert leaf_nbytes(np.zeros((4, 8), np.float32)) == 4 * 8 * 4
    assert leaf_nbytes(
        jax.ShapeDtypeStruct((16,), jnp.bfloat16)) == 32
    assert leaf_nbytes(object()) == 0
    tree = {"a": np.zeros((2, 2), np.float32),
            "b": [jnp.zeros((3,), jnp.int32)]}
    assert tree_nbytes(tree) == 16 + 12


def test_leaf_nbytes_sharded_is_per_device():
    """A data-sharded array counts ONE device's shard; a replicated
    array counts full size — exactly its per-chip cost."""
    from jax.sharding import NamedSharding, PartitionSpec
    from deepspeed_tpu.runtime.mesh import build_mesh
    n_dev = len(jax.devices())
    if n_dev < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = build_mesh({"pipe": 1, "data": n_dev, "model": 1})
    x = jax.device_put(
        np.zeros((n_dev * 4, 8), np.float32),
        NamedSharding(mesh, PartitionSpec("data", None)))
    assert leaf_nbytes(x) == 4 * 8 * 4                 # 1/n_dev shard
    assert leaf_nbytes(x, per_device=False) == n_dev * 4 * 8 * 4
    r = jax.device_put(np.zeros((8,), np.float32),
                       NamedSharding(mesh, PartitionSpec()))
    assert leaf_nbytes(r) == 32                        # replicated


def test_host_rss_bytes_reads_statm():
    rss = host_rss_bytes()
    assert rss is not None and rss > 1 << 20           # >1 MiB resident


# ----------------------------------------------------------------------
# ledger unit behavior
# ----------------------------------------------------------------------
def test_ledger_register_release_totals_top():
    led = MemoryLedger()
    t1 = led.register(mem.CAT_PARAMS, "p", 100)
    led.register(mem.CAT_OPT, "o", 300)
    led.register(mem.CAT_HOST_MASTER, "hm", 50, space=mem.SPACE_HOST)
    totals = led.totals()
    assert totals[mem.SPACE_HBM] == {"params": 100, "opt_state": 300}
    assert totals[mem.SPACE_HOST] == {"host_master": 50}
    top = led.top_buffers(2)
    assert [b["name"] for b in top] == ["o", "p"]
    # same (category, name) replaces, release drops, unknown is a no-op
    led.register(mem.CAT_PARAMS, "p", 700)
    assert led.totals()[mem.SPACE_HBM]["params"] == 700
    led.release(t1)
    assert "params" not in led.totals()[mem.SPACE_HBM]
    led.release(("nope", "nothing"))
    led.release(None)


def test_ledger_dynamic_entry_sampled_and_fault_isolated():
    led = MemoryLedger()
    vals = {"n": 5}
    led.register_dynamic(mem.CAT_PREFETCH, "q", lambda: vals["n"] * 10)
    assert led.totals()[mem.SPACE_HBM]["prefetch"] == 50
    vals["n"] = 2
    assert led.totals()[mem.SPACE_HBM]["prefetch"] == 20
    led.register_dynamic(mem.CAT_PREFETCH, "boom", lambda: 1 / 0)
    assert led.totals()[mem.SPACE_HBM]["prefetch"] == 20


def test_ledger_reconcile_residual_and_peak_attribution():
    """The peak watermark keeps the attribution snapshot taken AT the
    fence that observed the peak — not the current composition."""
    led = MemoryLedger()
    led.register(mem.CAT_PARAMS, "p", 400)
    tok = led.register(mem.CAT_CKPT, "snap", 600)
    # 2 devices, 1500 in use EACH: the ledger is per-device, so the
    # residual compares against in_use / device_count, not the sum
    pay = led.reconcile({"in_use_bytes": 3000, "peak_bytes": 2000,
                         "device_count": 2}, rss=None, step=10)
    assert pay["hbm"]["ledger_bytes"] == 1000
    assert pay["hbm"]["measured_in_use"] == 3000
    assert pay["hbm"]["measured_in_use_per_device"] == 1500
    assert pay["hbm"]["residual_bytes"] == 500
    assert pay["peak"]["bytes"] == 2000
    assert pay["peak"]["categories"] == {"params": 400,
                                         "ckpt_snapshot": 600}
    # snapshot released, allocator lower: the PEAK attribution persists
    led.release(tok)
    pay = led.reconcile({"in_use_bytes": 400, "peak_bytes": 2000,
                         "device_count": 2}, rss=None, step=20)
    assert pay["hbm"]["categories"] == {"params": 400}
    assert pay["peak"]["step"] == 10
    assert pay["peak"]["categories"]["ckpt_snapshot"] == 600
    # a HIGHER peak re-attributes
    pay = led.reconcile({"in_use_bytes": 3000, "peak_bytes": 3000,
                         "device_count": 2}, rss=None, step=30)
    assert pay["peak"]["step"] == 30
    assert "ckpt_snapshot" not in pay["peak"]["categories"]


def test_ledger_reconcile_host_fallback_off_device():
    """device_count == 0 (backend exposes no memory_stats): the
    reconciliation falls back to host RSS — the gauge stays meaningful
    off-TPU."""
    led = MemoryLedger()
    led.register(mem.CAT_HOST_MASTER, "m", 1 << 20,
                 space=mem.SPACE_HOST)
    pay = led.reconcile({"in_use_bytes": 0, "peak_bytes": 0,
                         "device_count": 0,
                         "host_rss_bytes": 8 << 20}, step=1)
    assert pay["hbm"]["measured_in_use"] is None
    assert pay["host"]["rss_bytes"] == 8 << 20
    assert pay["host"]["residual_bytes"] == 7 << 20
    assert pay["peak"]["space"] == mem.SPACE_HOST
    assert pay["peak"]["bytes"] == 8 << 20


def test_plan_vs_measured_deltas():
    out = plan_vs_measured({"params": 1000, "master": 0},
                           {"params": 1100, "extra": 7})
    assert out["params"]["delta_pct"] == 10.0
    assert out["master"]["delta_pct"] is None      # planned 0
    assert out["extra"]["planned_bytes"] is None


# ----------------------------------------------------------------------
# OOM classification units
# ----------------------------------------------------------------------
def test_classify_oom_markers():
    assert classify_oom(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 123 bytes"))
    assert classify_oom(MemoryError())
    assert classify_oom(RuntimeError("Failed to allocate 4.2GiB"))
    assert classify_oom(RuntimeError("hbm OOM at step 4"))
    assert not classify_oom(ValueError("shape mismatch"))
    assert not classify_oom(RuntimeError("INVALID_ARGUMENT: nope"))
    # "OOM" only as a word: ordinary messages must not trigger
    # memory forensics
    assert not classify_oom(RuntimeError("no room left in ring"))
    assert not classify_oom(RuntimeError("zoom factor wrong"))


def test_oom_hints_name_the_dominant_knob():
    gib = 1 << 30
    pay = {"hbm": {"categories": {"params": gib,
                                  "ckpt_snapshot": 2 * gib},
                   "ledger_bytes": 3 * gib,
                   "measured_in_use": 16 * gib,
                   "measured_in_use_per_device": 16 * gib,
                   "residual_bytes": 13 * gib},
           "host": {"categories": {}}}
    hints = oom_hints(pay)
    text = " ".join(hints)
    assert "save_fused_epilogues" in text          # residual dominates
    assert "writer_queue_depth" in text            # snapshot alive
    # a payload with nothing dominant still says something actionable
    assert oom_hints({"hbm": {"categories": {}}, "host": {}})


# ----------------------------------------------------------------------
# fence alignment guards (memory ledger ON is the default)
# ----------------------------------------------------------------------
class _SyncCounters:
    def __init__(self, monkeypatch):
        self.device_get = 0
        self.effects_barrier = 0
        real_get, real_barrier = jax.device_get, jax.effects_barrier

        def counting_get(x):
            self.device_get += 1
            return real_get(x)

        def counting_barrier():
            self.effects_barrier += 1
            return real_barrier()

        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "effects_barrier", counting_barrier)


def test_memory_ledger_keeps_hot_path_sync_free(tmp_path, monkeypatch):
    """Reconciliation is fence-aligned host arithmetic: with the
    ledger ON (default), N steps between fences perform ZERO
    device_get/effects_barrier calls and a fenced window still costs
    exactly ONE device_get per fence."""
    engine = _engine(
        {"bf16": {"enabled": True},
         "async_dispatch": {"enabled": True, "steps_per_sync": 4}},
        monitor={"enabled": True, "sinks": ["jsonl"],
                 "output_path": str(tmp_path)})
    assert engine.monitor.memory_enabled
    batches = [engine.stage_batch(_make_stacked(i)) for i in range(16)]
    for b in batches[:8]:
        engine.train_batch(batch=b)
    assert engine._host_steps == 8    # next fences at 12 and 16
    counters = _SyncCounters(monkeypatch)
    for b in batches[8:]:
        engine.train_batch(batch=b)
    assert counters.device_get == 2, \
        f"expected 1 device_get per fence (2 fences), got " \
        f"{counters.device_get}"
    assert counters.effects_barrier == 0
    log = os.path.join(str(tmp_path), "events.jsonl")
    kinds = [json.loads(l)["kind"] for l in open(log)]
    assert kinds.count("memory") >= 2
    engine.monitor.close()


# ----------------------------------------------------------------------
# event schema through both sinks
# ----------------------------------------------------------------------
def test_memory_event_schema_jsonl_and_tfevents(tmp_path):
    import glob
    engine = _engine(
        {"bf16": {"enabled": True},
         "async_dispatch": {"enabled": True, "steps_per_sync": 2}},
        monitor={"enabled": True, "sinks": ["jsonl", "tensorboard"],
                 "output_path": str(tmp_path)})
    for i in range(4):
        engine.train_batch(batch=_make_stacked(i))
    engine.monitor.close()

    events = [json.loads(l) for l in
              open(os.path.join(str(tmp_path), "events.jsonl"))]
    mems = [e for e in events if e["kind"] == "memory"]
    assert mems
    for e in mems:
        assert e["v"] == 1 and isinstance(e["step"], int)
        for space in ("hbm", "host"):
            blk = e[space]
            for key in ("categories", "ledger_bytes",
                        "residual_bytes"):
                assert key in blk, (space, key, e)
        assert {"params", "master", "opt_state"} <= \
            set(e["hbm"]["categories"])
        assert e["hbm"]["ledger_bytes"] == \
            sum(e["hbm"]["categories"].values())
        assert e["host"]["rss_bytes"] > 0     # the off-TPU fallback
        assert isinstance(e["top_buffers"], list) and e["top_buffers"]
        assert e["peak"] is None or "categories" in e["peak"]

    tb = glob.glob(os.path.join(str(tmp_path), "tb",
                                "events.out.tfevents.*"))
    assert tb
    tags = set()
    for ev in read_tfevents(tb[0]):
        tags |= set(ev.get("scalars", {}))
    assert "monitor/memory/hbm/ledger_bytes" in tags
    assert "monitor/memory/hbm/categories/params" in tags
    assert "monitor/memory/host/rss_bytes" in tags


def test_snapshot_carries_memory_ledger(tmp_path):
    engine = _engine({"bf16": {"enabled": True}},
                     monitor={"enabled": True, "sinks": [],
                              "output_path": str(tmp_path)})
    engine.train_batch(batch=_make_stacked(0))
    snap = engine.monitor.snapshot()
    assert set(snap) == set(Monitor.SNAPSHOT_KEYS)
    led = snap["memory_ledger"]
    assert led["hbm"]["categories"]["params"] > 0
    # memory off -> stable key, None value
    engine2 = _engine({"bf16": {"enabled": True}},
                      monitor={"enabled": True, "sinks": [],
                               "output_path": str(tmp_path),
                               "memory": {"enabled": False}})
    engine2.train_batch(batch=_make_stacked(0))
    snap2 = engine2.monitor.snapshot()
    assert set(snap2) == set(Monitor.SNAPSHOT_KEYS)
    assert snap2["memory_ledger"] is None
    engine.monitor.close()
    engine2.monitor.close()


# ----------------------------------------------------------------------
# Perfetto counter tracks + ds_trace summary
# ----------------------------------------------------------------------
def test_memory_counter_tracks_validate_and_summarize(tmp_path,
                                                      capsys):
    engine = _engine(
        {"bf16": {"enabled": True},
         "async_dispatch": {"enabled": True, "steps_per_sync": 2}},
        monitor={"enabled": True, "sinks": ["jsonl"],
                 "output_path": str(tmp_path),
                 "trace": {"enabled": True}})
    plan = {"params": 100, "master": 200, "opt_state": 400}
    engine.monitor.set_memory_plan(plan)
    for i in range(4):
        engine.train_batch(batch=_make_stacked(i))
    path = engine.monitor.export_trace()
    engine.monitor.close()

    doc = json.load(open(path))
    validate_chrome_trace(doc)
    counters = [e for e in doc["traceEvents"]
                if e.get("ph") == "C" and
                e["name"] in ("hbm_bytes", "host_bytes")]
    assert counters
    hbm = [e for e in counters if e["name"] == "hbm_bytes"]
    assert hbm and {"params", "master", "opt_state"} <= \
        set(hbm[0]["args"])
    assert doc["otherData"]["memory_plan"] == plan

    s = summarize_trace(doc)
    assert "memory" in s
    assert s["memory"]["hbm_bytes"]["params"]["peak_bytes"] > 0
    pvm = s["memory"]["plan_vs_measured"]
    assert pvm["params"]["measured_bytes"] > 0
    assert pvm["params"]["delta_pct"] is not None

    # the plan survives a multi-rank merge (promoted like `pipeline`)
    from deepspeed_tpu.monitor.trace_export import merge_traces
    merged = summarize_trace(merge_traces([doc]))
    assert "plan_vs_measured" in merged["memory"]

    # the CLI prints the memory section
    from deepspeed_tpu.monitor.trace_cli import main as trace_main
    assert trace_main(["summary", path]) == 0
    out = capsys.readouterr().out
    assert "memory (hbm):" in out
    assert "plan vs measured" in out


def test_memory_counter_emits_zero_for_released_category(tmp_path):
    """Chrome counter semantics keep the last value per key: a
    released buffer must emit one explicit 0, or the stacked area (and
    summarize_trace's 'last') stays at its old height forever."""
    engine = _engine(
        {"bf16": {"enabled": True},
         "async_dispatch": {"enabled": True, "steps_per_sync": 1}},
        monitor={"enabled": True, "sinks": [],
                 "output_path": str(tmp_path),
                 "trace": {"enabled": True}})
    tok = engine.monitor.ledger.register(mem.CAT_CKPT, "snap", 1234)
    engine.train_batch(batch=_make_stacked(0))
    engine.monitor.ledger.release(tok)
    engine.train_batch(batch=_make_stacked(1))
    doc = engine.monitor.trace_export.to_dict()
    hbm = [e for e in doc["traceEvents"]
           if e.get("ph") == "C" and e["name"] == "hbm_bytes"]
    assert hbm[0]["args"]["ckpt_snapshot"] == 1234
    assert hbm[1]["args"]["ckpt_snapshot"] == 0
    s = summarize_trace(doc)
    assert s["memory"]["hbm_bytes"]["ckpt_snapshot"]["last_bytes"] == 0
    assert s["memory"]["hbm_bytes"]["ckpt_snapshot"]["peak_bytes"] == \
        1234
    engine.monitor.close()


def test_summarize_memory_counters_keep_ranks_apart():
    """Counters from different ranks merge by per-key MAX (per-device
    semantics), not by interleaved last-wins."""
    from deepspeed_tpu.monitor.trace_export import (TraceExporter,
                                                    merge_traces)
    ex0 = TraceExporter(rank=0)
    ex1 = TraceExporter(rank=1)
    ex0.counter("memory", "hbm_bytes", {"params": 100})
    ex1.counter("memory", "hbm_bytes", {"params": 700})
    ex0.counter("memory", "hbm_bytes", {"params": 50})
    s = summarize_trace(merge_traces([ex0.to_dict(), ex1.to_dict()]))
    row = s["memory"]["hbm_bytes"]["params"]
    # rank 0's last is 50, rank 1's 700: the merge reports the binding
    # per-device number, never rank 0's tail overwriting rank 1's
    assert row["last_bytes"] == 700
    assert row["peak_bytes"] == 700
    assert s["memory"]["ranks"] == 2


# ----------------------------------------------------------------------
# engine registration across modes
# ----------------------------------------------------------------------
def test_engine_registers_state_groups_bf16(tmp_path):
    engine = _engine({"bf16": {"enabled": True}},
                     monitor={"enabled": True, "sinks": [],
                              "output_path": str(tmp_path)})
    cats = engine.monitor.ledger.totals()[mem.SPACE_HBM]
    assert cats["params"] > 0
    assert cats["master"] > 0          # mixed precision: fp32 masters
    assert cats["opt_state"] > cats["master"]   # 2 moments + master-ish
    assert "grads" not in cats         # gas=1: no persistent accumulator
    engine.monitor.close()


def test_engine_registers_grad_accumulator_gas2(tmp_path):
    engine = _engine(
        {"bf16": {"enabled": True},
         "train_batch_size": 32,
         "gradient_accumulation_steps": 2},
        monitor={"enabled": True, "sinks": [],
                 "output_path": str(tmp_path)})
    cats = engine.monitor.ledger.totals()[mem.SPACE_HBM]
    assert cats["grads"] > 0
    engine.monitor.close()


def test_offload_registers_host_state_and_wire(tmp_path):
    engine = _engine(
        {"bf16": {"enabled": True},
         "zero_optimization": {"stage": 2, "cpu_offload": True,
                               "offload_wire": {"grad_bits": 1,
                                                "param_bits": 8}}},
        monitor={"enabled": True, "sinks": [],
                 "output_path": str(tmp_path)})
    totals = engine.monitor.ledger.totals()
    host = totals[mem.SPACE_HOST]
    hbm = totals[mem.SPACE_HBM]
    n = engine._host_master.size
    assert host["host_master"] == n * 4
    assert host["host_opt_state"] == 2 * n * 4
    # 1-bit residual (device) + int8 shadow (host) + device flat copy
    assert hbm["wire"] >= engine._offload_grad_residual.nbytes
    assert host["wire"] == engine._offload_param_shadow.nbytes
    names = {b["name"] for b in engine.monitor.ledger.top_buffers(20)}
    assert {"offload.host_master", "offload.adam_moments",
            "offload.grad_residual", "offload.param_shadow",
            "offload.device_flat"} <= names
    engine.monitor.close()


def test_ckpt_snapshot_registered_then_released(tmp_path):
    engine = _engine({"bf16": {"enabled": True}},
                     monitor={"enabled": True, "sinks": [],
                              "output_path": str(tmp_path)})
    engine.train_batch(batch=_make_stacked(0))
    led = engine.monitor.ledger
    assert "ckpt_snapshot" not in led.totals()[mem.SPACE_HBM]
    # a paused writer holds the snapshot alive; the category must be
    # visible exactly while the double-buffers exist
    import threading
    gate = threading.Event()
    orig = engine._write_checkpoint

    def slow_write(*a, **kw):
        gate.wait(timeout=30)
        return orig(*a, **kw)

    engine._write_checkpoint = slow_write
    assert engine.save_checkpoint(str(tmp_path / "ckpt"),
                                  async_save=True)
    cats = led.totals()[mem.SPACE_HBM]
    assert cats.get("ckpt_snapshot", 0) > 0
    gate.set()
    engine.wait_for_checkpoint()
    assert "ckpt_snapshot" not in led.totals()[mem.SPACE_HBM]
    engine.monitor.close()


def test_prefetch_buffer_bytes_dynamic_entry(tmp_path):
    engine = _engine(
        {"bf16": {"enabled": True}},
        monitor={"enabled": True, "sinks": [],
                 "output_path": str(tmp_path)})
    micro = [{k: v[0] for k, v in _make_stacked(i).items()}
             for i in range(6)]
    loader = engine.prefetch(iter(micro))
    engine.train_batch(data_iter=loader)
    # the worker runs ahead: wait until something is queued + sized
    import time
    deadline = time.time() + 5.0
    while time.time() < deadline and \
            (not loader.staged_nbytes or not loader.occupancy()):
        time.sleep(0.02)
    assert loader.staged_nbytes > 0
    cats = engine.monitor.ledger.totals()[mem.SPACE_HBM]
    assert cats.get("prefetch", 0) == \
        loader.occupancy() * loader.staged_nbytes
    loader.close()
    engine.monitor.close()


def test_pipe_1f1b_registers_buffer_bytes():
    """The compiled 1F1B executor's per-stage carry (saved-input
    recompute buffers + delivery rings) registers under pipe_buffers
    once the interpreter compiles — the schedule's activation bound,
    attributed."""
    import flax.linen as nn
    from deepspeed_tpu.runtime.pipe.module import (LayerSpec,
                                                   PipelineModule)
    if len(jax.devices()) < 4:
        pytest.skip("needs the multi-device mesh")

    def mse(pred, labels):
        return jnp.mean((pred.astype(jnp.float32) -
                         labels.astype(jnp.float32)) ** 2)

    module = PipelineModule(
        [LayerSpec(nn.Dense, 16), jnp.tanh, LayerSpec(nn.Dense, 8)],
        num_stages=2, loss_fn=mse, partition_method="uniform")
    rng = np.random.RandomState(0)
    params = module.init_params(
        jax.random.PRNGKey(0), jnp.asarray(rng.randn(4, 16),
                                           jnp.float32))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 2,
                "steps_per_print": 1000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "mesh": {"pipe": 2, "data": 4, "model": 1},
                "monitor": {"enabled": True, "sinks": []}})
    assert "pipe_buffers" not in \
        engine.monitor.ledger.totals()[mem.SPACE_HBM]
    x = rng.randn(16, 16).astype(np.float32)
    w = np.linspace(-1, 1, 16 * 8).reshape(16, 8).astype(np.float32)
    engine.train_batch(batch={"x": x, "y": x @ w})
    cats = engine.monitor.ledger.totals()[mem.SPACE_HBM]
    bm = engine._interp_fn.buffer_meta
    assert cats["pipe_buffers"] == bm["bytes_per_stage"] > 0
    # the bound in the meta is the schedule's, not an ad-hoc number
    from deepspeed_tpu.runtime.pipe.interp import num_pipe_buffers
    assert bm["saved_input_buffers"] == num_pipe_buffers(2, 2)
    engine.monitor.close()


# ----------------------------------------------------------------------
# plan vs measured on the live mesh (the 3B-analogue executed check)
# ----------------------------------------------------------------------
def test_memory_plan_agrees_with_ledger_and_measured():
    """ZeroShardingPolicy.memory_plan vs the ledger vs REAL per-device
    shard bytes, through the exact 13B code path (bf16 master-less
    ZeRO-3) at CI scale — pinned to 15% (count scalars and replicated
    tiny leaves are the only slack)."""
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, gpt2_config
    from deepspeed_tpu.runtime.mesh import build_mesh
    n_dev = len(jax.devices())
    if n_dev < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = build_mesh({"pipe": 1, "data": n_dev, "model": 1})
    cfg = gpt2_config("gpt2-125m", dropout=0.0, dtype=jnp.bfloat16,
                      param_dtype=jnp.bfloat16, vocab_size=512,
                      n_positions=64, n_layer=2)
    model = GPT2ForCausalLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0),
        {"input_ids": np.zeros((n_dev, 64), np.int32)})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        config={
            "train_micro_batch_size_per_gpu": n_dev,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 1000,
            "bf16": {"enabled": True, "master_weights": False},
            "zero_optimization": {"stage": 3},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "monitor": {"enabled": True, "sinks": []},
        })
    del params
    shapes = jax.eval_shape(lambda t: t, engine.state.params)
    plan = engine.zero_policy.memory_plan(shapes, compute_bytes=2,
                                          sr_mode=True, gas=1)
    cats = engine.monitor.ledger.totals()[mem.SPACE_HBM]

    dev0 = jax.devices()[0]

    def dev_bytes(tree):
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, jax.Array):
                for sh in leaf.addressable_shards:
                    if sh.device == dev0:
                        total += sh.data.nbytes
        return total

    measured = {"params": dev_bytes(engine.state.params),
                "opt_state": dev_bytes(engine.state.opt_state)}
    for scored in (plan_vs_measured(plan, cats),
                   plan_vs_measured(plan, measured)):
        for comp in ("params", "opt_state"):
            assert scored[comp]["delta_pct"] is not None, scored
            assert abs(scored[comp]["delta_pct"]) < 15.0, \
                (comp, scored)
    engine.monitor.close()


# ----------------------------------------------------------------------
# subprocess OOM-classification flight dump
# ----------------------------------------------------------------------
def test_subprocess_oom_crash_dumps_attributed_flight(tmp_path):
    """An injected allocator failure (RESOURCE_EXHAUSTED out of the
    jitted step — the XlaRuntimeError text) must leave a flight dump
    classified as reason "oom" carrying the ledger categories, the top
    buffers, and actionable hints."""
    out = str(tmp_path / "mon")
    script = f"""
import os, sys, json
import numpy as np
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, {REPO!r})
sys.path.insert(0, os.path.join({REPO!r}, 'tests'))
import deepspeed_tpu
from simple_model import SimpleModel

def mk(seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(16, 8).astype(np.float32)
    return {{"x": x[None], "y": (x * 0.5)[None]}}

model = SimpleModel(hidden_dim=8)
cfg = {{"train_batch_size": 16, "steps_per_print": 10000,
       "bf16": {{"enabled": True}},
       "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
       "async_dispatch": {{"enabled": True, "steps_per_sync": 1}},
       "monitor": {{"enabled": True, "sinks": ["jsonl"],
                   "output_path": {out!r}}}}}
e, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=model.params, config=cfg)
for i in range(3):
    e.train_batch(batch=mk(i))

# injected allocator failure: the step fn raises what jaxlib's
# XlaRuntimeError carries on a real HBM exhaustion
real_step = e._fused_step_jit
def oom_step(*a, **kw):
    raise RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "17179869184 bytes.")
e._fused_step_jit = oom_step
e.train_batch(batch=mk(9))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode != 0
    assert "RESOURCE_EXHAUSTED" in proc.stderr
    dumps = list_flight_dumps(out)
    assert dumps, "OOM subprocess left no flight dump"
    docs = [json.load(open(p)) for p in dumps]
    ooms = [d for d in docs if d["reason"] == "oom"]
    assert ooms, [d["reason"] for d in docs]
    doc = ooms[-1]
    oom = doc["extra"]["oom"]
    # the ledger categories survive into the dump with real bytes
    assert oom["hbm"]["categories"]["params"] > 0
    assert oom["hbm"]["categories"]["opt_state"] > 0
    top_cats = {b["category"] for b in oom["top_buffers"]}
    assert {"params", "master", "opt_state"} <= top_cats
    assert oom["hints"] and all(isinstance(h, str)
                                for h in oom["hints"])
    # the sticky peak context rode along too (set at every fence)
    assert "memory_peak" in doc["context"]
    assert doc["extra"]["error"].startswith("RuntimeError")


# ----------------------------------------------------------------------
# satellites: see_memory_usage consolidation + RSS fallback
# ----------------------------------------------------------------------
class _CollectLog:
    """Capture DeepSpeedTPU log lines (the logger does not propagate,
    so caplog misses it — the test_monitor _Collect pattern)."""

    def __enter__(self):
        import logging
        from deepspeed_tpu.utils.logging import logger

        class H(logging.Handler):
            def __init__(self):
                super().__init__()
                self.lines = []

            def emit(self, record):
                self.lines.append(record.getMessage())

        self._logger = logger
        self._h = H()
        logger.addHandler(self._h)
        return self._h.lines

    def __exit__(self, *exc):
        self._logger.removeHandler(self._h)
        return False


def test_see_memory_usage_aggregates_all_devices(monkeypatch):
    """see_memory_usage now rides device_memory_stats: SUM of in-use
    over all local devices (it used to read only device 0)."""

    class FakeDev:
        def __init__(self, in_use, peak):
            self._s = {"bytes_in_use": in_use,
                       "peak_bytes_in_use": peak}

        def memory_stats(self):
            return self._s

    gib = 1024 ** 3
    monkeypatch.setattr(jax, "local_devices",
                        lambda: [FakeDev(1 * gib, 2 * gib),
                                 FakeDev(3 * gib, 5 * gib)])
    from deepspeed_tpu.runtime.utils import see_memory_usage
    with _CollectLog() as lines:
        see_memory_usage("probe", force=True)
    text = " ".join(lines)
    assert "4.00 GB" in text and "5.00 GB" in text
    assert "2 local devices" in text


def test_see_memory_usage_host_rss_fallback(monkeypatch):
    class NoStatsDev:
        def memory_stats(self):
            return None

    monkeypatch.setattr(jax, "local_devices", lambda: [NoStatsDev()])
    from deepspeed_tpu.runtime.utils import see_memory_usage
    with _CollectLog() as lines:
        see_memory_usage("probe", force=True)
    assert any("host RSS" in l for l in lines)


def test_device_memory_stats_carries_host_rss():
    from deepspeed_tpu.utils.timer import device_memory_stats
    stats = device_memory_stats()
    assert stats.get("host_rss_bytes", 0) > 1 << 20
