"""Standalone CPU-Adam perf guard (counterpart of the reference's
`tests/perf/adam_test1.py`, which times `deepspeed.ops.adam.DeepSpeedCPUAdam`
on a bare parameter blob).

The ZeRO-Offload path lives or dies by the native OpenMP/AVX CPU-Adam
kernel: the host optimizer step sits on the critical path between D2H
grads and H2D params, and a silent regression to the numpy reference
implementation (broken native build, wheel without the extension,
ctypes loader change) would tank offload throughput without failing a
single numerics test. This guard times native vs numpy at the
reference's sizes and asserts the native kernel keeps a >= 5x lead
(measured 100-165x on the CI container; the reference observed ~11x on
its hardware — 5x leaves headroom for a loaded host while still
catching "accidentally running numpy").

Skips (not passes) when the native build is unavailable, so the
report distinguishes "no native kernel here" from "native is slow"."""

import time

import numpy as np
import pytest

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam

MIN_SPEEDUP = 5.0


def _native_or_skip(n):
    try:
        opt = DeepSpeedCPUAdam(n, lr=1e-3, use_native=True)
    except Exception as e:  # loader/build errors
        pytest.skip(f"native cpu_adam unavailable: {e}")
    if not getattr(opt, "native", True):
        pytest.skip("native cpu_adam unavailable")
    return opt


def _best_of(fn, reps):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _assert_native_speedup(n, reps=5):
    rng = np.random.RandomState(7)
    p0 = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    nat = _native_or_skip(n)
    ref = DeepSpeedCPUAdam(n, lr=1e-3, use_native=False)
    pn, pr = p0.copy(), p0.copy()
    nat.step(pn, g)  # warmup: page-in, OpenMP thread-pool spin-up
    ref.step(pr, g)
    t_nat = _best_of(lambda: nat.step(pn, g), reps)
    t_ref = _best_of(lambda: ref.step(pr, g), reps)
    speedup = t_ref / t_nat
    assert speedup >= MIN_SPEEDUP, (
        f"native CPU-Adam at {n/1e6:.0f}M params: {t_nat*1e3:.2f} ms vs "
        f"numpy {t_ref*1e3:.2f} ms — only {speedup:.1f}x (need >= "
        f"{MIN_SPEEDUP}x); the native build has likely regressed or the "
        "offload path silently fell back to the numpy reference")


def test_native_adam_speedup_1m():
    _assert_native_speedup(1_000_000)


def test_native_adam_speedup_10m():
    _assert_native_speedup(10_000_000)


@pytest.mark.slow
def test_native_adam_speedup_100m():
    # the reference's largest leg; numpy needs ~3 s/step here, so this
    # stays in the slow tier
    _assert_native_speedup(100_000_000, reps=3)
