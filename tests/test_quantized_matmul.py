"""Quantized-compute GEMM family (ISSUE 13 tentpole): the shared
per-block-scale layout, the dequant epilogues (weight-only + full
int8xint8, XLA fallback and interpret-mode Pallas kernel), the
straight-through backward, stochastic rounding, the GPT-2 weave
behind the `quantized_compute` config block (param-tree identity +
engine loss tracking), the boundary fusion that rides along, and the
inference dedupe (serving's quant module must BE the shared
primitive)."""

import dataclasses
import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# the package re-exports the quantized_matmul FUNCTION, which shadows
# the submodule under `from ... import quantized_matmul`
qm = importlib.import_module(
    "deepspeed_tpu.ops.transformer.quantized_matmul")


def _rand(shape, dtype=jnp.float32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), dtype)


# ----------------------------------------------------------------------
# quantizers: np/jnp twins, scale layout, stochastic rounding
# ----------------------------------------------------------------------
def test_np_and_jnp_weight_quantizers_agree():
    w = np.random.default_rng(0).standard_normal((96, 40)) \
        .astype(np.float32)
    qn, sn = qm.quantize_kernel_int8_np(w, 32)
    qj, sj = qm.quantize_kernel_int8(jnp.asarray(w), 32,
                                     values_dtype=jnp.int8)
    # the jnp twin REALLY pads K to nb*block; the real rows must match
    # the numpy layout bit for bit, the pad rows must be zero
    assert np.array_equal(qn, np.asarray(qj)[:96])
    assert np.array_equal(sn, np.asarray(sj))
    assert qj.shape == (96, 40) and sn.shape == (3, 40)


def test_weight_quantizer_pads_k_and_zero_blocks_are_safe():
    w = np.zeros((50, 8), np.float32)
    w[:10, 0] = 3.0
    q, s = qm.quantize_kernel_int8(jnp.asarray(w), 32)
    assert q.shape == (64, 8)           # padded to 2 blocks
    assert np.asarray(q)[50:].max() == 0
    # all-zero blocks clamp their scale to 1 (no divide-by-zero, and
    # dequant reproduces the zeros exactly)
    deq = qm.dequantize_kernel(q, s, 32, k=50)
    assert np.allclose(np.asarray(deq), w, atol=3.0 / 127 / 2 + 1e-6)


def test_row_quantizer_layout_and_bound():
    x = _rand((5, 70))
    q, s = qm.quantize_rows_int8(x)
    assert q.shape == (5, 70) and s.shape == (5, 1)
    assert int(np.abs(np.asarray(q)).max()) <= 127
    deq = np.asarray(q).astype(np.float32) * np.asarray(s)
    step = np.asarray(s)  # one quantization step per row
    assert (np.abs(deq - np.asarray(x)) <= step / 2 + 1e-6).all()


def test_stochastic_rounding_is_unbiased_and_keyed():
    # row 0 pins the block scale at 0.3/127; the remaining rows sit at
    # 0.1 -> 42.33 quantization steps, a genuine straddle point
    w = np.full((256, 4), 0.1, np.float32)
    w[0] = 0.3
    w = jnp.asarray(w)
    q_n, s_n = qm.quantize_kernel_int8(w, 256)
    outs = []
    for seed in range(2):
        q_s, _ = qm.quantize_kernel_int8(
            w, 256, rng=jax.random.PRNGKey(seed))
        outs.append(np.asarray(q_s, np.float32))
    # different keys -> different rounding patterns, straddling the
    # true value; the mean over many draws recovers it (unbiased)
    assert not np.array_equal(outs[0], outs[1])
    scale = float(np.asarray(s_n)[0, 0])
    mean = outs[0][1:].mean() * scale
    assert abs(mean - 0.1) < 0.005
    assert set(np.unique(outs[0][1:])) <= {42.0, 43.0}


# ----------------------------------------------------------------------
# epilogues: weight-only (serving) + quantized compute (training)
# ----------------------------------------------------------------------
def test_weight_only_epilogue_tracks_dense():
    x = _rand((3, 7, 96))
    w = _rand((96, 32), seed=1)
    q, s = qm.quantize_kernel_int8_np(np.asarray(w), 32)
    y = qm.int8_matmul(x, jnp.asarray(q), jnp.asarray(s), 32,
                       jnp.float32)
    ref = np.asarray(x @ w)
    rel = np.abs(np.asarray(y) - ref).max() / np.abs(ref).max()
    assert rel < 0.05


def test_quantized_matmul_fallback_tracks_dense():
    x = _rand((16, 200))                    # K=200: padding to 2 blocks
    w = _rand((200, 48), seed=1)
    wq, sw = qm.quantize_kernel_int8(w, 128, values_dtype=jnp.float32)
    y = qm.quantized_matmul(x, wq, sw, block=128, impl="xla")
    ref = np.asarray(x @ w)
    rel = np.abs(np.asarray(y) - ref).max() / np.abs(ref).max()
    assert rel < 0.05


def test_pallas_kernel_matches_fallback_interpret():
    """The interpret-mode Pallas kernel (same kernel logic as real
    TPU) must agree with the XLA fallback to fp32 roundoff — integer
    products and block partial sums are exact in both."""
    x = _rand((40, 256))
    w = _rand((256, 192), seed=3)
    wq, sw = qm.quantize_kernel_int8(w, 128, values_dtype=jnp.int8)
    a = qm.quantized_matmul(x, wq.astype(jnp.float32), sw, block=128,
                            impl="xla")
    b = qm.quantized_matmul(x, wq, sw, block=128, impl="interpret",
                            block_m=128, block_n=128)
    assert np.abs(np.asarray(a) - np.asarray(b)).max() < 1e-4


def test_pallas_kernel_pads_m_and_n(monkeypatch):
    x = _rand((5, 128))                      # M=5 -> padded to bm
    w = _rand((128, 40), seed=2)             # N=40 -> padded to bn
    wq, sw = qm.quantize_kernel_int8(w, 128, values_dtype=jnp.int8)
    a = qm.quantized_matmul(x, wq.astype(jnp.float32), sw, block=128,
                            impl="xla")
    b = qm.quantized_matmul(x, wq, sw, block=128, impl="interpret",
                            block_m=128, block_n=128)
    assert a.shape == b.shape == (5, 40)
    assert np.abs(np.asarray(a) - np.asarray(b)).max() < 1e-4


def test_quantized_dense_ste_gradients():
    """Straight-through contract: dW is the exact full-precision
    x^T g; dx flows through the DEQUANTIZED effective weights."""
    x = _rand((6, 96))
    w = _rand((96, 32), seed=1)
    g = jnp.ones((6, 32))
    dx, dw = jax.grad(
        lambda x, w: qm.quantized_dense(x, w, block=128,
                                        impl="xla").sum(),
        argnums=(0, 1))(x, w)
    w_eff = qm.dequantize_kernel(
        *qm.quantize_kernel_int8(w, 128, values_dtype=jnp.float32),
        128, k=96)
    assert np.allclose(np.asarray(dx), np.asarray(g @ w_eff.T),
                       atol=1e-5)
    assert np.allclose(np.asarray(dw), np.asarray(x.T @ g), atol=1e-5)


def test_resolve_and_block_validation():
    assert qm.resolve_quantized_compute("off") is False
    assert qm.resolve_quantized_compute("on") is True
    assert qm.resolve_quantized_compute("auto") is False  # CPU CI
    with pytest.raises(ValueError):
        qm.resolve_quantized_compute("maybe")
    with pytest.raises(ValueError):
        qm.quantized_dense(_rand((4, 128)), _rand((128, 8)), block=0)
    with pytest.raises(ValueError):
        # Pallas path requires 128-multiple blocks (int8 lane tiling)
        qm.quantized_dense(_rand((4, 128)), _rand((128, 8)), block=64,
                           impl="interpret")
    # ...but the XLA fallback takes finer blocks
    y = qm.quantized_dense(_rand((4, 128)), _rand((128, 8)), block=64,
                           impl="xla")
    assert y.shape == (4, 8)


def test_bf16_fallback_is_bit_identical_without_sr():
    x = _rand((8, 64), jnp.bfloat16)
    w = _rand((64, 32), jnp.bfloat16, seed=1)
    y = qm.bf16_fallback_matmul(x, w, out_dtype=jnp.bfloat16)
    ref = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())))
    assert np.array_equal(np.asarray(y, np.float32),
                          np.asarray(ref, np.float32))
    # SR + rng: still close, not identical
    ysr = qm.bf16_fallback_matmul(
        _rand((8, 64)), _rand((64, 32), seed=1),
        out_dtype=jnp.bfloat16, stochastic_rounding=True,
        rng=jax.random.PRNGKey(0))
    assert not np.array_equal(np.asarray(ysr, np.float32),
                              np.asarray(ref, np.float32))
    assert np.abs(np.asarray(ysr, np.float32) -
                  np.asarray(ref, np.float32)).max() < 0.5


# ----------------------------------------------------------------------
# the serving dedupe: inference/quant.py IS the shared primitive
# ----------------------------------------------------------------------
def test_inference_quant_is_the_shared_primitive():
    from deepspeed_tpu.inference import quant as iq
    assert iq.int8_matmul is qm.int8_matmul
    assert iq.quantize_kernel_int8 is qm.quantize_kernel_int8_np


# ----------------------------------------------------------------------
# the GPT-2 weave: config block -> engine hook -> projections
# ----------------------------------------------------------------------
def _tiny(**kw):
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, \
        tiny_gpt2_config
    cfg = tiny_gpt2_config(n_positions=64, **kw)
    return GPT2ForCausalLM(cfg)


def test_param_tree_identical_quantized_or_not():
    ids = np.zeros((2, 64), np.int32)
    trees = []
    for kw in ({}, {"quantized_compute": "on"},
               {"quantized_compute": "on", "fused_ops": "on"}):
        m = _tiny(**kw)
        p = m.init(jax.random.PRNGKey(0), {"input_ids": ids})
        trees.append(str(jax.tree_util.tree_map(
            lambda l: (l.shape, str(l.dtype)), p)))
    assert trees[0] == trees[1] == trees[2]


def test_quantized_loss_tracks_unquantized():
    ids = np.random.default_rng(0).integers(
        0, 256, (2, 64)).astype(np.int32)
    batch = {"input_ids": ids}
    m0, m1 = _tiny(), _tiny(quantized_compute="on")
    p = m0.init(jax.random.PRNGKey(0), {"input_ids": ids})
    l0 = float(m0.loss_fn(p, batch, deterministic=True))
    l1 = float(m1.loss_fn(p, batch, deterministic=True))
    assert l0 != l1                      # it actually quantized
    assert abs(l0 - l1) / abs(l0) < 0.01


def test_configure_hook_and_mode_validation():
    m = _tiny()
    with pytest.raises(ValueError):
        m.configure_quantized_compute("sideways")
    m.configure_quantized_compute("on", block=128,
                                  stochastic_rounding=True)
    assert m.config.quantized_compute == "on"
    assert m.config.quant_block == 128
    assert m.config.quant_stochastic_rounding is True


def test_engine_wires_quantized_compute_and_emits_event(tmp_path):
    """The `quantized_compute` config block reaches the model through
    the engine (configure hook), the per-step "quant" rng stream
    feeds stochastic rounding, and one `quantized_matmul` event lands
    in the JSONL sink."""
    import json
    import deepspeed_tpu
    ids = np.random.default_rng(0).integers(
        0, 256, (1, 8, 64)).astype(np.int32)
    model = _tiny()
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": ids[0]})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 1000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "quantized_compute": {"enabled": True, "mode": "on",
                                  "block": 128,
                                  "stochastic_rounding": True},
            "monitor": {"enabled": True, "sinks": ["jsonl"],
                        "output_path": str(tmp_path)},
        })
    assert model.config.quantized_compute == "on"
    assert model.config.quant_stochastic_rounding is True
    loss = engine.train_batch(batch={"input_ids": ids})
    assert np.isfinite(float(jax.device_get(loss)))
    engine.monitor.close()
    events = [json.loads(l) for l in
              open(tmp_path / "events.jsonl")]
    qevents = [e for e in events if e["kind"] == "quantized_matmul"]
    assert len(qevents) == 1
    ev = qevents[0]
    assert ev["applied"] is True and ev["active"] is True
    assert ev["mode"] == "on" and ev["block"] == 128
    assert ev["stochastic_rounding"] is True


def test_engine_warns_when_model_lacks_hook(caplog):
    import deepspeed_tpu

    def loss_fn(params, batch, rngs=None, deterministic=False):
        return jnp.mean((batch["x"] @ params["w"]) ** 2)

    class Plain:
        pass

    model = Plain()
    model.loss_fn = loss_fn
    params = {"w": _rand((8, 8))}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "quantized_compute": {"enabled": True, "mode": "on"},
        })
    # no hook -> warned, engine still works
    assert engine is not None


def test_config_block_validation():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    base = {"train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1}
    for bad in ({"quantized_compute": {"mode": "nope"}},
                {"quantized_compute": {"block": 0}},
                {"quantized_compute": {"block": True}},
                {"quantized_compute": "yes"},
                {"autotune": {"table_path": 7}},
                {"autotune": []}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({**base, **bad}, world_size=1)
    cfg = DeepSpeedConfig(
        {**base,
         "quantized_compute": {"enabled": True, "mode": "on",
                               "block": 256,
                               "stochastic_rounding": True},
         "autotune": {"enabled": False, "table_path": "/tmp/t.json"}},
        world_size=1)
    assert cfg.quantized_compute == {
        "enabled": True, "mode": "on", "block": 256,
        "stochastic_rounding": True}
    assert cfg.autotune == {"enabled": False,
                            "table_path": "/tmp/t.json"}


def test_sr_bf16_fallback_is_wired_when_quant_resolves_off():
    """quantized_compute 'auto' resolves OFF on CPU; with
    stochastic_rounding the documented bf16 fallback must engage:
    bit-identical to the plain model without a "quant" rng,
    stochastically perturbed (but close) with one."""
    ids = np.random.default_rng(4).integers(
        0, 256, (2, 64)).astype(np.int32)
    batch = {"input_ids": ids}
    m_plain = _tiny(dtype=jnp.bfloat16)
    m_sr = _tiny(dtype=jnp.bfloat16, quantized_compute="auto",
                 quant_stochastic_rounding=True)
    p = m_plain.init(jax.random.PRNGKey(0), {"input_ids": ids})
    l_plain = float(m_plain.loss_fn(p, batch, deterministic=True))
    l_no_rng = float(m_sr.loss_fn(p, batch, deterministic=True))
    assert l_plain == l_no_rng      # backward compatible without rng
    l_rng = float(m_sr.loss_fn(
        p, batch, rngs={"quant": jax.random.PRNGKey(1)},
        deterministic=True))
    assert l_rng != l_plain         # SR casts actually engaged
    assert abs(l_rng - l_plain) / abs(l_plain) < 0.01


# ----------------------------------------------------------------------
# boundary fusion (ISSUE 13(c)) — rides the fused path
# ----------------------------------------------------------------------
def test_boundary_fused_loss_bit_exact_and_grads_roundoff():
    ids = np.random.default_rng(1).integers(
        0, 256, (2, 64)).astype(np.int32)
    batch = {"input_ids": ids}
    m0, m1 = _tiny(), _tiny(fused_ops="on")
    p = m0.init(jax.random.PRNGKey(0), {"input_ids": ids})
    l0 = float(m0.loss_fn(p, batch, deterministic=True))
    l1 = float(m1.loss_fn(p, batch, deterministic=True))
    assert l0 == l1                      # fp32 forward is bit-exact
    g0 = jax.grad(lambda p: m0.loss_fn(p, batch,
                                       deterministic=True))(p)
    g1 = jax.grad(lambda p: m1.loss_fn(p, batch,
                                       deterministic=True))(p)
    gmax = max(float(jnp.abs(l).max())
               for l in jax.tree_util.tree_leaves(g0))
    gd = max(float(jnp.abs(a - b).max())
             for a, b in zip(jax.tree_util.tree_leaves(g1),
                             jax.tree_util.tree_leaves(g0)))
    assert gd / gmax < 1e-5


def test_boundary_fusion_mirrors_on_zero3_scheduled_path():
    """The stage-3 scheduled loss must run the same boundary-fused op
    sequence as the module path: loss parity at the fused-path
    tolerance with the scheduler bound."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, \
        tiny_gpt2_config
    ids = np.random.default_rng(2).integers(
        0, 256, (1, 8, 64)).astype(np.int32)
    cfg = tiny_gpt2_config(n_positions=64, fused_ops="on")
    model = GPT2ForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": ids[0]})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 1000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {
                "stage": 3, "stage3": {"prefetch_layers": 1}},
        })
    assert engine.zero3_scheduler is not None
    losses = [float(jax.device_get(
        engine.train_batch(batch={"input_ids": ids})))
        for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)


def test_boundary_block_requires_fused_path():
    from deepspeed_tpu.models.gpt2 import GPT2Block, tiny_gpt2_config
    cfg = tiny_gpt2_config(n_positions=64)   # fused auto -> off on CPU
    blk = GPT2Block(cfg)
    x = _rand((2, 8, 64))
    with pytest.raises(ValueError):
        blk.init(jax.random.PRNGKey(0), x, True, None, True)


def test_pld_keeps_plain_carry_under_fused():
    """layer_keep_prob forces the non-boundary carry (PLD gates on
    completed block outputs) — and still runs with fused_ops on."""
    ids = np.random.default_rng(3).integers(
        0, 256, (2, 64)).astype(np.int32)
    m = _tiny(fused_ops="on")
    p = m.init(jax.random.PRNGKey(0), {"input_ids": ids})
    l = float(m.loss_fn(p, {"input_ids": ids}, deterministic=True,
                        layer_keep_prob=jnp.float32(1.0)))
    l_ref = float(_tiny().loss_fn(p, {"input_ids": ids},
                                  deterministic=True))
    assert abs(l - l_ref) < 1e-5
