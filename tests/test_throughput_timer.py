"""ThroughputTimer samples/sec accounting (ISSUE 2 satellite).

The audit point: `avg_samples_per_sec` multiplies
`batch_size (micro per worker) * num_workers`, while `stop(count=...)`
counts MICROBATCHES — these units must cancel so that gas>1 fused steps
(count=gas) and dp>1 both report train_batch_size * steps / elapsed.
These tests pin that with a fake clock, and the pre-warmup return value
(0.0, not -inf).
"""

import pytest

import deepspeed_tpu.utils.timer as timer_mod
from deepspeed_tpu.utils.timer import ThroughputTimer


class _FakeTime:
    """Deterministic stand-in for the `time` module inside timer.py."""

    def __init__(self):
        # non-zero start: the timer uses start_time == 0 as its
        # "window not yet open" sentinel
        self.now = 1000.0

    def time(self):
        return self.now

    def advance(self, dt):
        self.now += dt


@pytest.fixture
def fake_time(monkeypatch):
    ft = _FakeTime()
    monkeypatch.setattr(timer_mod, "time", ft)
    # the window fences call jax.effects_barrier; irrelevant here
    monkeypatch.setattr(timer_mod, "_device_sync", lambda: None)
    return ft


def _run_steps(t, fake_time, n, count, step_seconds):
    for _ in range(n):
        t.start()
        fake_time.advance(step_seconds)
        t.stop(count=count)


def test_avg_samples_per_sec_prewarmup_is_zero():
    t = ThroughputTimer(batch_size=4, num_workers=2)
    assert t.avg_samples_per_sec() == 0.0
    t.start()
    t.stop(count=1)   # still inside warmup (start_step=2)
    assert t.avg_samples_per_sec() == 0.0


def test_samples_per_sec_gas_gt_1(fake_time):
    """One fused step = gas microbatches (stop(count=gas)): reported
    rate must be micro_bs * gas / step_time (dp=1)."""
    micro_bs, gas, step_s = 2, 4, 0.5
    logged = []
    t = ThroughputTimer(batch_size=micro_bs, num_workers=1,
                        start_step=2, steps_per_output=gas * 2,
                        logging_fn=logged.append)
    # step 1 ends warmup (gsc=4 >= 2) and opens the window
    _run_steps(t, fake_time, 1, gas, step_s)
    assert t.avg_samples_per_sec() == 0.0   # window open, nothing fenced
    # two more steps; gsc hits 8 then 12 → reports at both
    _run_steps(t, fake_time, 2, gas, step_s)
    expected = micro_bs * gas / step_s      # 16 samples/sec
    assert t.avg_samples_per_sec() == pytest.approx(expected)
    assert logged, "steps_per_output fence did not log"


def test_samples_per_sec_dp_gt_1(fake_time):
    """dp>1 at gas=1: every worker consumes micro_bs samples per
    microbatch tick → micro_bs * dp / step_time."""
    micro_bs, dp, step_s = 3, 4, 0.25
    t = ThroughputTimer(batch_size=micro_bs, num_workers=dp,
                        start_step=2, steps_per_output=2,
                        logging_fn=lambda *_: None)
    _run_steps(t, fake_time, 2, 1, step_s)   # warmup + window open
    _run_steps(t, fake_time, 4, 1, step_s)
    expected = micro_bs * dp / step_s        # 48 samples/sec
    assert t.avg_samples_per_sec() == pytest.approx(expected)


def test_samples_per_sec_gas_and_dp(fake_time):
    """gas>1 AND dp>1 combined: rate = train_batch_size / step_time
    where train_batch_size = micro_bs * gas * dp."""
    micro_bs, gas, dp, step_s = 2, 3, 4, 1.0
    t = ThroughputTimer(batch_size=micro_bs, num_workers=dp,
                        start_step=2, steps_per_output=gas,
                        logging_fn=lambda *_: None)
    _run_steps(t, fake_time, 1, gas, step_s)   # warmup + window open
    _run_steps(t, fake_time, 3, gas, step_s)
    expected = micro_bs * gas * dp / step_s    # 24 samples/sec
    assert t.avg_samples_per_sec() == pytest.approx(expected)


def test_mid_window_steps_not_counted_until_fence(fake_time):
    t = ThroughputTimer(batch_size=2, num_workers=1, start_step=2,
                        steps_per_output=100,
                        logging_fn=lambda *_: None)
    _run_steps(t, fake_time, 2, 1, 0.5)   # warmup + window open
    _run_steps(t, fake_time, 5, 1, 0.5)   # all mid-window (no fence)
    # unfenced in-flight steps are not claimed as measured throughput
    assert t.avg_samples_per_sec() == 0.0
