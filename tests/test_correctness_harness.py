"""In-situ A/B correctness harness (parity target: ref
`stage2.py:25,1060` pg_correctness_test — a live A/B of the partitioned
path against a dense fp32 reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import ABCorrectnessChecker, DivergenceError
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config


def _setup(**cfg_over):
    cfg = tiny_gpt2_config(dtype=jnp.bfloat16)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    primary = {
        "train_batch_size": 8,
        "steps_per_print": 1000,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    }
    primary.update(cfg_over)
    return model, params, primary, ids


def test_sharded_bf16_agrees_with_fp32_reference():
    """ZeRO-2 + bf16 must track the plain fp32 ZeRO-0 trajectory on a
    real model — the reference's pg_correctness_test claim, checked
    end-to-end."""
    model, params, primary, ids = _setup()
    checker = ABCorrectnessChecker(model, params, primary, interval=5,
                                   loss_atol=0.08, param_rtol=0.02)
    for i in range(15):
        checker.train_batch(batch={"input_ids": ids[None]})
    summary = checker.report()
    assert summary["checks"] == 3
    assert summary["max_loss_gap"] <= 0.08


def test_divergence_is_detected():
    """A perturbed primary step must trip the checker (the harness is
    only useful if it actually fires)."""
    model, params, primary, ids = _setup()
    checker = ABCorrectnessChecker(model, params, primary, interval=2,
                                   loss_atol=0.01)
    checker.train_batch(batch={"input_ids": ids[None]})
    # sabotage: perturb the primary's parameters out-of-band
    checker.primary.state = checker.primary.state._replace(
        params=jax.tree_util.tree_map(
            lambda p: p + jnp.asarray(0.5, p.dtype),
            checker.primary.state.params))
    with pytest.raises(DivergenceError):
        checker.train_batch(batch={"input_ids": ids[None]})


def test_fp32_primary_agrees_tightly():
    """With an fp32 primary the only difference is the ZeRO sharding —
    trajectories must agree to float tolerance."""
    model, params, primary, ids = _setup()
    primary.pop("bf16")
    # 5e-4 on a ~5.2 fp32 loss (rel ~1e-4): the sharded and replicated
    # engines reduce in different orders, and the gap is XLA-version
    # dependent (measured 1.5e-4 on jaxlib 0.4.37-cpu)
    checker = ABCorrectnessChecker(model, params, primary, interval=4,
                                   loss_atol=5e-4)
    for i in range(8):
        checker.train_batch(batch={"input_ids": ids[None]})
    assert checker.report()["max_loss_gap"] <= 5e-4


def test_harness_on_3d_pipeline_engine():
    """The A/B harness runs on the compiled 1F1B substrate too: primary
    = bf16-SR + ZeRO-1 on a pipe=2 x data=2 x model=2 mesh, shadow =
    fp32 ZeRO-0 on the SAME mesh — certifying the sharded
    runtime/precision path on top of the pipeline executor."""
    import flax.linen as nn
    from deepspeed_tpu.runtime.mesh import build_mesh
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

    def mse(pred, labels):
        return jnp.mean((pred.astype(jnp.float32) -
                         labels.astype(jnp.float32)) ** 2)

    module = PipelineModule(
        [LayerSpec(nn.Dense, 32, dtype=jnp.bfloat16), jnp.tanh,
         LayerSpec(nn.Dense, 8, dtype=jnp.bfloat16)],
        num_stages=2, loss_fn=mse, partition_method="uniform")
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.randn(4, 16), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(0), x0)
    mesh = build_mesh({"pipe": 2, "data": 2, "model": 2})
    primary = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 1000,
        "bf16": {"enabled": True, "master_weights": False},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    checker = ABCorrectnessChecker(module, params, primary, mesh=mesh,
                                   interval=2, loss_atol=0.05)
    w = np.linspace(-1, 1, 16 * 8).reshape(16, 8).astype(np.float32)
    for i in range(4):
        x = rng.randn(32, 16).astype(np.float32)
        checker.train_batch(batch={"x": x, "y": x @ w})
    assert checker.checks >= 2 and checker.max_loss_gap < 0.05
