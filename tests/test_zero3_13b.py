"""Executed ZeRO-3 big-model memory validation (VERDICT r3 #4).

The round-3 bench computed the 13B memory plan analytically; these
tests EXECUTE the same code path on the 8-device CPU mesh and measure
real per-device buffer bytes: sharded init (no unsharded tree is ever
materialized), bf16 master-less state (params + mu + nu = 6 B/param),
two real sharded optimizer-update steps, and the assertion that each
device holds ~1/dp of the state.

The always-on test runs a scaled GPT-2 (same code path, CI-sized); the
full 13.2B-parameter run — identical function, real gpt2-13b
layer-count/width — is executed by `__graft_entry__.dryrun_multichip`
(driver leg) and locally via DS_TPU_RUN_13B=1.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, gpt2_config
from deepspeed_tpu.runtime.mesh import build_mesh
from deepspeed_tpu.runtime.zero.partition import ZeroShardingPolicy


def run_zero3_sr_memory_check(model_name, overrides, steps=2,
                              tolerance=0.15, train_steps=0):
    """Init `model_name` under ZeRO-3 + bf16 master-less on a data mesh
    spanning all devices, run `steps` real sharded update steps, and
    return measured per-device state bytes vs the plan formula.

    Params are constant-initialized straight into the sharded layout
    (values are irrelevant to the memory claim; a threefry init of
    12.6B elements takes ~20 min on one CPU core), and the update runs
    with zero gradients generated inside the jit — the same compiled
    sharded program as a real step minus the fwd/bwd FLOPs, which at
    13B exceed what a 1-core CI host can execute.

    `train_steps` > 0 additionally runs REAL train_batch steps through
    the ISSUE-9 stage-3 runtime (layer-granular gather prefetch,
    reduce-scatter grad ownership) and cross-asserts three ways:
    `ZeroShardingPolicy.memory_plan` vs the memory ledger vs measured
    addressable-shard bytes, plus the gathered-window bound — the
    executed proof that the runtime honors the plan (CI-sized here;
    flops at full 13B exceed the 1-core host).
    """
    n_dev = len(jax.devices())
    mesh = build_mesh({"pipe": 1, "data": n_dev, "model": 1})
    cfg = gpt2_config(model_name, dropout=0.0, dtype=jnp.bfloat16,
                      param_dtype=jnp.bfloat16, **overrides)
    model = GPT2ForCausalLM(cfg)
    example = {"input_ids": np.zeros((1, cfg.n_positions), np.int32)}

    shapes = jax.eval_shape(lambda r: model.init(r, example),
                            jax.random.PRNGKey(0))
    policy = ZeroShardingPolicy(mesh, 3)
    shardings = policy.param_shardings(shapes)
    init_fn = jax.jit(
        lambda: jax.tree_util.tree_map(
            lambda s: jnp.full(s.shape, 0.01, s.dtype), shapes),
        out_shardings=shardings)
    params = init_fn()
    jax.block_until_ready(params)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        config={
            "train_micro_batch_size_per_gpu": n_dev,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 1000,
            "bf16": {"enabled": True, "master_weights": False},
            "zero_optimization": {"stage": 3},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        })
    del params

    dev0 = jax.devices()[0]

    def dev_bytes(tree):
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, jax.Array):
                for sh in leaf.addressable_shards:
                    if sh.device == dev0:
                        total += sh.data.nbytes
        return total

    measured = dev_bytes(engine.state.params) + \
        dev_bytes(engine.state.opt_state)
    # plan formula: bf16 params + bf16 mu + bf16 nu = 6 B/param, / dp
    planned = 6.0 * n_params / n_dev
    rel_err = abs(measured - planned) / planned
    assert rel_err < tolerance, (
        f"per-device state {measured/2**30:.3f} GB vs planned "
        f"{planned/2**30:.3f} GB (rel err {rel_err:.2%}) — state is "
        "replicating instead of sharding")

    # memory-ledger cross-check (ISSUE 8): what the monitor's ledger
    # registered from sharding metadata must agree with the MEASURED
    # per-device shard bytes — the live validation the 13B memory
    # plan's credibility rests on (the ledger registers even with the
    # monitor disabled, so this big-model path always carries it)
    cats = engine.monitor.ledger.totals()["hbm"]
    ledgered = cats.get("params", 0) + cats.get("opt_state", 0)
    led_err = abs(ledgered - measured) / measured
    assert led_err < tolerance, (
        f"ledger {ledgered/2**30:.3f} GB vs measured "
        f"{measured/2**30:.3f} GB (rel err {led_err:.2%}) — the "
        "ledger's shard arithmetic disagrees with the allocator")

    report_extra = {}
    if train_steps:
        # -- the new stage-3 runtime path (ISSUE 9): real fwd/bwd with
        # the gather/release scheduler woven through the model apply —
        # not just the sharding-policy arithmetic
        assert engine.zero3_scheduler is not None, \
            "stage-3 engine did not weave the gather scheduler"
        from deepspeed_tpu.monitor.memory import plan_vs_measured
        plan = engine.zero_policy.memory_plan(
            shapes, compute_bytes=2, sr_mode=True, gas=1)
        engine.monitor.set_memory_plan(plan)
        for i in range(train_steps):
            ids = np.random.default_rng(i).integers(
                0, cfg.vocab_size,
                (1, n_dev, cfg.n_positions)).astype(np.int32)
            loss = engine.train_batch(batch={"input_ids": ids})
        assert np.isfinite(float(jax.device_get(loss)))
        cats = engine.monitor.ledger.totals()["hbm"]
        meas = {"params": dev_bytes(engine.state.params),
                "opt_state": dev_bytes(engine.state.opt_state)}
        for comp in ("params", "opt_state"):
            for got, src in ((cats.get(comp, 0), "ledger"),
                             (meas[comp], "measured")):
                delta = plan_vs_measured(
                    plan, {comp: got})[comp]["delta_pct"]
                assert abs(delta) < tolerance * 100, (
                    f"{comp}: plan {plan[comp]} vs {src} {got} "
                    f"({delta:+.1f}%) — the runtime does not honor "
                    "the memory plan")
        # gathered-window bound, computed INDEPENDENTLY from the raw
        # param tree (the ledger's zero3_gather entry IS the
        # scheduler's own live_window_bytes — comparing those would be
        # the scheduler vouching for itself)
        sched = engine.zero3_scheduler
        info = sched.stack_info["h"]
        assert info["window_layers"] == sched.prefetch_layers + 1

        def full_bytes(tree):
            return sum(int(np.prod(l.shape)) * l.dtype.itemsize
                       for l in jax.tree_util.tree_leaves(tree))

        (_, stacked), = engine.state.params["h"].items()
        L = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        expect = full_bytes(stacked) // L * (sched.prefetch_layers + 1) \
            + sum(full_bytes(engine.state.params[k])
                  for k in ("wte", "wpe", "ln_f"))
        assert cats["zero3_gather"] == expect, (
            cats["zero3_gather"], expect)
        report_extra = {
            "plan_gb_per_device": (plan["params"] + plan["opt_state"])
            / 2**30,
            "zero3_gather_gb": cats["zero3_gather"] / 2**30,
            "train_steps": train_steps,
        }

    # real sharded update steps (grads = zeros generated inside jit)
    enc_template = engine._params_enc_template

    def upd(state, lr):
        grads = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, jnp.bfloat16), enc_template)
        new_state, _, gnorm, _health = engine._unscale_clip_and_update(
            state, lr, grads=grads)
        return new_state, gnorm

    upd_jit = jax.jit(upd, donate_argnums=(0,))
    for _ in range(steps):
        engine.state, gnorm = upd_jit(engine.state, np.float32(1e-4))
        jax.block_until_ready(engine.state.params)
        assert np.isfinite(float(jax.device_get(gnorm)))

    post = dev_bytes(engine.state.params) + dev_bytes(engine.state.opt_state)
    assert abs(post - planned) / planned < tolerance, (
        "state grew after update steps — something materialized "
        f"unsharded ({post/2**30:.3f} GB vs {planned/2**30:.3f})")
    return {"params_b": n_params / 1e9,
            "state_gb_per_device": measured / 2**30,
            "planned_gb_per_device": planned / 2**30,
            "ledger_gb_per_device": ledgered / 2**30,
            "devices": n_dev, **report_extra}


def test_zero3_sr_memory_scaled():
    """CI-sized model (~100M) through the exact big-model code path:
    sharded constant init, per-device = total/dp, sharded update —
    PLUS real train_batch steps through the stage-3 gather/release
    runtime with the three-way plan/ledger/measured cross-assert
    (ISSUE 9: the executed check runs the runtime path, not just the
    sharding-policy path)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    out = run_zero3_sr_memory_check(
        "gpt2-125m", dict(vocab_size=512, n_positions=64),
        train_steps=2)
    assert out["params_b"] > 0.05
    assert out["train_steps"] == 2


@pytest.mark.skipif(os.environ.get("DS_TPU_RUN_13B") != "1",
                    reason="full 13B run takes ~15 min + ~110 GB host "
                           "RAM; set DS_TPU_RUN_13B=1 to run")
def test_zero3_sr_memory_13b_init():
    """The real thing: gpt2-13b layer count/width (12.85B params),
    tiny vocab, on the 8-device mesh — sharded init + measured
    per-device state bytes. steps=0: a full-13B update step is ~20 min
    of EMULATED-bf16 elementwise work on this 1-core CPU host and its
    transient peak (~125 GB) sits exactly at the RAM limit; the update
    program itself is executed at 6.4B by the companion test below and
    at CI size by test_zero3_sr_memory_scaled — it is depth-repeated
    per layer, so running more layers changes no program structure."""
    out = run_zero3_sr_memory_check(
        "gpt2-13b", dict(vocab_size=512, n_positions=32), steps=0)
    assert out["params_b"] > 12.0


@pytest.mark.skipif(os.environ.get("DS_TPU_RUN_13B") != "1",
                    reason="~15 min + ~70 GB host RAM; set "
                           "DS_TPU_RUN_13B=1 to run")
def test_zero3_sr_update_3b_executed():
    """Real sharded update execution at 13B WIDTH and quarter depth
    (3.2B params, program structure identical to 13B — the update is
    depth-repeated): per-device bytes + one executed step. The
    XLA-CPU update graph's elementwise transients run ~3x the state
    size, which is what bounds the depth on this 125 GB host (on TPU
    the same program's transients are fused tiles)."""
    out = run_zero3_sr_memory_check(
        "gpt2-13b", dict(vocab_size=512, n_positions=32, n_layer=10),
        steps=1)
    assert out["params_b"] > 3.0
