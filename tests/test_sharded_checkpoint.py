"""Sharded-checkpoint tests (VERDICT r1 #4).

The reference writes per-dp-rank zero shard files with barriers
(`engine.py:1522-1531`), per-layer pipeline files (`pipe/module.py:536-567`)
and validates tags cross-rank (`engine.py:1448-1463`).  Here: per-shard
npz bucket files (no pickle, no full-host gather on save), per-layer
files, elastic reload onto a different mesh shape.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import initialize
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config
from deepspeed_tpu.runtime.mesh import build_mesh


def _make_engine(mesh, stage=2, lr=1e-3):
    cfg = tiny_gpt2_config(n_layer=2, n_embd=64, n_head=4,
                          n_positions=64, vocab_size=256)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, 256, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "zero_optimization": {"stage": stage},
                "optimizer": {"type": "Adam", "params": {"lr": lr}}},
        mesh=mesh)
    return engine, ids


def _train(engine, ids, steps=3):
    loss = None
    for i in range(steps):
        loss = engine.train_batch(
            batch={"input_ids": ids[None]})
    return float(jax.device_get(loss))


def test_save_writes_shard_files_no_pickle(tmp_path):
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, ids = _make_engine(mesh, stage=2)
    _train(engine, ids)
    engine.save_checkpoint(str(tmp_path), tag="t1")
    engine.wait_for_checkpoint()

    d = str(tmp_path / "t1")
    files = os.listdir(d)
    # no pickle anywhere
    assert not any(f.endswith(".pt") for f in files), files
    # ZeRO-2: optimizer moments are data-sharded -> per-ordinal buckets
    opt_shards = glob.glob(os.path.join(d, "zero_pp_rank_*optim*.npz"))
    assert len(opt_shards) == 8, sorted(files)
    # manifest is valid JSON with a format version
    with open(os.path.join(d, "mp_rank_00_model_states.json")) as f:
        manifest = json.load(f)
    assert manifest["format_version"] >= 2


def test_roundtrip_same_mesh(tmp_path):
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, ids = _make_engine(mesh, stage=2)
    _train(engine, ids)
    before = jax.device_get(engine.state.params)
    m_before = jax.device_get(
        jax.tree_util.tree_leaves(engine.state.opt_state))
    engine.save_checkpoint(str(tmp_path), tag="rt")
    engine.wait_for_checkpoint()

    engine2, _ = _make_engine(mesh, stage=2)
    engine2.load_checkpoint(str(tmp_path), tag="rt")
    after = jax.device_get(engine2.state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6),
        before, after)
    m_after = jax.device_get(
        jax.tree_util.tree_leaves(engine2.state.opt_state))
    for a, b in zip(m_before, m_after):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=1e-6)


def test_elastic_reload_different_mesh(tmp_path):
    """Save on data=8, reload on data=4 x model=2 — the elastic
    behaviour the reference only supports for ZeRO-1 dp resize
    (`stage1.py:1048`)."""
    mesh8 = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, ids = _make_engine(mesh8, stage=3)
    _train(engine, ids)
    loss_before = _train(engine, ids, steps=1)
    engine.save_checkpoint(str(tmp_path), tag="elastic")
    engine.wait_for_checkpoint()

    mesh42 = build_mesh({"pipe": 1, "data": 4, "model": 2})
    engine2, _ = _make_engine(mesh42, stage=2)
    engine2.load_checkpoint(str(tmp_path), tag="elastic")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a), np.float32),
            np.asarray(jax.device_get(b), np.float32), rtol=1e-6),
        jax.device_get(engine.state.params),
        jax.device_get(engine2.state.params))
    # training continues at the restored point
    loss_after = _train(engine2, ids, steps=1)
    assert abs(loss_after - loss_before) < 0.5


def test_per_layer_pipeline_files(tmp_path):
    """PipelineModule checkpoints write layer_NN files and reload onto
    a different stage count (ref test_checkpointing.py:633)."""
    import flax.linen as nn
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

    class Dense(nn.Module):
        feats: int = 16

        @nn.compact
        def __call__(self, x):
            return nn.Dense(self.feats)(x)

    specs = [LayerSpec(Dense, 16) for _ in range(4)]
    mod2 = PipelineModule(layers=specs, num_stages=2)
    x = np.zeros((2, 16), np.float32)
    params = mod2.init_params(jax.random.PRNGKey(0), x)

    ckpt_dir = str(tmp_path / "layers")
    mod2.save_state_dict(ckpt_dir, params)
    files = sorted(os.listdir(ckpt_dir))
    assert [f for f in files if f.startswith("layer_")] == [
        f"layer_{i:02d}-model_states.npz" for i in range(4)]

    # reload with a different partitioning (4 stages)
    mod4 = PipelineModule(layers=specs, num_stages=4)
    template = mod4.init_params(jax.random.PRNGKey(1), x)
    restored = mod4.load_state_dir(ckpt_dir, template)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b)),
        params, restored)


def test_tag_validation_single_process():
    from deepspeed_tpu.runtime.checkpoint import validate_checkpoint_tag
    assert validate_checkpoint_tag("step5", fail_on_mismatch=True)


def test_legacy_pickle_checkpoint_still_loads(tmp_path):
    """Round-1 checkpoints (pickle .pt) remain readable."""
    import pickle
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, ids = _make_engine(mesh, stage=0)
    d = tmp_path / "old"
    os.makedirs(d)
    module = jax.device_get(engine.state.params)
    sd = {"module": module, "global_steps": 7, "skipped_steps": 0,
          "micro_steps": 7, "dp_world_size": 8, "lr_scheduler": None,
          "rng": np.zeros(2, np.uint32)}
    with open(d / "mp_rank_00_model_states.pt", "wb") as f:
        pickle.dump(sd, f)
    (tmp_path / "latest").write_text("old")
    path, _ = engine.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine.global_steps == 7


# ----------------------------------------------------------------------
# format versioning + corruption detection (VERDICT r3 #10)
# ----------------------------------------------------------------------
def _find_one(pattern, tmp_path):
    files = glob.glob(os.path.join(str(tmp_path), "**", pattern),
                      recursive=True)
    assert files, pattern
    return files[0]


def test_format_version_written_and_future_rejected(tmp_path):
    from deepspeed_tpu.runtime.checkpoint import FORMAT_VERSION
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, ids = _make_engine(mesh, stage=2)
    engine.train_batch(batch={"input_ids": ids[None]})
    engine.save_checkpoint(str(tmp_path), tag="v")
    engine.wait_for_checkpoint()

    # exact main-manifest name: a bare '*model_states.json' would also
    # match shard-bucket manifests, which the loader never version-checks
    manifest_path = _find_one("mp_rank_*_model_states.json", tmp_path)
    with open(manifest_path) as f:
        manifest = json.load(f)
    assert manifest["format_version"] == FORMAT_VERSION

    # bump to a future version: load must fail with a clear error
    manifest["format_version"] = FORMAT_VERSION + 1
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    engine2, _ = _make_engine(mesh, stage=2)
    with pytest.raises(ValueError, match="format_version"):
        engine2.load_checkpoint(str(tmp_path), tag="v")


def test_missing_shard_file_detected(tmp_path):
    """Deleting one zero_pp_rank shard bucket must raise a coverage
    error, not silently zero-fill the hole."""
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, ids = _make_engine(mesh, stage=2)
    engine.train_batch(batch={"input_ids": ids[None]})
    engine.save_checkpoint(str(tmp_path), tag="v")
    engine.wait_for_checkpoint()

    shard = _find_one("zero_pp_rank_1_*.npz", tmp_path)
    os.remove(shard)
    os.remove(shard[:-len(".npz")] + ".json")
    engine2, _ = _make_engine(mesh, stage=2)
    with pytest.raises(ValueError, match="coverage"):
        engine2.load_checkpoint(str(tmp_path), tag="v")


def test_truncated_shard_file_detected(tmp_path):
    """A truncated shard npz must raise, not load garbage."""
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, ids = _make_engine(mesh, stage=2)
    engine.train_batch(batch={"input_ids": ids[None]})
    engine.save_checkpoint(str(tmp_path), tag="v")
    engine.wait_for_checkpoint()

    shard = _find_one("zero_pp_rank_0_*.npz", tmp_path)
    data = open(shard, "rb").read()
    with open(shard, "wb") as f:
        f.write(data[:max(16, len(data) // 3)])
    engine2, _ = _make_engine(mesh, stage=2)
    with pytest.raises(Exception):
        engine2.load_checkpoint(str(tmp_path), tag="v")
