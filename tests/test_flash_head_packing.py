"""Head-packed vs unpacked flash-kernel parity (ISSUE 4 satellite).

The packed kernel processes two d=64 heads per grid step in a
feature-packed [rows, T, 128] layout with block-diagonal K/V so every
score/output contraction runs at the MXU's native K=128
(flash_attention.py module docstring). The zero lanes contribute exact
+0 to every fp32 partial sum, so packed and unpacked must agree to
fp32 roundoff — forward AND backward — across head counts (even, and
odd B·H exercising the one-row zero pad), seq lengths that are and are
not multiples of the default block, causal/bidirectional, and
bf16/fp32. Everything runs the real Pallas kernels in interpreter mode
on CPU (head_packing="packed" forces the packed body; "auto" stays
unpacked off-TPU by design)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.flash_attention import (
    _resolve_head_packing, flash_attention, flash_attention_merge,
    flash_attention_with_lse)


def qkv(b, t, h, d, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, t, h, d), dtype) for _ in range(3)]


def ab(x, dtype=np.float32):
    return np.asarray(x, dtype)


# fp32 accumulates identically in both kernels (the packed zero lanes
# add exact +0); bf16 pays one output-rounding step per kernel, so the
# two paths can land one ULP apart after the fp32->bf16 cast.
TOL = {jnp.float32: dict(atol=2e-6, rtol=2e-6),
       jnp.bfloat16: dict(atol=2e-2, rtol=2e-2)}


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
@pytest.mark.parametrize("causal", [True, False],
                         ids=["causal", "bidir"])
@pytest.mark.parametrize("b,t,h", [
    (2, 128, 2),    # even B*H, single 128 tile
    (1, 256, 3),    # ODD B*H -> one-row zero pad, multi-tile
    (1, 384, 2),    # T=384: NOT a multiple of the 1024 default block
                    # (_fit_block shrinks to 128-wide tiles)
])
def test_forward_parity(b, t, h, causal, dtype):
    q, k, v = qkv(b, t, h, 64, dtype)
    packed = flash_attention(q, k, v, causal=causal, interpret=True,
                             head_packing="packed")
    unpacked = flash_attention(q, k, v, causal=causal, interpret=True,
                               head_packing="off")
    assert packed.dtype == unpacked.dtype == dtype
    np.testing.assert_allclose(ab(packed), ab(unpacked), **TOL[dtype])


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
@pytest.mark.parametrize("causal", [True, False],
                         ids=["causal", "bidir"])
@pytest.mark.parametrize("b,t,h", [
    (2, 128, 2),    # single-tile -> fused one-pass backward kernel
    (1, 256, 3),    # odd B*H + multi-tile -> dkv+dq sweep kernels
])
def test_backward_parity(b, t, h, causal, dtype):
    q, k, v = qkv(b, t, h, 64, dtype, seed=3)

    def loss(hp):
        def f(q, k, v):
            out = flash_attention(q, k, v, causal=causal, interpret=True,
                                  head_packing=hp)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for g_p, g_u in zip(loss("packed"), loss("off")):
        assert g_p.dtype == g_u.dtype == dtype
        np.testing.assert_allclose(ab(g_p), ab(g_u), **TOL[dtype])


def test_lse_parity():
    """The saved logsumexp rows (log2 space) drive both backward
    kernels and the ring merge — they must match too, including on the
    odd pad row's real neighbors."""
    q, k, v = qkv(1, 256, 3, 64, seed=5)
    out_p, lse_p = flash_attention_with_lse(
        q, k, v, causal=True, interpret=True, head_packing="packed")
    out_u, lse_u = flash_attention_with_lse(
        q, k, v, causal=True, interpret=True, head_packing="off")
    np.testing.assert_allclose(ab(out_p), ab(out_u), atol=2e-6, rtol=2e-6)
    np.testing.assert_allclose(ab(lse_p), ab(lse_u), atol=2e-6, rtol=2e-6)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "bidir"])
def test_merge_parity(causal):
    """Ring-step epilogue merge: packed vs unpacked kernels folding the
    same prior (out, lse) partial must agree in the merged result AND
    in the gradients flowing to the prior partial (the ring backward
    differentiates through every step's carry)."""
    b, t, h = 1, 256, 2
    q, k, v = qkv(b, t, h, 64, seed=7)
    k2, v2 = qkv(b, t, h, 64, seed=11)[:2]
    prev_out, prev_lse = flash_attention_with_lse(
        q, k2, v2, causal=False, interpret=True, head_packing="off")

    def merged(hp):
        def f(q, k, v, po, pl):
            o, l = flash_attention_merge(q, k, v, po, pl, causal=causal,
                                         interpret=True, head_packing=hp)
            return jnp.sum(o ** 2) + jnp.sum(l ** 2)
        out = flash_attention_merge(q, k, v, prev_out, prev_lse,
                                    causal=causal, interpret=True,
                                    head_packing=hp)
        grads = jax.grad(f, argnums=(0, 1, 2, 3, 4))(
            q, k, v, prev_out, prev_lse)
        return out, grads

    (o_p, l_p), g_p = merged("packed")
    (o_u, l_u), g_u = merged("off")
    np.testing.assert_allclose(ab(o_p), ab(o_u), atol=2e-6, rtol=2e-6)
    np.testing.assert_allclose(ab(l_p), ab(l_u), atol=2e-6, rtol=2e-6)
    for a, b_ in zip(g_p, g_u):
        np.testing.assert_allclose(ab(a), ab(b_), atol=1e-4, rtol=1e-4)


def test_packed_matches_dense_reference():
    """Not just self-consistency: the packed kernel against the plain
    XLA softmax(QK^T)V reference."""
    q, k, v = qkv(1, 256, 4, 64, seed=13)
    scale = 1.0 / np.sqrt(64)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = jnp.tril(jnp.ones((256, 256), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
    out = flash_attention(q, k, v, causal=True, interpret=True,
                          head_packing="packed")
    np.testing.assert_allclose(ab(out), ab(ref), atol=2e-5, rtol=2e-5)


def test_resolution_rules():
    # d != 64 cannot pack: forcing is an error, auto falls back
    with pytest.raises(ValueError, match="head_dim 64"):
        _resolve_head_packing("packed", 128, False)
    assert not _resolve_head_packing("auto", 128, False)
    # interpreter path (CPU CI) stays unpacked under auto, packs on TPU
    assert not _resolve_head_packing("auto", 64, True)
    assert _resolve_head_packing("auto", 64, False)
    assert _resolve_head_packing("packed", 64, True)
    assert not _resolve_head_packing("off", 64, False)
    with pytest.raises(ValueError, match="head_packing"):
        _resolve_head_packing("sideways", 64, False)
    # d=128 (no packing possible) still runs fine under auto
    q, k, v = qkv(1, 128, 2, 128, seed=17)
    out = flash_attention(q, k, v, causal=True, interpret=True,
                          head_packing="auto")
    assert out.shape == (1, 128, 2, 128)
