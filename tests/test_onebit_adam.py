"""1-bit Adam tests (parity target: ref `deepspeed/runtime/fp16/
onebit_adam.py:104-372`): warmup phase must be exact Adam, the
freeze_step transition must switch the engine onto the compressed
shard_map program whose only cross-worker payload is bit-packed signs,
and the compressed phase must still converge.

Runs on the 8-device virtual CPU mesh from conftest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from simple_model import SimpleModel
from deepspeed_tpu.runtime.fp16.onebit_adam import (
    pack_signs, unpack_signs, compress, compressed_allreduce)

DIM = 16
BS = 16


def onebit_config(freeze_step, lr=1e-2, **over):
    cfg = {
        "train_batch_size": BS,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": lr, "freeze_step": freeze_step}},
    }
    cfg.update(over)
    return cfg


def adam_config(lr=1e-2):
    return {
        "train_batch_size": BS,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
    }


def make_stacked_batch(seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(BS, DIM).astype(np.float32)
    w = np.linspace(-1, 1, DIM * DIM).reshape(DIM, DIM).astype(np.float32)
    # leading gas=1 dim for the fused train_batch path
    return {"x": x[None], "y": (x @ w)[None]}


def run_train(config, steps, seed=0):
    model = SimpleModel(hidden_dim=DIM, seed=seed)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=config)
    losses = []
    for i in range(steps):
        loss = engine.train_batch(batch=make_stacked_batch(i % 4))
        losses.append(float(jax.device_get(loss)))
    return engine, losses


# ----------------------------------------------------------------------
# compression primitives
# ----------------------------------------------------------------------
def test_pack_unpack_roundtrip():
    x = jnp.asarray(np.random.RandomState(0).randn(37), jnp.float32)
    signs = unpack_signs(pack_signs(x), 37)
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_error_feedback_invariant():
    """compress() must satisfy scale*signs + new_error == x + error —
    nothing is lost, only deferred (ref worker_error semantics)."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(64), jnp.float32)
    err = jnp.asarray(rng.randn(64) * 0.1, jnp.float32)
    scale, packed, new_err = compress(x, err)
    recon = unpack_signs(packed, 64) * scale + new_err
    np.testing.assert_allclose(np.asarray(recon), np.asarray(x + err),
                               rtol=1e-5, atol=1e-6)


def test_compressed_allreduce_approximates_mean(mesh8):
    """Across 8 shards with distinct inputs, the compressed result must
    approximate the true mean (one sign+scale quantization away)."""
    from deepspeed_tpu.runtime.compat import shard_map
    from jax.sharding import PartitionSpec as P

    n = 128
    rng = np.random.RandomState(2)
    data = jnp.asarray(rng.randn(8, n), jnp.float32)

    def per_shard(x):
        x = x[0]
        out, werr, serr = compressed_allreduce(
            x, jnp.zeros_like(x), jnp.zeros_like(x), "data")
        return out[None]

    out = shard_map(per_shard, mesh=mesh8,
                    in_specs=P("data"), out_specs=P("data"),
                    check_vma=False)(data)
    out = np.asarray(out)
    # every shard holds the same server-compressed average
    for i in range(1, 8):
        np.testing.assert_allclose(out[i], out[0], rtol=1e-6)
    true_mean = np.asarray(data).mean(axis=0)
    # sign*scale quantization: direction must correlate strongly
    cos = np.dot(out[0], true_mean) / (
        np.linalg.norm(out[0]) * np.linalg.norm(true_mean))
    assert cos > 0.5, cos


# ----------------------------------------------------------------------
# engine integration
# ----------------------------------------------------------------------
def test_warmup_matches_adam():
    """Before freeze_step, 1-bit Adam IS Adam (ref onebit_adam.py:320:
    warmup runs the uncompressed update)."""
    _, losses_1bit = run_train(onebit_config(freeze_step=1000), steps=8)
    _, losses_adam = run_train(adam_config(), steps=8)
    np.testing.assert_allclose(losses_1bit, losses_adam, rtol=1e-5)


def test_compressed_phase_activates_and_converges():
    engine, losses = run_train(onebit_config(freeze_step=3), steps=40)
    assert engine._use_onebit_shardmap
    assert engine._onebit_compressed_active
    assert np.isfinite(losses).all()
    # compressed phase continues to make progress
    assert losses[-1] < losses[3] * 0.5, losses


def test_compressed_converges_comparably_to_adam():
    """End-to-end convergence parity claim (ref README.md:39: same
    convergence as Adam)."""
    _, losses_1bit = run_train(onebit_config(freeze_step=5), steps=50)
    _, losses_adam = run_train(adam_config(), steps=50)
    assert losses_1bit[-1] < max(losses_adam[-1] * 3.0, 1e-3), \
        (losses_1bit[-1], losses_adam[-1])


def test_compressed_wire_is_bitpacked():
    """The compressed-phase program's gradient communication must be
    uint8 sign payloads — no dense fp32 grad allreduce may remain
    (the point of ref onebit_adam.py:372 disabling backward allreduce)."""
    model = SimpleModel(hidden_dim=DIM)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=onebit_config(freeze_step=1))
    batch = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x), make_stacked_batch(0))
    lowered = engine._onebit_compressed_jit.lower(
        engine.state, batch, jax.random.PRNGKey(0),
        jnp.float32(1e-2), jnp.float32(1.0))
    text = lowered.as_text()
    # the momentum collective: bit-packed uint8 all_gather
    assert "ui8" in text and "all_gather" in text
    # any surviving all_reduce must be scalar (loss pmean / norm vote);
    # a non-scalar one would be a dense gradient reduction
    import re
    operand_types = re.findall(
        r'"stablehlo\.all_reduce".*?\}\) : \(tensor<([^>]*)>', text, re.S)
    assert operand_types, "no all_reduce found (expected scalar votes)"
    for t in operand_types:
        assert not re.match(r"^\d", t), \
            f"dense grad allreduce survived: tensor<{t}>"


def test_worker_error_is_per_worker_state():
    """worker_error must carry a leading [dp] dim sharded over data —
    each worker owns its own error-feedback slice (ref allocates it per
    rank, onebit_adam.py:305). After compressed steps the slices must
    actually diverge (they see different local momenta)."""
    engine, _ = run_train(onebit_config(freeze_step=2), steps=10)
    werr = engine.state.opt_state.worker_error
    for leaf, p in zip(jax.tree_util.tree_leaves(werr),
                       jax.tree_util.tree_leaves(engine.state.params)):
        assert leaf.shape == (8,) + p.shape, (leaf.shape, p.shape)
        host = np.asarray(jax.device_get(leaf))
        assert not np.allclose(host[0], host[1]), \
            "worker error slices identical: per-worker feedback collapsed"


def test_resume_without_optimizer_states_rewarms(tmp_path):
    """Reloading past freeze_step with load_optimizer_states=False must
    re-enter warmup (fresh count=0, all-zero frozen variance would
    otherwise explode)."""
    engine, _ = run_train(onebit_config(freeze_step=3), steps=6)
    assert engine._onebit_compressed_active
    engine.save_checkpoint(str(tmp_path))
    engine.wait_for_checkpoint()

    model = SimpleModel(hidden_dim=DIM)
    engine2, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=onebit_config(freeze_step=3))
    engine2.load_checkpoint(str(tmp_path), load_optimizer_states=False)
    loss = engine2.train_batch(batch=make_stacked_batch(0))
    assert not engine2._onebit_compressed_active
    assert np.isfinite(float(jax.device_get(loss)))

    # with optimizer states the phase resumes compressed
    engine3, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=DIM).__class__(hidden_dim=DIM),
        model_parameters=SimpleModel(hidden_dim=DIM).params,
        config=onebit_config(freeze_step=3))
    engine3.load_checkpoint(str(tmp_path), load_optimizer_states=True)
    engine3.train_batch(batch=make_stacked_batch(0))
    assert engine3._onebit_compressed_active


def test_onebit_respects_lr_scheduler():
    """OnebitAdamState exposes an injectable learning_rate hyperparam
    so LR schedules apply (the reference reads group['lr'] each step)."""
    cfg = onebit_config(freeze_step=100)
    cfg["scheduler"] = {"type": "WarmupLR",
                        "params": {"warmup_min_lr": 0.0,
                                   "warmup_max_lr": 1e-2,
                                   "warmup_num_steps": 10}}
    model = SimpleModel(hidden_dim=DIM)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    p0 = jax.device_get(engine.state.params)
    engine.train_batch(batch=make_stacked_batch(0))
    p1 = jax.device_get(engine.state.params)
    # first warmup step: lr ~ 0 → params barely move
    delta = max(float(np.abs(np.asarray(a) - np.asarray(b)).max())
                for a, b in zip(jax.tree_util.tree_leaves(p0),
                                jax.tree_util.tree_leaves(p1)))
    assert delta < 1e-4, f"scheduler lr not applied (delta={delta})"


def test_onebit_fallback_single_worker():
    """With a trivial mesh gate miss (zero stage 2), the engine must
    fall back to the dynamic single-worker form and still train."""
    cfg = onebit_config(freeze_step=3,
                        zero_optimization={"stage": 2})
    engine, losses = run_train(cfg, steps=10)
    assert not engine._use_onebit_shardmap
    assert np.isfinite(losses).all()
