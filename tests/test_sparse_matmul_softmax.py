"""Standalone block-sparse MatMul/Softmax primitives vs dense reference
(parity target: ref `tests/unit/test_sparse_attention.py:163-239` —
sdd/dsd/dds x trans_a x trans_b sweep, softmax with masks, and the
end-to-end sdd->softmax->dsd attention composition)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (MatMul, Softmax,
                                                to_sparse, to_dense)

B, H, BLOCK = 2, 3, 16
R = C = 4   # block grid
M = R * BLOCK
K = 24


def _layout(seed=0, density=0.5):
    rng = np.random.RandomState(seed)
    lay = (rng.rand(H, R, C) < density).astype(np.int64)
    lay[:, 0, 0] = 1   # no empty layout
    return lay


def _dense_mask(lay):
    return np.kron(lay, np.ones((BLOCK, BLOCK)))  # [H, M, M]


@pytest.mark.parametrize("trans_a", [False, True])
@pytest.mark.parametrize("trans_b", [False, True])
def test_sdd_matches_dense(trans_a, trans_b):
    lay = _layout()
    rng = np.random.RandomState(1)
    a = jnp.asarray(rng.randn(B, H, *((K, M) if trans_a else (M, K))),
                    jnp.float32)
    b = jnp.asarray(rng.randn(B, H, *((M, K) if trans_b else (K, M))),
                    jnp.float32)
    out = MatMul(lay, BLOCK, "sdd", trans_a, trans_b)(a, b)
    ad = np.swapaxes(a, -1, -2) if trans_a else np.asarray(a)
    bd = np.swapaxes(b, -1, -2) if trans_b else np.asarray(b)
    ref = np.einsum("bhmk,bhkn->bhmn", ad, bd) * _dense_mask(lay)[None]
    got = to_dense(out, lay, BLOCK)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("trans_a", [False, True])
def test_dsd_matches_dense(trans_a):
    lay = _layout(2)
    rng = np.random.RandomState(3)
    a_dense = rng.randn(B, H, M, M) * _dense_mask(lay)[None]
    a_sparse = to_sparse(jnp.asarray(a_dense, jnp.float32), lay, BLOCK)
    b = jnp.asarray(rng.randn(B, H, M, K), jnp.float32)
    out = MatMul(lay, BLOCK, "dsd", trans_a=trans_a)(a_sparse, b)
    ad = np.swapaxes(a_dense, -1, -2) if trans_a else a_dense
    ref = np.einsum("bhmn,bhnk->bhmk", ad, np.asarray(b))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("trans_b", [False, True])
def test_dds_matches_dense(trans_b):
    lay = _layout(4)
    rng = np.random.RandomState(5)
    b_dense = rng.randn(B, H, M, M) * _dense_mask(lay)[None]
    b_sparse = to_sparse(jnp.asarray(b_dense, jnp.float32), lay, BLOCK)
    a = jnp.asarray(rng.randn(B, H, K, M), jnp.float32)
    out = MatMul(lay, BLOCK, "dds", trans_b=trans_b)(a, b_sparse)
    bd = np.swapaxes(b_dense, -1, -2) if trans_b else b_dense
    ref = np.einsum("bhkm,bhmn->bhkn", np.asarray(a), bd)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-4)


def test_softmax_matches_dense_with_masks():
    lay = _layout(6)
    rng = np.random.RandomState(7)
    scores = rng.randn(B, H, M, M).astype(np.float32)
    sp = to_sparse(jnp.asarray(scores), lay, BLOCK)
    kpm = np.where(rng.rand(B, M) < 0.2, -1e30, 0.0).astype(np.float32)
    am = np.where(rng.rand(M, M) < 0.1, -1e30, 0.0).astype(np.float32)
    out = Softmax(lay, BLOCK)(sp, scale=0.5, key_padding_mask=jnp.asarray(kpm),
                              attn_mask=jnp.asarray(am))
    mask = _dense_mask(lay)[None]
    dense = scores * 0.5 + kpm[:, None, None, :] + am[None, None]
    dense = np.where(mask > 0, dense, -np.inf)
    e = np.exp(dense - dense.max(-1, keepdims=True))
    e = np.where(np.isfinite(dense), e, 0.0)
    ref = e / np.maximum(e.sum(-1, keepdims=True), 1e-30)
    got = np.asarray(to_dense(out, lay, BLOCK))
    np.testing.assert_allclose(got * mask, ref * mask, rtol=1e-4,
                               atol=1e-5)


def test_softmax_mul_mode_and_empty_rows():
    lay = _layout(8)
    rng = np.random.RandomState(9)
    sp = to_sparse(jnp.asarray(rng.randn(B, H, M, M), jnp.float32),
                   lay, BLOCK)
    kpm = np.zeros((B, M), np.float32)   # mul-mode: 0 masks EVERYTHING
    out = Softmax(lay, BLOCK)(sp, key_padding_mask=jnp.asarray(kpm),
                              key_padding_mask_mode="mul")
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_attention_composition_grads():
    """sdd -> softmax -> dsd equals dense attention, and grads flow."""
    lay = _layout(10, density=0.6)
    d = 32
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(B, H, M, d), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, M, d), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, M, d), jnp.float32)
    sdd = MatMul(lay, BLOCK, "sdd", trans_b=True)
    sm = Softmax(lay, BLOCK)
    dsd = MatMul(lay, BLOCK, "dsd")

    def attn(q, k, v):
        return dsd(sm(sdd(q, k), scale=d ** -0.5), v)

    out = attn(q, k, v)
    mask = _dense_mask(lay)[None]
    s = np.einsum("bhmd,bhnd->bhmn", q, k) * d ** -0.5
    s = np.where(mask > 0, s, -np.inf)
    e = np.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhmn,bhnd->bhmd", p, np.asarray(v))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)

    g = jax.grad(lambda q: attn(q, k, v).sum())(q)
    assert np.isfinite(np.asarray(g)).all()
    gref = jax.grad(lambda q: jnp.sum(
        jnp.einsum("bhmn,bhnd->bhmd",
                   jax.nn.softmax(jnp.where(
                       jnp.asarray(mask) > 0,
                       jnp.einsum("bhmd,bhnd->bhmn", q, k) * d ** -0.5,
                       -jnp.inf), axis=-1), v)))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-4, atol=1e-4)
