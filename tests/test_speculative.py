"""Speculative decoding tests (ISSUE 18, inference/speculative.py).

Covers:
  * LOSSLESSNESS, the headline contract: at temperature 0 the
    speculative serving loop's per-request outputs are BIT-IDENTICAL
    to vanilla decode — with a perfect draft (100% acceptance), with a
    deliberately mismatched external draft (partial acceptance +
    rollbacks), and through EOS / max-tokens edge cases;
  * the modified-rejection-sampling acceptance math at temp > 0,
    statistically pinned in isolation (accept x~q with prob
    min(1, p/q), resample from norm(max(p-q, 0)) => the emitted
    distribution IS p), and its exactness corollary on device: a
    draft identical to the flagship is never rejected;
  * the HOTSYNC guard extended to the speculative loop: spec_block
    dispatches draft+verify rounds with ZERO host syncs, and the
    serving fence stays ONE fused device_get;
  * adaptive k: garbage drafts drive per-slot k to k_min and shrink
    the host's draft dispatch depth; perfect drafts keep k at the cap;
  * mixed-k continuous batching: slots at different accepted lengths
    with mid-round finishes still produce per-request streams
    identical to vanilla;
  * `speculative.enabled=false` (the default) leaves the engine
    byte-for-byte at vanilla behavior (no draft programs, no spec
    state keys, identical outputs);
  * the `speculative` monitor event schema and the tracker's
    drafted-vs-verified split (docs/monitoring.md EVTSCHEMA row).
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference import (InferenceConfigError, InferenceEngine,
                                     Request, ServingLoop)
from deepspeed_tpu.inference import speculative as spec_mod
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config


def _params(model):
    return model.init(jax.random.PRNGKey(0),
                      {"input_ids": np.zeros((1, 8), np.int32)})


def _inference_cfg(**speculative):
    block = {"max_slots": 4, "prefill_chunk": 16, "sync_every": 4,
             "max_new_tokens": 32,
             "kv_cache": {"num_pages": 120, "page_size": 4}}
    if speculative:
        block["speculative"] = dict({"enabled": True}, **speculative)
    return {"inference": block}


def _perturbed(params, scale, seed=99):
    """Flagship params with small noise on every block leaf: a draft
    that mostly agrees with the flagship but diverges often enough to
    exercise rejection + rollback."""
    r = np.random.RandomState(seed)
    blocks = jax.tree_util.tree_map(
        lambda x: x + scale * r.randn(*x.shape).astype(x.dtype),
        params["h"])
    return dict(params, h=blocks)


@pytest.fixture(scope="module")
def base():
    """One flagship + a vanilla engine and a truncate:1 speculative
    engine over the SAME params (the bit-identity pair)."""
    cfg = tiny_gpt2_config()
    model = GPT2ForCausalLM(cfg)
    params = _params(model)
    vanilla = InferenceEngine(cfg, params, _inference_cfg())
    spec = InferenceEngine(cfg, params, _inference_cfg(
        draft_model="truncate:1", k=4, k_min=1, adaptive=True))
    return cfg, model, params, vanilla, spec


@pytest.fixture(scope="module")
def ext(base):
    """A speculative engine whose EXTERNAL draft is the flagship with
    perturbed block weights: high-but-partial acceptance, so rollback
    and the correction path run on every request."""
    cfg, model, params, vanilla, _ = base
    engine = InferenceEngine(
        cfg, params, _inference_cfg(draft_model="external", k=3),
        draft_params=_perturbed(params, 0.01),
        draft_model_config=cfg)
    return cfg, vanilla, engine


def _serve(engine, reqs):
    engine.reset()
    res = ServingLoop(engine).serve(reqs)
    return {q.rid: (q.out_tokens.tolist(), q.finish_reason)
            for q in res}


def _mixed_requests(cfg, seed, n=7, eos=None):
    r = np.random.RandomState(seed)
    return [Request(rid=i,
                    tokens=r.randint(0, cfg.vocab_size,
                                     size=int(r.randint(3, 30))
                                     ).astype(np.int32),
                    max_new_tokens=int(r.randint(3, 14)),
                    eos_token_id=eos)
            for i in range(n)]


# ----------------------------------------------------------------------
# config validation
# ----------------------------------------------------------------------
def test_speculative_config_validation():
    cfg = tiny_gpt2_config()
    params = _params(GPT2ForCausalLM(cfg))
    for bad in ({"draft_model": "half"}, {"draft_model": "truncate:0"},
                {"draft_model": "truncate:x"}, {"k": 0},
                {"k": 2, "k_min": 3}):
        with pytest.raises(InferenceConfigError,
                           match="inference\\.speculative\\."):
            InferenceEngine(cfg, params, _inference_cfg(**bad))
    # truncate deeper than the flagship
    with pytest.raises(ValueError, match="only"):
        InferenceEngine(cfg, params,
                        _inference_cfg(draft_model="truncate:9"))
    # external without the weights
    with pytest.raises(ValueError, match="external"):
        InferenceEngine(cfg, params,
                        _inference_cfg(draft_model="external"))


def test_derive_draft_shares_embeddings_and_slices_blocks():
    cfg = tiny_gpt2_config()
    params = _params(GPT2ForCausalLM(cfg))
    dcfg, dparams = spec_mod.derive_draft(cfg, params, "truncate:1")
    assert dcfg.n_layer == 1 and cfg.n_layer == 2
    # wte/wpe/ln_f are SHARED (same buffers, zero new bytes)
    assert dparams["wte"] is params["wte"]
    assert dparams["wpe"] is params["wpe"]
    assert dparams["ln_f"] is params["ln_f"]
    (_, stacked), = params["h"].items()
    (_, sliced), = dparams["h"].items()
    full = jax.tree_util.tree_leaves(stacked)
    cut = jax.tree_util.tree_leaves(sliced)
    for f, c in zip(full, cut):
        assert c.shape[0] == 1 and f.shape[0] == 2
        assert np.array_equal(np.asarray(f[:1]), np.asarray(c))


# ----------------------------------------------------------------------
# acceptance math, in isolation
# ----------------------------------------------------------------------
def test_leading_accept_count():
    flags = jnp.asarray([[1, 1, 0, 1], [0, 1, 1, 1],
                         [1, 1, 1, 1], [0, 0, 0, 0]], bool)
    assert spec_mod.leading_accept_count(flags).tolist() == [2, 0, 4, 0]


def test_residual_distribution_properties():
    r = np.random.RandomState(0)
    p = r.dirichlet(np.ones(16), size=3).astype(np.float32)
    q = r.dirichlet(np.ones(16), size=3).astype(np.float32)
    res = np.asarray(spec_mod.residual_distribution(
        jnp.asarray(p), jnp.asarray(q)))
    np.testing.assert_allclose(res.sum(-1), 1.0, atol=1e-5)
    # support: only where p > q
    assert (res[p <= q] == 0).all()
    ref = np.maximum(p - q, 0)
    ref /= ref.sum(-1, keepdims=True)
    np.testing.assert_allclose(res, ref, atol=1e-6)
    # zero residual mass (p == q) degenerates to p, not NaN
    same = np.asarray(spec_mod.residual_distribution(
        jnp.asarray(p), jnp.asarray(p)))
    np.testing.assert_allclose(same, p, atol=1e-6)


def test_process_logits_matches_topk_mask_and_temperature():
    r = np.random.RandomState(1)
    l32 = r.randn(2, 16).astype(np.float32)
    out = np.asarray(spec_mod.process_logits(
        jnp.asarray(l32), jnp.asarray([2, 0], np.int32),
        jnp.asarray([0.5, 2.0], np.float32), top_k_cap=16))
    # slot 0: only the top-2 survive, scaled by 1/0.5
    kth = np.sort(l32[0])[-2]
    ref0 = np.where(l32[0] < kth, -np.inf, l32[0]) / 0.5
    np.testing.assert_allclose(out[0], ref0, atol=1e-6)
    # slot 1: top_k=0 disables the mask
    np.testing.assert_allclose(out[1], l32[1] / 2.0, atol=1e-6)


def test_modified_rejection_sampling_targets_p_statistically():
    """The losslessness theorem, pinned numerically: drawing x ~ q,
    accepting when u < p(x)/q(x), and resampling from
    norm(max(p - q, 0)) on rejection emits EXACTLY p. Mirrors the
    verify program's formulas (same accept rule, same residual)."""
    r = np.random.RandomState(2)
    vocab, n = 8, 200_000
    p = r.dirichlet(np.ones(vocab) * 2).astype(np.float64)
    q = r.dirichlet(np.ones(vocab) * 2).astype(np.float64)
    x = r.choice(vocab, size=n, p=q)
    u = r.rand(n)
    accept = u < (p[x] / q[x])
    res = np.asarray(spec_mod.residual_distribution(
        jnp.asarray(p[None].astype(np.float32)),
        jnp.asarray(q[None].astype(np.float32))))[0].astype(np.float64)
    res /= res.sum()
    corr = r.choice(vocab, size=n, p=res)
    emitted = np.where(accept, x, corr)
    empirical = np.bincount(emitted, minlength=vocab) / n
    # 200k draws: ~3-sigma bound on each bucket is ~0.0034
    np.testing.assert_allclose(empirical, p, atol=0.006)
    # sanity: the acceptance path was actually partial
    assert 0.05 < accept.mean() < 0.999


# ----------------------------------------------------------------------
# temp-0 bit-identity (the headline contract)
# ----------------------------------------------------------------------
def test_temp0_bitexact_perfect_draft(base):
    """truncate:1 draft, 7 mixed continuous-batched requests queued
    through 4 slots: every output token stream and finish reason is
    identical to vanilla decode."""
    cfg, model, params, vanilla, spec = base
    reqs = _mixed_requests(cfg, seed=31)
    want = _serve(vanilla, _mixed_requests(cfg, seed=31))
    got = _serve(spec, reqs)
    assert got == want


def test_temp0_bitexact_partial_acceptance(ext):
    """Mismatched external draft: acceptance is PARTIAL (rollbacks
    happen), yet the output is still bit-identical — rejection +
    correction + rollback never leak into the emitted stream."""
    cfg, vanilla, spec = ext
    for seed in (41, 42, 43):
        want = _serve(vanilla, _mixed_requests(cfg, seed=seed, n=5))
        got = _serve(spec, _mixed_requests(cfg, seed=seed, n=5))
        assert got == want, seed
    snap = spec.fetch_state()["speculative"]
    drafted = int(snap["drafted"].sum())
    accepted = int(snap["accepted"].sum())
    assert drafted > 0
    assert 0 < accepted < drafted, "draft must be partially accepted"
    assert int(snap["rollbacks"].sum()) > 0, \
        "a mismatched draft must trigger rejected-suffix rollbacks"


def test_temp0_bitexact_eos_and_budget_edges(ext):
    """EOS hit mid-round (inside an accepted prefix AND via the
    correction token) and max_new exhaustion mid-round both truncate
    identically to vanilla."""
    cfg, vanilla, spec = ext
    r = np.random.RandomState(55)
    prompt = r.randint(0, cfg.vocab_size, size=9).astype(np.int32)
    probe = _serve(vanilla, [Request(rid="p", tokens=prompt.copy(),
                                     max_new_tokens=12)])
    out = probe["p"][0]
    assert len(out) == 12
    # pick EOS ids that cut the stream at different round offsets
    for eos in (out[0], out[2], out[5], out[11]):
        reqs = lambda: [Request(rid="e", tokens=prompt.copy(),
                                max_new_tokens=12, eos_token_id=eos)]
        want = _serve(vanilla, reqs())
        got = _serve(spec, reqs())
        assert got == want, eos
        assert want["e"][1] == "eos"
    # budget edge: max_new smaller than one full round
    for m in (1, 2, 3):
        reqs = lambda: [Request(rid="b", tokens=prompt.copy(),
                                max_new_tokens=m)]
        assert _serve(spec, reqs()) == _serve(vanilla, reqs()), m


# ----------------------------------------------------------------------
# temp > 0
# ----------------------------------------------------------------------
def test_temp_positive_identical_draft_never_rejected(base):
    """Exactness corollary of the accept rule on DEVICE: truncate:2 of
    a 2-layer flagship IS the flagship, so p == q and
    u < p/q == 1 always — every draft accepted, zero rollbacks, even
    at high temperature."""
    cfg, model, params, vanilla, _ = base
    engine = InferenceEngine(cfg, params, _inference_cfg(
        draft_model="truncate:2", k=3, adaptive=False))
    r = np.random.RandomState(61)
    res = ServingLoop(engine).serve(
        [Request(rid=i, tokens=r.randint(0, cfg.vocab_size, size=7 + i),
                 max_new_tokens=10, temperature=1.2, top_k=32)
         for i in range(3)])
    assert all(len(q.out_tokens) == 10 for q in res)
    assert all(0 <= t < cfg.vocab_size
               for q in res for t in q.out_tokens)
    snap = engine.fetch_state()["speculative"]
    assert int(snap["drafted"].sum()) > 0
    assert int(snap["accepted"].sum()) == int(snap["drafted"].sum())
    assert int(snap["rollbacks"].sum()) == 0


def test_temp_positive_mismatched_draft_smoke(ext):
    """End-to-end at temp > 0 with a mismatched draft: valid tokens,
    partial acceptance, deterministic under the same seed (the
    rejection coins and correction draws ride the engine RNG)."""
    cfg, vanilla, spec = ext
    r = np.random.RandomState(62)
    prompt = r.randint(0, cfg.vocab_size, size=11).astype(np.int32)

    def run():
        spec.reset()
        return ServingLoop(spec).serve(
            [Request(rid="t", tokens=prompt.copy(), max_new_tokens=10,
                     temperature=0.9, top_k=16)])[0].out_tokens.tolist()

    a = run()
    assert a == run(), "same seed must replay the same stream"
    assert len(a) == 10 and all(0 <= t < cfg.vocab_size for t in a)
    snap = spec.fetch_state()["speculative"]
    assert 0 < int(snap["accepted"].sum()) <= int(snap["drafted"].sum())


# ----------------------------------------------------------------------
# HOTSYNC: the speculative loop stays sync-free
# ----------------------------------------------------------------------
class _SyncCounters:
    """Same instrumentation as tests/test_inference.py: count the
    host-sync entry points."""

    def __init__(self, monkeypatch):
        self.device_get = 0
        self.effects_barrier = 0
        real_get, real_barrier = jax.device_get, jax.effects_barrier

        def counting_get(x):
            self.device_get += 1
            return real_get(x)

        def counting_barrier():
            self.effects_barrier += 1
            return real_barrier()

        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "effects_barrier", counting_barrier)


def test_spec_block_zero_host_syncs(base, monkeypatch):
    """Draft chaining, device-side acceptance, adaptive-k updates —
    ALL of it without a single host<->device rendezvous between
    fences; the fence stays ONE fused device_get (now carrying the
    speculative counters too)."""
    cfg, model, params, vanilla, spec = base
    spec.reset()
    r = np.random.RandomState(71)
    for slot in range(3):
        prompt = r.randint(0, cfg.vocab_size,
                           size=6 + 3 * slot).astype(np.int32)
        spec.start_request(slot, prompt, max_new=24)
    spec.spec_block(2)      # warm the dispatch path
    counters = _SyncCounters(monkeypatch)
    for _ in range(3):
        spec.spec_block(2)
    assert counters.device_get == 0, \
        f"spec loop called jax.device_get {counters.device_get}x"
    assert counters.effects_barrier == 0
    snap = spec.fetch_state()
    assert counters.device_get == 1, \
        "the serving fence must stay exactly ONE device_get"
    assert snap["n_gen"][:3].min() > 0
    assert int(snap["speculative"]["drafted"].sum()) > 0
    spec.reset()


# ----------------------------------------------------------------------
# adaptive k
# ----------------------------------------------------------------------
def test_adaptive_k_backs_off_on_hopeless_draft(base):
    """A draft that NEVER matches the flagship (ln_f zeroed => its
    logits are identically 0, so it always proposes token 0) drives
    the per-slot k down to k_min and shrinks the host's draft dispatch
    depth, so the next block stops paying for dead draft steps."""
    cfg, model, params, vanilla, _ = base
    r = np.random.RandomState(81)
    prompt = r.randint(0, cfg.vocab_size, size=8).astype(np.int32)
    # precondition: the flagship's greedy stream never emits token 0,
    # so the constant-0 draft is rejected every single round
    vanilla.reset()
    ref = ServingLoop(vanilla).serve(
        [Request(rid="v", tokens=prompt.copy(), max_new_tokens=28)])[0]
    assert 0 not in ref.out_tokens.tolist()
    zero_head = dict(params, ln_f=jax.tree_util.tree_map(
        np.zeros_like, params["ln_f"]))
    engine = InferenceEngine(
        cfg, params, _inference_cfg(draft_model="external", k=4,
                                    k_min=1, adaptive=True),
        draft_params=zero_head, draft_model_config=cfg)
    engine.start_request(0, prompt, max_new=28)
    assert engine.spec_next_draft() == 4
    for _ in range(4):
        engine.spec_block(2)
        engine.fetch_state()
    snap = engine.fetch_state()
    assert int(snap["speculative"]["accepted"].sum()) == 0
    assert int(snap["speculative"]["k_slot"][0]) == 1
    assert engine.spec_next_draft() == 1
    engine.reset()
    # reset restores the optimistic depth
    assert engine.spec_next_draft() == 4


def test_adaptive_k_stays_at_cap_for_perfect_draft(base):
    cfg, model, params, vanilla, spec = base
    spec.reset()
    r = np.random.RandomState(82)
    spec.start_request(0, r.randint(0, cfg.vocab_size,
                                    size=8).astype(np.int32),
                       max_new=28)
    for _ in range(3):
        spec.spec_block(2)
        spec.fetch_state()
    snap = spec.fetch_state()
    assert int(snap["speculative"]["k_slot"][0]) == spec.config.spec_k
    assert spec.spec_next_draft() == spec.config.spec_k
    spec.reset()


# ----------------------------------------------------------------------
# mixed-k continuous batching (scheduler)
# ----------------------------------------------------------------------
def test_mixed_k_continuous_batching_mid_round_finish(ext):
    """Slots at different accepted lengths — a partial-acceptance
    draft guarantees heterogeneous per-slot commits — with tiny
    max_new requests finishing mid-round while others keep decoding,
    plus queueing past the slot count: the batch stays dense and
    every stream matches vanilla."""
    cfg, vanilla, spec = ext

    def reqs():
        r = np.random.RandomState(91)
        lens = [3, 17, 9, 24, 5, 12, 7, 20]
        news = [2, 13, 1, 9, 3, 11, 2, 6]    # 1- and 2-token finishers
        return [Request(rid=i,
                        tokens=r.randint(0, cfg.vocab_size,
                                         size=n).astype(np.int32),
                        max_new_tokens=m)
                for i, (n, m) in enumerate(zip(lens, news))]

    want = _serve(vanilla, reqs())
    got = _serve(spec, reqs())
    assert got == want
    assert sorted(len(v[0]) for v in got.values()) == \
        sorted([2, 13, 1, 9, 3, 11, 2, 6])


# ----------------------------------------------------------------------
# disabled by default: byte-for-byte vanilla
# ----------------------------------------------------------------------
def test_disabled_default_is_vanilla(base):
    cfg, model, params, vanilla, spec = base
    assert vanilla.speculative_enabled is False
    assert vanilla._draft_decode is None
    assert vanilla._verify is None
    assert vanilla._draft_prefill is None
    assert vanilla.cache.draft_n_layer == 0
    # explicit enabled=false is the same engine
    off = InferenceEngine(cfg, params, {"inference": dict(
        _inference_cfg()["inference"],
        speculative={"enabled": False, "k": 8})})
    assert off.speculative_enabled is False
    assert set(off._state.keys()) == set(vanilla._state.keys())
    snap = off.fetch_state()
    assert "speculative" not in snap
    want = _serve(vanilla, _mixed_requests(cfg, seed=101, n=4))
    got = _serve(off, _mixed_requests(cfg, seed=101, n=4))
    assert got == want


# ----------------------------------------------------------------------
# monitor event + tracker split
# ----------------------------------------------------------------------
def test_speculative_monitor_event_schema_and_tracker(tmp_path):
    cfg = tiny_gpt2_config()
    params = _params(GPT2ForCausalLM(cfg))
    engine = InferenceEngine(cfg, params, {
        "inference": {"max_slots": 2, "prefill_chunk": 8,
                      "sync_every": 4, "max_new_tokens": 16,
                      "kv_cache": {"num_pages": 48, "page_size": 4},
                      "speculative": {"enabled": True,
                                      "draft_model": "truncate:1"}},
        "monitor": {"enabled": True, "sinks": ["jsonl"],
                    "output_path": str(tmp_path)}})
    r = np.random.RandomState(111)
    ServingLoop(engine).serve(
        [Request(rid=f"r{i}", tokens=r.randint(0, cfg.vocab_size,
                                               size=6 + i),
                 max_new_tokens=8) for i in range(3)])
    trk = engine.tracker.snapshot()
    engine.monitor.close()
    events = []
    for root, _, files in os.walk(tmp_path):
        for f in files:
            if f.endswith(".jsonl"):
                with open(os.path.join(root, f)) as fh:
                    events += [json.loads(line) for line in fh]
    spec_events = [e for e in events if e["kind"] == "speculative"]
    assert spec_events, "serving fences must emit speculative events"
    keys = {"rounds", "drafted_tokens", "accepted_tokens",
            "acceptance_rate", "tokens_per_verify", "rollback_events",
            "rollback_pages", "mean_k", "draft_dispatch_ms",
            "verify_dispatch_ms"}
    for e in spec_events:
        assert keys <= set(e), keys - set(e)
    tot_drafted = sum(e["drafted_tokens"] for e in spec_events)
    tot_accepted = sum(e["accepted_tokens"] for e in spec_events)
    assert 0 < tot_accepted <= tot_drafted
    busy = [e for e in spec_events if e["acceptance_rate"] is not None]
    assert busy and all(0.0 <= e["acceptance_rate"] <= 1.0
                        for e in busy)
    assert all(e["tokens_per_verify"] >= 1.0 for e in busy
               if e["tokens_per_verify"] is not None)
    # the tracker carries the drafted-vs-verified dispatch split
    sp = trk["speculative"]
    assert sp["drafted_tokens"] == tot_drafted
    assert sp["accepted_tokens"] == tot_accepted
    assert sp["tokens_per_verify"] >= 1.0
    assert sp["draft_dispatch_s"] >= 0.0
    assert sp["verify_dispatch_s"] > 0.0
