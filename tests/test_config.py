"""Config tests (parity with ref tests/unit/test_config.py +
test_ds_config.py: batch triple resolution, duplicate keys, zero block)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.runtime.config_utils import load_config_dict


def base_config(**over):
    cfg = {"train_batch_size": 32, "gradient_accumulation_steps": 2}
    cfg.update(over)
    return cfg


def test_batch_triple_all_given():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4,
         "gradient_accumulation_steps": 2}, world_size=8)
    assert cfg.train_batch_size == 64
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 2


def test_batch_triple_infer_gas():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4},
        world_size=8)
    assert cfg.gradient_accumulation_steps == 2


def test_batch_triple_infer_micro():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 64, "gradient_accumulation_steps": 2},
        world_size=8)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_triple_infer_train():
    cfg = DeepSpeedConfig(
        {"train_micro_batch_size_per_gpu": 4,
         "gradient_accumulation_steps": 2}, world_size=8)
    assert cfg.train_batch_size == 64


def test_batch_triple_only_train():
    cfg = DeepSpeedConfig({"train_batch_size": 64}, world_size=8)
    assert cfg.train_micro_batch_size_per_gpu == 8
    assert cfg.gradient_accumulation_steps == 1


def test_batch_triple_mismatch_raises():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(
            {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 5,
             "gradient_accumulation_steps": 2}, world_size=8)


def test_batch_triple_none_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=8)


def test_duplicate_json_keys_rejected(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        load_config_dict(str(p))


def test_config_from_file(tmp_path):
    p = tmp_path / "ds.json"
    p.write_text(json.dumps(base_config()))
    cfg = DeepSpeedConfig(str(p), world_size=4)
    assert cfg.train_batch_size == 32
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_zero_config_defaults():
    cfg = DeepSpeedConfig(base_config(), world_size=1)
    assert cfg.zero_optimization_stage == 0
    assert not cfg.zero_enabled


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages(stage):
    cfg = DeepSpeedConfig(
        base_config(zero_optimization={"stage": stage}), world_size=1)
    assert cfg.zero_optimization_stage == stage
    assert cfg.zero_enabled == (stage > 0)


def test_zero_stage_too_high():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(
            base_config(zero_optimization={"stage": 4}), world_size=1)


def test_fp16_block():
    cfg = DeepSpeedConfig(
        base_config(fp16={"enabled": True, "loss_scale": 0,
                          "initial_scale_power": 16,
                          "loss_scale_window": 500, "hysteresis": 2,
                          "min_loss_scale": 1}), world_size=1)
    assert cfg.fp16_enabled
    assert cfg.initial_dynamic_scale == 2**16
    assert cfg.dynamic_loss_scale_args["scale_window"] == 500


def test_bf16_block():
    cfg = DeepSpeedConfig(base_config(bf16={"enabled": True}), world_size=1)
    assert cfg.bfloat16_enabled
    assert not cfg.fp16_enabled


def test_fp16_bf16_exclusive():
    with pytest.raises(AssertionError):
        DeepSpeedConfig(
            base_config(fp16={"enabled": True}, bf16={"enabled": True}),
            world_size=1)


def test_optimizer_scheduler_blocks():
    cfg = DeepSpeedConfig(
        base_config(
            optimizer={"type": "Adam", "params": {"lr": 0.015}},
            scheduler={"type": "WarmupLR",
                       "params": {"warmup_num_steps": 10}}), world_size=1)
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["lr"] == 0.015
    assert cfg.scheduler_name == "WarmupLR"


def test_gradient_clipping_key():
    cfg = DeepSpeedConfig(base_config(gradient_clipping=1.0), world_size=1)
    assert cfg.gradient_clipping == 1.0


def test_checkpoint_tag_validation_modes():
    cfg = DeepSpeedConfig(
        base_config(checkpoint={"tag_validation": "FAIL"}), world_size=1)
    assert cfg.checkpoint_tag_validation_enabled
    assert cfg.checkpoint_tag_validation_fail
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            base_config(checkpoint={"tag_validation": "bogus"}),
            world_size=1)


def test_amp_maps_to_bf16():
    """Apex AMP parity (ref config.py:66-77): amp.enabled engages bf16
    mixed precision on TPU and exposes amp_params."""
    import deepspeed_tpu
    from simple_model import SimpleModel
    m = SimpleModel()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m, model_parameters=m.params,
        config={"train_batch_size": 16,
                "amp": {"enabled": True, "opt_level": "O1"},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    assert engine.amp_enabled()
    assert engine.bfloat16_enabled()
    assert engine.amp_params() == {"opt_level": "O1"}
