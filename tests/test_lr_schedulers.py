"""LR schedule tests (parity with ref tests/unit/test_lr_schedulers.py)."""

import math

import pytest

from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle,
                                                WarmupLR, WarmupDecayLR,
                                                _OptimizerShim)


def test_warmup_lr_values():
    opt = _OptimizerShim(lr=0.0)
    sched = WarmupLR(opt, warmup_min_lr=0.0, warmup_max_lr=0.1,
                     warmup_num_steps=10)
    lrs = []
    for _ in range(15):
        sched.step()
        lrs.append(sched.get_last_lr()[0])
    # warmup is log-shaped, monotonic, reaching max at warmup_num_steps
    assert all(b >= a for a, b in zip(lrs, lrs[1:]))
    assert lrs[9] == pytest.approx(0.1)
    assert lrs[-1] == pytest.approx(0.1)


def test_warmup_decay_lr():
    opt = _OptimizerShim(lr=0.0)
    sched = WarmupDecayLR(opt, total_num_steps=20, warmup_min_lr=0.0,
                          warmup_max_lr=0.1, warmup_num_steps=10)
    lrs = []
    for _ in range(21):
        sched.step()
        lrs.append(sched.get_last_lr()[0])
    assert lrs[9] == pytest.approx(0.1)
    # linear decay after warmup, hitting 0 at iteration == total_num_steps
    assert lrs[-1] == pytest.approx(0.0, abs=1e-12)
    assert lrs[14] < lrs[9]


def test_lr_range_test_continuous():
    opt = _OptimizerShim(lr=0.0)
    sched = LRRangeTest(opt, lr_range_test_min_lr=0.01,
                        lr_range_test_step_size=5,
                        lr_range_test_step_rate=1.0)
    sched.step()
    first = sched.get_last_lr()[0]
    for _ in range(9):
        sched.step()
    later = sched.get_last_lr()[0]
    assert later > first
    # continuous growth: lr = min_lr * (1 + rate * it/step_size)
    assert later == pytest.approx(0.01 * (1 + 10 / 5))


def test_lr_range_test_staircase():
    opt = _OptimizerShim(lr=0.0)
    sched = LRRangeTest(opt, lr_range_test_min_lr=0.01,
                        lr_range_test_step_size=5,
                        lr_range_test_step_rate=1.0,
                        lr_range_test_staircase=True)
    vals = []
    for _ in range(10):
        sched.step()
        vals.append(sched.get_last_lr()[0])
    assert vals[0] == vals[3]  # flat within a stair
    assert vals[5] > vals[4] or vals[4] > vals[0]


def test_one_cycle_shape():
    opt = _OptimizerShim(lr=0.0)
    sched = OneCycle(opt, cycle_min_lr=0.01, cycle_max_lr=0.1,
                     cycle_first_step_size=10, cycle_momentum=False)
    lrs = []
    for _ in range(20):
        sched.step()
        lrs.append(sched.get_last_lr()[0])
    peak_idx = lrs.index(max(lrs))
    assert 8 <= peak_idx <= 11
    assert lrs[0] < lrs[peak_idx]
    assert lrs[-1] < lrs[peak_idx]


def test_scheduler_state_dict_roundtrip():
    opt = _OptimizerShim(lr=0.0)
    s1 = WarmupLR(opt, warmup_max_lr=0.1, warmup_num_steps=10)
    for _ in range(5):
        s1.step()
    sd = s1.state_dict()
    s2 = WarmupLR(_OptimizerShim(lr=0.0), warmup_max_lr=0.1,
                  warmup_num_steps=10)
    s2.load_state_dict(sd)
    s1.step()
    s2.step()
    assert s1.get_last_lr() == s2.get_last_lr()


def test_get_config_from_args():
    import argparse
    parser = argparse.ArgumentParser()
    parser = lr_schedules.add_tuning_arguments(parser)
    args = parser.parse_args(["--lr_schedule", "WarmupLR",
                              "--warmup_num_steps", "50"])
    config, err = lr_schedules.get_config_from_args(args)
    assert err is None
    assert config["type"] == "WarmupLR"
    assert config["params"]["warmup_num_steps"] == 50
