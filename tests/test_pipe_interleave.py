"""Interleaved (virtual-stage) zero-bubble 1F1B (ISSUE 6): the
Megatron-style schedule through the compiled executor — clock-table
invariants (completeness, chunk dataflow order, ring-channel FIFO,
buffer bounds, bubble reduction), engine-level BIT-EXACT parity with
plain 1F1B, eval path, checkpoint round-trip of the round-robin flat
layout, and config validation.

Runs on the 8-device virtual CPU mesh (pipe=4 x data=2)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.pipe.interp import (build_clock_tables,
                                               num_pipe_buffers)
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule
from deepspeed_tpu.runtime.pipe.schedule import InterleavedTrainSchedule

DIN, DOUT = 16, 8


def mse_loss(pred, labels):
    return jnp.mean((pred.astype(jnp.float32) -
                     labels.astype(jnp.float32)) ** 2)


# ----------------------------------------------------------------------
# schedule + clock tables
# ----------------------------------------------------------------------
@pytest.mark.parametrize("m,S,v", [(8, 4, 2), (4, 2, 2), (4, 4, 2),
                                   (8, 2, 4), (6, 3, 2), (12, 4, 3)])
def test_interleaved_tables_complete_and_ordered(m, S, v):
    t = build_clock_tables(m, S, num_virtual_stages=v)
    n_chunks = S * v
    scheds = [InterleavedTrainSchedule(m, S, s, v) for s in range(S)]
    fwd_tick, bwd_tick = {}, {}
    fcount = [0] * S
    bcount = [0] * S
    for tick in range(t["num_ticks"]):
        for s in range(S):
            if t["fwd_mb"][tick, s] >= 0:
                vidx, mb = scheds[s]._fwd_cm(fcount[s])
                fcount[s] += 1
                q = vidx * S + s
                # the chunk table carries the global chunk id and the
                # mb table the true microbatch id
                assert t["fwd_chunk"][tick, s] == q
                assert t["fwd_mb"][tick, s] == mb
                fwd_tick[(q, mb)] = tick
            if t["bwd_mb"][tick, s] >= 0:
                vidx, mb = scheds[s]._bwd_cm(bcount[s])
                bcount[s] += 1
                q = vidx * S + s
                assert t["bwd_chunk"][tick, s] == q
                assert t["bwd_mb"][tick, s] == mb
                bwd_tick[(q, mb)] = tick
    # every (chunk, microbatch) forwards and backwards exactly once
    assert set(fwd_tick) == {(q, mb) for q in range(n_chunks)
                             for mb in range(m)}
    assert set(bwd_tick) == set(fwd_tick)
    for mb in range(m):
        for q in range(n_chunks - 1):
            assert fwd_tick[(q, mb)] < fwd_tick[(q + 1, mb)], \
                "activation must flow down the chunk chain"
            assert bwd_tick[(q + 1, mb)] < bwd_tick[(q, mb)], \
                "cotangent must flow back up"
        for q in range(n_chunks):
            assert fwd_tick[(q, mb)] < bwd_tick[(q, mb)]


def test_interleaving_shrinks_the_bubble():
    """The point of virtual stages: fewer idle stage-time units.
    Wall in stage-units = ticks / v; at p=4, m=8, v=2 the analytic
    bubble drops from (p-1)/(m+p-1) toward (p-1)/(vm+p-1)."""
    m, S = 8, 4
    t1 = build_clock_tables(m, S, num_virtual_stages=1)
    t2 = build_clock_tables(m, S, num_virtual_stages=2)
    assert t2["num_ticks"] / 2 < t1["num_ticks"], \
        "interleaved wall (stage-units) must beat plain 1F1B"

    def bubble(t, v):
        busy = (t["fwd_mb"] >= 0).sum() + (t["bwd_mb"] >= 0).sum()
        return 1 - busy / (t["num_ticks"] * S)
    assert bubble(t2, 2) < bubble(t1, 1)


def test_interleaved_buffer_bound_holds():
    """In-flight forwards per (stage, chunk) never exceed the
    schedule's per-chunk bound, and buffer ids never collide among
    live microbatches."""
    for m, S, v in [(8, 4, 2), (8, 2, 4), (12, 4, 3)]:
        t = build_clock_tables(m, S, num_virtual_stages=v)
        scheds = [InterleavedTrainSchedule(m, S, s, v) for s in range(S)]
        for s in range(S):
            bound = scheds[s].per_chunk_buffers()
            live = {}
            fcount = bcount = 0
            for tick in range(t["num_ticks"]):
                if t["fwd_mb"][tick, s] >= 0:
                    vidx, mb = scheds[s]._fwd_cm(fcount)
                    fcount += 1
                    buf = t["fwd_buf"][tick, s]
                    assert buf not in live, "live buffer clobbered"
                    live[buf] = (vidx, mb)
                    assert sum(1 for (vv, _) in live.values()
                               if vv == vidx) <= bound
                if t["bwd_mb"][tick, s] >= 0:
                    vidx, mb = scheds[s]._bwd_cm(bcount)
                    bcount += 1
                    buf = t["bwd_buf"][tick, s]
                    assert live.pop(buf) == (vidx, mb)
            assert not live
        assert num_pipe_buffers(m, S, v) == max(
            sc.num_pipe_buffers() for sc in scheds)


def test_plain_tables_unchanged_by_generalization():
    """v=1 must produce the exact pre-interleaving tables: single
    delivery slot, no wrap-channel deliveries, mb == fwd ordinal."""
    t = build_clock_tables(8, 4, num_virtual_stages=1)
    assert t["channel_depth"] == 1
    assert not t["deliver_act"][:, 0].any()      # no wrap 3->0
    assert not t["deliver_grad"][:, -1].any()    # no wrap 0->3
    for s in range(4):
        col = t["fwd_mb"][:, s]
        assert (col[col >= 0] == np.arange(8)).all()


def test_schedule_requires_divisible_microbatches():
    with pytest.raises(ValueError):
        InterleavedTrainSchedule(6, 4, 0, 2)    # 6 % 4 != 0


# ----------------------------------------------------------------------
# engine-level parity
# ----------------------------------------------------------------------
def _hetero_layers():
    from deepspeed_tpu.models.gpt2 import GPT2Block, tiny_gpt2_config
    cfg = tiny_gpt2_config(n_layer=8, n_embd=32, n_head=4,
                           n_positions=32)
    return [LayerSpec(GPT2Block, cfg) for _ in range(8)], 32


def _build_engine(v, gas=8, pipe=4, seed=0, **cfg_over):
    layers = [LayerSpec(nn.Dense, 32), jnp.tanh, LayerSpec(nn.Dense, 32),
              LayerSpec(nn.Dense, 32), LayerSpec(nn.Dense, 32), jnp.tanh,
              LayerSpec(nn.Dense, 32), LayerSpec(nn.Dense, DOUT)]
    module = PipelineModule(layers, num_stages=pipe, loss_fn=mse_loss,
                            partition_method="uniform")
    rng = np.random.RandomState(seed)
    example = jnp.asarray(rng.randn(4, DIN), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(seed), example)
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"pipe": pipe, "data": 8 // pipe, "model": 1},
        "pipeline": {"num_virtual_stages": v},
    }
    cfg.update(cfg_over)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params, config=cfg)
    return engine


def _batch(gas, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(8 * gas, DIN).astype(np.float32)
    w = np.linspace(-1, 1, DIN * DOUT).reshape(DIN, DOUT) \
        .astype(np.float32)
    return {"x": x, "y": x @ w}


def test_interleaved_matches_plain_1f1b_bit_exact():
    """Same module, same init, same batches: v=2 executes the SAME
    microbatch computations with the same accumulation structure as
    plain 1F1B — train losses, eval loss and post-training parameters
    agree bit-for-bit over 4 steps."""
    e1 = _build_engine(1)
    e2 = _build_engine(2)
    assert e2._pipe_virtual_stages == 2
    for i in range(4):
        l1 = float(jax.device_get(e1.train_batch(batch=_batch(8, i))))
        l2 = float(jax.device_get(e2.train_batch(batch=_batch(8, i))))
        assert l1 == l2, (i, l1, l2)
    ev1 = float(jax.device_get(e1.eval_batch(batch=_batch(8, 100))))
    ev2 = float(jax.device_get(e2.eval_batch(batch=_batch(8, 100))))
    assert ev1 == ev2
    for a, b in zip(jax.tree_util.tree_leaves(e1.module_params),
                    jax.tree_util.tree_leaves(e2.module_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_interleaved_checkpoint_roundtrip(tmp_path):
    """The round-robin flat layout (stage s stores chunks {s, s+S})
    must save/reload through the per-layer checkpoint path."""
    e = _build_engine(2)
    for i in range(2):
        e.train_batch(batch=_batch(8, i))
    e.save_checkpoint(str(tmp_path), tag="ck")
    e.wait_for_checkpoint()
    before = jax.device_get(e.module_params)
    e2 = _build_engine(2, seed=1)
    e2.load_checkpoint(str(tmp_path), tag="ck")
    after = jax.device_get(e2.module_params)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_virtual_stages_config_validation():
    # gas not divisible by stage count
    with pytest.raises(ValueError):
        _build_engine(2, gas=6)
    # too few layers for S*v chunks (8 layers < 4*4)
    with pytest.raises(ValueError):
        _build_engine(4, gas=8, pipe=4)
    # malformed config value
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "gradient_accumulation_steps": 1,
                         "pipeline": {"num_virtual_stages": 0}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "gradient_accumulation_steps": 1,
                         "pipeline": {"num_virtual_stages": "two"}})


def test_virtual_stages_refused_without_compiled_1f1b():
    """Review fix: num_virtual_stages > 1 on a pipe=1 mesh (or any
    path that cannot interleave) must raise instead of silently
    training uninterleaved."""
    with pytest.raises(ValueError):
        _build_engine(2, gas=8, pipe=1,
                      mesh={"pipe": 1, "data": 8, "model": 1})
