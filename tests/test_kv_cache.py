"""PagedKVCache rollback tests (ISSUE 18 satellite).

The rejected-suffix rollback of speculative decoding is pure host
accounting — no page data moves — so these tests pin the allocator
invariants speculation leans on, independent of any engine:

  * rewinding `kv_limit` across a page boundary releases exactly the
    tail pages and resets their table columns to the scratch page;
  * re-advancing into a previously-rolled-back region pops the SAME
    physical pages into the SAME table columns (the LIFO free list's
    reversed() push is what guarantees it);
  * ledger byte accounting after rollback: the `kv_cache` (and, with
    a draft attached, `kv_cache_draft`) category totals stay equal to
    their pool bytes through arbitrary rollback/regrow churn;
  * a rollback that trims nothing is a true no-op (no table_version
    bump, so the engine skips the device table upload).
"""

import numpy as np
import pytest

from deepspeed_tpu.inference import PagedKVCache
from deepspeed_tpu.monitor.memory import CAT_KV, CAT_KV_DRAFT, MemoryLedger


def _cache(ledger=None, draft_layers=0):
    cache = PagedKVCache(n_layer=2, n_head=4, head_dim=16,
                         num_pages=32, page_size=4, max_slots=4,
                         max_pages_per_slot=8, dtype=np.float32,
                         ledger=ledger)
    if draft_layers:
        cache.attach_draft(draft_layers)
    return cache


@pytest.mark.parametrize("tokens_before,tokens_after,freed", [
    (10, 5, 1),    # 3 pages -> 2: rewind crosses one page boundary
    (16, 1, 3),    # deep rewind to the first page
    (13, 12, 1),   # one token back across the 12|13 boundary
    (13, 9, 1),    # both land in page 3's span -> only page 4 goes
    (8, 8, 0),     # same count: nothing to trim
    (8, 11, 0),    # "rollback" forward never frees (ensure grows)
])
def test_rollback_releases_exact_tail_pages(tokens_before, tokens_after,
                                            freed):
    cache = _cache()
    cache.admit(0, 17, name="a")
    cache.ensure(0, tokens_before)
    before_pages = list(cache.tables[0])
    n_before = cache.allocated_pages(0)
    ver = cache.table_version
    got = cache.rollback(0, tokens_after)
    assert got == freed
    assert cache.allocated_pages(0) == n_before - freed
    keep = cache.pages_for_tokens(min(tokens_after, tokens_before))
    # kept columns untouched, trimmed columns back to scratch page 0
    assert list(cache.tables[0][:keep]) == before_pages[:keep]
    assert (cache.tables[0][n_before - freed:] == 0).all()
    if freed == 0:
        assert cache.table_version == ver, \
            "a no-op rollback must not bump table_version"
    else:
        assert cache.table_version == ver + 1


def test_readvance_reassigns_same_pages_same_columns():
    """LIFO regrowth: after a rollback, growing the SAME slot back
    re-pops the very pages that were trimmed, page-for-page, so the
    device table row is bit-identical to before the rollback — the
    property that lets speculation skip any K/V copying."""
    cache = _cache()
    cache.admit(0, 24, name="a")
    cache.ensure(0, 23)                   # 6 pages
    row_before = list(cache.tables[0])
    cache.rollback(0, 6)                  # keep 2, free 4
    assert cache.allocated_pages(0) == 2
    cache.ensure(0, 23)
    assert list(cache.tables[0]) == row_before
    # repeated churn at a different depth, same invariant
    cache.rollback(0, 17)
    cache.ensure(0, 21)
    assert list(cache.tables[0]) == row_before


def test_rollback_interleaved_with_other_slots():
    """Rollback's freed pages are ordinary free-list pages: another
    slot may take them, after which regrowth gets different physical
    pages — tables stay consistent and no page is double-assigned."""
    cache = _cache()
    cache.admit(0, 16, name="a")
    cache.admit(1, 16, name="b")
    cache.ensure(0, 16)
    cache.rollback(0, 4)                  # frees 3 of slot 0's pages
    cache.ensure(1, 12)                   # slot 1 adopts them (LIFO)
    cache.ensure(0, 16)                   # slot 0 regrows from elsewhere
    a = [p for p in cache.tables[0] if p != 0]
    b = [p for p in cache.tables[1] if p != 0]
    assert len(a) == 4 and len(b) == 3
    assert not set(a) & set(b), "a physical page leaked to two slots"


def test_rollback_ledger_accounting_with_draft_category():
    """Through rollback/regrow churn both ledger categories keep
    total == pool bytes, and the per-request entries track the page
    count in each category's own page-byte unit."""
    ledger = MemoryLedger()
    cache = _cache(ledger=ledger, draft_layers=1)
    # independent arithmetic: flagship 2 layers, draft 1 layer
    page_bytes = 2 * 2 * 4 * 4 * 16 * 4
    draft_page_bytes = 2 * 1 * 4 * 4 * 16 * 4
    assert cache.page_bytes == page_bytes
    assert cache.draft_page_bytes == draft_page_bytes

    def totals():
        t = ledger.totals()["hbm"]
        return t.get(CAT_KV, 0), t.get(CAT_KV_DRAFT, 0)

    assert totals() == (cache.pool_bytes, cache.draft_pool_bytes)
    cache.admit(0, 17, name="a")
    cache.ensure(0, 15)                   # 4 pages
    assert totals() == (cache.pool_bytes, cache.draft_pool_bytes)
    tops = {(b["category"], b["name"]): b["bytes"]
            for b in ledger.top_buffers(32)}
    assert tops[(CAT_KV, "request.s0.a")] == 4 * page_bytes
    assert tops[(CAT_KV_DRAFT, "request.s0.a")] == 4 * draft_page_bytes
    cache.rollback(0, 6)                  # 4 pages -> 2
    assert totals() == (cache.pool_bytes, cache.draft_pool_bytes)
    tops = {(b["category"], b["name"]): b["bytes"]
            for b in ledger.top_buffers(32)}
    assert tops[(CAT_KV, "request.s0.a")] == 2 * page_bytes
    assert tops[(CAT_KV_DRAFT, "request.s0.a")] == 2 * draft_page_bytes
    cache.ensure(0, 17)
    assert totals() == (cache.pool_bytes, cache.draft_pool_bytes)
    cache.free(0)
    assert totals() == (cache.pool_bytes, cache.draft_pool_bytes)
    tops = {b["name"] for b in ledger.top_buffers(32)}
    assert "request.s0.a" not in tops


def test_rollback_unadmitted_slot_raises():
    cache = _cache()
    with pytest.raises(ValueError, match="not admitted"):
        cache.rollback(2, 4)
