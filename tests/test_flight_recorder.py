"""Flight recorder (ISSUE 7 tentpole b).

Acceptance subprocess runs: a training loop that STALLS (watchdog
fire) and one that RAISES (uncaught train_batch exception) — plus a
SIGTERM'd run — each leave an atomic `flight_<ts>.json` containing the
last monitor events, per-subsystem heartbeat ages, and (for an
injected per-layer NaN) the correct first-NaN layer attribution.
Plus in-process unit coverage: bounded ring, atomic dump format,
terminal-heartbeat handling, crash-path dump from train_batch.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.monitor.flight import (FLIGHT_SCHEMA_VERSION,
                                          FlightRecorder,
                                          list_flight_dumps)
from simple_model import SimpleModel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# unit behavior
# ----------------------------------------------------------------------
def test_ring_is_bounded_and_dump_is_atomic(tmp_path):
    rec = FlightRecorder(out_dir=str(tmp_path), capacity=5, rank=1,
                         step_fn=lambda: 42,
                         heartbeats_fn=lambda: ({"prefetch": 1.5},
                                                ["ckpt"]))
    for i in range(20):
        rec.record({"kind": "metrics", "step": i})
    rec.set_context(numerics={"first_nonfinite": None})
    path = rec.dump("test", extra={"why": "unit"})
    assert path and os.path.exists(path)
    assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]
    doc = json.load(open(path))
    assert doc["v"] == FLIGHT_SCHEMA_VERSION
    assert doc["reason"] == "test" and doc["rank"] == 1
    assert doc["step"] == 42
    assert len(doc["events"]) == 5                    # bounded ring
    assert [e["step"] for e in doc["events"]] == list(range(15, 20))
    assert doc["heartbeat_age_sec"] == {"prefetch": 1.5}
    assert doc["terminal_subsystems"] == ["ckpt"]
    assert doc["extra"] == {"why": "unit"}
    assert "numerics" in doc["context"]
    assert list_flight_dumps(str(tmp_path)) == [path]
    rec.disarm()


def test_dump_survives_unwritable_dir():
    rec = FlightRecorder(out_dir="/proc/definitely/not/writable")
    rec.record({"kind": "metrics"})
    assert rec.dump("test") is None     # swallowed, never raises
    rec.disarm()


# ----------------------------------------------------------------------
# in-process engine wiring
# ----------------------------------------------------------------------
def _mk_batch(seed, bs=16, dim=8):
    rng = np.random.RandomState(seed)
    x = rng.randn(bs, dim).astype(np.float32)
    return {"x": x[None], "y": (x * 0.5)[None]}


def test_train_batch_exception_dumps_flight(tmp_path):
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={"train_batch_size": 16, "steps_per_print": 10000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "async_dispatch": {"enabled": True, "steps_per_sync": 1},
                "monitor": {"enabled": True, "sinks": [],
                            "output_path": str(tmp_path)}})
    for i in range(3):
        engine.train_batch(batch=_mk_batch(i))
    with pytest.raises(AssertionError):
        # stacked leading dim != gas -> the step-loop assertion fires
        bad = {k: np.concatenate([v, v]) for k, v in
               _mk_batch(99).items()}
        engine.train_batch(batch=bad)
    dumps = list_flight_dumps(str(tmp_path))
    assert dumps, "no flight dump after an uncaught exception"
    doc = json.load(open(dumps[-1]))
    assert doc["reason"] == "exception"
    assert doc["step"] == 3
    kinds = [e.get("kind") for e in doc["events"]]
    assert "crash" in kinds and "metrics" in kinds
    crash = [e for e in doc["events"] if e.get("kind") == "crash"][-1]
    assert "AssertionError" in crash["error"]
    engine.monitor.close()


def test_clean_close_disarms_and_double_crash_dumps_once(tmp_path):
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={"train_batch_size": 16, "steps_per_print": 10000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "monitor": {"enabled": True, "sinks": [],
                            "output_path": str(tmp_path)}})
    engine.train_batch(batch=_mk_batch(0))
    assert engine.monitor.flight.armed
    engine.monitor.close()
    assert not engine.monitor.flight.armed


def test_finished_prefetch_goes_terminal_not_stalled(tmp_path):
    """ISSUE 7 satellite: after the loader exhausts, the prefetch
    worker exits cleanly — its heartbeat must go TERMINAL (excluded
    from the stall verdict's age table) instead of aging forever."""
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={"train_batch_size": 16, "steps_per_print": 10000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "monitor": {"enabled": True, "sinks": [],
                            "output_path": str(tmp_path),
                            "stall_timeout_sec": 30}})
    micro = [{k: v[0] for k, v in _mk_batch(i).items()}
             for i in range(4)]
    loader = engine.prefetch(iter(micro))
    for _ in range(4):
        engine.train_batch(data_iter=loader)
    deadline = time.time() + 5.0
    while time.time() < deadline:
        ages, terminal = engine.monitor._heartbeat_state()
        if "prefetch" in terminal:
            break
        time.sleep(0.05)
    ages, terminal = engine.monitor._heartbeat_state()
    assert "prefetch" in terminal
    assert "prefetch" not in ages
    diag = engine.monitor.watchdog._diagnose(time.monotonic(), 1.0)
    assert "prefetch" not in diag["heartbeat_age_sec"]
    assert "prefetch" in diag["terminal_subsystems"]
    # a NEW loader revives the subsystem
    loader2 = engine.prefetch(iter(micro))
    engine.train_batch(data_iter=loader2)
    ages, terminal = engine.monitor._heartbeat_state()
    assert "prefetch" in ages and "prefetch" not in terminal
    loader.close()
    loader2.close()
    engine.monitor.close()


# ----------------------------------------------------------------------
# subprocess acceptance runs
# ----------------------------------------------------------------------
_CHILD_PRELUDE = r"""
import os, sys, json
import numpy as np
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, 'tests'))
import deepspeed_tpu
from simple_model import SimpleModel

def mk(seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(16, 8).astype(np.float32)
    return {{"x": x[None], "y": (x * 0.5)[None]}}

def engine(outdir, **mon):
    model = SimpleModel(hidden_dim=8)
    cfg = {{"train_batch_size": 16, "steps_per_print": 10000,
           "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
           "async_dispatch": {{"enabled": True, "steps_per_sync": 1}},
           "monitor": dict({{"enabled": True, "sinks": ["jsonl"],
                            "output_path": outdir}}, **mon)}}
    e, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    return e
"""


def _run_child(body, tmp_path, timeout=240, expect_rc=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    script = _CHILD_PRELUDE.format(repo=REPO) + body
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True,
                          timeout=timeout)
    if expect_rc is not None:
        assert proc.returncode == expect_rc, \
            (proc.returncode, proc.stderr[-2000:])
    return proc


def test_subprocess_stall_leaves_flight_dump(tmp_path):
    """A run that stops stepping trips the watchdog; the process is
    killed while stalled — the flight dump left behind explains its
    final seconds (last events + heartbeat ages)."""
    out = str(tmp_path / "mon")
    body = f"""
e = engine({out!r}, stall_timeout_sec=0.6)
e.monitor.watchdog._poll = 0.05
micro = [{{k: v[0] for k, v in mk(i).items()}} for i in range(4)]
loader = e.prefetch(iter(micro))
for i in range(4):
    e.train_batch(data_iter=loader)
import time
time.sleep(3.0)        # mid-training stall: the loop stops stepping
os._exit(7)            # die WITHOUT cleanup, like a wedged run killed
"""
    _run_child(body, tmp_path, expect_rc=7)
    dumps = list_flight_dumps(out)
    assert dumps, "stalled subprocess left no flight dump"
    doc = json.load(open(dumps[-1]))
    assert doc["reason"] == "stall"
    assert doc["step"] == 4
    assert doc["extra"]["fence_age_sec"] >= 0.6
    kinds = [e.get("kind") for e in doc["events"]]
    assert "metrics" in kinds and "stall" in kinds
    # the finished prefetch worker reads as terminal, not as the stall
    assert "prefetch" in doc["terminal_subsystems"]
    assert "prefetch" not in doc["heartbeat_age_sec"]
    # the stall event itself also reached the JSONL sink
    events = [json.loads(line) for line in
              open(os.path.join(out, "events.jsonl"))]
    assert any(ev["kind"] == "stall" for ev in events)


def test_subprocess_raise_with_nan_injection_attributes_layer(tmp_path):
    """A run that raises mid-training dumps the flight ring — and with
    monitor.numerics on and a NaN-producing layer injected, the dump's
    context names the first-NaN layer (the acceptance criterion)."""
    out = str(tmp_path / "mon")
    body = f"""
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

def bad(x):
    # NaN injection: finite input, nonfinite output
    return x + jnp.log(-jnp.ones_like(x))

layers = [LayerSpec(nn.Dense, 16), jnp.tanh, bad, LayerSpec(nn.Dense, 8)]
module = PipelineModule(layers, num_stages=1,
                        loss_fn=lambda y, lab: jnp.mean(
                            (y - lab[..., :8]) ** 2))
params = module.init_params(jax.random.PRNGKey(0),
                            jnp.zeros((16, 8), jnp.float32))
cfg = {{"train_batch_size": 16, "steps_per_print": 10000,
       "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
       "async_dispatch": {{"enabled": True, "steps_per_sync": 1}},
       "mesh": {{"pipe": 1, "data": 1, "model": 1}},
       "monitor": {{"enabled": True, "sinks": ["jsonl"],
                   "output_path": {out!r},
                   "numerics": {{"enabled": True}}}}}}
e, _, _, _ = deepspeed_tpu.initialize(model=module,
                                      model_parameters=params,
                                      config=cfg)
for i in range(3):
    e.train_batch(batch=mk(i))
e.train_batch(batch="not a batch")   # mid-training crash
"""
    proc = _run_child(body, tmp_path)
    assert proc.returncode != 0
    dumps = list_flight_dumps(out)
    assert dumps, "raising subprocess left no flight dump"
    docs = [json.load(open(p)) for p in dumps]
    # the crash dump (an armed-at-exit recorder also dumps at atexit)
    by_reason = [d for d in docs if d["reason"] == "exception"]
    assert by_reason, [d["reason"] for d in docs]
    doc = by_reason[-1]
    kinds = [e.get("kind") for e in doc["events"]]
    assert "crash" in kinds and "numerics" in kinds
    # the injected NaN is attributed to the INJECTED layer: boundary 2
    # (Dense and tanh outputs are finite; `bad`'s output is not)
    first = doc["context"]["first_nonfinite"]
    assert first["kind"] == "activation"
    assert first["name"].startswith("layer2:"), first
    num = doc["context"]["numerics"]
    assert num["act_nonfinite"][first["name"]] > 0
    # and the numerics event stream carried the same attribution
    events = [json.loads(line) for line in
              open(os.path.join(out, "events.jsonl"))]
    num_events = [ev for ev in events if ev["kind"] == "numerics"]
    assert num_events
    assert num_events[0]["first_nonfinite"]["name"].startswith("layer2:")


def test_subprocess_sigterm_leaves_flight_dump(tmp_path):
    """SIGTERM mid-training: the module-level handler dumps every live
    recorder before the default disposition kills the process."""
    out = str(tmp_path / "mon")
    body = f"""
import signal
assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL
e = engine({out!r})
for i in range(3):
    e.train_batch(batch=mk(i))
print("READY", flush=True)
import time
time.sleep(30)
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    script = _CHILD_PRELUDE.format(repo=REPO) + body
    proc = subprocess.Popen([sys.executable, "-c", script], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 180
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "READY" in line or not line:
                break
        assert "READY" in line, line
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc != 0
    dumps = list_flight_dumps(out)
    assert dumps, "SIGTERM'd subprocess left no flight dump"
    doc = json.load(open(dumps[-1]))
    assert doc["reason"] in ("sigterm", "atexit")
    assert doc["step"] == 3
