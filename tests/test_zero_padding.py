"""ZeRO pad-to-divisible sharding (VERDICT r1 #8; parity target: ref
`stage1.py:198-261` sub-partition alignment padding).

Leaves whose dims don't divide the dp size must not silently replicate
their master/moments: the policy pads them on the largest free dim and
the engine keeps the padded ("encoded") layout for the sharded state
groups while params and checkpoints keep true shapes."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.runtime.mesh import build_mesh, DATA_AXIS
from deepspeed_tpu.runtime.zero.partition import ZeroShardingPolicy
from simple_model import SimpleModel

# 20 % 8 != 0 → every SimpleModel leaf needs padding at dp=8
DIM = 20
BS = 16


def ds_config(stage, dtype="bf16"):
    cfg = {
        "train_batch_size": BS,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
        "zero_optimization": {"stage": stage},
    }
    if dtype == "bf16":
        cfg["bf16"] = {"enabled": True}
    return cfg


def make_batch(seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(BS, DIM).astype(np.float32)
    w = np.linspace(-1, 1, DIM * DIM).reshape(DIM, DIM).astype(np.float32)
    return {"x": x[None], "y": (x @ w)[None]}


def make_engine(stage, dtype="bf16"):
    model = SimpleModel(hidden_dim=DIM)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=ds_config(stage, dtype))
    return engine


def test_pad_plan_targets_only_odd_leaves(mesh8):
    policy = ZeroShardingPolicy(mesh8, stage=2)
    params = {"odd": jnp.zeros((20, 20)),       # no dim % 8 == 0
              "even": jnp.zeros((16, 20)),      # dim0 divisible
              "tiny": jnp.zeros((3,))}          # below threshold
    plan = policy.pad_plan(params)
    assert set(plan) == {"['odd']"}, plan
    dim, padded, true = plan["['odd']"]
    assert (padded, true) == (24, 20) and dim in (0, 1)


def test_encode_decode_roundtrip(mesh8):
    policy = ZeroShardingPolicy(mesh8, stage=2)
    params = {"odd": jnp.arange(400, dtype=jnp.float32).reshape(20, 20)}
    plan = policy.pad_plan(params)
    enc = policy.encode(params, plan)
    assert enc["odd"].shape in ((24, 20), (20, 24))
    dec = policy.decode(enc, plan)
    np.testing.assert_array_equal(np.asarray(dec["odd"]),
                                  np.asarray(params["odd"]))


def test_master_and_moments_shard_despite_odd_dims():
    engine = make_engine(stage=2)
    assert engine._zero_pad_plan, "expected padding for 20x20 at dp=8"
    w_master = engine.state.master["w"]
    assert 24 in w_master.shape, w_master.shape
    # genuinely sharded: per-device shard holds 1/8 of the padded leaf
    shard = w_master.addressable_shards[0]
    assert np.prod(shard.data.shape) == np.prod(w_master.shape) // 8, \
        (shard.data.shape, w_master.shape)
    # optimizer moments follow the same layout
    mus = [l for l in jax.tree_util.tree_leaves(engine.state.opt_state)
           if getattr(l, "shape", ()) == w_master.shape]
    assert mus, "no moment leaf in padded master shape"
    assert np.prod(mus[0].addressable_shards[0].data.shape) == \
        np.prod(w_master.shape) // 8
    # compute-dtype params keep TRUE shapes
    assert engine.state.params["w"].shape == (DIM, DIM)
    # total optimizer-state bytes per device ~ total/dp (the ZeRO claim)
    total = sum(np.prod(l.shape) for l in
                jax.tree_util.tree_leaves(engine.state.master))
    per_dev = sum(np.prod(l.addressable_shards[0].data.shape) for l in
                  jax.tree_util.tree_leaves(engine.state.master))
    assert per_dev <= total / 8 + 1e-9, (per_dev, total)


def test_padded_training_matches_unpadded():
    """Padding must be a pure layout change: stage-2 (padded) training
    equals stage-0 (replicated, unpadded) training."""
    def run(stage):
        engine = make_engine(stage)
        losses = []
        for i in range(6):
            loss = engine.train_batch(batch=make_batch(i % 3))
            losses.append(float(jax.device_get(loss)))
        return losses, jax.device_get(engine.fp32_params)

    losses0, params0 = run(0)
    losses2, params2 = run(2)
    np.testing.assert_allclose(losses0, losses2, rtol=2e-3)
    for a, b in zip(jax.tree_util.tree_leaves(params0),
                    jax.tree_util.tree_leaves(params2)):
        assert a.shape == b.shape  # fp32_params decodes padding
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=1e-5)


def test_checkpoint_elastic_across_padding(tmp_path):
    """Checkpoints store TRUE shapes: a padded stage-2 save must reload
    both into another padded stage-2 engine and into an unpadded
    stage-0 engine."""
    engine = make_engine(stage=2)
    for i in range(4):
        engine.train_batch(batch=make_batch(i))
    ref = jax.device_get(engine.fp32_params)
    engine.save_checkpoint(str(tmp_path))
    engine.wait_for_checkpoint()

    for stage in (2, 0):
        e2 = make_engine(stage=stage)
        e2.load_checkpoint(str(tmp_path))
        got = jax.device_get(e2.fp32_params)
        for a, b in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)
        # training continues healthily after reload
        loss = e2.train_batch(batch=make_batch(9))
        assert np.isfinite(float(jax.device_get(loss)))


def test_pad_plan_respects_tp_claimed_dims(mesh8):
    """A dim already claimed by the model axis must not be chosen as
    the padding dim (padding composes with tensor parallelism)."""
    from jax.sharding import PartitionSpec as P
    params = {"w": jnp.zeros((20, 24))}
    specs = {"w": P("model", None)}          # dim0 is TP-claimed
    policy = ZeroShardingPolicy(mesh8, stage=2, param_specs=specs)
    plan = policy.pad_plan(params)
    # dim1=24 % 8 == 0 -> divisible free dim exists, no padding at all
    assert plan == {}
    params = {"w": jnp.zeros((20, 20))}
    specs = {"w": P("model", None)}
    policy = ZeroShardingPolicy(mesh8, stage=2, param_specs=specs)
    plan = policy.pad_plan(params)
    (dim, padded, true), = plan.values()
    assert dim == 1 and (padded, true) == (24, 20)


def test_compose_fallback_warns(monkeypatch):
    """ADVICE r5: a leaf whose model-sharded dim divides mp but NOT
    mp*dp silently loses the (model, data) composed sharding — the
    policy must say so (the regression is invisible in numerics; it
    only shows as per-device memory no longer dividing by dp)."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.runtime.mesh import build_mesh
    from deepspeed_tpu.utils.logging import logger

    mesh = build_mesh({"pipe": 1, "data": 4, "model": 2})
    warnings = []
    monkeypatch.setattr(logger, "warning",
                        lambda msg, *a: warnings.append(msg % a if a else msg))

    # dim1=6: % mp(2) == 0 so it is model-sharded, but % mp*dp(8) != 0
    # -> compose fails; dim0=3 offers no free dp dim; numel 18 >= 2*dp
    params = {"w": jnp.zeros((3, 6))}
    policy = ZeroShardingPolicy(mesh, stage=2,
                                param_specs={"w": P(None, "model")})
    specs = policy.master_pspecs(params)
    assert specs["w"] == P(None, "model")       # data-replicated fallback
    assert any("mp*dp" in w for w in warnings), warnings
    assert policy._warned_compose_fallback
    # warning is once-per-policy, not per-call
    n = len(warnings)
    policy.master_pspecs(params)
    assert len(warnings) == n

    # divisible by mp*dp -> composes, no compose warning
    warnings.clear()
    params = {"w": jnp.zeros((3, 16))}
    policy2 = ZeroShardingPolicy(mesh, stage=2,
                                 param_specs={"w": P(None, "model")})
    specs = policy2.master_pspecs(params)
    assert specs["w"] == P(None, ("model", "data"))
    assert not any("mp*dp" in w for w in warnings), warnings
