"""Compiled 1F1B pipeline execution for heterogeneous PipelineModules
(VERDICT r1 #7; parity targets: ref `pipe/engine.py:1135-1161` schedule
interpreter, `schedule.py:182-289` 1F1B, `schedule.py:243-247` buffer
bound, `module.py:405-409` tied-grad reduction).

Runs on the 8-device virtual CPU mesh (pipe=2 x data=4)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.pipe.interp import (build_clock_tables,
                                               num_pipe_buffers)
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec)
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule

DIN, DMID, DOUT = 16, 32, 8


def mse_loss(pred, labels):
    return jnp.mean((pred.astype(jnp.float32) -
                     labels.astype(jnp.float32)) ** 2)


def hetero_module(num_stages, layer_dtype=None):
    """Deliberately heterogeneous: different widths per stage and a
    plain-callable (paramless) layer in the chain."""
    layers = [
        LayerSpec(nn.Dense, DMID, dtype=layer_dtype),
        jnp.tanh,                       # paramless callable layer
        LayerSpec(nn.Dense, DMID * 2, dtype=layer_dtype),
        LayerSpec(nn.Dense, DOUT, dtype=layer_dtype),
    ]
    return PipelineModule(layers, num_stages=num_stages, loss_fn=mse_loss,
                          partition_method="uniform")


def make_engine(num_stages, pipe, data, gas, seed=0, layer_dtype=None,
                **cfg_over):
    module = hetero_module(num_stages, layer_dtype=layer_dtype)
    rng = np.random.RandomState(seed)
    example = jnp.asarray(rng.randn(4, DIN), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(seed), example)
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"pipe": pipe, "data": data, "model": 1},
    }
    cfg.update(cfg_over)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params, config=cfg)
    return engine


def full_batch(gas, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(8 * gas, DIN).astype(np.float32)
    w = np.linspace(-1, 1, DIN * DOUT).reshape(DIN, DOUT).astype(np.float32)
    return {"x": x, "y": x @ w}


# ----------------------------------------------------------------------
# clock tables
# ----------------------------------------------------------------------
@pytest.mark.parametrize("m,S", [(4, 2), (8, 4), (2, 2), (1, 2), (6, 3)])
def test_clock_tables_complete_and_ordered(m, S):
    t = build_clock_tables(m, S)
    fwd, bwd = t["fwd_mb"], t["bwd_mb"]
    for s in range(S):
        # every microbatch forwards and backwards exactly once per stage
        assert sorted(fwd[:, s][fwd[:, s] >= 0].tolist()) == list(range(m))
        assert sorted(bwd[:, s][bwd[:, s] >= 0].tolist()) == list(range(m))
    for mb in range(m):
        for s in range(S - 1):
            f0 = int(np.where(fwd[:, s] == mb)[0][0])
            f1 = int(np.where(fwd[:, s + 1] == mb)[0][0])
            assert f0 < f1, "activation must flow down the pipeline"
            b1 = int(np.where(bwd[:, s + 1] == mb)[0][0])
            b0 = int(np.where(bwd[:, s] == mb)[0][0])
            assert b1 < b0, "cotangent must flow up the pipeline"
        # a stage's backward needs its own forward first
        for s in range(S):
            f = int(np.where(fwd[:, s] == mb)[0][0])
            b = int(np.where(bwd[:, s] == mb)[0][0])
            assert f < b


def test_clock_tables_overlap_stages():
    """The point of 1F1B: in steady state different stages work on
    different microbatches in the SAME tick."""
    t = build_clock_tables(8, 4)
    busy = (t["fwd_mb"] >= 0) | (t["bwd_mb"] >= 0)
    assert (busy.sum(axis=1) >= 2).any(), "no tick overlaps stages"
    # total ticks must beat the sequential chain's m*S fwd + m*S bwd
    assert t["num_ticks"] < 2 * 8 * 4


def test_live_buffer_bound_matches_schedule():
    """In-flight forwards per stage (forwarded but not yet backwarded)
    must never exceed TrainSchedule.num_pipe_buffers (ref
    schedule.py:243-247) — the 1F1B memory claim."""
    for m, S in [(8, 2), (8, 4), (4, 4)]:
        t = build_clock_tables(m, S)
        for s in range(S):
            bound = TrainSchedule(m, S, s).num_pipe_buffers()
            live = 0
            for tick in range(t["num_ticks"]):
                if t["fwd_mb"][tick, s] >= 0:
                    live += 1
                if t["bwd_mb"][tick, s] >= 0:
                    live -= 1
                assert live <= bound, (m, S, s, tick, live, bound)
        assert num_pipe_buffers(m, S) == max(
            TrainSchedule(m, S, s).num_pipe_buffers() for s in range(S))


# ----------------------------------------------------------------------
# end-to-end equivalence
# ----------------------------------------------------------------------
def test_1f1b_matches_sequential_chain():
    """Pipelined execution is a pure schedule change: losses and params
    must match the pipe=1 sequential chain step for step."""
    def run(pipe, data):
        engine = make_engine(num_stages=pipe, pipe=pipe, data=data, gas=4)
        losses = []
        for i in range(5):
            loss = engine.train_batch(batch=full_batch(4, seed=i % 3))
            losses.append(float(jax.device_get(loss)))
        return losses, jax.device_get(engine.fp32_params)

    losses_seq, params_seq = run(pipe=1, data=8)
    losses_pp, params_pp = run(pipe=2, data=4)
    np.testing.assert_allclose(losses_pp, losses_seq, rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(params_seq),
                    jax.tree_util.tree_leaves(params_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_1f1b_mode_selected_and_loss_decreases():
    engine = make_engine(num_stages=2, pipe=2, data=4, gas=4)
    assert engine._use_1f1b
    losses = []
    for i in range(12):
        loss = engine.train_batch(batch=full_batch(4, seed=i % 3))
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.6, losses


def test_1f1b_tied_layers_sum_grads():
    """TiedLayerSpec shared across stages: the pipe-axis psum must SUM
    the tied grads (ReduceTiedGrads, ref module.py:405-409) — verified
    against the sequential chain where autodiff sums them."""
    class Emb(nn.Module):
        @nn.compact
        def __call__(self, x):
            w = self.param("embedding", nn.initializers.normal(0.1),
                           (DIN, DIN))
            return x @ w

    def tied_module(num_stages):
        layers = [
            TiedLayerSpec("emb", Emb, tied_weight_attr="embedding"),
            LayerSpec(nn.Dense, DIN),
            TiedLayerSpec("emb", Emb, tied_weight_attr="embedding",
                          forward_fn=lambda p, x: x @ p["embedding"].T),
        ]
        return PipelineModule(layers, num_stages=num_stages,
                              loss_fn=lambda pred, y: jnp.mean(
                                  (pred - y.astype(pred.dtype)) ** 2),
                              partition_method="uniform")

    def run(pipe, data):
        module = tied_module(pipe)
        rng = np.random.RandomState(0)
        example = jnp.asarray(rng.randn(4, DIN), jnp.float32)
        params = module.init_params(jax.random.PRNGKey(0), example)
        cfg = {
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 4,
            "steps_per_print": 1000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "mesh": {"pipe": pipe, "data": data, "model": 1},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=module, model_parameters=params, config=cfg)
        losses = []
        for i in range(4):
            x = np.random.RandomState(i).randn(32, DIN).astype(np.float32)
            loss = engine.train_batch(batch={"x": x, "y": x})
            losses.append(float(jax.device_get(loss)))
        return losses, jax.device_get(engine.fp32_params)

    losses_seq, params_seq = run(pipe=1, data=8)
    losses_pp, params_pp = run(pipe=2, data=4)
    np.testing.assert_allclose(losses_pp, losses_seq, rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(params_seq),
                    jax.tree_util.tree_leaves(params_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_inference_tables_fwd_only():
    from deepspeed_tpu.runtime.pipe.interp import build_clock_tables
    t = build_clock_tables(4, 2, train=False)
    assert (t["bwd_mb"] == -1).all()
    for s in range(2):
        f = t["fwd_mb"][:, s]
        assert sorted(f[f >= 0].tolist()) == [0, 1, 2, 3]
    # fill-drain pipeline: total ticks ~ m + S - 1 (plus channel slack)
    assert t["num_ticks"] <= 2 * (4 + 2)
    # buffer ids alternate within {0,1}: the InferenceSchedule bound
    assert set(t["fwd_buf"].reshape(-1).tolist()) <= {0, 1}


def test_pipelined_eval_matches_sequential():
    """Forward-only pipelined eval (InferenceSchedule dataflow) must
    equal the sequential chained loss exactly."""
    engine = make_engine(num_stages=2, pipe=2, data=4, gas=4)
    for i in range(3):
        engine.train_batch(batch=full_batch(4, seed=i))
    batch = full_batch(4, seed=7)
    loss_pp = float(jax.device_get(engine.eval_batch(batch=batch)))

    seq = make_engine(num_stages=1, pipe=1, data=8, gas=4)
    # copy trained params over for an apples-to-apples eval
    seq.state = seq.state._replace(params=jax.device_get(
        engine.module_params))
    loss_seq = float(jax.device_get(seq.eval_batch(batch=batch)))
    np.testing.assert_allclose(loss_pp, loss_seq, rtol=1e-5)


def test_1f1b_with_zero2_padding():
    """1F1B grads must enter the ZeRO-2 sharded layout: odd widths +
    bf16 + stage 2 + pipe 2. The flat [S, F] buffers are built with
    align=model*data, so the data-axis master sharding needs NO runtime
    pad plan — F is already divisible and masters shard over data."""
    layers = [LayerSpec(nn.Dense, 18), jnp.tanh, LayerSpec(nn.Dense, 10)]
    module = PipelineModule(layers, num_stages=2, loss_fn=mse_loss,
                            partition_method="uniform")
    rng = np.random.RandomState(0)
    example = jnp.asarray(rng.randn(4, 18), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(0), example)
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 1000,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"pipe": 2, "data": 4, "model": 1},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params, config=cfg)
    assert engine._use_1f1b and not engine._zero_pad_plan
    from deepspeed_tpu.runtime.mesh import DATA_AXIS as _DA
    flat_master_specs = [
        sh.spec for sh in jax.tree_util.tree_leaves(
            engine._master_shardings["flat"])]
    assert flat_master_specs and all(
        any(_DA in (ax if isinstance(ax, tuple) else (ax,))
            for ax in spec if ax is not None)
        for spec in flat_master_specs), flat_master_specs
    x = rng.randn(32, 18).astype(np.float32)
    y = rng.randn(32, 10).astype(np.float32)
    losses = [float(jax.device_get(
        engine.train_batch(batch={"x": x, "y": y}))) for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_1f1b_bf16_transport_matches_sequential():
    """bf16-activation models move bf16 activation/cotangent buffers
    through the pipe (half the wire bytes) and still match the
    sequential chain. Layers compute in bf16 so the stage boundaries
    really ARE bf16 (default-dtype Dense would promote back to f32)."""
    def run(pipe, data):
        engine = make_engine(num_stages=pipe, pipe=pipe, data=data,
                             gas=4, layer_dtype=jnp.bfloat16,
                             **{"bf16": {"enabled": True}})
        return engine, [float(jax.device_get(
            engine.train_batch(batch=full_batch(4, seed=i))))
            for i in range(4)]

    _, losses_seq = run(1, 8)
    pp, losses_pp = run(2, 4)
    np.testing.assert_allclose(losses_pp, losses_seq, rtol=5e-3)
    # the stage boundary (and hence the transport buffer dtype chosen
    # by build_pipeline_step) must actually be bf16
    out = pp.module.apply_layer(
        0, pp.module.layer_params(jax.device_get(pp.module_params), 0),
        jnp.zeros((2, DIN), jnp.float32))
    assert out.dtype == jnp.bfloat16, out.dtype


# ----------------------------------------------------------------------
# per-stage parameter memory partitioning (VERDICT r3 #2; ref
# module.py:197-249 — pipeline divides param/grad/optimizer memory by
# the stage count)
# ----------------------------------------------------------------------
def test_1f1b_params_partitioned_per_stage():
    """Under the flat-stage layout every pipe shard must hold only
    ~total/stages of the stage-exclusive parameter bytes (padding to
    the widest stage is the only allowed overhead), and the optimizer
    moments must follow the same layout."""
    engine = make_engine(num_stages=2, pipe=2, data=4, gas=4)
    assert getattr(engine, "_pipe_flat_mode", False)
    stored = engine.state.params
    assert set(stored) == {"flat", "tied"}
    layout = engine._pipe_layout

    for dt, buf in stored["flat"].items():
        S, F = buf.shape
        assert S == 2
        # each device's addressable shard holds exactly ONE stage row
        for shard in buf.addressable_shards:
            assert shard.data.shape == (1, F), shard.data.shape
        # and the rows really partition (stage params differ)
        rows = np.asarray(jax.device_get(buf))
        assert not np.allclose(rows[0], rows[1])

    # optimizer moments mirror the layout (sharded over pipe, same F)
    def find_mu(st):
        if hasattr(st, "mu"):
            return st.mu
        if hasattr(st, "inner_state"):
            return find_mu(st.inner_state)
        if isinstance(st, (tuple, list)):
            for item in st:
                got = find_mu(item)
                if got is not None:
                    return got
        return None

    mu = find_mu(engine.state.opt_state)
    assert mu is not None
    for dt, buf in mu["flat"].items():
        for shard in buf.addressable_shards:
            assert shard.data.shape == (1, buf.shape[1])

    # the unflattened view equals a fresh logical tree's structure
    logical = engine.module_params
    assert set(logical) == {"layers", "tied"}

    # training still descends
    losses = [float(jax.device_get(
        engine.train_batch(batch=full_batch(4, seed=i))))
        for i in range(6)]
    assert losses[-1] < losses[0], losses


def test_1f1b_flat_checkpoint_roundtrip(tmp_path):
    """Per-layer checkpoint files written from the flat layout reload
    into a fresh flat-layout engine (and into a SEQUENTIAL engine —
    files are keyed by layer index, not stage)."""
    engine = make_engine(num_stages=2, pipe=2, data=4, gas=4)
    for i in range(3):
        engine.train_batch(batch=full_batch(4, seed=i))
    engine.save_checkpoint(str(tmp_path), tag="t3")
    engine.wait_for_checkpoint()
    ref_next = float(jax.device_get(
        engine.train_batch(batch=full_batch(4, seed=9))))

    e2 = make_engine(num_stages=2, pipe=2, data=4, gas=4, seed=5)
    e2.load_checkpoint(str(tmp_path), tag="t3")
    got_next = float(jax.device_get(
        e2.train_batch(batch=full_batch(4, seed=9))))
    np.testing.assert_allclose(got_next, ref_next, rtol=1e-4)

    # cross-topology reload: sequential (pipe=1) engine reads the same
    # per-layer files (ref test_checkpointing.py:633 semantics)
    e3 = make_engine(num_stages=1, pipe=1, data=8, gas=4, seed=6)
    e3.load_checkpoint(str(tmp_path), tag="t3",
                       load_optimizer_states=False)
    got_seq = float(jax.device_get(
        e3.train_batch(batch=full_batch(4, seed=9))))
    np.testing.assert_allclose(got_seq, ref_next, rtol=5e-3)


def test_1f1b_flat_with_bf16_sr_mode():
    """bf16 master-less (stochastic rounding) on TOP of the per-stage
    flat layout: moments live as bf16 flat buffers sharded over pipe,
    tied leaves stay consistent across shards, loss descends."""
    engine = make_engine(num_stages=2, pipe=2, data=4, gas=4,
                         layer_dtype=jnp.bfloat16,
                         **{"bf16": {"enabled": True,
                                     "master_weights": False}})
    assert engine.bf16_sr_mode and engine._pipe_flat_mode
    assert engine.state.master is None

    def find_mu(st):
        if hasattr(st, "mu"):
            return st.mu
        if hasattr(st, "inner_state"):
            return find_mu(st.inner_state)
        if isinstance(st, (tuple, list)):
            for item in st:
                got = find_mu(item)
                if got is not None:
                    return got
        return None

    mu = find_mu(engine.state.opt_state)
    for dt, buf in mu["flat"].items():
        assert buf.dtype == jnp.bfloat16, (dt, buf.dtype)
        for shard in buf.addressable_shards:
            assert shard.data.shape == (1, buf.shape[1])

    losses = [float(jax.device_get(
        engine.train_batch(batch=full_batch(4, seed=i % 3))))
        for i in range(10)]
    assert losses[-1] < losses[0] * 0.8, losses


# ----------------------------------------------------------------------
# 1F1B x tensor parallelism (VERDICT r4 #3; ref topology.py:246-249 —
# the grid composes pipe with a model axis; pipe/engine.py:493-521
# partitions activations across TP ranks)
# ----------------------------------------------------------------------
def test_1f1b_composes_with_model_axis_3d():
    """pipe=2 x model=2 x data=2 on a heterogeneous PipelineModule:
    the flat [S, F] buffers shard over (pipe, model) so per-device
    parameter bytes ~ total/(pipe*model), masters/moments compose
    (model, data) on top, and the loss trajectory matches the
    sequential data-parallel engine."""
    def run(pipe, data, model):
        engine = make_engine(
            num_stages=max(pipe, 2) if pipe > 1 else 1,
            pipe=pipe, data=data, gas=4,
            mesh={"pipe": pipe, "data": data, "model": model},
            zero_optimization={"stage": 1})
        return engine, [float(jax.device_get(
            engine.train_batch(batch=full_batch(4, seed=i))))
            for i in range(4)]

    eseq, losses_seq = run(1, 8, 1)
    e3d, losses_3d = run(2, 2, 2)
    assert e3d._use_1f1b and e3d._pipe_flat_mode
    np.testing.assert_allclose(losses_3d, losses_seq, rtol=5e-3)

    # pipelined eval (InferenceSchedule dataflow) also gathers the
    # model-sharded stage buffers correctly
    ev = full_batch(4, seed=9)
    np.testing.assert_allclose(
        float(jax.device_get(e3d.eval_batch(batch=ev))),
        float(jax.device_get(eseq.eval_batch(batch=ev))), rtol=5e-3)

    # compute params: each (pipe, model) shard holds [1, F/2]
    for dt, buf in e3d.state.params["flat"].items():
        S, F = buf.shape
        assert S == 2 and F % 2 == 0
        for shard in buf.addressable_shards:
            assert shard.data.shape == (1, F // 2), shard.data.shape

    # ZeRO-1 moments divide by pipe*model*data — the (model, data)
    # tuple composition in zero/partition.py: local shard [1, F/(2*2)]
    def find_mu(st):
        if hasattr(st, "mu"):
            return st.mu
        if hasattr(st, "inner_state"):
            return find_mu(st.inner_state)
        if isinstance(st, (tuple, list)):
            for item in st:
                got = find_mu(item)
                if got is not None:
                    return got
        return None

    mu = find_mu(e3d.state.opt_state)
    for dt, buf in mu["flat"].items():
        S, F = buf.shape
        for shard in buf.addressable_shards:
            assert shard.data.shape == (1, F // 4), shard.data.shape

    # grads really partition: stage rows and model halves both differ
    rows = np.asarray(jax.device_get(e3d.state.params["flat"]["float32"]))
    assert not np.allclose(rows[0], rows[1])


def test_pipe_without_microbatching_raises():
    """pipe>1 with gradient_accumulation_steps==1 is a degenerate
    pipeline (no overlap, no memory division) — the engine must refuse
    loudly, not degrade to a silent sequential chain (VERDICT r4 #5)."""
    with pytest.raises(ValueError, match="gradient_accumulation_steps"):
        make_engine(num_stages=2, pipe=2, data=4, gas=1)


def test_1f1b_model_axis_with_bf16_sr_mode():
    """bf16 master-less SR on the composed pipe=2 x model=2 mesh: bf16
    flat moment buffers shard over BOTH axes and training descends."""
    engine = make_engine(num_stages=2, pipe=2, data=2, gas=4,
                         layer_dtype=jnp.bfloat16,
                         mesh={"pipe": 2, "data": 2, "model": 2},
                         zero_optimization={"stage": 1},
                         **{"bf16": {"enabled": True,
                                     "master_weights": False}})
    assert engine.bf16_sr_mode and engine._pipe_flat_mode

    def find_mu(st):
        if hasattr(st, "mu"):
            return st.mu
        if hasattr(st, "inner_state"):
            return find_mu(st.inner_state)
        if isinstance(st, (tuple, list)):
            for item in st:
                got = find_mu(item)
                if got is not None:
                    return got
        return None

    mu = find_mu(engine.state.opt_state)
    for dt, buf in mu["flat"].items():
        assert buf.dtype == jnp.bfloat16, (dt, buf.dtype)
        S, F = buf.shape
        # (pipe, (model, data)) composition: [1, F/4] per device
        for shard in buf.addressable_shards:
            assert shard.data.shape == (1, F // 4), shard.data.shape

    losses = [float(jax.device_get(
        engine.train_batch(batch=full_batch(4, seed=i % 3))))
        for i in range(10)]
    assert losses[-1] < losses[0] * 0.8, losses


@pytest.mark.parametrize("align", [1, 3, 8, 16])
def test_stage_flat_layout_roundtrip_any_align(align):
    """flatten/unflatten are exact inverses for ANY align (the engine
    passes model*data; the padding only widens F, never moves
    offsets), and num_params excludes the padding."""
    from deepspeed_tpu.runtime.pipe.flat_params import StageFlatLayout
    module = hetero_module(2)
    rng = np.random.RandomState(7)
    example = jnp.asarray(rng.randn(4, DIN), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(7), example)
    layout = StageFlatLayout(module, params, align=align)
    stored = layout.flatten(params)
    for dt, buf in stored["flat"].items():
        assert buf.shape[1] % align == 0, (dt, buf.shape, align)
    back = layout.unflatten(stored)
    for a, b in zip(jax.tree_util.tree_leaves(params["layers"]),
                    jax.tree_util.tree_leaves(back["layers"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    true_n = sum(int(np.prod(np.shape(l))) for l in
                 jax.tree_util.tree_leaves(params))
    assert layout.num_params(stored) == true_n
