"""Kernel block-size autotuner (ISSUE 13): table lifecycle —
roundtrip persist/load, kernel-source-hash invalidation, corrupt /
version-stale tables degrading to defaults with a single warning (no
crash, no silent reuse) — plus the search's never-slower floor, the
monitor events, and the trace-time lookups the kernel entry points
make (fused row blocks, flash blocks)."""

import json
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import autotune


@pytest.fixture(autouse=True)
def _isolated_table(tmp_path):
    """Every test gets its own table file and a clean module state."""
    autotune.reset()
    autotune.configure(table_path=str(tmp_path / "table.json"))
    yield tmp_path
    autotune.reset()


class _StubMonitor:
    def __init__(self):
        self.events = []

    def event(self, kind, **fields):
        self.events.append((kind, fields))


class _capture_warnings:
    """The ds logger has propagate=False, so caplog misses it; attach
    a list handler directly."""

    def __enter__(self):
        from deepspeed_tpu.utils.logging import logger as ds_logger
        self.records = []
        outer = self

        class H(logging.Handler):
            def emit(self, record):
                outer.records.append(record)

        self._h = H(level=logging.WARNING)
        self._logger = ds_logger
        ds_logger.addHandler(self._h)
        return self

    def __exit__(self, *exc):
        self._logger.removeHandler(self._h)
        return False

    def messages(self):
        return [r.getMessage() for r in self.records]


def _fake_search(times_by_block, kernel="fused_ln",
                 shape_class="rows256_h128", default_block=256,
                 persist=True):
    """Drive search() with an injected measure fn (no real kernels)."""
    return autotune.search(
        kernel, shape_class, jnp.float32,
        [{"row_block": b} for b in times_by_block
         if b != default_block],
        {"row_block": default_block},
        measure=lambda p: times_by_block[p["row_block"]],
        persist=persist)


# ----------------------------------------------------------------------
# search semantics
# ----------------------------------------------------------------------
def test_search_picks_fastest_candidate():
    res = _fake_search({256: 3e-3, 128: 1e-3, 512: 2e-3})
    assert res["params"] == {"row_block": 128}
    assert res["speedup_vs_default"] == 3.0
    assert res["candidates_tried"] == 3


def test_search_never_slower_floor():
    """Every candidate slower than the hand-picked default -> the
    default IS the recorded winner (applying the table can never
    regress)."""
    res = _fake_search({256: 1e-3, 128: 5e-3, 512: 9e-3})
    assert res["params"] == {"row_block": 256}
    assert res["speedup_vs_default"] == 1.0


def test_search_requires_a_measurement_source():
    with pytest.raises(ValueError):
        autotune.search("fused_ln", "s", jnp.float32, [], {})


# ----------------------------------------------------------------------
# persist / load roundtrip + invalidation
# ----------------------------------------------------------------------
def test_roundtrip_persist_and_reload(tmp_path):
    _fake_search({256: 2e-3, 128: 1e-3})
    # fresh module state, same path: the entry must come back
    autotune.reset()
    autotune.configure(table_path=str(tmp_path / "table.json"))
    params = autotune.lookup("fused_ln", "rows256_h128", jnp.float32)
    assert params == {"row_block": 128}
    # the file itself is the versioned document
    doc = json.load(open(tmp_path / "table.json"))
    assert doc["version"] == autotune.TABLE_VERSION
    (key, entry), = doc["entries"].items()
    assert key.startswith("fused_ln|")
    assert entry["source_hash"] == \
        autotune.kernel_source_hash("fused_ln")


def test_source_hash_invalidation_single_warning(tmp_path):
    """An entry measured on different kernel source must NOT steer
    the current kernel: dropped on lookup, ONE warning, defaults
    apply."""
    _fake_search({256: 2e-3, 128: 1e-3})
    doc = json.load(open(tmp_path / "table.json"))
    for entry in doc["entries"].values():
        entry["source_hash"] = "deadbeef"
    json.dump(doc, open(tmp_path / "table.json", "w"))
    autotune.reset()
    autotune.configure(table_path=str(tmp_path / "table.json"))
    with _capture_warnings() as cap:
        assert autotune.lookup("fused_ln", "rows256_h128",
                               jnp.float32) is None
        assert autotune.lookup("fused_ln", "rows256_h128",
                               jnp.float32) is None
    warns = [m for m in cap.messages()
             if "different kernel source" in m]
    assert len(warns) == 1


def test_corrupt_table_degrades_with_single_warning(tmp_path):
    (tmp_path / "table.json").write_text("{not json")
    autotune.reset()
    autotune.configure(table_path=str(tmp_path / "table.json"))
    with _capture_warnings() as cap:
        for _ in range(3):
            assert autotune.lookup("fused_ln", "rows256_h128",
                                   jnp.float32) is None
    warns = [m for m in cap.messages() if "unreadable" in m]
    assert len(warns) == 1
    # and a later search repopulates it cleanly
    res = _fake_search({256: 2e-3, 128: 1e-3})
    assert res["params"] == {"row_block": 128}


def test_version_stale_table_degrades(tmp_path):
    doc = {"version": autotune.TABLE_VERSION + 1, "entries": {
        "fused_ln|cpu|float32|rows256_h128": {
            "params": {"row_block": 64}, "source_hash": "x"}}}
    json.dump(doc, open(tmp_path / "table.json", "w"))
    autotune.reset()
    autotune.configure(table_path=str(tmp_path / "table.json"))
    with _capture_warnings() as cap:
        assert autotune.lookup("fused_ln", "rows256_h128",
                               jnp.float32) is None
    assert any("version" in m for m in cap.messages())


def test_disabled_lookups_return_none(tmp_path):
    _fake_search({256: 2e-3, 128: 1e-3})
    autotune.configure(enabled=False)
    assert autotune.lookup("fused_ln", "rows256_h128",
                           jnp.float32) is None
    autotune.configure(enabled=True)
    assert autotune.lookup("fused_ln", "rows256_h128",
                           jnp.float32) == {"row_block": 128}


# ----------------------------------------------------------------------
# monitor events
# ----------------------------------------------------------------------
def test_search_and_hit_events():
    mon = _StubMonitor()
    autotune.configure(monitor=mon)
    _fake_search({256: 2e-3, 128: 1e-3})
    kinds = [k for k, _ in mon.events]
    assert kinds == ["autotune_search"]
    _, fields = mon.events[0]
    assert fields["kernel"] == "fused_ln"
    assert fields["params"] == {"row_block": 128}
    assert fields["speedup_vs_default"] == 2.0
    # first lookup emits ONE autotune_hit; repeats stay silent
    autotune.lookup("fused_ln", "rows256_h128", jnp.float32)
    autotune.lookup("fused_ln", "rows256_h128", jnp.float32)
    kinds = [k for k, _ in mon.events]
    assert kinds == ["autotune_search", "autotune_hit"]


# ----------------------------------------------------------------------
# trace-time integration: the kernel entry points consult the table
# ----------------------------------------------------------------------
def test_fused_row_block_launcher_uses_tuned_value():
    from deepspeed_tpu.ops.transformer import fused_ops
    n, hp = 256, 128
    sc = autotune.row_kernel_shape_class(n, hp)
    assert fused_ops._tuned_row_block("fused_ln", n, hp,
                                      jnp.float32) == 256  # default
    autotune.record("fused_ln", sc, jnp.float32,
                    {"row_block": 64}, 1.0, 2.0, 2, persist=False)
    assert fused_ops._tuned_row_block("fused_ln", n, hp,
                                      jnp.float32) == 64


def test_flash_entry_point_resolves_tuned_blocks():
    import importlib
    fa = importlib.import_module(
        "deepspeed_tpu.ops.transformer.flash_attention")
    t, d = 512, 64
    q = jnp.zeros((1, t, 1, d), jnp.float32)
    sc = autotune.flash_shape_class(t, d, True, False)
    autotune.record("flash_fwd", sc, jnp.float32,
                    {"block_q": 128, "block_k": 256}, 1.0, 2.0, 2,
                    persist=False)
    args = fa._normalize_flash_args(q, q, q, True, None, None, None,
                                    None)
    assert (args[2], args[3]) == (128, 256)
    # explicit caller blocks always win over the table — INCLUDING an
    # explicit request for the default 1024/1024 shapes
    args = fa._normalize_flash_args(q, q, q, True, None, 512, 512,
                                    None)
    assert (args[2], args[3]) == (512, 512)
    args = fa._normalize_flash_args(q, q, q, True, None,
                                    fa._DEFAULT_BLOCK,
                                    fa._DEFAULT_BLOCK, None)
    assert (args[2], args[3]) == (512, 512)   # _fit_block clamps to t


def test_flash_lookup_rejects_incompatible_entries():
    """A table entry whose blocks do not divide this trace's T falls
    back to defaults instead of producing an illegal launch."""
    t, d = 384, 64
    sc = autotune.flash_shape_class(t, d, True, False)
    autotune.record("flash_fwd", sc, jnp.float32,
                    {"block_q": 256, "block_k": 256}, 1.0, 2.0, 2,
                    persist=False)
    assert autotune.flash_blocks(t, d, True, False,
                                 jnp.float32) is None


def test_qmm_blocks_lookup():
    m, k, n = 2048, 1024, 4096
    sc = autotune.qmm_shape_class(m, k, n)
    assert autotune.qmm_blocks(m, k, n, jnp.bfloat16) is None
    autotune.record("quantized_matmul", sc, jnp.bfloat16,
                    {"block_m": 512, "block_n": 128}, 1.0, 2.0, 2,
                    persist=False)
    assert autotune.qmm_blocks(m, k, n, jnp.bfloat16) == (512, 128)


def test_engine_configures_autotune(tmp_path):
    """The `autotune` config block reaches ops.autotune at engine
    init (path + enabled + monitor attach)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, \
        tiny_gpt2_config
    ids = np.zeros((8, 64), np.int32)
    model = GPT2ForCausalLM(tiny_gpt2_config(n_positions=64))
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    table = str(tmp_path / "engine_table.json")
    deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "autotune": {"enabled": True, "table_path": table},
        })
    assert autotune.table_path() == table


def test_shape_class_helpers():
    assert autotune.pow2_bucket(1) == 1
    assert autotune.pow2_bucket(200) == 256
    assert autotune.flash_shape_class(1024, 64, True, True) == \
        "t1024_d64_causal_packed"
    assert autotune.row_kernel_shape_class(200, 128) == "rows256_h128"
    assert {"block_q": 512, "block_k": 1024} in \
        autotune.flash_block_candidates(1024)
    assert all(1024 % c["block_q"] == 0
               for c in autotune.flash_block_candidates(1024))
