"""Fused non-attention epilogue kernels (ISSUE 6 tentpole): parity of
`fused_bias_residual_layernorm` / `fused_bias_gelu` against the unfused
reference chains — forward AND backward, across dtypes (fp32/bf16),
pre/post-LayerNorm wiring, odd hidden sizes, both the XLA-fallback impl
and the Pallas kernels in interpreter mode (same kernel logic CPU CI
can pin) — plus the per-fusion remat policy and a 10-step GPT-2 ZeRO-2
engine loss-tracking A/B (tolerance pinned like PR 4's packed-attention
sweep)."""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.fused_ops import (
    FUSED_EPILOGUE_SAVE_NAMES, fused_bias_gelu,
    fused_bias_residual_layernorm, resolve_fused_ops)


def ab(x):
    return np.asarray(x, np.float32)


def _ln_ref(y, b, r, g, bet, eps):
    """The unfused chain exactly as the models compose it: bias add,
    residual add, flax fast-variance LayerNorm in fp32."""
    s = (y.astype(jnp.float32) + b.astype(jnp.float32)) + \
        r.astype(jnp.float32)
    mu = jnp.mean(s, -1, keepdims=True)
    var = jnp.mean(s * s, -1, keepdims=True) - mu * mu
    out = (s - mu) * jax.lax.rsqrt(var + eps) * g.astype(jnp.float32) + \
        bet.astype(jnp.float32)
    return out, s


def _ln_args(h, dtype, seed=0):
    rng = np.random.default_rng(seed)
    y = jnp.asarray(rng.standard_normal((4, 16, h)), dtype)
    b = jnp.asarray(rng.standard_normal((h,)), jnp.float32)
    r = jnp.asarray(rng.standard_normal((4, 16, h)), dtype)
    g = jnp.asarray(rng.standard_normal((h,)) + 1.0, jnp.float32)
    bet = jnp.asarray(rng.standard_normal((h,)), jnp.float32)
    return y, b, r, g, bet


# ----------------------------------------------------------------------
# op-level parity sweep
# ----------------------------------------------------------------------
@pytest.mark.parametrize("impl", ["xla", "interpret"])
@pytest.mark.parametrize("h", [128, 256, 100, 96],
                         ids=["h128", "h256", "h100-odd", "h96-odd"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
def test_ln_chain_parity(impl, h, dtype):
    """Fused bias+residual+LN forward AND full backward vs the unfused
    reference, both outputs live (the pre-LN wiring: out feeds the next
    matmul, sum carries the residual stream)."""
    if dtype == jnp.bfloat16 and h in (100, 96):
        pytest.skip("odd-H bf16 adds nothing over fp32 odd-H + bf16 128")
    args = _ln_args(h, dtype)
    tol = dict(atol=1e-5, rtol=1e-5) if dtype == jnp.float32 \
        else dict(atol=1e-2, rtol=1e-2)

    def loss_fused(a):
        out, s = fused_bias_residual_layernorm(*a, eps=1e-5, impl=impl,
                                               out_dtype=jnp.float32,
                                               sum_dtype=jnp.float32)
        return (jnp.sin(out).sum() + jnp.cos(s).sum()).astype(jnp.float32)

    def loss_ref(a):
        out, s = _ln_ref(*a, eps=1e-5)
        return jnp.sin(out).sum() + jnp.cos(s).sum()

    np.testing.assert_allclose(ab(loss_fused(args)), ab(loss_ref(args)),
                               **tol)
    gf = jax.grad(loss_fused)(args)
    gr = jax.grad(loss_ref)(args)
    for name, a, b in zip(("y", "bias", "residual", "gamma", "beta"),
                          gf, gr):
        scale = max(np.abs(ab(b)).max(), 1.0)
        np.testing.assert_allclose(ab(a) / scale, ab(b) / scale,
                                   err_msg=name, **tol)


@pytest.mark.parametrize("impl", ["xla", "interpret"])
@pytest.mark.parametrize("h", [128, 100], ids=["h128", "h100-odd"])
@pytest.mark.parametrize("approximate", [False, True],
                         ids=["erf", "tanh"])
def test_gelu_parity(impl, h, approximate):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 8, h)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((h,)), jnp.float32)

    def loss_fused(a):
        return (fused_bias_gelu(a[0], a[1], approximate=approximate,
                                impl=impl) ** 3).sum()

    def loss_ref(a):
        return (jax.nn.gelu(a[0] + a[1], approximate=approximate)
                ** 3).sum()

    np.testing.assert_allclose(ab(loss_fused((x, b))),
                               ab(loss_ref((x, b))), rtol=1e-6)
    gf = jax.grad(loss_fused)((x, b))
    gr = jax.grad(loss_ref)((x, b))
    for a, b_ in zip(gf, gr):
        scale = max(np.abs(ab(b_)).max(), 1.0)
        np.testing.assert_allclose(ab(a) / scale, ab(b_) / scale,
                                   atol=1e-5, rtol=1e-5)


def test_post_ln_usage_sum_discarded():
    """Post-LN callers drop the sum output; gradients must still match
    the reference with only the normalized output live."""
    args = _ln_args(128, jnp.float32, seed=3)

    def loss_fused(a):
        out, _ = fused_bias_residual_layernorm(*a, eps=1e-12, impl="xla")
        return jnp.sin(out).sum()

    def loss_ref(a):
        out, _ = _ln_ref(*a, eps=1e-12)
        return jnp.sin(out).sum()

    gf, gr = jax.grad(loss_fused)(args), jax.grad(loss_ref)(args)
    for a, b in zip(gf, gr):
        scale = max(np.abs(ab(b)).max(), 1.0)
        np.testing.assert_allclose(ab(a) / scale, ab(b) / scale,
                                   atol=1e-5, rtol=1e-5)


def test_resolve_fused_ops_rules():
    import deepspeed_tpu.ops.transformer.fused_ops as fo
    assert resolve_fused_ops("off", True) is False
    assert resolve_fused_ops("on", True) is True
    # "auto" is backend-keyed (real TPU only), like head_packing
    assert resolve_fused_ops("auto", True) == fo._on_tpu()
    assert resolve_fused_ops("auto", False) is False
    with pytest.raises(ValueError):
        resolve_fused_ops("on", False)      # dropout inside the chain
    with pytest.raises(ValueError):
        resolve_fused_ops("maybe", True)


# ----------------------------------------------------------------------
# model wiring: identical param trees, fused == unfused numerics
# ----------------------------------------------------------------------
def test_gpt2_block_fused_parity_and_tree():
    from deepspeed_tpu.models.gpt2 import GPT2Block, tiny_gpt2_config
    cfg_off = tiny_gpt2_config(n_embd=128, n_head=4, fused_ops="off")
    cfg_on = dataclasses.replace(cfg_off, fused_ops="on")
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((4, 32, 128)), jnp.float32)
    tgt = jnp.asarray(rng.standard_normal((4, 32, 128)), jnp.float32)
    b_off, b_on = GPT2Block(cfg_off), GPT2Block(cfg_on)
    p_off = b_off.init(jax.random.PRNGKey(0), h, True)
    p_on = b_on.init(jax.random.PRNGKey(0), h, True)
    # the fused path declares the SAME parameters (checkpoints and
    # configs interchange freely)
    assert jax.tree_util.tree_structure(p_off) == \
        jax.tree_util.tree_structure(p_on)
    for a, b in zip(jax.tree_util.tree_leaves(p_off),
                    jax.tree_util.tree_leaves(p_on)):
        np.testing.assert_array_equal(ab(a), ab(b))

    def loss(block, p):
        return (block.apply(p, h, True) * tgt).sum()

    np.testing.assert_allclose(ab(loss(b_off, p_off)),
                               ab(loss(b_on, p_off)), rtol=1e-6)
    g_off = jax.grad(lambda p: loss(b_off, p))(p_off)
    g_on = jax.grad(lambda p: loss(b_on, p))(p_off)
    gmax = max(float(jnp.abs(l).max())
               for l in jax.tree_util.tree_leaves(g_off))
    for a, b in zip(jax.tree_util.tree_leaves(g_off),
                    jax.tree_util.tree_leaves(g_on)):
        np.testing.assert_allclose(ab(a) / gmax, ab(b) / gmax,
                                   atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("pre", [False, True], ids=["post-ln", "pre-ln"])
def test_ds_transformer_layer_fused_parity(pre):
    from deepspeed_tpu.ops.transformer import (
        DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
    rng = np.random.default_rng(0)
    tgt = jnp.asarray(rng.standard_normal((2, 32, 128)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 32, 128)), jnp.float32)

    def mk(fused):
        return DeepSpeedTransformerConfig(
            hidden_size=128, heads=4, intermediate_size=512,
            num_hidden_layers=2, attn_dropout_ratio=0.0,
            hidden_dropout_ratio=0.0, pre_layer_norm=pre,
            fused_ops=fused, training=True)

    lay_off = DeepSpeedTransformerLayer(mk("off"))
    lay_on = DeepSpeedTransformerLayer(mk("on"))
    p0 = lay_off.init(jax.random.PRNGKey(1), x, None, True)
    p1 = lay_on.init(jax.random.PRNGKey(1), x, None, True)
    assert jax.tree_util.tree_structure(p0) == \
        jax.tree_util.tree_structure(p1)

    def loss(lay, p):
        return (lay.apply(p, x, None, True) * tgt).sum()

    np.testing.assert_allclose(ab(loss(lay_off, p0)),
                               ab(loss(lay_on, p0)), rtol=1e-6)
    ga = jax.grad(lambda p: loss(lay_off, p))(p0)
    gb = jax.grad(lambda p: loss(lay_on, p))(p0)
    gmax = max(float(jnp.abs(l).max())
               for l in jax.tree_util.tree_leaves(ga))
    for a, b in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(ab(a) / gmax, ab(b) / gmax,
                                   atol=1e-5, rtol=1e-5)


def test_dropout_active_falls_back():
    """fused_ops='auto' with live dropout must take the unfused path
    (dropout sits between bias and residual) — the layer must still run
    and train-mode apply must not raise."""
    from deepspeed_tpu.models.gpt2 import GPT2Block, tiny_gpt2_config
    cfg = tiny_gpt2_config(n_embd=64, n_head=4, dropout=0.1,
                           fused_ops="auto")
    h = jnp.ones((2, 16, 64), jnp.float32)
    block = GPT2Block(cfg)
    p = block.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, h, False)
    out = block.apply(p, h, False,
                      rngs={"dropout": jax.random.PRNGKey(2)})
    assert out.shape == h.shape
    # forcing "on" under live dropout is a loud error
    cfg_on = tiny_gpt2_config(n_embd=64, n_head=4, dropout=0.1,
                              fused_ops="on")
    with pytest.raises(ValueError):
        GPT2Block(cfg_on).init({"params": jax.random.PRNGKey(0),
                                "dropout": jax.random.PRNGKey(1)},
                               h, False)


# ----------------------------------------------------------------------
# per-fusion remat policy
# ----------------------------------------------------------------------
def test_save_fused_epilogues_policy_resolves():
    from deepspeed_tpu.runtime.activation_checkpointing.checkpointing \
        import resolve_checkpoint_policy
    pol = resolve_checkpoint_policy("save_fused_epilogues")
    assert callable(pol)
    # legacy spellings still resolve
    assert callable(resolve_checkpoint_policy(
        "save_only_these_names:attn_out"))
    assert callable(resolve_checkpoint_policy("dots_saveable"))
    assert resolve_checkpoint_policy(None) is None
    with pytest.raises(ValueError):
        resolve_checkpoint_policy("no_such_policy")
    # the fused save-name set excludes the 4H-wide GeLU output (the
    # roofline bytes verdict) but keeps both LN outputs + the GeLU sum
    assert "fused_gelu_out" not in FUSED_EPILOGUE_SAVE_NAMES
    assert {"fused_ln_out", "fused_ln_sum", "fused_gelu_sum"} <= \
        set(FUSED_EPILOGUE_SAVE_NAMES)


def test_remat_policy_grads_bit_identical():
    """Remat with save_fused_epilogues recomputes strictly less but
    must produce the SAME gradients as full-block remat of the fused
    model (remat never changes values, only what is saved)."""
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, gpt2_config
    ids = np.random.default_rng(0).integers(0, 256, (4, 64)) \
        .astype(np.int32)
    batch = {"input_ids": ids}

    def build(policy):
        cfg = gpt2_config("gpt2-tiny", n_positions=64, dropout=0.0,
                          dtype=jnp.float32, remat=True,
                          remat_policy=policy, fused_ops="on")
        return GPT2ForCausalLM(cfg)

    m_pol, m_full = build("save_fused_epilogues"), build(None)
    p = m_full.init(jax.random.PRNGKey(0),
                    {"input_ids": np.zeros((4, 64), np.int32)})
    g_pol = jax.jit(jax.grad(
        lambda p: m_pol.loss_fn(p, batch, deterministic=True)))(p)
    g_full = jax.jit(jax.grad(
        lambda p: m_full.loss_fn(p, batch, deterministic=True)))(p)
    for a, b in zip(jax.tree_util.tree_leaves(g_pol),
                    jax.tree_util.tree_leaves(g_full)):
        np.testing.assert_array_equal(ab(a), ab(b))


def test_checkpointing_configure_accepts_named_policy():
    from deepspeed_tpu.runtime.activation_checkpointing import \
        checkpointing as ckpt
    ckpt.configure(checkpoint_policy="save_fused_epilogues")
    try:
        def f(x):
            return jnp.sin(x * 2.0).sum()
        x = jnp.ones((8, 8))
        out = jax.grad(lambda x: ckpt.checkpoint(f, x))(x)
        np.testing.assert_allclose(ab(out), ab(jax.grad(f)(x)),
                                   rtol=1e-6)
    finally:
        ckpt.configure()   # reset module state for other tests


# ----------------------------------------------------------------------
# 10-step GPT-2 ZeRO-2 engine loss-tracking A/B
# ----------------------------------------------------------------------
@pytest.mark.parametrize("dtype,tol", [("fp32", 1e-5), ("bf16", 1e-2)],
                         ids=["fp32", "bf16"])
def test_engine_loss_tracking_fused_vs_unfused(dtype, tol):
    """10 ZeRO-2 train steps with fused_ops on vs off: losses track
    within the parity budget (fp32: reassociation roundoff only; bf16:
    the fused fp32 epilogue chain is strictly more precise than the
    bf16-rounded unfused adds, so the arms drift at bf16 epsilon)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, \
        tiny_gpt2_config
    batch, seq = 8, 64
    jdt = jnp.float32 if dtype == "fp32" else jnp.bfloat16

    def build(fused):
        cfg = tiny_gpt2_config(n_positions=seq, dropout=0.0, dtype=jdt,
                               fused_ops=fused)
        model = GPT2ForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            {"input_ids": np.zeros((batch, seq),
                                                   np.int32)})
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={
                "train_micro_batch_size_per_gpu": batch,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 1000,
                "bf16": {"enabled": dtype == "bf16"},
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            })
        return engine

    def mk(i):
        ids = np.random.default_rng(i).integers(
            0, 256, (1, batch, seq)).astype(np.int32)
        return {"input_ids": ids}

    e_on, e_off = build("on"), build("off")
    losses_on, losses_off = [], []
    for i in range(10):
        losses_on.append(float(jax.device_get(
            e_on.train_batch(batch=mk(i)))))
        losses_off.append(float(jax.device_get(
            e_off.train_batch(batch=mk(i)))))
    np.testing.assert_allclose(losses_on, losses_off, atol=tol,
                               rtol=tol)


def test_plain_layernorm_no_nan_on_constant_rows():
    """Review fix: the fast-variance formula can go negative past eps
    under fp32 roundoff on near-constant large rows; the clamp keeps
    the pre-LN leading norm finite (same formula as the fused
    kernel's _ln_stats)."""
    from deepspeed_tpu.ops.transformer.transformer import plain_layernorm
    for mag in (63732.47, 1e4, 987654.0):
        x = jnp.full((1, 768), mag, jnp.float32)
        out = plain_layernorm(x, jnp.ones((768,)), jnp.zeros((768,)),
                              1e-5)
        assert np.isfinite(ab(out)).all(), mag
