"""Zero-stall async checkpointing (ISSUE 3).

The save path is split into a device-side snapshot (jitted copy into
fresh buffers the donating step cannot alias, plus host copies of the
ZeRO-Offload state) and a background writer that serializes into a
`<tag>.tmp` staging dir, fsyncs, atomically renames, and updates
`latest` last. These tests pin:

  * async-saved checkpoints are BIT-identical to sync-saved ones, even
    when training keeps stepping (donating/mutating state) while the
    writer is still serializing — the snapshot-isolation contract;
  * crash atomicity: a save killed mid-write leaves the previous
    `latest` loadable and only a skipped `.tmp` staging dir behind;
  * backpressure (block/drop per checkpoint.queue_policy), rotation
    (checkpoint.keep_last), writer-error propagation;
  * the satellite fixes: fused/mirrored `global_steps`, tag-validation
    behavior, the legacy-pickle deprecation warning, and the
    flops-profiler fallback traceback.
"""

import os
import subprocess
import sys
import threading
import time
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import initialize
from deepspeed_tpu.runtime import checkpoint as ckpt_io
from deepspeed_tpu.runtime.mesh import build_mesh

from tests.simple_model import SimpleModel


def _make_engine(tmp=None, fp16=True, extra_config=None, seed=0):
    model = SimpleModel(hidden_dim=16, seed=seed)
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    if fp16:
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    for k, v in (extra_config or {}).items():
        cfg[k] = v
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, _, _, _ = initialize(model=model,
                                 model_parameters=model.params,
                                 config=cfg, mesh=mesh)
    return engine


def _batch(i, dim=16):
    rng = np.random.RandomState(i)
    x = rng.randn(8, dim).astype(np.float32)
    return {"x": x[None], "y": (x @ np.eye(dim, dtype=np.float32))[None]}


def _train(engine, steps, start=0):
    loss = None
    for i in range(steps):
        loss = engine.train_batch(batch=_batch(start + i))
    return loss


def _assert_dirs_bit_identical(d1, d2):
    assert ckpt_io.checkpoint_dirs_bit_identical(d1, d2), \
        (sorted(os.listdir(d1)), sorted(os.listdir(d2)))


# ----------------------------------------------------------------------
# tentpole: async commit + snapshot isolation
# ----------------------------------------------------------------------
def test_async_save_commits_atomically(tmp_path):
    engine = _make_engine()
    _train(engine, 3)
    assert engine.save_checkpoint(str(tmp_path), tag="t1") is True
    engine.wait_for_checkpoint()
    assert os.path.isdir(tmp_path / "t1")
    assert not os.path.exists(tmp_path / ("t1" + ckpt_io.STAGING_SUFFIX))
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "t1"
    path, _ = engine.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("t1")


def test_async_bit_identical_to_sync_under_concurrent_training(tmp_path):
    """The core contract: a sync save and an async save of the SAME
    state produce bit-identical files — and training onward (donating
    every state buffer) while the async writer is still serializing
    must not change a byte of what lands on disk."""
    engine = _make_engine()
    _train(engine, 3)
    engine.save_checkpoint(str(tmp_path), tag="sync_ref",
                           async_save=False, save_latest=False)
    engine.save_checkpoint(str(tmp_path), tag="async_ref",
                           async_save=True)
    # the state the saves captured, fetched before training moves on
    ref_opt = jax.device_get(engine.state.opt_state)
    # mutate the live state while the writer may still be reading the
    # snapshot: 4 donating steps invalidate every old state buffer
    _train(engine, 4, start=100)
    engine.wait_for_checkpoint()
    _assert_dirs_bit_identical(str(tmp_path / "sync_ref"),
                               str(tmp_path / "async_ref"))
    # and the async checkpoint round-trips into a fresh engine: its
    # opt_state after load equals the saving engine's at save time
    engine2 = _make_engine(seed=7)
    engine2.load_checkpoint(str(tmp_path), tag="async_ref")
    jax.tree_util.tree_map(
        lambda ref, loaded: np.testing.assert_array_equal(
            np.asarray(ref), np.asarray(jax.device_get(loaded))),
        ref_opt, engine2.state.opt_state)


def test_async_bit_identical_offload_wire(tmp_path):
    """Offload engines snapshot host masters/Adam moments/wire
    residual+shadow by copy; continuing to train (which mutates the
    host master IN PLACE) while the writer runs must not leak into the
    files. Compares every npz entry, including aux/offload_wire/*."""
    extra = {
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "offload_wire": {"grad_bits": 8,
                                               "param_bits": 8}},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    }
    engine = _make_engine(fp16=False, extra_config=extra)
    _train(engine, 3)
    engine.save_checkpoint(str(tmp_path), tag="sync_ref",
                           async_save=False, save_latest=False)
    engine.save_checkpoint(str(tmp_path), tag="async_ref")
    _train(engine, 3, start=100)   # in-place host master/moment updates
    engine.wait_for_checkpoint()
    _assert_dirs_bit_identical(str(tmp_path / "sync_ref"),
                               str(tmp_path / "async_ref"))
    engine2 = _make_engine(fp16=False, extra_config=extra, seed=7)
    engine2.load_checkpoint(str(tmp_path), tag="async_ref")
    np.testing.assert_array_equal(engine2._host_master,
                                  np.load(tmp_path / "sync_ref" /
                                          "mp_rank_00_model_states.npz")
                                  ["aux/host_master"])


def test_async_per_layer_pipeline_module(tmp_path):
    """PipelineModule per-layer files ride the same snapshot protocol:
    layer_NN files written by the background writer match a sync save
    byte for byte."""
    import flax.linen as nn
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

    class Dense(nn.Module):
        feats: int = 16

        @nn.compact
        def __call__(self, x):
            return nn.Dense(self.feats)(x)

    specs = [LayerSpec(Dense, 16) for _ in range(4)]
    mod = PipelineModule(layers=specs, num_stages=2,
                         loss_fn=lambda y, lab: jnp.mean(
                             (y - lab).astype(jnp.float32) ** 2))
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    params = mod.init_params(jax.random.PRNGKey(0), x)
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    engine, _, _, _ = initialize(
        model=mod, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 1000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        mesh=mesh)
    engine.train_batch(batch={"x": x, "y": x * 0.5})
    engine.save_checkpoint(str(tmp_path), tag="sync_ref",
                           async_save=False, save_latest=False)
    engine.save_checkpoint(str(tmp_path), tag="async_ref")
    engine.train_batch(batch={"x": x, "y": x * 0.5})
    engine.wait_for_checkpoint()
    assert any(f.startswith("layer_")
               for f in os.listdir(tmp_path / "async_ref"))
    _assert_dirs_bit_identical(str(tmp_path / "sync_ref"),
                               str(tmp_path / "async_ref"))


# ----------------------------------------------------------------------
# backpressure + error propagation
# ----------------------------------------------------------------------
def test_writer_backpressure_blocks(tmp_path):
    engine = _make_engine()   # writer_queue_depth defaults to 1
    _train(engine, 2)
    # warm the snapshot jit so the timed first submit below measures
    # dispatch, not one-time compilation
    engine.save_checkpoint(str(tmp_path), tag="warm")
    engine.wait_for_checkpoint()
    orig = engine._write_checkpoint

    def slow(*a, **k):
        time.sleep(0.5)
        return orig(*a, **k)

    engine._write_checkpoint = slow
    t0 = time.perf_counter()
    engine.save_checkpoint(str(tmp_path), tag="a")
    first_submit = time.perf_counter() - t0
    t1 = time.perf_counter()
    engine.save_checkpoint(str(tmp_path), tag="b")
    second_submit = time.perf_counter() - t1
    engine.wait_for_checkpoint()
    # first submit returns without waiting for the write; the second
    # hits the depth-1 queue and blocks until the first commits
    assert first_submit < 0.4, first_submit
    assert second_submit >= 0.4, second_submit
    assert os.path.isdir(tmp_path / "a") and os.path.isdir(tmp_path / "b")
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "b"


def test_writer_backpressure_drops(tmp_path):
    engine = _make_engine(
        extra_config={"checkpoint": {"queue_policy": "drop"}})
    _train(engine, 2)
    orig = engine._write_checkpoint
    started, release = threading.Event(), threading.Event()

    def gated(*a, **k):
        started.set()
        assert release.wait(timeout=30)
        return orig(*a, **k)

    engine._write_checkpoint = gated
    assert engine.save_checkpoint(str(tmp_path), tag="a") is True
    assert started.wait(timeout=10)
    # second save over the depth: dropped, nothing written for it —
    # and dropped BEFORE paying for the device+host snapshot
    with mock.patch.object(engine, "_checkpoint_snapshot") as snap:
        assert engine.save_checkpoint(str(tmp_path), tag="b") is False
    assert snap.call_count == 0
    release.set()
    engine.wait_for_checkpoint()
    assert os.path.isdir(tmp_path / "a")
    assert not os.path.exists(tmp_path / "b")
    assert not os.path.exists(tmp_path / ("b" + ckpt_io.STAGING_SUFFIX))


def test_same_tag_saves_serialize_under_queue_depth_2(tmp_path):
    """With writer_queue_depth >= 2, a second save to the SAME tag must
    not race the first writer's staging dir (it would rmtree it out
    from under the mid-write first job): same-tag jobs serialize."""
    engine = _make_engine(
        extra_config={"checkpoint": {"writer_queue_depth": 2}})
    _train(engine, 2)
    orig = engine._write_checkpoint
    started, release = threading.Event(), threading.Event()

    def gated(*a, **k):
        if not started.is_set():
            started.set()
            assert release.wait(timeout=30)
        return orig(*a, **k)

    engine._write_checkpoint = gated
    assert engine.save_checkpoint(str(tmp_path), tag="t") is True
    assert started.wait(timeout=10)
    threading.Timer(0.5, release.set).start()
    t0 = time.perf_counter()
    # submit blocks until the in-flight same-tag job commits
    assert engine.save_checkpoint(str(tmp_path), tag="t") is True
    assert time.perf_counter() - t0 >= 0.3
    engine.wait_for_checkpoint()
    assert os.path.isdir(tmp_path / "t")
    assert not os.path.exists(tmp_path / ("t" + ckpt_io.STAGING_SUFFIX))
    path, _ = engine.load_checkpoint(str(tmp_path), tag="t")
    assert path is not None


def test_commits_happen_in_submission_order(tmp_path):
    """queue_depth >= 2: even when the FIRST writer is slow, `latest`
    must end at the last-submitted tag and keep_last rotation must
    never delete it — concurrent writers commit in submission order."""
    engine = _make_engine(
        extra_config={"checkpoint": {"writer_queue_depth": 2,
                                     "keep_last": 1}})
    _train(engine, 2)
    orig = engine._write_checkpoint
    first = threading.Event()

    def stagger(*a, **k):
        if not first.is_set():
            first.set()
            time.sleep(0.5)   # first job serializes slowly
        return orig(*a, **k)

    engine._write_checkpoint = stagger
    assert engine.save_checkpoint(str(tmp_path), tag="older") is True
    assert engine.save_checkpoint(str(tmp_path), tag="newer") is True
    engine.wait_for_checkpoint()
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "newer"
    assert os.path.isdir(tmp_path / "newer")
    assert not os.path.isdir(tmp_path / "older")   # rotated out


def test_later_job_failure_does_not_deadlock_earlier_writer(tmp_path):
    """queue_depth >= 2: a later-submitted job that dies BEFORE its
    commit gate must release only its own turn — the earlier, slower
    writer must still commit (a skipped turn would strand it at the
    gate forever and hang shutdown)."""
    engine = _make_engine(
        extra_config={"checkpoint": {"writer_queue_depth": 2}})
    _train(engine, 2)
    orig = engine._write_checkpoint

    def hooked(save_dir, tag, snap, save_latest, **k):
        if tag == "a":
            time.sleep(0.5)    # job A serializes slowly
            return orig(save_dir, tag, snap, save_latest, **k)
        raise OSError("disk full")   # job B dies before its gate

    engine._write_checkpoint = hooked
    assert engine.save_checkpoint(str(tmp_path), tag="a") is True
    assert engine.save_checkpoint(str(tmp_path), tag="b") is True
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        engine.wait_for_checkpoint()   # must raise, not hang
    assert os.path.isdir(tmp_path / "a")   # A still committed


def test_sync_save_drains_inflight_async_writers(tmp_path):
    """save_checkpoint(async_save=False) with an async writer still in
    flight must wait for it — otherwise it can rmtree the writer's live
    staging dir (same tag) or let `latest` regress (older tag commits
    after the sync save)."""
    engine = _make_engine()
    _train(engine, 2)
    orig = engine._write_checkpoint
    release = threading.Event()

    def gated(save_dir, tag, snap, save_latest, **k):
        if tag == "slow":
            assert release.wait(timeout=30)
        return orig(save_dir, tag, snap, save_latest, **k)

    engine._write_checkpoint = gated
    engine.save_checkpoint(str(tmp_path), tag="slow")
    threading.Timer(0.4, release.set).start()
    t0 = time.perf_counter()
    engine.save_checkpoint(str(tmp_path), tag="final", async_save=False)
    assert time.perf_counter() - t0 >= 0.3   # drained the async writer
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "final"
    assert os.path.isdir(tmp_path / "slow")
    assert os.path.isdir(tmp_path / "final")


def test_global_steps_mirror_survives_gas_change_across_reload(tmp_path):
    """The restored host step mirror comes from the checkpoint's own
    global_steps — rederiving it from micro_steps would double it when
    resuming with a smaller gradient_accumulation_steps."""
    eng_a = _make_engine(
        extra_config={"gradient_accumulation_steps": 2})
    x = np.random.RandomState(0).randn(2, 8, 16).astype(np.float32)
    for _ in range(2):
        eng_a.train_batch(batch={"x": x, "y": x})
    assert eng_a.global_steps == 2
    eng_a.save_checkpoint(str(tmp_path), tag="t")
    eng_a.wait_for_checkpoint()
    eng_b = _make_engine(seed=7)   # gas=1
    eng_b.load_checkpoint(str(tmp_path), tag="t")
    assert eng_b.global_steps == 2   # micro_steps//gas would say 4


def test_resave_existing_tag_commits_and_cleans_up(tmp_path):
    """Re-saving an existing tag replaces it via rename-aside (no
    rmtree of the live checkpoint before the new one is visible) and
    leaves no staging/trash dirs behind."""
    engine = _make_engine()
    _train(engine, 1)
    engine.save_checkpoint(str(tmp_path), tag="t")
    engine.wait_for_checkpoint()
    _train(engine, 2, start=50)
    engine.save_checkpoint(str(tmp_path), tag="t")
    engine.wait_for_checkpoint()
    assert sorted(os.listdir(tmp_path)) == ["latest", "t"]
    path, _ = engine.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("t")


def test_client_state_snapshot_isolated(tmp_path):
    """Nested client_state values mutated after save_checkpoint returns
    (while the writer is still serializing) must not leak into the
    checkpoint — the snapshot deep-copies them."""
    engine = _make_engine()
    _train(engine, 1)
    orig = engine._write_checkpoint
    gate = threading.Event()

    def slow(*a, **k):
        assert gate.wait(timeout=30)
        return orig(*a, **k)

    engine._write_checkpoint = slow
    state = {"metrics": {"acc": 1}}
    engine.save_checkpoint(str(tmp_path), tag="t", client_state=state)
    state["metrics"]["acc"] = 999   # mutate while the writer waits
    gate.set()
    engine.wait_for_checkpoint()
    sd, _ = ckpt_io.load_checkpoint_files(str(tmp_path), "t")
    assert sd["metrics"] == {"acc": 1}


def test_writer_error_reraised_at_barrier(tmp_path):
    engine = _make_engine()
    _train(engine, 1)

    def boom(*a, **k):
        raise OSError("disk full")

    engine._write_checkpoint = boom
    engine.save_checkpoint(str(tmp_path), tag="t")
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        engine.wait_for_checkpoint()
    # the error is consumed; the writer is usable again
    engine.wait_for_checkpoint()


# ----------------------------------------------------------------------
# crash atomicity (satellite 1)
# ----------------------------------------------------------------------
def test_kill_mid_save_previous_latest_still_loads(tmp_path):
    """A process killed between writing the staging files and the
    atomic commit must leave `latest` -> the previous complete tag and
    only a `.tmp` dir for the torn save."""
    child = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import numpy as np
from deepspeed_tpu import initialize
from deepspeed_tpu.runtime import checkpoint as ckpt_io
from deepspeed_tpu.runtime.mesh import build_mesh
from tests.simple_model import SimpleModel

model = SimpleModel(hidden_dim=16, seed=0)
engine, _, _, _ = initialize(
    model=model, model_parameters=model.params,
    config={{"train_micro_batch_size_per_gpu": 8,
            "optimizer": {{"type": "Adam", "params": {{"lr": 1e-3}}}}}},
    mesh=build_mesh({{"pipe": 1, "data": 1, "model": 1}}))
rng = np.random.RandomState(0)
x = rng.randn(8, 16).astype(np.float32)
engine.train_batch(batch={{"x": x[None], "y": x[None]}})
engine.save_checkpoint({str(tmp_path)!r}, tag="good", async_save=False)
# SIGKILL-equivalent at the commit point of the NEXT save: staging
# files exist, the rename and the latest update never happen
ckpt_io.commit_staging_dir = lambda *a, **k: os._exit(9)
engine.save_checkpoint({str(tmp_path)!r}, tag="bad", async_save=False)
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 9, proc.stderr[-2000:]
    # torn save visible only as staging; previous tag + latest intact
    assert os.path.isdir(tmp_path / "good")
    assert not os.path.exists(tmp_path / "bad")
    assert os.path.isdir(tmp_path / ("bad" + ckpt_io.STAGING_SUFFIX))
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "good"

    # elastic reload: saved on the child's 1-device mesh, loaded onto
    # this process's 8-device data mesh
    model = SimpleModel(hidden_dim=16, seed=0)
    engine, _, _, _ = initialize(
        model=model, model_parameters=model.params,
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        mesh=build_mesh({"pipe": 1, "data": 8, "model": 1}))
    path, _ = engine.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("good")


def test_interrupted_save_tag_raises_clear_error(tmp_path):
    os.makedirs(tmp_path / ("t" + ckpt_io.STAGING_SUFFIX))
    with pytest.raises(FileNotFoundError, match="interrupted save"):
        ckpt_io.load_checkpoint_flat(str(tmp_path), "t")


def test_read_latest_tag_skips_staging_names(tmp_path):
    (tmp_path / "latest").write_text("t" + ckpt_io.STAGING_SUFFIX)
    assert ckpt_io.read_latest_tag(str(tmp_path)) is None
    ckpt_io.write_latest_tag(str(tmp_path), "real")
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "real"
    # atomic write leaves no tmp pointer behind
    assert not os.path.exists(tmp_path / ("latest"
                                          + ckpt_io.STAGING_SUFFIX))


# ----------------------------------------------------------------------
# rotation
# ----------------------------------------------------------------------
def test_keep_last_rotation(tmp_path):
    engine = _make_engine(extra_config={"checkpoint": {"keep_last": 2}})
    _train(engine, 1)
    for i in range(3):
        engine.save_checkpoint(str(tmp_path), tag=f"t{i}")
        engine.wait_for_checkpoint()
        time.sleep(0.05)   # distinct mtimes on coarse filesystems
    dirs = sorted(d for d in os.listdir(tmp_path)
                  if os.path.isdir(tmp_path / d))
    assert dirs == ["t1", "t2"], dirs
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "t2"
    # rotation never deletes latest's target even when it is old
    assert engine.load_checkpoint(str(tmp_path))[0].endswith("t2")


# ----------------------------------------------------------------------
# satellite: fused / mirrored global_steps
# ----------------------------------------------------------------------
def test_global_steps_served_from_mirror_under_async_dispatch():
    engine = _make_engine()
    assert engine.async_dispatch_enabled()
    _train(engine, 3)
    with mock.patch.object(jax, "device_get",
                           side_effect=jax.device_get) as dg:
        assert engine.global_steps == 3
    assert dg.call_count == 0
    # the mirror agrees with the device counters at a fence
    gs, sk = jax.device_get((engine.state.global_steps,
                             engine.state.skipped))
    assert int(gs) + int(sk) == 3


def test_global_steps_single_fused_fetch_in_sync_mode():
    engine = _make_engine(
        extra_config={"async_dispatch": {"enabled": False}})
    assert not engine.async_dispatch_enabled()
    _train(engine, 2)
    with mock.patch.object(jax, "device_get",
                           side_effect=jax.device_get) as dg:
        assert engine.global_steps == 2
    assert dg.call_count == 1   # one fused (global_steps, skipped) fetch


# ----------------------------------------------------------------------
# satellite: tag validation + legacy pickle warning
# ----------------------------------------------------------------------
def test_validate_checkpoint_tag_single_process_passes():
    assert ckpt_io.validate_checkpoint_tag("step5") is True
    assert ckpt_io.validate_checkpoint_tag("step5",
                                           fail_on_mismatch=True) is True


def test_validate_checkpoint_tag_mismatch(monkeypatch):
    from jax.experimental import multihost_utils
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda digest: np.stack([digest, digest + 1]))
    with pytest.raises(ValueError,
                       match="not consistent across all processes"):
        ckpt_io.validate_checkpoint_tag("tag_rank0", fail_on_mismatch=True)
    # warn mode: returns False and logs instead of raising
    from deepspeed_tpu.utils.logging import logger
    with mock.patch.object(logger, "warning") as warn:
        assert ckpt_io.validate_checkpoint_tag("tag_rank0") is False
    assert warn.called


def test_legacy_pickle_load_emits_deprecation_warning(tmp_path):
    import pickle
    d = tmp_path / "old"
    d.mkdir()
    with open(d / "mp_rank_00_model_states.pt", "wb") as f:
        pickle.dump({"module": {"w": np.zeros(2, np.float32)},
                     "global_steps": 1}, f)
    from deepspeed_tpu.utils.logging import logger
    with mock.patch.object(logger, "warning") as warn:
        sd, optim_sd = ckpt_io.load_checkpoint_files(str(tmp_path), "old")
    assert any("legacy" in str(c.args[0]) and "pickle" in str(c.args[0])
               for c in warn.call_args_list)
    assert "module" in sd and optim_sd is None


# ----------------------------------------------------------------------
# satellite: flops-profiler fallback logs the full traceback
# ----------------------------------------------------------------------
def test_flops_profiler_fallback_logs_traceback():
    from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
    from deepspeed_tpu.utils.logging import logger
    engine = _make_engine()
    _train(engine, 1)

    def boom(self, *a, **k):
        raise ValueError("donated-buffer retrace boom")

    with mock.patch.object(FlopsProfiler, "profile_jitted", boom), \
            mock.patch.object(logger, "warning") as warn:
        engine._profile_fused_step(_batch(0), None)
    msgs = [str(c.args[0]) for c in warn.call_args_list]
    assert any("flops profile failed" in m and "Traceback" in m
               and "donated-buffer retrace boom" in m for m in msgs)
