"""Unified telemetry tests (ISSUE 5).

Covers:
  * JSONL sink — schema version, atomic whole-line appends (including
    from concurrent threads), parseability;
  * native tfevents sink — file readable without torch/tensorflow,
    CRC-verified, scalars round-trip; `get_summary_writer` serves the
    native writer;
  * fence alignment — with monitor enabled and async dispatch on, the
    hot loop performs ZERO per-step `device_get`/`effects_barrier`
    calls, and a fenced window pays exactly ONE device_get per fence
    (the PR 2 guard, extended);
  * the stall watchdog — fires on an artificially stalled loop, stays
    silent on a healthy one;
  * snapshot() — stable key set across bf16 / fp16 / ZeRO-2 / offload
    engines;
  * a 10-step ZeRO-2(+offload wire) run producing a parseable event
    log with loss, lr, loss_scale, throughput, memory, wire bytes and
    checkpoint-commit events;
  * SynchronizedWallClockTimer.memory_usage aggregation across local
    devices;
  * wall_clock_breakdown riding the fence-aligned span path (no
    per-microstep effects_barrier).
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

import jax

import deepspeed_tpu
from simple_model import SimpleModel
from deepspeed_tpu.monitor import Monitor, SCHEMA_VERSION
from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                          MonitorConfigError)
from deepspeed_tpu.monitor.registry import MetricsRegistry
from deepspeed_tpu.monitor.sinks import JsonlSink
from deepspeed_tpu.monitor.tfevents import (TFEventsWriter, crc32c,
                                            read_tfevents)
from deepspeed_tpu.monitor.watchdog import StallWatchdog


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------
def _make_stacked(seed, bs=16, dim=8, bad=False):
    rng = np.random.RandomState(seed)
    x = rng.randn(bs, dim).astype(np.float32)
    if bad:
        x = np.full((bs, dim), 1e30, np.float32)
    w = np.linspace(-1, 1, dim * dim).reshape(dim, dim).astype(np.float32)
    return {"x": x[None], "y": (x @ w)[None]}


def _engine(config_over=None, monitor=None):
    model = SimpleModel(hidden_dim=8)
    cfg = {
        "train_batch_size": 16,
        "steps_per_print": 10000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(config_over or {})
    if monitor is not None:
        cfg["monitor"] = monitor
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    return engine


# ----------------------------------------------------------------------
# JSONL sink
# ----------------------------------------------------------------------
def test_jsonl_sink_schema_and_parse(tmp_path):
    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path)
    sink.emit({"v": SCHEMA_VERSION, "kind": "metrics", "step": 1,
               "loss": 0.5})
    sink.emit({"v": SCHEMA_VERSION, "kind": "ckpt_commit", "step": 2,
               "tag": "t"})
    sink.close()
    lines = open(path).read().splitlines()
    assert len(lines) == 2
    events = [json.loads(l) for l in lines]
    assert all(e["v"] == SCHEMA_VERSION for e in events)
    assert events[0]["kind"] == "metrics"
    assert events[1]["tag"] == "t"


def test_jsonl_sink_concurrent_appends_stay_whole_lines(tmp_path):
    """The atomic-append contract: events emitted from many threads
    (checkpoint writer, watchdog) interleave as whole lines."""
    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path)
    n_threads, per_thread = 8, 50

    def worker(tid):
        for i in range(per_thread):
            sink.emit({"v": 1, "kind": "metrics", "step": i, "tid": tid,
                       "pad": "x" * 200})

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sink.close()
    events = [json.loads(l) for l in open(path)]   # every line parses
    assert len(events) == n_threads * per_thread
    from collections import Counter
    counts = Counter(e["tid"] for e in events)
    assert all(counts[t] == per_thread for t in range(n_threads))


def test_jsonl_sink_appends_across_instances(tmp_path):
    path = str(tmp_path / "events.jsonl")
    for i in range(2):
        sink = JsonlSink(path)
        sink.emit({"v": 1, "kind": "metrics", "step": i})
        sink.close()
    assert [json.loads(l)["step"] for l in open(path)] == [0, 1]


# ----------------------------------------------------------------------
# native tfevents
# ----------------------------------------------------------------------
def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 zero bytes -> 0x8A9136AA
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"123456789") == 0xE3069283


def test_tfevents_roundtrip_without_torch(tmp_path):
    w = TFEventsWriter(str(tmp_path))
    w.add_scalar("Train/loss", 1.5, step=3, wall_time=123.0)
    w.add_scalars({"a": 1.0, "b": 2.0}, step=4)
    w.close()
    events = read_tfevents(w.path)
    assert events[0]["file_version"] == "brain.Event:2"
    assert events[1]["step"] == 3
    assert events[1]["wall_time"] == 123.0
    assert events[1]["scalars"] == {"Train/loss": 1.5}
    assert events[2]["step"] == 4
    assert events[2]["scalars"] == {"a": 1.0, "b": 2.0}


def test_tfevents_reader_detects_corruption(tmp_path):
    w = TFEventsWriter(str(tmp_path))
    w.add_scalar("x", 1.0, step=1)
    w.close()
    blob = bytearray(open(w.path, "rb").read())
    blob[-6] ^= 0xFF   # flip a byte inside the last record body
    open(w.path, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="corrupt"):
        read_tfevents(w.path)


def test_get_summary_writer_is_native(tmp_path, monkeypatch):
    """The legacy tensorboard config block routes through the native
    writer — importing torch anywhere on this path is a regression."""
    import builtins
    real_import = builtins.__import__

    def no_torch(name, *a, **kw):
        if name == "torch" or name.startswith("torch."):
            raise ImportError("torch is not installed")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_torch)
    engine = _engine({
        "tensorboard": {"enabled": True,
                        "output_path": str(tmp_path / "tb"),
                        "job_name": "job"}})
    assert engine.summary_writer is not None
    engine.summary_writer.add_scalar("t", 2.0, 1)
    engine.summary_writer.close()
    files = glob.glob(str(tmp_path / "tb" / "job" /
                          "events.out.tfevents.*"))
    assert files
    events = read_tfevents(files[0])
    assert events[1]["scalars"] == {"t": 2.0}


def test_summary_writer_fallback_warns_and_returns_none(tmp_path):
    engine = _engine()
    # unusable log dir (a file where the dir should be)
    blocker = tmp_path / "blocked"
    blocker.write_text("not a dir")
    engine._config.tensorboard_output_path = str(blocker)
    assert engine.get_summary_writer() is None


# ----------------------------------------------------------------------
# config block
# ----------------------------------------------------------------------
def test_monitor_config_defaults_and_validation():
    cfg = DeepSpeedMonitorConfig({})
    assert cfg.enabled is False
    assert list(cfg.sinks) == ["jsonl"]
    assert cfg.stall_timeout_sec == 0
    with pytest.raises(MonitorConfigError):
        DeepSpeedMonitorConfig({"monitor": {"sinks": ["nope"]}})
    with pytest.raises(MonitorConfigError):
        DeepSpeedMonitorConfig({"monitor": {"stall_timeout_sec": -1}})
    with pytest.raises(MonitorConfigError):
        DeepSpeedMonitorConfig({"monitor": {"flush_interval": -2}})
    cfg = DeepSpeedMonitorConfig(
        {"monitor": {"enabled": True,
                     "sinks": [{"type": "tensorboard"}, "jsonl"],
                     "stall_timeout_sec": 5}})
    assert cfg.enabled and cfg.stall_timeout_sec == 5


# ----------------------------------------------------------------------
# fence alignment (the PR 2 guard, extended for the monitor)
# ----------------------------------------------------------------------
class _SyncCounters:
    def __init__(self, monkeypatch):
        self.device_get = 0
        self.effects_barrier = 0
        real_get, real_barrier = jax.device_get, jax.effects_barrier

        def counting_get(x):
            self.device_get += 1
            return real_get(x)

        def counting_barrier():
            self.effects_barrier += 1
            return real_barrier()

        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "effects_barrier", counting_barrier)


def _guard_engine(tmp_path, mode="bf16", steps_per_sync=10000,
                  wall_clock=False):
    cfg = {
        "train_batch_size": 16,
        "steps_per_print": 10000,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_max_lr": 1e-3,
                                 "warmup_num_steps": 10}},
        "async_dispatch": {"enabled": True,
                           "steps_per_sync": steps_per_sync},
        "wall_clock_breakdown": wall_clock,
    }
    cfg["fp16" if mode == "fp16" else "bf16"] = \
        {"enabled": True, "initial_scale_power": 4} \
        if mode == "fp16" else {"enabled": True}
    return _engine(cfg, monitor={"enabled": True, "sinks": ["jsonl"],
                                 "output_path": str(tmp_path)})


@pytest.mark.parametrize("mode", ["bf16", "fp16"])
def test_monitor_hot_path_zero_per_step_syncs(mode, tmp_path,
                                              monkeypatch):
    """monitor.enabled=true + async dispatch: N train_batch steps
    between fences perform ZERO device_get / effects_barrier calls —
    telemetry folds device-side."""
    engine = _guard_engine(tmp_path, mode)
    batches = [engine.stage_batch(_make_stacked(i)) for i in range(8)]
    for b in batches[:3]:
        engine.train_batch(batch=b)
    counters = _SyncCounters(monkeypatch)
    for b in batches[3:]:
        engine.train_batch(batch=b)
    assert counters.device_get == 0, \
        f"{mode}+monitor hot path device_get x{counters.device_get}"
    assert counters.effects_barrier == 0
    engine.monitor.close()


def test_monitor_fence_costs_exactly_one_device_get(tmp_path,
                                                    monkeypatch):
    """A fenced window pays ONE device_get per fence — the drain of
    the retained device metrics — and nothing per step."""
    engine = _guard_engine(tmp_path, "bf16", steps_per_sync=4)
    batches = [engine.stage_batch(_make_stacked(i)) for i in range(16)]
    # warmup past compile AND past the first fences
    for b in batches[:8]:
        engine.train_batch(batch=b)
    assert engine._host_steps == 8   # next fences at 12 and 16
    counters = _SyncCounters(monkeypatch)
    for b in batches[8:]:
        engine.train_batch(batch=b)
    assert counters.device_get == 2, \
        f"expected 1 device_get per fence (2 fences), got " \
        f"{counters.device_get}"
    assert counters.effects_barrier == 0
    # and the fences actually recorded metrics
    log = os.path.join(str(tmp_path), "events.jsonl")
    kinds = [json.loads(l)["kind"] for l in open(log)]
    assert kinds.count("metrics") >= 2
    engine.monitor.close()


def test_wall_clock_breakdown_does_not_barrier_per_step(tmp_path,
                                                        monkeypatch):
    """wall_clock_breakdown=true now rides the fence-aligned span path:
    zero effects_barrier in the hot loop (the legacy timers fenced the
    device twice per microstep)."""
    engine = _guard_engine(tmp_path, "bf16", wall_clock=True)
    batches = [engine.stage_batch(_make_stacked(i)) for i in range(6)]
    for b in batches[:3]:
        engine.train_batch(batch=b)
    counters = _SyncCounters(monkeypatch)
    for b in batches[3:]:
        engine.train_batch(batch=b)
    assert counters.effects_barrier == 0
    assert counters.device_get == 0
    # spans recorded host-side and drain at the fence
    spans = engine.monitor.trace.drain()
    assert "step" in spans and spans["step"]["count"] == 6
    engine.monitor.close()


def test_wall_clock_breakdown_logs_spans_without_monitor():
    """wall_clock_breakdown=true must keep producing breakdown output on
    its own — the monitor block is NOT required (regression: the span
    line only ever fired inside the monitor.enabled branch)."""
    import logging

    class _Collect(logging.Handler):
        def __init__(self):
            super().__init__()
            self.messages = []

        def emit(self, record):
            self.messages.append(record.getMessage())

    engine = _engine({"wall_clock_breakdown": True, "steps_per_print": 2})
    assert not engine.monitor.enabled
    handler = _Collect()
    logging.getLogger("DeepSpeedTPU").addHandler(handler)
    try:
        for i in range(4):
            engine.train_batch(batch=_make_stacked(i))
    finally:
        logging.getLogger("DeepSpeedTPU").removeHandler(handler)
    span_lines = [m for m in handler.messages if "span ms/step" in m]
    assert span_lines, "no span breakdown logged with monitor disabled"
    assert "step" in span_lines[-1]
    engine.monitor.close()


def test_flatten_numeric_keeps_nested_metadata_names():
    """Only TOP-level event metadata (v/ts/step/kind) is excluded from
    the TensorBoard flattening — a nested span named "step" must
    survive (regression: the filter applied at every depth)."""
    from deepspeed_tpu.monitor.sinks import _flatten_numeric
    event = {"v": 1, "ts": 1.0, "step": 10, "kind": "metrics",
             "loss": 2.5,
             "spans": {"forward": {"ms_per": 1.0},
                       "step": {"ms": 4.0, "count": 2, "ms_per": 2.0}}}
    flat = _flatten_numeric(event)
    assert flat["spans/step/ms_per"] == 2.0
    assert flat["spans/forward/ms_per"] == 1.0
    assert flat["loss"] == 2.5
    assert "step" not in flat and "v" not in flat


def test_forward_backward_step_spans_recorded(tmp_path):
    engine = _guard_engine(tmp_path, "bf16", wall_clock=True)
    batch = {"x": np.random.RandomState(0).randn(16, 8).astype(np.float32),
             "y": np.zeros((16, 8), np.float32)}
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    spans = engine.monitor.trace.drain()
    assert {"forward", "backward", "step"} <= set(spans)
    engine.monitor.close()


# ----------------------------------------------------------------------
# watchdog
# ----------------------------------------------------------------------
def test_watchdog_fires_on_stall_and_not_on_healthy():
    fired = []
    wd = StallWatchdog(timeout_sec=0.3, on_stall=fired.append,
                       poll_interval=0.05)
    try:
        wd.arm()
        # healthy: fences keep arriving inside the timeout
        for _ in range(4):
            time.sleep(0.1)
            wd.notify_fence()
        assert not fired and wd.stall_count == 0
        # stall: no fence for > timeout
        wd.heartbeat("prefetch")
        deadline = time.time() + 3.0
        while not fired and time.time() < deadline:
            time.sleep(0.05)
        assert fired, "watchdog did not fire on a stalled loop"
        diag = fired[0]
        assert diag["fence_age_sec"] >= 0.3
        assert "prefetch" in diag["heartbeat_age_sec"]
        # one episode fires once, then re-arms on the next fence
        n = len(fired)
        time.sleep(0.5)
        assert len(fired) == n
        wd.notify_fence()
        assert wd.stall_count == 1
    finally:
        wd.stop()


def test_watchdog_engine_wiring_stalled_vs_healthy(tmp_path):
    """End-to-end: a training loop that stops stepping trips the
    watchdog; one that keeps fencing does not."""
    engine = _engine(
        {"async_dispatch": {"enabled": True, "steps_per_sync": 1},
         "bf16": {"enabled": True}},
        monitor={"enabled": True, "sinks": ["jsonl"],
                 "output_path": str(tmp_path),
                 "stall_timeout_sec": 0.4})
    engine.monitor.watchdog._poll = 0.05   # fast polling for the test
    fired = []
    engine.monitor.watchdog.on_stall = fired.append
    for i in range(6):
        engine.train_batch(batch=_make_stacked(i))
    assert not fired, "healthy loop tripped the watchdog"
    time.sleep(1.0)     # artificial stall: loop stops stepping
    assert fired, "stalled loop did not trip the watchdog"
    # the stall event also landed in the sink
    log = os.path.join(str(tmp_path), "events.jsonl")
    kinds = [json.loads(l)["kind"] for l in open(log)]
    assert "stall" in kinds
    engine.monitor.close()


def test_monitor_disabled_creates_no_watchdog_or_sinks(tmp_path):
    engine = _engine({"bf16": {"enabled": True}})
    assert engine.monitor.enabled is False
    assert engine.monitor.watchdog is None
    assert engine.monitor.sinks == []
    engine.train_batch(batch=_make_stacked(0))
    assert engine.monitor.on_fence() is None
    # snapshot still answers with the stable schema
    snap = engine.monitor.snapshot()
    assert set(snap) == set(Monitor.SNAPSHOT_KEYS)


# ----------------------------------------------------------------------
# snapshot schema stability
# ----------------------------------------------------------------------
_SNAP_CONFIGS = {
    "bf16": {"bf16": {"enabled": True}},
    "fp16": {"fp16": {"enabled": True, "initial_scale_power": 4}},
    "zero2": {"bf16": {"enabled": True},
              "zero_optimization": {"stage": 2}},
    "offload": {"bf16": {"enabled": True},
                "zero_optimization": {
                    "stage": 2, "cpu_offload": True,
                    "offload_wire": {"grad_bits": 8, "param_bits": 8}}},
}


@pytest.mark.parametrize("name", sorted(_SNAP_CONFIGS))
def test_snapshot_keys_stable_across_engines(name, tmp_path):
    engine = _engine(_SNAP_CONFIGS[name],
                     monitor={"enabled": True, "sinks": [],
                              "output_path": str(tmp_path)})
    for i in range(3):
        engine.train_batch(batch=_make_stacked(i))
    snap = engine.monitor.snapshot()
    assert set(snap) == set(Monitor.SNAPSHOT_KEYS)
    assert snap["schema"] == SCHEMA_VERSION
    assert snap["step"] == 3
    assert np.isfinite(snap["loss"])
    assert snap["lr"] is not None
    assert set(snap["wire"]) == {"d2h_bytes", "h2d_bytes", "grad_bits",
                                 "param_bits"}
    assert set(snap["checkpoint"]) == {"queue_depth", "commits",
                                       "last_commit_ms"}
    assert set(snap["prefetch"]) == {"occupancy", "depth"}
    if name == "offload":
        assert snap["wire"]["d2h_bytes"] > 0
        assert snap["wire"]["grad_bits"] == 8
    else:
        assert snap["wire"]["d2h_bytes"] == 0
    engine.monitor.close()


# ----------------------------------------------------------------------
# the acceptance run: 10-step ZeRO-2 with the JSONL sink
# ----------------------------------------------------------------------
def test_ten_step_zero2_event_log(tmp_path):
    """10 ZeRO-2(+offload-wire) steps with a checkpoint save produce a
    parseable event log containing loss, lr, loss_scale, throughput,
    memory, wire bytes, and a checkpoint-commit event."""
    engine = _engine(
        {"bf16": {"enabled": True},
         "steps_per_print": 5,
         "zero_optimization": {"stage": 2, "cpu_offload": True,
                               "offload_wire": {"grad_bits": 8,
                                                "param_bits": 8}}},
        monitor={"enabled": True, "sinks": ["jsonl", "tensorboard"],
                 "output_path": str(tmp_path)})
    micro = [{k: v[0] for k, v in _make_stacked(i).items()}
             for i in range(10)]
    loader = engine.prefetch(iter(micro))
    for i in range(10):
        engine.train_batch(data_iter=loader)
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    engine.wait_for_checkpoint()
    engine.monitor.on_fence()     # final drain for the tail steps
    engine.monitor.close()
    loader.close()

    log = os.path.join(str(tmp_path), "events.jsonl")
    events = [json.loads(l) for l in open(log)]
    assert all(e["v"] == SCHEMA_VERSION for e in events)
    metrics = [e for e in events if e["kind"] == "metrics"]
    assert metrics, events
    for e in metrics:
        for key in ("loss", "lr", "loss_scale", "samples_per_sec",
                    "memory", "wire", "checkpoint", "prefetch"):
            assert key in e, (key, e)
        assert np.isfinite(e["loss"])
    assert any(e["wire"]["d2h_bytes"] > 0 for e in metrics)
    commits = [e for e in events if e["kind"] == "ckpt_commit"]
    assert commits and commits[0]["wall_ms"] > 0
    assert commits[0]["tag"].startswith("global_step")

    # the tensorboard sink wrote a loadable (torch-free) file
    tb = glob.glob(os.path.join(str(tmp_path), "tb",
                                "events.out.tfevents.*"))
    assert tb
    tb_events = read_tfevents(tb[0])
    tags = set()
    for e in tb_events:
        tags |= set(e["scalars"])
    assert "monitor/metrics/loss" in tags
    assert "monitor/metrics/wire/d2h_bytes" in tags


# ----------------------------------------------------------------------
# registry unit behavior
# ----------------------------------------------------------------------
def test_registry_compaction_bounds_retention():
    reg = MetricsRegistry()
    reg._COMPACT_AT = 8
    for i in range(30):
        reg.fold_step(loss=float(i), grad_norm=1.0, loss_scale=2.0,
                      overflow=(i % 10 == 0), tokens=100)
    assert len(reg._pending) < 8
    out = reg.drain_device()
    assert out["steps"] == 30
    np.testing.assert_allclose(out["loss"], np.mean(np.arange(30.0)))
    assert out["overflow_count"] == 3
    assert out["tokens"] == 3000
    assert out["loss_scale"] == 2.0
    assert reg.drain_device() is None


def test_registry_counters_and_gauges():
    reg = MetricsRegistry()
    reg.inc("c", 2.0)
    reg.inc("c")
    reg.set_counter("d", 7.0)
    assert reg.counters() == {"c": 3.0, "d": 7.0}
    reg.add_gauge("g", lambda: 1.5)
    reg.add_gauge("h", lambda: {"a": 1.0})
    reg.add_gauge("boom", lambda: 1 / 0)   # failures are swallowed
    assert reg.sample_gauges() == {"g": 1.5, "h/a": 1.0}


# ----------------------------------------------------------------------
# memory aggregation satellite
# ----------------------------------------------------------------------
def test_memory_usage_aggregates_local_devices(monkeypatch):
    from deepspeed_tpu.utils import timer as timer_mod

    class FakeDev:
        def __init__(self, in_use, peak):
            self._s = {"bytes_in_use": in_use, "peak_bytes_in_use": peak}

        def memory_stats(self):
            return self._s

    gib = 1024 ** 3
    monkeypatch.setattr(jax, "local_devices",
                        lambda: [FakeDev(1 * gib, 2 * gib),
                                 FakeDev(3 * gib, 5 * gib)])
    stats = timer_mod.device_memory_stats()
    assert stats["in_use_bytes"] == 4 * gib     # sum across devices
    assert stats["peak_bytes"] == 5 * gib       # max across devices
    assert stats["device_count"] == 2
    text = timer_mod.SynchronizedWallClockTimer.memory_usage()
    assert "4.0 GB" in text and "5.0 GB" in text and "2 local" in text


def test_ds_report_smoke(capsys):
    from deepspeed_tpu import env_report
    env_report.main()
    out = capsys.readouterr().out
    assert "monitor sinks" in out
    assert "jax version" in out
    assert "Pallas flash attention" in out


def test_snapshot_mfu_and_tokens_per_sec(tmp_path):
    """ISSUE 6 satellite: once the throughput timer has a warmed
    measurement window, snapshot() (and the fence metrics event) carry
    the bench-computed tokens_per_sec_per_chip — and mfu on TPU (None
    on CPU, where no nominal peak applies).  Pre-warmup both keys are
    present with None (schema stability, not missing keys)."""
    engine = _engine({"steps_per_print": 4},
                     monitor={"enabled": True, "sinks": [],
                              "output_path": str(tmp_path)})
    snap0 = engine.monitor.snapshot()
    assert set(snap0) == set(Monitor.SNAPSHOT_KEYS)
    assert snap0["tokens_per_sec_per_chip"] is None
    assert snap0["mfu"] is None
    # steps_per_print=4 -> the tput window fences after ~4 microsteps
    for i in range(10):
        engine.train_batch(batch=_make_stacked(i))
    snap = engine.monitor.snapshot()
    assert snap["tokens_per_sec_per_chip"] is not None
    assert snap["tokens_per_sec_per_chip"] > 0
    import jax
    if jax.devices()[0].platform != "tpu":
        assert snap["mfu"] is None   # no nominal CPU peak to divide by
    # the fence event shares the derived keys
    event = engine.monitor.on_fence()
    if event is not None:
        assert "tokens_per_sec_per_chip" in event and "mfu" in event
