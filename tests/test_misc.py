"""Parity tests for clusters the reference covers in separate files:
argparse helpers (ref tests/unit/test_ds_arguments.py), multi-output
models (test_multi_output_model.py), the dataloader (test_data.py),
progressive layer drop (test_pld.py), and partition utilities
(test_runtime_utils.py)."""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader)
from deepspeed_tpu.runtime.utils import (partition_balanced,
                                         partition_uniform)
from simple_model import SimpleModel


# ----------------------------------------------------------------------
# argparse helpers (ref test_ds_arguments.py)
# ----------------------------------------------------------------------
def test_add_config_arguments():
    parser = argparse.ArgumentParser()
    parser = deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args(["--deepspeed", "--deepspeed_config",
                              "cfg.json"])
    assert args.deepspeed is True
    assert args.deepspeed_config == "cfg.json"
    args = parser.parse_args([])
    assert args.deepspeed is False
    assert args.deepspeed_config is None


def test_add_config_arguments_preserves_existing():
    parser = argparse.ArgumentParser()
    parser.add_argument("--my_flag", type=int, default=3)
    parser = deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args(["--my_flag", "7", "--deepspeed"])
    assert args.my_flag == 7 and args.deepspeed


# ----------------------------------------------------------------------
# multi-output model (ref test_multi_output_model.py)
# ----------------------------------------------------------------------
class TwoOutputModel:
    """Engine-protocol model with two heads whose weighted losses sum —
    the reference's MultiOutputModel shape."""

    def __init__(self, dim=16, seed=0):
        rng = np.random.RandomState(seed)
        self.params = {
            "w1": jnp.asarray(rng.randn(dim, dim) * 0.1, jnp.float32),
            "w2": jnp.asarray(rng.randn(dim, dim) * 0.1, jnp.float32),
        }
        self.weights = (0.3, 0.7)

    def loss_fn(self, params, batch, rngs=None, deterministic=False):
        x = batch["x"].astype(jnp.float32)
        y1 = batch["y1"].astype(jnp.float32)
        y2 = batch["y2"].astype(jnp.float32)
        l1 = jnp.mean((x @ params["w1"] - y1) ** 2)
        l2 = jnp.mean((x @ params["w2"] - y2) ** 2)
        return self.weights[0] * l1 + self.weights[1] * l2


def test_multi_output_model_trains():
    model = TwoOutputModel()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={"train_batch_size": 16, "steps_per_print": 1000,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-2}}})
    rng = np.random.RandomState(0)
    x = rng.randn(16, 16).astype(np.float32)
    w = np.linspace(-1, 1, 256).reshape(16, 16).astype(np.float32)
    batch = {"x": x[None], "y1": (x @ w)[None], "y2": (x @ w.T)[None]}
    losses = [float(jax.device_get(engine.train_batch(batch=batch)))
              for _ in range(20)]
    assert losses[-1] < losses[0] * 0.5, losses


# ----------------------------------------------------------------------
# dataloader (ref test_data.py)
# ----------------------------------------------------------------------
def test_dataloader_batches_and_len():
    data = [{"x": np.full((4,), i, np.float32)} for i in range(32)]
    dl = DeepSpeedDataLoader(dataset=data, batch_size=8)
    batches = list(dl)
    assert len(dl) == 4 and len(batches) == 4
    assert batches[0]["x"].shape == (8, 4)


def test_dataloader_rank_slicing():
    """Each dp rank must see a disjoint shard of the dataset."""
    data = [{"x": np.full((2,), i, np.float32)} for i in range(16)]
    seen = []
    for rank in range(2):
        dl = DeepSpeedDataLoader(dataset=data, batch_size=4,
                                 data_parallel_world_size=2,
                                 data_parallel_rank=rank)
        for b in dl:
            seen.extend(b["x"][:, 0].tolist())
    assert sorted(set(seen)) == list(range(16))
    assert len(seen) == 16  # disjoint, complete


def test_repeating_loader():
    data = [{"x": np.zeros((2,), np.float32)} for _ in range(4)]
    dl = RepeatingLoader(DeepSpeedDataLoader(dataset=data, batch_size=2))
    # draws past one epoch (2 batches) keep yielding
    got = [next(dl) for _ in range(7)]
    assert len(got) == 7


# ----------------------------------------------------------------------
# progressive layer drop (ref test_pld.py)
# ----------------------------------------------------------------------
def test_pld_theta_schedule_and_training():
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config
    cfg = tiny_gpt2_config(n_layer=2, dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(0, 256,
                                           (8, 32)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "steps_per_print": 1000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "progressive_layer_drop": {"enabled": True,
                                           "theta": 0.5, "gamma": 0.01}})
    assert engine.pld_enabled()
    thetas = []
    for _ in range(3):
        loss = engine.train_batch(batch={"input_ids": ids[None]})
        thetas.append(engine.pld_theta())
    assert np.isfinite(float(jax.device_get(loss)))
    # theta(t) = (1-theta)exp(-gamma t) + theta: decreasing toward theta
    assert thetas[0] >= thetas[-1] >= 0.5


# ----------------------------------------------------------------------
# partition utilities (ref test_runtime_utils.py)
# ----------------------------------------------------------------------
def test_partition_uniform():
    parts = partition_uniform(10, 3)
    assert parts[0] == 0 and parts[-1] == 10 and len(parts) == 4
    sizes = np.diff(parts)
    assert sizes.max() - sizes.min() <= 1


def test_partition_balanced():
    weights = [1, 1, 1, 100, 1, 1]
    parts = partition_balanced(weights, 2)
    assert parts[0] == 0 and parts[-1] == len(weights)
    # the heavy item must not share a part with everything else
    loads = [sum(weights[parts[i]:parts[i + 1]]) for i in range(2)]
    assert max(loads) <= 103
