"""CSR sparse-gradient tests (ref `tests/unit/test_csr.py` + the
engine's sparse embedding-grad path, ref `engine.py:1190-1246`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.csr_tensor import CSRTensor, csr_mean_rows
from deepspeed_tpu.runtime.mesh import build_mesh


def _row_sparse(rows=32, cols=8, touched=(1, 5, 17), seed=0):
    rng = np.random.default_rng(seed)
    dense = np.zeros((rows, cols), np.float32)
    for r in touched:
        dense[r] = rng.normal(size=cols)
    return jnp.asarray(dense)


def test_csr_roundtrip():
    dense = _row_sparse()
    csr = CSRTensor(dense, capacity=8)
    np.testing.assert_allclose(np.asarray(csr.to_dense()),
                               np.asarray(dense))
    sparse_size, dense_size = csr.sparse_size()
    assert sparse_size < dense_size


def test_csr_add():
    a = CSRTensor(_row_sparse(touched=(1, 5)), capacity=4)
    b = CSRTensor(_row_sparse(touched=(2, 5), seed=1), capacity=4)
    expected = np.asarray(a.to_dense()) + np.asarray(b.to_dense())
    a.add(b)
    np.testing.assert_allclose(np.asarray(a.to_dense()), expected,
                               rtol=1e-6)


def test_csr_mean_rows_matches_pmean():
    """Inside shard_map, the sparse gather-reduce must equal the dense
    pmean for row-sparse per-device grads."""
    from deepspeed_tpu.runtime.compat import shard_map
    mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    rows, cols = 64, 16
    rng = np.random.default_rng(0)
    # per-device row-sparse grads: each device touches 3 distinct rows
    locals_ = np.zeros((8, rows, cols), np.float32)
    for d in range(8):
        for r in rng.choice(rows, size=3, replace=False):
            locals_[d, r] = rng.normal(size=cols)
    stacked = jnp.asarray(locals_.reshape(8 * rows, cols))

    def sparse_fn(x):
        return csr_mean_rows(x, "data", capacity=3)

    def dense_fn(x):
        return jax.lax.pmean(x, "data")

    out_sparse = shard_map(
        sparse_fn, mesh=mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False)(stacked)
    out_dense = shard_map(
        dense_fn, mesh=mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False)(stacked)
    np.testing.assert_allclose(np.asarray(out_sparse),
                               np.asarray(out_dense), rtol=1e-6,
                               atol=1e-7)


class _EmbeddingClassifier:
    """Untied-embedding model (the reference's CSR scope is
    torch.nn.Embedding grads, which are pure-gather row-sparse —
    a tied LM head would make the grad dense)."""

    VOCAB, DIM, CLASSES = 512, 16, 4

    def __init__(self):
        import flax.linen as nn

        class Mod(nn.Module):
            @nn.compact
            def __call__(self, ids):
                emb = self.param("embedding",
                                 nn.initializers.normal(0.02),
                                 (_EmbeddingClassifier.VOCAB,
                                  _EmbeddingClassifier.DIM))
                h = emb[ids].mean(axis=1)
                return nn.Dense(_EmbeddingClassifier.CLASSES)(h)
        self.module = Mod()

    def init(self, rng, batch):
        return self.module.init(rng, batch["input_ids"])["params"]

    def loss_fn(self, params, batch, rngs=None, deterministic=False):
        logits = self.module.apply({"params": params},
                                   batch["input_ids"])
        labels = batch["input_ids"][:, 0] % self.CLASSES
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[:, None], 1)[:, 0]
        return jnp.mean(logz - gold)

    def sparse_grad_paths(self):
        return ("embedding",)


def _engine(sparse, mesh):
    from deepspeed_tpu import initialize
    model = _EmbeddingClassifier()
    ids = np.random.default_rng(0).integers(
        0, model.VOCAB, (16, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 16,
                "sparse_gradients": sparse,
                "zero_optimization": {"stage": 0},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        mesh=mesh)
    return engine, ids


def test_sparse_path_update_matches_dense(mesh8):
    """End-to-end: training with sparse_gradients on/off produces the
    same losses and parameters (the CSR path changes the communication
    pattern, never the numerics)."""
    e_dense, ids = _engine(False, mesh8)
    e_sparse, _ = _engine(True, mesh8)
    assert e_sparse._use_shardmap_grads
    assert not e_dense._use_shardmap_grads

    for i in range(3):
        ld = e_dense.train_batch(batch={"input_ids": ids[None]})
        ls = e_sparse.train_batch(batch={"input_ids": ids[None]})
    ld, ls = float(jax.device_get(ld)), float(jax.device_get(ls))
    assert abs(ld - ls) < 1e-4, (ld, ls)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)),
            rtol=1e-4, atol=1e-5),
        jax.device_get(e_dense.state.params),
        jax.device_get(e_sparse.state.params))


def test_sparse_path_uses_all_gather(mesh8):
    """The embedding grad must ride an all-gather of (indices, values),
    not a dense allreduce (the whole point, ref engine.py:1190)."""
    e_sparse, ids = _engine(True, mesh8)
    jaxpr = jax.make_jaxpr(
        lambda p, b, r, s: e_sparse._micro_grad(p, b, r, s, None))(
            e_sparse.state.params, {"input_ids": jnp.asarray(ids)},
            jax.random.PRNGKey(0), jnp.float32(1.0))
    assert "all_gather" in str(jaxpr)
