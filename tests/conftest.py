"""Test harness: single-process multi-device on CPU.

The reference forks N processes with real NCCL per distributed test
(`tests/unit/common.py:16-104`); the TPU-native equivalent is an 8-device
virtual CPU mesh in one process (SURVEY §4). Must set XLA flags before
jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    _flags += " --xla_force_host_platform_device_count=8"
if "collective_call_terminate_timeout" not in _flags and \
        os.environ.get("DS_TPU_RUN_13B"):
    # 8 virtual device threads share ONE core here: at big-model scale
    # (test_zero3_13b full run, DS_TPU_RUN_13B=1) they reach a
    # collective's rendezvous minutes apart, tripping XLA-CPU's default
    # 40 s terminate deadline. XLA aborts the PROCESS on unknown flags
    # (parse_flags_from_env), and newer builds dropped these names — so
    # they are gated to the 13B run and probed in a subprocess first;
    # the regular tier never risks the abort.
    _cand = (" --xla_cpu_collective_call_terminate_timeout_seconds=3600"
             " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600")
    import subprocess
    import sys
    _probe = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"],
        env={**os.environ, "XLA_FLAGS": _flags + _cand,
             "JAX_PLATFORMS": "cpu"},
        capture_output=True)
    if _probe.returncode == 0:
        _flags += _cand
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402

# The container's sitecustomize pins jax_platforms to the TPU plugin before
# conftest runs; override it after import (env alone is not enough).
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, jax.devices()

# Persistent compilation cache: most of the suite's wall time is XLA
# compiles of the same tiny-model programs; caching them makes reruns
# minutes faster (first run pays full price and fills the cache).
_cache_dir = os.environ.get("JAX_TEST_COMPILATION_CACHE",
                            os.path.join(os.path.dirname(__file__),
                                         "..", ".jax_test_cache"))
jax.config.update("jax_compilation_cache_dir",
                  os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (deselect with -m 'not slow' "
                   "for the <3 min fast tier)")


# Heaviest tests by measured duration (cold-cache full-suite run); the
# fast tier is `pytest -m "not slow"`. Subprocess-based suites
# (tests/model/, launcher e2e, parity sweep) mark themselves.
_SLOW_TESTS = {
    "test_gpt2_trains_with_sequence_parallel_config",
    "test_pipeline_engine_matches_dense_engine_losses",
    "test_offload_engine_matches_device_engine",
    "test_gpt2_tiny_trains",
    "test_gpt2_ring_sequence_parallel_matches",
    "test_elastic_reload_different_mesh",
    "test_ring_attention_grads_match_dense",
    "test_pipeline_engine_trains_3d",
    "test_engine_sr_mode_loss_descends",
    "test_save_writes_shard_files_no_pickle",
    "test_engine_profile_step_runs",
    "test_bert_pretraining_trains",
    "test_pld_theta_schedule_and_training",
    "test_sr_trajectory_matches_fp32_master",
    "test_1f1b_matches_sequential_chain",
    "test_offload_checkpoint_roundtrip",
    "test_1f1b_bf16_transport_matches_sequential",
    "test_sparse_path_update_matches_dense",
    "test_1f1b_with_zero2_padding",
    "test_offload_multi_chunk_pipeline_matches_device",
    "test_1f1b_tied_layers_sum_grads",
    "test_grads_match_dense",
    "test_tied_layer_spec_shares_weights",
    "test_csr_mean_rows_matches_pmean",
    "test_ulysses_grads_match_dense",
    "test_pipeline_loss_matches_sequential",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.name.split("[")[0]
        if base in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def mesh8():
    """2-axis (data=8) mesh over the virtual devices."""
    from deepspeed_tpu.runtime.mesh import build_mesh
    return build_mesh({"pipe": 1, "data": 8, "model": 1})


@pytest.fixture
def tmp_ckpt_dir(tmp_path):
    return str(tmp_path / "ckpt")
