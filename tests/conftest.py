"""Test harness: single-process multi-device on CPU.

The reference forks N processes with real NCCL per distributed test
(`tests/unit/common.py:16-104`); the TPU-native equivalent is an 8-device
virtual CPU mesh in one process (SURVEY §4). Must set XLA flags before
jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = \
        _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The container's sitecustomize pins jax_platforms to the TPU plugin before
# conftest runs; override it after import (env alone is not enough).
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, jax.devices()

import pytest  # noqa: E402


@pytest.fixture
def mesh8():
    """2-axis (data=8) mesh over the virtual devices."""
    from deepspeed_tpu.runtime.mesh import build_mesh
    return build_mesh({"pipe": 1, "data": 8, "model": 1})


@pytest.fixture
def tmp_ckpt_dir(tmp_path):
    return str(tmp_path / "ckpt")
