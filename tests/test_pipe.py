"""Pipeline parallelism tests (parity targets: ref
tests/unit/test_topology.py, test_pipe_schedule.py, test_pipe_module.py,
test_pipe.py's loss-parity-vs-dense criterion)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.mesh import build_mesh
from deepspeed_tpu.runtime.pipe.topology import (
    ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
    PipelineParallelGrid)
from deepspeed_tpu.runtime.pipe.schedule import (
    TrainSchedule, InferenceSchedule, ForwardPass, BackwardPass,
    SendActivation, RecvActivation, SendGrad, RecvGrad, LoadMicroBatch,
    OptimizerStep, ReduceGrads, ReduceTiedGrads)
from deepspeed_tpu.runtime.pipe.module import (PipelineModule, LayerSpec,
                                               TiedLayerSpec)
from deepspeed_tpu.models.gpt2 import tiny_gpt2_config
from deepspeed_tpu.models.gpt2_pipe import PipelinedGPT2


# ----------------------------------------------------------------------
# topology (ref test_topology.py)
# ----------------------------------------------------------------------
def test_topology_2d_rank_coord_roundtrip():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3
    for r in range(4):
        c = topo.get_coord(r)
        assert topo.get_rank(row=c.row, col=c.col) == r


def test_topology_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    # pipe groups vary pipe coord with data fixed
    pipe_lists = topo.get_axis_comm_lists("pipe")
    assert pipe_lists == [[0, 2], [1, 3]]
    data_lists = topo.get_axis_comm_lists("data")
    assert data_lists == [[0, 1], [2, 3]]


def test_topology_filter_and_axis_list():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.world_size() == 8
    assert topo.filter_match(pipe=0) == topo.get_axis_list("pipe", 0)
    assert len(topo.filter_match(pipe=0)) == 4
    assert len(topo.filter_match(pipe=0, data=1)) == 2


def test_topology_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=1)
    # omit data/pipe by default -> model coordinate only
    assert topo.get_rank_repr(rank=0) == "model_00"
    assert topo.get_rank_repr(rank=1) == "model_01"


def test_grid_from_mesh(mesh8):
    grid = PipelineParallelGrid(mesh=mesh8)
    assert grid.data_parallel_size == 8
    assert grid.pipe_parallel_size == 1
    assert grid.get_stage_id() == 0
    assert grid.is_first_stage() and grid.is_last_stage()


# ----------------------------------------------------------------------
# schedules (ref test_pipe_schedule.py)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("micro,stages", [(4, 2), (8, 4), (2, 2), (6, 3)])
def test_train_schedule_completeness(micro, stages):
    """Every stage forwards and backwards each microbatch exactly once,
    ending with reduce+step."""
    for sid in range(stages):
        sched = TrainSchedule(micro_batches=micro, stages=stages,
                              stage_id=sid)
        steps = list(sched.steps())
        fwd = [c.buffer_id for st in steps for c in st
               if isinstance(c, ForwardPass)]
        bwd = [c.buffer_id for st in steps for c in st
               if isinstance(c, BackwardPass)]
        assert len(fwd) == micro
        assert len(bwd) == micro
        tail = [c for c in steps[-1]]
        assert any(isinstance(c, ReduceTiedGrads) for c in tail)
        assert any(isinstance(c, ReduceGrads) for c in tail)
        assert isinstance(tail[-1], OptimizerStep)


def test_train_schedule_send_recv_pairing():
    """Stage s's activation sends equal stage s+1's recvs, in order."""
    micro, stages = 4, 3
    scheds = [list(TrainSchedule(micro, stages, s).steps())
              for s in range(stages)]

    def count(sched_steps, cls):
        return sum(1 for st in sched_steps for c in st
                   if isinstance(c, cls))

    for s in range(stages - 1):
        assert count(scheds[s], SendActivation) == \
            count(scheds[s + 1], RecvActivation) == micro
        assert count(scheds[s + 1], SendGrad) == \
            count(scheds[s], RecvGrad) == micro
    # boundary stages don't talk past the ends
    assert count(scheds[0], RecvActivation) == 0
    assert count(scheds[0], SendGrad) == 0
    assert count(scheds[-1], SendActivation) == 0
    assert count(scheds[-1], RecvGrad) == 0


def test_train_schedule_buffer_bound():
    """Live forwards never exceed num_pipe_buffers (1F1B property)."""
    micro, stages = 8, 4
    for sid in range(stages):
        sched = TrainSchedule(micro, stages, sid)
        live = 0
        peak = 0
        for st in sched.steps():
            for cmd in st:
                if isinstance(cmd, ForwardPass):
                    live += 1
                elif isinstance(cmd, BackwardPass):
                    live -= 1
                peak = max(peak, live)
        assert peak <= sched.num_pipe_buffers(), \
            f"stage {sid}: peak {peak} > buffers {sched.num_pipe_buffers()}"


def test_inference_schedule():
    sched = InferenceSchedule(micro_batches=3, stages=2, stage_id=0)
    steps = list(sched.steps())
    fwd = sum(1 for st in steps for c in st if isinstance(c, ForwardPass))
    assert fwd == 3
    assert sched.num_pipe_buffers() == 2


# ----------------------------------------------------------------------
# PipelineModule partitioning (ref test_pipe_module.py)
# ----------------------------------------------------------------------
def test_pipeline_module_uniform_partition():
    layers = [LayerSpec(lambda: (lambda x: x)) for _ in range(8)]
    mod = PipelineModule(layers=[lambda x: x] * 8, num_stages=4,
                         partition_method="uniform")
    assert mod.parts == [0, 2, 4, 6, 8]
    for s in range(4):
        assert len(mod.stage_layers(s)) == 2


def test_pipeline_module_type_partition():
    class Marked:
        def __call__(self, x):
            return x

    class Plain:
        def __call__(self, x):
            return x

    layers = [Plain(), Marked(), Plain(), Marked(), Plain(), Marked()]
    mod = PipelineModule(layers=layers, num_stages=3,
                         partition_method="type:Marked")
    # each stage gets exactly one Marked layer
    for s in range(3):
        start, stop = mod.stage_layer_range(s)
        marked = sum(1 for l in layers[start:stop]
                     if isinstance(l, Marked))
        assert marked == 1


# ----------------------------------------------------------------------
# SPMD pipeline correctness (the heart of the subsystem)
# ----------------------------------------------------------------------
def _pipe_fixture(n_layer=4, stages=2, micro=2, bsz=8, seq=32):
    cfg = tiny_gpt2_config(n_layer=n_layer, dropout=0.0, n_positions=seq)
    model = PipelinedGPT2(cfg, num_stages=stages, num_micro_batches=micro)
    ids = np.random.RandomState(0).randint(0, 256, (bsz, seq)).astype(
        np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    return cfg, model, ids, params


def sequential_reference_loss(model, params, ids):
    """Apply embed -> stages in order -> head on the full batch: the
    ground truth the pipelined schedule must reproduce exactly."""
    cfg = model.config
    labels = jnp.concatenate(
        [ids[:, 1:], jnp.full_like(ids[:, :1], -100)], axis=1)
    x = model._embed(params["embed"], jnp.asarray(ids),
                     jax.random.PRNGKey(0), True)
    for s in range(model.num_stages):
        stage_params = jax.tree_util.tree_map(lambda l: l[s],
                                              params["stages"])
        x = model._stage_apply(stage_params, x, jax.random.PRNGKey(0), True)
    return model._head_loss(params["head"], params["embed"], x, labels)


def test_pipeline_loss_matches_sequential(mesh8):
    """Pipelined execution == sequential execution, bit-for-bit modulo
    float reassociation (ref test_pipe.py compares loss trajectories)."""
    cfg, model, ids, params = _pipe_fixture()
    ref = sequential_reference_loss(model, params, ids)
    got = model.loss_fn(params, {"input_ids": ids}, rngs=None,
                        deterministic=True, mesh=None)
    np.testing.assert_allclose(float(ref), float(got), rtol=1e-5)


def test_pipeline_loss_matches_on_pipe_mesh():
    mesh = build_mesh({"pipe": 2, "data": 2, "model": 2})
    cfg, model, ids, params = _pipe_fixture()
    ref = sequential_reference_loss(model, params, ids)

    def f(p, i):
        return model.loss_fn(p, {"input_ids": i}, deterministic=True,
                             mesh=mesh)

    got = jax.jit(f)(params, jnp.asarray(ids))
    np.testing.assert_allclose(float(ref), float(got), rtol=1e-5)


@pytest.mark.parametrize("stages,micro", [(2, 4), (4, 4), (2, 2)])
def test_pipeline_stage_micro_combos(stages, micro):
    cfg, model, ids, params = _pipe_fixture(n_layer=4, stages=stages,
                                            micro=micro, bsz=8)
    ref = sequential_reference_loss(model, params, ids)
    got = model.loss_fn(params, {"input_ids": ids}, deterministic=True,
                        mesh=None)
    np.testing.assert_allclose(float(ref), float(got), rtol=1e-5)


def test_pipeline_engine_trains_3d():
    """End-to-end: pp2 x dp2 x tp2 mesh, ZeRO-1, loss descends."""
    mesh = build_mesh({"pipe": 2, "data": 2, "model": 2})
    cfg, model, ids, params = _pipe_fixture()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
                "zero_optimization": {"stage": 1},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        mesh=mesh)
    assert type(engine).__name__ == "PipelineEngine"
    assert engine.is_first_stage() and engine.grid.pipe_parallel_size == 2

    losses = [float(jax.device_get(
        engine.train_batch(batch={"input_ids": ids}))) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_pipeline_engine_matches_dense_engine_losses():
    """The pipeline engine's loss trajectory matches a dense GPT-2 with
    identical math run through the plain engine (ref test_pipe.py:181
    asserts pipe-vs-dense loss agreement)."""
    mesh = build_mesh({"pipe": 2, "data": 4, "model": 1})
    cfg, model, ids, params = _pipe_fixture()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}},
        mesh=mesh)

    # dense twin: same params run sequentially via a plain engine
    class DenseTwin:
        def __init__(self, model):
            self.m = model

        def loss_fn(self, params, batch, rngs=None, deterministic=False,
                    **_):
            return self.m.loss_fn(params, batch, rngs=rngs,
                                  deterministic=deterministic, mesh=None)

    dense_mesh = build_mesh({"pipe": 1, "data": 8, "model": 1})
    dense_engine, _, _, _ = deepspeed_tpu.initialize(
        model=DenseTwin(model), model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}},
        mesh=dense_mesh)

    for i in range(4):
        lp = float(jax.device_get(
            engine.train_batch(batch={"input_ids": ids})))
        ld = float(jax.device_get(
            dense_engine.train_batch(batch={"input_ids": ids[None]})))
        np.testing.assert_allclose(lp, ld, rtol=2e-4), (i, lp, ld)


# ----------------------------------------------------------------------
# PipelineModule sequential path through the engine
# ----------------------------------------------------------------------
def test_pipeline_module_engine_trains(mesh8):
    import flax.linen as nn

    class Linear(nn.Module):
        dim: int = 16

        @nn.compact
        def __call__(self, x, **kw):
            return nn.Dense(self.dim)(x)

    def mse(out, labels):
        return jnp.mean((out - labels) ** 2)

    module = PipelineModule(
        layers=[LayerSpec(Linear, 16) for _ in range(4)],
        num_stages=2, loss_fn=mse, partition_method="uniform")
    rng = np.random.RandomState(0)
    x = rng.randn(16, 16).astype(np.float32)
    w = rng.randn(16, 16).astype(np.float32)
    y = x @ w
    params = module.init_params(jax.random.PRNGKey(0), jnp.asarray(x))

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config={"train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        mesh=mesh8)
    losses = []
    for i in range(10):
        loss = engine.train_batch(batch=(x, y))
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.7, losses


def test_tied_layer_spec_shares_weights(mesh8):
    """Tied layers must share ONE param tree: the embedding used as both
    input embed and output head stays identical after training steps
    (ref TiedLayerSpec, module.py:71-82)."""
    import flax.linen as nn

    class Embed(nn.Module):
        vocab: int = 16
        dim: int = 8

        @nn.compact
        def __call__(self, ids, **kw):
            emb = self.param("embedding", nn.initializers.normal(0.02),
                             (self.vocab, self.dim))
            return emb[ids]

    class Mid(nn.Module):
        dim: int = 8

        @nn.compact
        def __call__(self, x, **kw):
            return nn.Dense(self.dim)(x)

    def head_fn(params, x):
        # tied head: logits via embedding transpose
        return x @ params["embedding"].T

    def ce(logits, labels):
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None],
                                   axis=-1).squeeze(-1)
        return jnp.mean(logz - gold)

    module = PipelineModule(
        layers=[TiedLayerSpec("embed", Embed),
                LayerSpec(Mid),
                TiedLayerSpec("embed", Embed, forward_fn=head_fn)],
        num_stages=2, loss_fn=ce, partition_method="uniform")
    assert module.tied_layer_keys == {0: "embed", 2: "embed"}

    ids = np.random.RandomState(0).randint(0, 16, (8, 4)).astype(np.int32)
    params = module.init_params(jax.random.PRNGKey(0), jnp.asarray(ids))
    # the tied tree appears exactly once
    assert list(params["tied"].keys()) == ["embed"]
    assert "0" not in params["layers"] and "2" not in params["layers"]

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        mesh=mesh8)
    labels = ids.copy()
    losses = [float(jax.device_get(engine.train_batch(batch=(ids, labels))))
              for _ in range(10)]
    assert losses[-1] < losses[0], losses


def test_pld_forwarded_on_sequential_pipeline_chain():
    """PLD theta(t) must reach PipelineModule layers that accept
    layer_keep_prob when the module runs as a sequential chain (pipe=1)
    — the inheritance the reference gets through its generic engine
    forward (ref engine.py:809-810). VERDICT r4 #9."""
    import flax.linen as nn

    seen = []

    class GatedDense(nn.Module):
        feats: int

        @nn.compact
        def __call__(self, x, layer_keep_prob=None, deterministic=False):
            if layer_keep_prob is not None:
                seen.append(True)
                x = x * layer_keep_prob
            return nn.Dense(self.feats)(x)

    module = PipelineModule(
        [LayerSpec(GatedDense, 8), LayerSpec(GatedDense, 4)],
        num_stages=1,
        loss_fn=lambda y, lab: jnp.mean((y - lab) ** 2))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 8), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(0), x)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params,
        config={"train_batch_size": 8, "steps_per_print": 1000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "progressive_layer_drop": {"enabled": True,
                                           "theta": 0.5, "gamma": 0.01}},
        mesh=build_mesh({"pipe": 1, "data": 1, "model": 1},
                        devices=jax.devices()[:1]))
    assert engine.progressive_layer_drop is not None
    xs = rng.randn(8, 8).astype(np.float32)
    ys = rng.randn(8, 4).astype(np.float32)
    loss = engine.train_batch(batch=(xs, ys))
    assert np.isfinite(float(jax.device_get(loss)))
    assert seen, "layer_keep_prob never reached the accepting layers"
    # theta advances by the reference formula
    t0 = engine.progressive_layer_drop.get_theta()
    engine.train_batch(batch=(xs, ys))
    assert engine.progressive_layer_drop.get_theta() < t0
