"""Fused-layer parity sweep (parity target: ref
`tests/unit/test_cuda_forward.py` / `test_cuda_backward.py`, which sweep
(batch, seq, hidden, heads, pre/post-LN, fp16) against the vendored
dense BERT in `tests/unit/modeling.py`).

Here the known-good comparator is an INDEPENDENT dense re-statement of
the layer math (naive fp32 softmax attention, plain matmuls) consuming
the fused layer's own parameters — any fusion/flash/remat bug shows up
as a numeric divergence. 36 forward cases + 8 backward cases.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)


def exact_gelu(z):
    """erf-based GELU in float64 (no scipy in the image)."""
    import math
    return (np.asarray(z, np.float64) * 0.5 *
            (1.0 + np.vectorize(math.erf)(
                np.asarray(z, np.float64) / np.sqrt(2.0)))
            ).astype(np.float32)


def dense_reference(params, x, mask, cfg):
    """fp32 dense math twin of _TransformerLayerCore."""
    p = params["params"]["core"]

    def ln(name, h):
        s, b = p[name]["scale"], p[name]["bias"]
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        return (h - mu) / np.sqrt(var + cfg.layer_norm_eps) * s + b

    def dense(name, h):
        return h @ p[name]["kernel"] + p[name]["bias"]

    h = cfg.hidden_size
    nh = cfg.heads
    hd = h // nh
    b, t, _ = x.shape
    x = np.asarray(x, np.float64).astype(np.float32)

    attn_in = ln("attn_layer_norm", x) if cfg.pre_layer_norm else x
    qkv = dense("attn_qkvw", attn_in)
    q, k, v = np.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, nh, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, t, nh, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, t, nh, hd).transpose(0, 2, 1, 3)
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hd)
    if mask is not None:
        s = s + np.asarray(mask)
    s = s - s.max(-1, keepdims=True)
    e = np.exp(s)
    probs = e / e.sum(-1, keepdims=True)
    ctx = np.einsum("bhqk,bhkd->bhqd", probs, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, h)
    attn_out = dense("attn_ow", ctx)
    x = x + attn_out
    if not cfg.pre_layer_norm:
        x = ln("attn_layer_norm", x)

    mlp_in = ln("layer_norm", x) if cfg.pre_layer_norm else x
    inter = exact_gelu(dense("inter_w", mlp_in))
    x = x + dense("output_w", inter)
    if not cfg.pre_layer_norm:
        x = ln("layer_norm", x)
    return x


def build(b, t, h, heads, pre_ln, dtype_flag, seed=0, with_mask=False):
    cfg = DeepSpeedTransformerConfig(
        batch_size=b, max_seq_length=t, hidden_size=h,
        intermediate_size=4 * h, heads=heads, attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0, num_hidden_layers=2,
        initializer_range=0.02, pre_layer_norm=pre_ln, training=True,
        bf16=(dtype_flag == "bf16"))
    layer = DeepSpeedTransformerLayer(cfg)
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(b, t, h) * 0.5, jnp.float32)
    mask = None
    if with_mask:
        keylen = rng.randint(t // 2, t, size=b)
        mask_np = np.zeros((b, 1, 1, t), np.float32)
        for i, kl in enumerate(keylen):
            mask_np[i, :, :, kl:] = -1e9
        mask = jnp.asarray(mask_np)
    params = layer.init({"params": jax.random.PRNGKey(seed),
                         "dropout": jax.random.PRNGKey(1)}, x, mask, True)
    return layer, cfg, params, x, mask


# ---- forward sweep: 3 shapes x {128,512} seq x preln x dtype = 24,
#      plus masked + odd-seq variants = 36 cases ----
SHAPES = [(1, 64, 4), (3, 128, 8), (8, 256, 8)]


@pytest.mark.slow
@pytest.mark.parametrize("dtype_flag", ["fp32", "bf16"])
@pytest.mark.parametrize("pre_ln", [True, False])
@pytest.mark.parametrize("seq", [128, 512])
@pytest.mark.parametrize("b,h,heads", SHAPES)
def test_forward_parity(b, h, heads, seq, pre_ln, dtype_flag):
    layer, cfg, params, x, _ = build(b, seq, h, heads, pre_ln, dtype_flag)
    got = np.asarray(layer.apply(params, x, None, True), np.float32)
    want = dense_reference(params, x, None, cfg)
    tol = dict(atol=2e-4, rtol=2e-4) if dtype_flag == "fp32" else \
        dict(atol=0.15, rtol=0.08)
    np.testing.assert_allclose(got, want, **tol)


@pytest.mark.slow
@pytest.mark.parametrize("dtype_flag", ["fp32", "bf16"])
@pytest.mark.parametrize("pre_ln", [True, False])
@pytest.mark.parametrize("seq", [120, 128])   # 120: XLA fallback path
def test_forward_parity_with_padding_mask(seq, pre_ln, dtype_flag):
    layer, cfg, params, x, mask = build(2, seq, 128, 8, pre_ln,
                                        dtype_flag, with_mask=True)
    got = np.asarray(layer.apply(params, x, mask, True), np.float32)
    want = dense_reference(params, x, mask, cfg)
    tol = dict(atol=2e-4, rtol=2e-4) if dtype_flag == "fp32" else \
        dict(atol=0.15, rtol=0.08)
    np.testing.assert_allclose(got, want, **tol)


# ---- backward sweep: fp32 grads vs numeric reference twin ----
@pytest.mark.slow
@pytest.mark.parametrize("pre_ln", [True, False])
@pytest.mark.parametrize("seq", [128, 512])
@pytest.mark.parametrize("b,h,heads", [(2, 64, 4), (2, 128, 8)])
def test_backward_parity_fp32(b, h, heads, seq, pre_ln):
    """d(sum(out^2))/dx of the fused layer must match the same gradient
    taken through a pure-jax restatement of the dense math (autodiff on
    an independent implementation — the reference checks its CUDA
    backward against torch autograd the same way)."""
    if (seq, h) == (512, 128) and pre_ln:
        pytest.skip("512x128 preln covered by fwd sweep; keep bwd <8")
    layer, cfg, params, x, _ = build(b, seq, h, heads, pre_ln, "fp32")

    def fused_loss(xx):
        return jnp.sum(layer.apply(params, xx, None, True)
                       .astype(jnp.float32) ** 2)

    def dense_twin(xx):
        p = params["params"]["core"]

        def ln(name, hh):
            s_, b_ = p[name]["scale"], p[name]["bias"]
            mu = hh.mean(-1, keepdims=True)
            var = ((hh - mu) ** 2).mean(-1, keepdims=True)
            return (hh - mu) / jnp.sqrt(var + cfg.layer_norm_eps) * s_ + b_

        def dense(name, hh):
            return hh @ p[name]["kernel"] + p[name]["bias"]

        nh, hd = cfg.heads, cfg.hidden_size // cfg.heads
        bb, tt, hh_ = xx.shape
        attn_in = ln("attn_layer_norm", xx) if cfg.pre_layer_norm else xx
        qkv = dense("attn_qkvw", attn_in)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(bb, tt, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(bb, tt, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(bb, tt, nh, hd).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hd)
        probs = jax.nn.softmax(s, axis=-1)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(bb, tt, hh_)
        y = xx + dense("attn_ow", ctx)
        if not cfg.pre_layer_norm:
            y = ln("attn_layer_norm", y)
        mlp_in = ln("layer_norm", y) if cfg.pre_layer_norm else y
        inter = jax.nn.gelu(dense("inter_w", mlp_in), approximate=False)
        y = y + dense("output_w", inter)
        if not cfg.pre_layer_norm:
            y = ln("layer_norm", y)
        return jnp.sum(y ** 2)

    g_fused = np.asarray(jax.grad(fused_loss)(x))
    g_dense = np.asarray(jax.grad(dense_twin)(x))
    np.testing.assert_allclose(g_fused, g_dense, atol=2e-3, rtol=2e-3)
