"""Numerics health (ISSUE 7 tentpole c) + peak_flops_override.

Covers:
  * fence-alignment guards with monitor.numerics enabled: ZERO
    per-step device_get/effects_barrier, exactly ONE device_get per
    fence (the health arrays ride the same fused fetch);
  * per-group grad stats + per-layer activation stats end to end:
    JSONL `numerics` events + tfevents round-trip of the flattened
    numerics scalars;
  * first-NaN attribution (in-process twin of the subprocess
    acceptance test) including through registry compaction;
  * fold_entries/summarize_window unit behavior;
  * monitor.peak_flops_override: MFU reported on CPU runs.
"""

import json
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.monitor import Monitor, numerics
from deepspeed_tpu.monitor.registry import MetricsRegistry
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule
from simple_model import SimpleModel


def _make_stacked(seed, bs=16, dim=8):
    rng = np.random.RandomState(seed)
    x = rng.randn(bs, dim).astype(np.float32)
    w = np.linspace(-1, 1, dim * dim).reshape(dim, dim).astype(np.float32)
    return {"x": x[None], "y": (x @ w)[None]}


def _engine(tmp_path, sinks=("jsonl",), steps_per_sync=10000,
            extra=None, **mon_extra):
    model = SimpleModel(hidden_dim=8)
    cfg = {
        "train_batch_size": 16,
        "steps_per_print": 10000,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "async_dispatch": {"enabled": True,
                           "steps_per_sync": steps_per_sync},
    }
    cfg.update(extra or {})
    cfg["monitor"] = {"enabled": True, "sinks": list(sinks),
                      "output_path": str(tmp_path),
                      "numerics": {"enabled": True}, **mon_extra}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    return engine


# ----------------------------------------------------------------------
# fence-alignment guards (the acceptance criterion: zero NEW syncs)
# ----------------------------------------------------------------------
class _SyncCounters:
    def __init__(self, monkeypatch):
        self.device_get = 0
        self.effects_barrier = 0
        real_get, real_barrier = jax.device_get, jax.effects_barrier

        def counting_get(x):
            self.device_get += 1
            return real_get(x)

        def counting_barrier():
            self.effects_barrier += 1
            return real_barrier()

        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "effects_barrier", counting_barrier)


def test_numerics_hot_path_zero_per_step_syncs(tmp_path, monkeypatch):
    """monitor.numerics=on adds NO per-step host<->device sync: the
    stat arrays are jitted-step outputs retained by a list append."""
    engine = _engine(tmp_path)
    assert engine._numerics_on
    batches = [engine.stage_batch(_make_stacked(i)) for i in range(8)]
    for b in batches[:3]:
        engine.train_batch(batch=b)
    counters = _SyncCounters(monkeypatch)
    for b in batches[3:]:
        engine.train_batch(batch=b)
    assert counters.device_get == 0, \
        f"numerics hot path device_get x{counters.device_get}"
    assert counters.effects_barrier == 0
    engine.monitor.close()


def test_numerics_fence_still_costs_exactly_one_device_get(
        tmp_path, monkeypatch):
    """The health arrays ride the SAME single per-fence device_get as
    the scalar metrics — numerics must not add a second fetch."""
    engine = _engine(tmp_path, steps_per_sync=4)
    batches = [engine.stage_batch(_make_stacked(i)) for i in range(16)]
    for b in batches[:8]:
        engine.train_batch(batch=b)
    assert engine._host_steps == 8
    counters = _SyncCounters(monkeypatch)
    for b in batches[8:]:
        engine.train_batch(batch=b)
    assert counters.device_get == 2, \
        f"expected 1 device_get per fence (2 fences), got " \
        f"{counters.device_get}"
    assert counters.effects_barrier == 0
    log = os.path.join(str(tmp_path), "events.jsonl")
    kinds = [json.loads(line)["kind"] for line in open(log)]
    assert kinds.count("numerics") >= 2
    engine.monitor.close()


# ----------------------------------------------------------------------
# event stream: JSONL + tfevents round-trip
# ----------------------------------------------------------------------
def test_numerics_events_roundtrip_jsonl_and_tfevents(tmp_path):
    engine = _engine(tmp_path, sinks=("jsonl", "tensorboard"),
                     steps_per_sync=2)
    for i in range(4):
        engine.train_batch(batch=_make_stacked(i))
    engine.monitor.close()

    log = os.path.join(str(tmp_path), "events.jsonl")
    events = [json.loads(line) for line in open(log)]
    nums = [e for e in events if e["kind"] == "numerics"]
    assert nums
    for e in nums:
        # SimpleModel grad groups: its two top-level params
        assert set(e["grad_norm"]) == {"w", "b"}
        assert all(np.isfinite(v) for v in e["grad_norm"].values())
        assert set(e["grad_absmax"]) == {"w", "b"}
        assert e["grad_nonfinite"] == {"w": 0, "b": 0}
        assert e["first_nonfinite"] is None
        assert e["window_steps"] >= 1

    import glob
    from deepspeed_tpu.monitor.tfevents import read_tfevents
    tb = glob.glob(os.path.join(str(tmp_path), "tb",
                                "events.out.tfevents.*"))
    assert tb
    tags = set()
    for e in read_tfevents(tb[0]):
        tags |= set(e.get("scalars", {}))
    assert "monitor/numerics/grad_norm/w" in tags
    assert "monitor/numerics/grad_nonfinite/b" in tags


def test_snapshot_carries_numerics_and_stable_keys(tmp_path):
    engine = _engine(tmp_path, sinks=())
    for i in range(3):
        engine.train_batch(batch=_make_stacked(i))
    snap = engine.monitor.snapshot()
    assert set(snap) == set(Monitor.SNAPSHOT_KEYS)
    assert snap["numerics"] is not None
    assert set(snap["numerics"]["grad_norm"]) == {"w", "b"}
    engine.monitor.close()


# ----------------------------------------------------------------------
# first-NaN attribution (in-process twin of the subprocess acceptance)
# ----------------------------------------------------------------------
def _nan_layer(x):
    return x + jnp.log(-jnp.ones_like(x))


def _nan_pipe_engine(tmp_path, steps_per_sync=1):
    layers = [LayerSpec(nn.Dense, 16), jnp.tanh, _nan_layer,
              LayerSpec(nn.Dense, 8)]
    module = PipelineModule(
        layers, num_stages=1,
        loss_fn=lambda y, lab: jnp.mean(
            (y - lab.astype(jnp.float32)[..., :8]) ** 2))
    params = module.init_params(jax.random.PRNGKey(0),
                                jnp.zeros((16, 8), jnp.float32))
    cfg = {
        "train_batch_size": 16, "steps_per_print": 10000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "async_dispatch": {"enabled": True,
                           "steps_per_sync": steps_per_sync},
        "mesh": {"pipe": 1, "data": 8, "model": 1},
        "monitor": {"enabled": True, "sinks": [],
                    "output_path": str(tmp_path),
                    "numerics": {"enabled": True}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params, config=cfg)
    return engine


def _flat_batch(seed):
    # the pipe engine collects a FULL batch (no stacked gas dim)
    return {k: v[0] for k, v in _make_stacked(seed).items()}


def test_first_nan_layer_attribution(tmp_path):
    engine = _nan_pipe_engine(tmp_path)
    engine.train_batch(batch=_flat_batch(0))
    num = engine.monitor._last_numerics
    assert num is not None
    first = num["first_nonfinite"]
    # boundaries 0 (Dense) and 1 (tanh) are finite; 2 (_nan_layer) is
    # the injection point — activation attribution outranks the (also
    # nonfinite) gradients
    assert first["kind"] == "activation"
    assert first["name"].startswith("layer2:"), first
    assert first["index"] == 2
    assert num["act_nonfinite"][first["name"]] > 0
    # sticky across later windows (poisoned params blame layer 0 after
    # the first update — the forensic answer stays the FIRST window)
    engine.train_batch(batch=_flat_batch(1))
    assert engine.monitor._first_nonfinite["name"].startswith("layer2:")
    engine.monitor.close()


def test_first_nan_attribution_survives_compaction(tmp_path):
    """The first-bad candidate is folded on DEVICE at compaction, so a
    long fence window (> _COMPACT_AT steps) keeps the attribution."""
    engine = _nan_pipe_engine(tmp_path, steps_per_sync=10000)
    engine.monitor.registry._COMPACT_AT = 4
    for i in range(10):     # 2 compactions before any fence
        engine.train_batch(batch=_flat_batch(i))
    assert len(engine.monitor.registry._pending_health) < 4
    snap = engine.monitor.snapshot()
    first = snap["numerics"]["first_nonfinite"]
    assert first["kind"] == "activation"
    assert first["name"].startswith("layer2:")
    assert first["window_step"] == 0
    engine.monitor.close()


# ----------------------------------------------------------------------
# unit: fold_entries / summarize_window / group_paths
# ----------------------------------------------------------------------
def test_group_paths_and_grad_group_stats():
    tree = {"block0": {"w": jnp.ones((2, 2)), "b": jnp.zeros((2,))},
            "block1": {"w": jnp.full((2,), jnp.inf)}}
    names = numerics.group_paths(tree)
    assert names == ["block0/b", "block0/w", "block1/w"] or \
        len(names) == 3
    stats = np.asarray(numerics.grad_group_stats(tree))
    assert stats.shape == (len(names), 3)
    by = dict(zip(names, stats))
    assert by["block0/w"][0] == pytest.approx(2.0)      # l2 norm
    assert by["block0/w"][1] == pytest.approx(1.0)      # absmax
    assert by["block0/w"][2] == 0                       # finite
    assert by["block1/w"][2] == 1    # nonfinite flag (derived, 2-pass)
    # NaN leaves flag too (max/sum both propagate)
    nan_tree = {"g": {"w": jnp.asarray([1.0, np.nan])}}
    assert np.asarray(numerics.grad_group_stats(nan_tree))[0, 2] == 1


def test_fold_entries_and_summarize_merge():
    acts = [np.array([[1.0, 0.5, 0.0], [2.0, 0.5, 0.0]], np.float32),
            np.array([[3.0, 0.5, 0.0], [np.inf, 0.5, 2.0]], np.float32)]
    grads = [np.array([[1.0, 0.1, 0.0]], np.float32),
             np.array([[np.nan, np.nan, 4.0]], np.float32)]
    entries = [(i, {"act": jnp.asarray(acts[i]),
                    "grad": jnp.asarray(grads[i])}) for i in range(2)]
    acc = numerics.fold_entries([s for s, _ in entries],
                                [h for _, h in entries], None)
    acc = jax.device_get(acc)
    # compacted-only summary
    out = numerics.summarize_window([], acc,
                                    grad_names=["g0"],
                                    act_names=["l0", "l1"])
    assert out["act_absmax"]["l0"] == 3.0
    assert out["act_nonfinite"]["l1"] == 2
    assert out["grad_nonfinite"]["g0"] == 4
    # act (window_step 1, layer 1) fires before the grad of the same
    # step
    assert out["first_nonfinite"] == {
        "kind": "activation", "name": "l1", "index": 1,
        "window_step": 1}
    # tail entries merge with the accumulator: earlier acc candidate
    # wins over a later tail one
    tail = [(5, {"act": jnp.asarray(acts[1]),
                 "grad": jnp.asarray(grads[0])})]
    out2 = numerics.summarize_window(
        [(s, jax.device_get(h)) for s, h in tail], acc,
        grad_names=["g0"], act_names=["l0", "l1"])
    assert out2["first_nonfinite"]["window_step"] == 1
    assert out2["act_nonfinite"]["l1"] == 4        # 2 + 2


def test_summarize_window_handles_grad_only():
    entries = [(0, {"grad": np.array([[1.0, 0.5, 0.0]], np.float32),
                    "act": None})]
    out = numerics.summarize_window(entries, None, grad_names=["g0"],
                                    act_names=None)
    assert out["grad_norm"] == {"g0": 1.0}
    assert out["act_absmax"] is None
    assert out["first_nonfinite"] is None


def test_registry_health_rides_drain():
    reg = MetricsRegistry()
    h = {"grad": jnp.asarray([[1.0, 0.5, 0.0]]), "act": None}
    reg.fold_step(loss=1.0, grad_norm=1.0, loss_scale=1.0,
                  overflow=False, tokens=10, health=h)
    reg.fold_step(loss=2.0, grad_norm=1.0, loss_scale=1.0,
                  overflow=False, tokens=10)       # health-less step
    out = reg.drain_device()
    entries, acc = out["health"]
    assert len(entries) == 1 and acc is None
    assert entries[0][0] == 0
    assert reg.drain_device() is None


# ----------------------------------------------------------------------
# peak_flops_override satellite
# ----------------------------------------------------------------------
def test_peak_flops_override_reports_mfu_on_cpu(tmp_path):
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={"train_batch_size": 16, "steps_per_print": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "monitor": {"enabled": True, "sinks": [],
                            "output_path": str(tmp_path),
                            "peak_flops_override": 1e9}})
    for i in range(10):
        engine.train_batch(batch=_make_stacked(i))
    snap = engine.monitor.snapshot()
    assert snap["tokens_per_sec_per_chip"] is not None
    if jax.devices()[0].platform != "tpu":
        # PR 6 left mfu None off-TPU; the override supplies the
        # denominator
        assert snap["mfu"] is not None and snap["mfu"] > 0
    engine.monitor.close()


def test_peak_flops_override_validation():
    from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                              MonitorConfigError)
    cfg = DeepSpeedMonitorConfig(
        {"monitor": {"peak_flops_override": 197e12}})
    assert cfg.peak_flops_override == 197e12
    with pytest.raises(MonitorConfigError):
        DeepSpeedMonitorConfig({"monitor": {"peak_flops_override": -1}})
    assert DeepSpeedMonitorConfig({}).peak_flops_override == 0.0
