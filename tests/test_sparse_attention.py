"""Sparse attention tests (parity target: ref
tests/unit/test_sparse_attention.py compares block-sparse ops vs dense
references with layout masks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    DenseSparsityConfig, FixedSparsityConfig, VariableSparsityConfig,
    BigBirdSparsityConfig, BSLongformerSparsityConfig,
    block_sparse_attention, layout_to_dense_mask, SparseSelfAttention,
    BertSparseSelfAttention, SparseAttentionUtils)
from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
    block_sparse_attention_dense_fallback)

BLOCK = 32  # small block for CPU-interpret tests (TPU default is 128)
H, T, D = 2, 256, 32


def qkv(b=1, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, T, H, D), jnp.float32)
            for _ in range(3)]


ALL_CONFIGS = [
    DenseSparsityConfig(num_heads=H, block=BLOCK),
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=2,
                        num_global_blocks=1),
    FixedSparsityConfig(num_heads=H, block=BLOCK, num_local_blocks=2,
                        attention="unidirectional"),
    VariableSparsityConfig(num_heads=H, block=BLOCK,
                           local_window_blocks=[1, 2],
                           global_block_indices=[0]),
    BigBirdSparsityConfig(num_heads=H, block=BLOCK, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
    BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                               num_sliding_window_blocks=3,
                               global_block_indices=[0]),
]


@pytest.mark.parametrize("cfg", ALL_CONFIGS,
                         ids=lambda c: type(c).__name__)
def test_layout_shape_and_coverage(cfg):
    layout = cfg.make_layout(T)
    nb = T // BLOCK
    assert layout.shape == (H, nb, nb)
    assert set(np.unique(layout)) <= {0, 1}
    # every query block attends somewhere
    assert (layout.sum(-1) > 0).all()
    # diagonal present (needed for causal use)
    assert layout[:, np.arange(nb), np.arange(nb)].all()


def test_layout_seq_len_must_divide():
    cfg = FixedSparsityConfig(num_heads=H, block=BLOCK)
    with pytest.raises(ValueError):
        cfg.make_layout(T + 1)


@pytest.mark.parametrize("cfg", ALL_CONFIGS[:4],
                         ids=lambda c: type(c).__name__)
@pytest.mark.parametrize("causal", [False, True])
def test_kernel_matches_dense_fallback(cfg, causal):
    q, k, v = qkv()
    layout = cfg.make_layout(T)
    out = block_sparse_attention(q, k, v, layout, BLOCK, causal=causal)
    ref = block_sparse_attention_dense_fallback(q, k, v, layout, BLOCK,
                                                causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_kernel_grads_match_dense_fallback(causal):
    q, k, v = qkv(seed=7)
    layout = FixedSparsityConfig(
        num_heads=H, block=BLOCK, num_local_blocks=2).make_layout(T)

    def loss_sparse(q, k, v):
        return jnp.sum(block_sparse_attention(
            q, k, v, layout, BLOCK, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(block_sparse_attention_dense_fallback(
            q, k, v, layout, BLOCK, causal=causal) ** 2)

    gs = jax.grad(loss_sparse, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_dense_config_equals_full_attention():
    from deepspeed_tpu.ops.transformer.flash_attention import dense_attention
    q, k, v = qkv()
    layout = DenseSparsityConfig(num_heads=H, block=BLOCK).make_layout(T)
    out = block_sparse_attention(q, k, v, layout, BLOCK, causal=False)
    ref = dense_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_sparse_self_attention_module():
    attn = SparseSelfAttention(
        sparsity_config=FixedSparsityConfig(num_heads=H, block=BLOCK,
                                            num_local_blocks=2))
    q, k, v = qkv()
    out = attn(q, k, v)
    assert out.shape == q.shape
    # key padding mask path (mask second half of keys)
    kp = jnp.zeros((1, T)).at[:, T // 2:].set(-1e9)
    out_masked = attn(q, k, v, key_padding_mask=kp)
    assert not np.allclose(np.asarray(out), np.asarray(out_masked))


def test_bert_sparse_self_attention_trains():
    module = BertSparseSelfAttention(
        hidden_size=64, num_attention_heads=H,
        sparsity_config=FixedSparsityConfig(num_heads=H, block=BLOCK,
                                            num_local_blocks=2))
    x = jnp.asarray(np.random.RandomState(0).randn(1, T, 64), jnp.float32)
    params = module.init(jax.random.PRNGKey(0), x)
    out = module.apply(params, x)
    assert out.shape == (1, T, 64)
    grads = jax.grad(
        lambda p: jnp.sum(module.apply(p, x) ** 2))(params)
    assert all(float(jnp.max(jnp.abs(l))) > 0
               for l in jax.tree_util.tree_leaves(grads))


def test_pad_to_block_size():
    ids = jnp.ones((2, 100), jnp.int32)
    mask = jnp.ones((2, 100), jnp.int32)
    pad_len, ids_p, mask_p, _, _, _ = SparseAttentionUtils.pad_to_block_size(
        block_size=64, input_ids=ids, attention_mask=mask, pad_token_id=9)
    assert pad_len == 28
    assert ids_p.shape == (2, 128)
    assert int(ids_p[0, -1]) == 9 and int(mask_p[0, -1]) == 0
    out = SparseAttentionUtils.unpad_sequence_output(
        pad_len, jnp.zeros((2, 128, 8)))
    assert out.shape == (2, 100, 8)


def test_extend_position_embedding():
    pe = jnp.asarray(np.random.randn(128, 16), jnp.float32)
    ext = SparseAttentionUtils.extend_position_embedding(pe, 300)
    assert ext.shape == (300, 16)
    np.testing.assert_array_equal(np.asarray(ext[:128]), np.asarray(pe))
    np.testing.assert_array_equal(np.asarray(ext[128:256]), np.asarray(pe))


def test_bslongformer_band_path_matches_fallback():
    """The BSLongformer causal layout (the bench headline) decomposes
    into the band+global fast forward; fwd AND grads must match the
    dense fallback."""
    from deepspeed_tpu.ops.sparse_attention import BSLongformerSparsityConfig
    from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
        _band_decompose, block_sparse_attention,
        block_sparse_attention_dense_fallback)
    cfg = BSLongformerSparsityConfig(num_heads=H, block=BLOCK,
                                     num_sliding_window_blocks=3)
    layout = cfg.make_layout(T)
    assert _band_decompose(layout, True) is not None, \
        "BSLongformer must take the band fast path"
    q, k, v = qkv()

    def loss_s(q):
        return jnp.sum(block_sparse_attention(
            q, k, v, layout, BLOCK, causal=True).astype(jnp.float32) ** 2)

    def loss_d(q):
        return jnp.sum(block_sparse_attention_dense_fallback(
            q, k, v, layout, BLOCK, causal=True).astype(jnp.float32) ** 2)

    np.testing.assert_allclose(float(loss_s(q)), float(loss_d(q)),
                               rtol=1e-5)
    gs = jax.grad(loss_s)(q)
    gd = jax.grad(loss_d)(q)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gd),
                               atol=2e-4, rtol=2e-4)


def test_lse2d_branch_with_eight_heads():
    """bh = 8 engages the 2-D lse layout (g == 8): fwd + grads must
    still match the fallback (this branch is otherwise TPU-only)."""
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
    from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
        block_sparse_attention, block_sparse_attention_dense_fallback)
    h8 = 8
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(1, T, h8, D), jnp.float32)
    cfg = FixedSparsityConfig(num_heads=h8, block=BLOCK,
                              num_local_blocks=2, num_global_blocks=1)
    layout = cfg.make_layout(T)

    def loss_s(q):
        return jnp.sum(block_sparse_attention(
            q, q, q, layout, BLOCK, causal=True).astype(jnp.float32) ** 2)

    def loss_d(q):
        return jnp.sum(block_sparse_attention_dense_fallback(
            q, q, q, layout, BLOCK, causal=True).astype(jnp.float32) ** 2)

    np.testing.assert_allclose(float(loss_s(q)), float(loss_d(q)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(jax.grad(loss_s)(q)),
                               np.asarray(jax.grad(loss_d)(q)),
                               atol=2e-4, rtol=2e-4)


def test_fixed_pattern_rides_band_fast_path():
    """VERDICT r3 #7: the reference's default Fixed pattern (window-
    ALIGNED local blocks + summary columns) must decompose onto the
    band+global fast forward, like BSLongformer's sliding window."""
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
    from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import \
        _band_decompose
    cfg = FixedSparsityConfig(num_heads=4, block=128, num_local_blocks=4,
                              num_global_blocks=1)
    lay = cfg.make_layout(4096)
    for causal in (True, False):
        band = _band_decompose(lay, causal)
        assert band is not None and band[0] == "aligned", (causal, band)
        assert band[1] == 4  # the window width in blocks
