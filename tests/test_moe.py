"""Mixture-of-Experts subsystem (ISSUE 15).

What these pin:
  * router math: capacity formula, choice-major priority, drop
    counting, dropless at production token counts, stats layout;
  * grouped-GEMM experts: packed block-diagonal parity vs the plain
    batched einsum and vs the unpacked per-expert-loop reference —
    forward AND gradients <= 1e-5 fp32, aux-loss gradients exact;
  * the SPMD stats-replication contract: on an expert mesh the jitted
    stats vector still sums to 1 (the partial-sum regression);
  * GPT-2 integration: dense-block parameter subtrees identical to
    the dense model's (checkpoint compat), scheduled ZeRO-3 path
    bit-equal to the module path, structural-key verification;
  * ZeRO-3 composition: Zero3GatherScheduler.apply_layers with
    param_specs keeps expert leaves expert-sharded (bytes accounted
    at 1/expert-axis), a 10-step stage-3 MoE engine run composes with
    scheduled gathers and plan-vs-ledger params bytes within 15%;
  * engine wiring: the moe config block (validation, structural
    verification, expert-axis divisibility), the per-fence `router`
    event, the `moe_dispatch` memory-ledger category cross-checked
    against independent byte math (the PR-9 window-bound pattern),
    and oom_hints naming moe.capacity_factor when it dominates;
  * mesh/topology: the opt-in `expert` axis (build/reform/batch
    sharding) and the extensible PipelineParallelGrid axis list.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.moe import (MoEConfig, MoEMLP, STAT_AUX, STAT_DROP,
                               moe_mlp_reference, resolve_pack_experts,
                               reset_dispatch_accounting,
                               router_capacity, top_k_gating)
from deepspeed_tpu.moe.dispatch import (dispatch_buffer_nbytes,
                                        dispatch_tokens, combine_tokens,
                                        per_device_fraction)
from deepspeed_tpu.moe.experts import (ExpertFFN, expert_ffn_reference,
                                       grouped_gemm)
from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2ForCausalLM,
                                       stacked_block_params)
from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError,
                                          get_moe_config)
from deepspeed_tpu.runtime.mesh import (EXPERT_AXIS, batch_axes,
                                        build_mesh, data_sharding,
                                        expert_axis_size, reform_mesh,
                                        stacked_batch_pspecs)

D, F = 16, 32


# ----------------------------------------------------------------------
# router
# ----------------------------------------------------------------------
def test_router_capacity_formula():
    # C = ceil(cf * k * tokens / E), floored at 1
    assert router_capacity(128, 8, 2, 1.25) == 40
    assert router_capacity(128, 8, 1, 1.25) == 20
    assert router_capacity(4, 8, 1, 0.1) == 1
    with pytest.raises(ValueError):
        router_capacity(0, 8, 1, 1.0)


def test_top_k_gating_shapes_and_stats_layout():
    logits = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
    cap = router_capacity(64, 4, 2, 1.5)
    d, c, stats = top_k_gating(logits, 2, cap)
    assert d.shape == (64, 4, cap) and c.shape == (64, 4, cap)
    assert stats.shape == (4 + 2,)
    # loads over ALL k choices sum to 1 pre-capacity
    assert abs(float(jnp.sum(stats[:4])) - 1.0) < 1e-6
    # at cap = 1.5x mean nothing should drop for this seed
    assert float(stats[STAT_DROP]) == 0.0
    # every token occupies at most k slots; every expert at most cap
    assert float(jnp.max(jnp.sum(d, axis=(1, 2)))) <= 2.0
    assert float(jnp.max(jnp.sum(d, axis=(0, 2)))) <= cap
    with pytest.raises(ValueError):
        top_k_gating(logits, 5, cap)


def test_top_k_gating_choice_major_priority_and_drop_count():
    # 3 tokens all pick expert 0 first at capacity 2: the LAST token's
    # first choice drops (token-major within a choice), and the drop
    # fraction counts it
    logits = jnp.asarray([[9.0, 0.0], [9.0, 0.0], [9.0, 0.0]])
    d, c, stats = top_k_gating(logits, 1, 2)
    kept = jnp.sum(d, axis=(1, 2))
    assert kept.tolist() == [1.0, 1.0, 0.0]
    assert abs(float(stats[STAT_DROP]) - 1.0 / 3.0) < 1e-6
    # combine weights are the renormalized gate probs (k=1 -> 1.0)
    assert abs(float(jnp.sum(c)) - 2.0) < 1e-5


def test_router_dropless_at_production_token_counts():
    # the 25% capacity margin dwarfs the multinomial per-expert count
    # fluctuation at N/E >= 1k — the bench leg's dropless contract
    for k in (1, 2):
        logits = jax.random.normal(jax.random.PRNGKey(7), (8192, 8))
        cap = router_capacity(8192, 8, k, 1.25)
        _, _, stats = jax.jit(
            lambda lg: top_k_gating(lg, k, cap))(logits)
        assert float(stats[STAT_DROP]) == 0.0


def test_router_jitter_changes_only_training_decisions():
    logits = jax.random.normal(jax.random.PRNGKey(1), (32, 4))
    cap = router_capacity(32, 4, 2, 2.0)
    d0, _, _ = top_k_gating(logits, 2, cap)
    d1, _, _ = top_k_gating(logits, 2, cap, rng=None, jitter_eps=0.3)
    # rng=None: jitter is OFF regardless of eps (deterministic traces)
    assert jnp.array_equal(d0, d1)
    d2, _, _ = top_k_gating(logits, 2, cap,
                            rng=jax.random.PRNGKey(2), jitter_eps=0.9)
    assert d2.shape == d0.shape   # same compiled shapes either way


# ----------------------------------------------------------------------
# grouped GEMMs / experts
# ----------------------------------------------------------------------
def test_grouped_gemm_packed_parity_even_and_odd_groups():
    for g in (4, 5):   # odd count exercises the zero-expert padding
        x = jax.random.normal(jax.random.PRNGKey(g), (g, 8, D))
        w = jax.random.normal(jax.random.PRNGKey(g + 1), (g, D, F))
        ref = jnp.einsum("gmk,gkn->gmn", x, w)
        out = grouped_gemm(x, w, pack=True)
        assert out.shape == ref.shape
        assert float(jnp.max(jnp.abs(out - ref))) <= 1e-5
    with pytest.raises(ValueError):
        grouped_gemm(jnp.zeros((2, 8, D)), jnp.zeros((3, D, F)))


def test_expert_ffn_parity_vs_reference_fwd_and_grad():
    e = 4
    ffn = ExpertFFN(num_experts=e, d_model=D, d_ff=F, pack=True)
    xe = jax.random.normal(jax.random.PRNGKey(0), (e, 8, D))
    params = ffn.init(jax.random.PRNGKey(1), xe)["params"]

    def f(p):
        return jnp.sum(ffn.apply({"params": p}, xe) ** 2)

    def fr(p):
        return jnp.sum(expert_ffn_reference(p, xe) ** 2)

    y = ffn.apply({"params": params}, xe)
    yr = expert_ffn_reference(params, xe)
    assert float(jnp.max(jnp.abs(y - yr))) <= 1e-5
    g, gr = jax.grad(f)(params), jax.grad(fr)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(gr)):
        assert float(jnp.max(jnp.abs(a - b))) <= 1e-4


def test_quantized_experts_same_param_tree():
    e = 4
    xe = jnp.zeros((e, 8, D))
    base = ExpertFFN(num_experts=e, d_model=D, d_ff=F)
    quant = ExpertFFN(num_experts=e, d_model=D, d_ff=F,
                      quantized="on")
    p0 = base.init(jax.random.PRNGKey(0), xe)["params"]
    p1 = quant.init(jax.random.PRNGKey(0), xe)["params"]
    assert jax.tree_util.tree_structure(p0) == \
        jax.tree_util.tree_structure(p1)
    # the quantized forward runs (XLA fallback on CPU) and keeps shape
    y = quant.apply({"params": p1}, xe)
    assert y.shape == (e, 8, D) and bool(jnp.all(jnp.isfinite(y)))


def test_resolve_pack_experts():
    assert resolve_pack_experts(True) is True
    assert resolve_pack_experts(False) is False
    # "auto" = real TPU only (this suite runs on CPU)
    assert resolve_pack_experts("auto") is False
    with pytest.raises(ValueError):
        resolve_pack_experts("maybe")


# ----------------------------------------------------------------------
# MoEMLP parity + aux gradients
# ----------------------------------------------------------------------
@pytest.mark.parametrize("pack", [True, False])
def test_moe_mlp_parity_vs_unpacked_reference(pack):
    moe = MoEConfig(num_experts=4, top_k=2, capacity_factor=1.5,
                    pack_experts=pack).validate()
    mlp = MoEMLP(moe=moe, d_model=D, d_ff=F)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, D))
    params = mlp.init(jax.random.PRNGKey(1), x)["params"]

    y, stats = mlp.apply({"params": params}, x)
    yr, stats_r = moe_mlp_reference(params, x, moe)
    assert float(jnp.max(jnp.abs(y - yr))) <= 1e-5
    assert jnp.array_equal(stats, stats_r)

    def f(p):
        yy, _ = mlp.apply({"params": p}, x)
        return jnp.sum(yy ** 2)

    def fr(p):
        yy, _ = moe_mlp_reference(p, x, moe)
        return jnp.sum(yy ** 2)

    g, gr = jax.grad(f)(params), jax.grad(fr)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(gr)):
        assert float(jnp.max(jnp.abs(a - b))) <= 1e-5


def test_aux_loss_gradients_exact():
    """The aux term's gradient flows through P_e (mean router prob)
    only — f_e and the dispatch masks are stop-gradiented (the
    Switch estimator). MoEMLP's aux gradient must be EXACT vs the
    reference path (same gating math, no packing/fusion)."""
    moe = MoEConfig(num_experts=4, top_k=2,
                    capacity_factor=1.5).validate()
    mlp = MoEMLP(moe=moe, d_model=D, d_ff=F)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, D))
    params = mlp.init(jax.random.PRNGKey(4), x)["params"]

    def aux(p):
        _, stats = mlp.apply({"params": p}, x)
        return stats[STAT_AUX]

    def aux_r(p):
        _, stats = moe_mlp_reference(p, x, moe)
        return stats[STAT_AUX]

    g, gr = jax.grad(aux)(params), jax.grad(aux_r)(params)
    # only the router weights feel the aux term; expert params get 0
    assert float(jnp.max(jnp.abs(g["wg"]))) > 0.0
    for key in ("wi", "bi", "wo", "bo"):
        assert float(jnp.max(jnp.abs(g["experts"][key]))) == 0.0
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(gr)):
        assert jnp.array_equal(a, b)


def test_moe_config_validation():
    with pytest.raises(ValueError):
        MoEConfig(num_experts=1).validate()
    with pytest.raises(ValueError):
        MoEConfig(num_experts=4, top_k=5).validate()
    with pytest.raises(ValueError):
        MoEConfig(capacity_factor=0.0).validate()
    with pytest.raises(ValueError):
        MoEConfig(every_n_layers=0).validate()
    with pytest.raises(ValueError):
        MoEConfig(aux_loss_weight=-1.0).validate()
    with pytest.raises(ValueError):
        MoEConfig(pack_experts="sometimes").validate()
    assert MoEConfig().validate().num_experts == 8


# ----------------------------------------------------------------------
# mesh: the opt-in expert axis
# ----------------------------------------------------------------------
def test_build_mesh_expert_axis_opt_in():
    m3 = build_mesh({"data": -1})
    assert EXPERT_AXIS not in m3.axis_names
    m4 = build_mesh({"data": -1, "expert": 2})
    assert dict(m4.shape) == {"pipe": 1, "data": 4, "expert": 2,
                              "model": 1}
    assert expert_axis_size(m4) == 2 and expert_axis_size(m3) == 1
    with pytest.raises(AssertionError):
        build_mesh({"data": 8, "expert": 3})   # 24 != 8 devices


def test_reform_mesh_keeps_pinned_expert_axis():
    devices = jax.devices()[:6]    # a 2-device host died
    m = reform_mesh(devices, {"expert": 2})
    assert dict(m.shape)["expert"] == 2 and dict(m.shape)["data"] == 3


def test_batch_sharding_over_expert_axis():
    m4 = build_mesh({"data": -1, "expert": 2})
    assert batch_axes(m4) == ("data", "expert")
    sh = data_sharding(m4, 2)
    assert sh.spec == PartitionSpec(("data", "expert"), None)
    specs = stacked_batch_pspecs({"x": np.zeros((2, 8, 4))}, m4)
    assert specs["x"] == PartitionSpec(None, ("data", "expert"), None)
    # 3-axis meshes keep the historical literal spec
    m3 = build_mesh({"data": -1})
    assert data_sharding(m3, 2).spec == PartitionSpec("data", None)
    specs3 = stacked_batch_pspecs({"x": np.zeros((2, 8, 4))}, m3)
    assert specs3["x"] == PartitionSpec(None, "data", None)


def test_stats_replicated_under_expert_mesh_jit():
    """The SPMD partial-sum regression: jitted under an expert mesh
    with dispatch constraints active, the stats vector must STILL sum
    to 1 (replicate_stats forces the all-reduce)."""
    mesh = build_mesh({"data": -1, "expert": 2})
    moe = MoEConfig(num_experts=4, top_k=2, capacity_factor=1.5,
                    mesh=mesh).validate()
    mlp = MoEMLP(moe=moe, d_model=D, d_ff=F)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 8, D))
    params = mlp.init(jax.random.PRNGKey(1), x)["params"]
    y, stats = jax.jit(
        lambda p, xx: mlp.apply({"params": p}, xx))(params, x)
    assert abs(float(jnp.sum(stats[:4])) - 1.0) < 1e-5
    y0, stats0 = mlp.apply(
        {"params": params},
        x)   # eager trace, constraints resharding only
    assert float(jnp.max(jnp.abs(y - y0))) <= 1e-5
    assert float(jnp.max(jnp.abs(stats - stats0))) <= 1e-5


def test_dispatch_byte_accounting_cross_check():
    """moe_dispatch byte math vs independent arithmetic (the PR-9
    window-bound pattern): [E, C, H] send + recv, divided across the
    (expert, data) shards."""
    mesh = build_mesh({"data": -1, "expert": 2})
    assert per_device_fraction(mesh) == 1.0 / 8.0
    nbytes = dispatch_buffer_nbytes(8, 40, 64, np.float32, mesh)
    assert nbytes == 2 * 8 * 40 * 64 * 4 // 8
    assert dispatch_buffer_nbytes(8, 40, 64, np.float32, None) == \
        2 * 8 * 40 * 64 * 4


# ----------------------------------------------------------------------
# ZeRO-3 scheduler param_specs composition
# ----------------------------------------------------------------------
def test_zero3_apply_layers_param_specs_parity_and_bytes():
    from deepspeed_tpu.runtime.zero.stage3 import Zero3GatherScheduler
    mesh = build_mesh({"data": -1, "expert": 2})
    sched = Zero3GatherScheduler(mesh, prefetch_layers=1)
    L, E, H = 3, 4, 8
    stacked = {
        "wi": jax.random.normal(jax.random.PRNGKey(0), (L, E, H, F)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (L, F))}
    specs = {"wi": PartitionSpec(None, EXPERT_AXIS, None, None),
             "b": PartitionSpec(None)}

    def body(lp, h, rng_k):
        y = jnp.einsum("eh,ehf->ef", h, lp["wi"]) + lp["b"][None, :]
        return jnp.tanh(jnp.einsum("ef,ehf->eh", y, lp["wi"]))

    h0 = jnp.ones((E, H))

    def loss(st, h):
        return jnp.sum(sched.apply_layers(
            body, st, h, jax.random.PRNGKey(0), name="h",
            param_specs=specs) ** 2)

    def ref(st, h):
        for k in range(L):
            h = body(jax.tree_util.tree_map(lambda a: a[k], st), h,
                     None)
        return jnp.sum(h ** 2)

    v, g = jax.jit(jax.value_and_grad(loss))(stacked, h0)
    vr, gr = jax.jit(jax.value_and_grad(ref))(stacked, h0)
    assert abs(float(v - vr)) <= 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(g),
                    jax.tree_util.tree_leaves(gr)):
        assert float(jnp.max(jnp.abs(a - b))) <= 1e-5
    # gathered bytes: the expert leaf counts at 1/expert_axis (its
    # gathered copy STAYS expert-sharded), the dense leaf at full
    info = sched.stack_info["h"]
    expert_leaf = E * H * F * 4 // 2
    dense_leaf = F * 4
    assert info["per_layer_bytes"] == expert_leaf + dense_leaf
    assert sched._gather_bytes["h"] == 2 * (expert_leaf + dense_leaf)


# ----------------------------------------------------------------------
# GPT-2 integration
# ----------------------------------------------------------------------
def _tiny_moe_cfg(**over):
    moe = MoEConfig(num_experts=4, top_k=2, capacity_factor=1.5,
                    every_n_layers=2).validate()
    base = dict(n_layer=4, n_head=2, n_embd=D, n_positions=32,
                vocab_size=64, dropout=0.0, moe=moe)
    base.update(over)
    return GPT2Config(**base)


def _ids(rows=8, t=16, seed=0):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (rows, t), 0, 64), np.int32)


def test_gpt2_moe_dense_blocks_share_param_tree():
    """Dense cells inside the MoE model carry the EXACT dense-block
    subtree (same submodule names/shapes as the dense model's
    scanned blocks) so dense checkpoints' block weights load."""
    cfg = _tiny_moe_cfg()
    model = GPT2ForCausalLM(cfg)
    params = model.module.init(jax.random.PRNGKey(0),
                               jnp.asarray(_ids()), True)["params"]
    dense_cfg = dataclasses.replace(cfg, moe=None)
    dense = GPT2ForCausalLM(dense_cfg)
    dparams = dense.module.init(jax.random.PRNGKey(0),
                                jnp.asarray(_ids()), True)["params"]
    cell = params["h"]
    dense_sub = [v for k, v in cell.items() if "MoE" not in k]
    assert len(dense_sub) == 1
    dense_keys = jax.tree_util.tree_structure(dense_sub[0])
    # the dense model's stacked block tree has the same structure
    assert jax.tree_util.tree_structure(
        jax.tree_util.tree_map(lambda x: 0, dparams["h"])) \
        .num_leaves == dense_keys.num_leaves
    # embeddings/ln_f identical across the two models
    for key in ("wte", "wpe"):
        assert params[key].shape == dparams[key].shape


def test_gpt2_moe_loss_and_stats_and_moe_info():
    cfg = _tiny_moe_cfg()
    model = GPT2ForCausalLM(cfg)
    params = model.module.init(jax.random.PRNGKey(1),
                               jnp.asarray(_ids()), True)["params"]
    batch = {"input_ids": _ids()}
    loss = model.loss_fn(params, batch, deterministic=True)
    assert np.isfinite(float(loss))
    loss2, stats = model.loss_fn(params, batch, deterministic=True,
                                 return_router_stats=True)
    assert float(loss) == float(loss2)
    assert stats.shape == (4 + 2,)
    assert abs(float(jnp.sum(stats[:4])) - 1.0) < 1e-5
    info = model.moe_info()
    assert info["num_experts"] == 4 and info["moe_layers"] == 2
    # aux term really rides the loss
    no_aux = dataclasses.replace(
        cfg, moe=dataclasses.replace(cfg.moe, aux_loss_weight=0.0))
    m0 = GPT2ForCausalLM(no_aux)
    l0 = m0.loss_fn(params, batch, deterministic=True)
    expect = float(l0) + 0.01 * float(stats[STAT_AUX])
    assert abs(float(loss) - expect) < 1e-5
    # logits-only apply drops the stats tuple
    logits = model.apply(params, jnp.asarray(_ids()))
    assert logits.shape == (8, 16, 64)


def test_gpt2_moe_structural_keys_verified():
    model = GPT2ForCausalLM(_tiny_moe_cfg())
    with pytest.raises(ValueError):
        model.configure_moe(num_experts=8)
    with pytest.raises(ValueError):
        model.configure_moe(every_n_layers=1)
    model.configure_moe(top_k=1, capacity_factor=2.0)
    assert model.config.moe.top_k == 1
    dense = GPT2ForCausalLM(
        dataclasses.replace(_tiny_moe_cfg(), moe=None))
    with pytest.raises(ValueError):
        dense.configure_moe(num_experts=4)
    with pytest.raises(ValueError):
        GPT2ForCausalLM(_tiny_moe_cfg(n_layer=3)).config.moe_cells
    with pytest.raises(ValueError):
        # PLD has no per-cell keep-prob gate on the MoE path
        model.loss_fn(
            model.module.init(jax.random.PRNGKey(0),
                              jnp.asarray(_ids()), True)["params"],
            {"input_ids": _ids()}, deterministic=True,
            layer_keep_prob=0.5)


def test_gpt2_moe_zero3_scheduled_path_matches_module_path():
    from deepspeed_tpu.runtime.zero.stage3 import Zero3GatherScheduler
    mesh = build_mesh({"data": -1, "expert": 2})
    model = GPT2ForCausalLM(_tiny_moe_cfg())
    model.configure_moe(mesh=mesh)
    params = model.module.init(jax.random.PRNGKey(2),
                               jnp.asarray(_ids()), True)["params"]
    batch = {"input_ids": _ids()}
    l_mod, s_mod = jax.jit(lambda p, b: model.loss_fn(
        p, b, deterministic=True, return_router_stats=True))(
        params, batch)
    model.bind_zero3_scheduler(Zero3GatherScheduler(mesh,
                                                    prefetch_layers=1))
    try:
        l_sch, s_sch = jax.jit(lambda p, b: model.loss_fn(
            p, b, deterministic=True, return_router_stats=True))(
            params, batch)
    finally:
        model.bind_zero3_scheduler(None)
    assert abs(float(l_mod - l_sch)) <= 1e-6
    assert float(jnp.max(jnp.abs(s_mod - s_sch))) <= 1e-6


# ----------------------------------------------------------------------
# engine wiring
# ----------------------------------------------------------------------
def test_get_moe_config_validation():
    assert get_moe_config({})["enabled"] is False
    cfg = get_moe_config({"moe": {"enabled": True, "num_experts": 4}})
    assert cfg["num_experts"] == 4 and cfg["top_k"] == 2
    for bad in ({"moe": {"num_experts": 1}},
                {"moe": {"top_k": 0}},
                {"moe": {"num_experts": 4, "top_k": 5}},
                {"moe": {"capacity_factor": 0}},
                {"moe": {"every_n_layers": 0}},
                {"moe": {"aux_loss_weight": -1}},
                {"moe": {"jitter_eps": -0.1}},
                {"moe": "yes"}):
        with pytest.raises(DeepSpeedConfigError):
            get_moe_config(bad)
    # parsed into DeepSpeedConfig
    dsc = DeepSpeedConfig({"train_batch_size": 8,
                           "moe": {"enabled": True}})
    assert dsc.moe["enabled"] is True


def _moe_engine(zero_stage=3, expert=2, monitor=True, rows=8):
    model = GPT2ForCausalLM(_tiny_moe_cfg())
    params = model.module.init(jax.random.PRNGKey(0),
                               jnp.asarray(_ids(rows)), True)["params"]
    ds = {"train_micro_batch_size_per_gpu": 1,
          "gradient_accumulation_steps": 1,
          "train_batch_size": rows,
          "steps_per_print": 1,
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "mesh": {"data": -1, "expert": expert},
          "moe": {"enabled": True, "num_experts": 4, "top_k": 2,
                  "capacity_factor": 1.5, "every_n_layers": 2}}
    if zero_stage:
        ds["zero_optimization"] = {"stage": zero_stage,
                                   "stage3": {"enabled": True,
                                              "prefetch_layers": 1}}
    if monitor:
        ds["monitor"] = {"enabled": True, "sinks": []}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds)
    return engine, model


def test_moe_engine_expert_axis_divisibility_error():
    model = GPT2ForCausalLM(_tiny_moe_cfg())   # 4 experts
    params = model.module.init(jax.random.PRNGKey(0),
                               jnp.asarray(_ids()), True)["params"]
    with pytest.raises(ValueError, match="must divide"):
        deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": 8,
                    "mesh": {"data": 1, "expert": 8},
                    "moe": {"enabled": True, "num_experts": 4},
                    "optimizer": {"type": "Adam",
                                  "params": {"lr": 1e-3}}})


@pytest.mark.slow
def test_moe_engine_zero3_ten_steps_composes():
    """The acceptance contract: a 10-step MoE engine run composes
    with ZeRO-3 — scheduled gathers of expert leaves (the stack's
    window accounted at expert-sharded bytes), loss decreasing and
    finite, router events at every fence, moe_dispatch ledger entry
    matching independent byte math, plan-vs-ledger params bytes
    within 15%."""
    reset_dispatch_accounting()
    engine, model = _moe_engine()
    assert engine.zero3_scheduler is not None
    assert engine._moe_active and engine.dp_world_size == 8
    losses = []
    fixed = {"input_ids": _ids(seed=0)[None]}   # overfit one batch:
    for step in range(10):                      # monotone-ish descent
        loss = engine.train_batch(batch=fixed)
        losses.append(float(jax.device_get(loss)))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]

    # scheduled gathers happened, with expert leaves priced sharded:
    # per-layer bytes < the full (unsharded) cell bytes
    info = engine.zero3_scheduler.stack_info["h"]
    stacked = engine.state.params["h"]
    full_per_layer = sum(
        int(np.prod(np.shape(l)[1:])) * 4
        for l in jax.tree_util.tree_leaves(stacked))
    assert 0 < info["per_layer_bytes"] < full_per_layer
    assert info["window_layers"] == 2

    # router event at the fence
    snap = engine.monitor.snapshot()
    router = snap["router"]
    assert router is not None and router["num_experts"] == 4
    assert abs(sum(router["expert_load"]) - 1.0) < 1e-3

    # moe_dispatch ledger vs independent byte math (the model's
    # compute dtype — bf16 by GPT2Config default — sizes the buffers)
    from deepspeed_tpu.moe.router import router_capacity as rc
    cap = rc(8 * 16, 4, 2, 1.5)
    indep = dispatch_buffer_nbytes(4, cap, D,
                                   np.dtype(model.config.dtype),
                                   engine.mesh) * 2
    led = engine.monitor.ledger.category_breakdown("moe_dispatch")
    assert led.get("moe.dispatch_buffers") == indep

    # plan vs ledger: params bytes within 15%
    shapes = jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(np.shape(l), l.dtype),
        engine.state.params)
    plan = engine.zero_policy.memory_plan(shapes, compute_bytes=4)
    measured = engine.monitor.ledger.totals()["hbm"]["params"]
    assert abs(measured - plan["params"]) <= 0.15 * plan["params"]


def test_moe_engine_dense_config_unaffected():
    """A dense model + no moe block: engine runs exactly as before
    (moe inactive, no router events, no moe_dispatch entry)."""
    cfg = dataclasses.replace(_tiny_moe_cfg(), moe=None)
    model = GPT2ForCausalLM(cfg)
    params = model.module.init(jax.random.PRNGKey(0),
                               jnp.asarray(_ids()), True)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "steps_per_print": 1,
                "monitor": {"enabled": True, "sinks": []},
                "optimizer": {"type": "Adam",
                              "params": {"lr": 1e-3}}})
    assert not engine._moe_active
    loss = engine.train_batch(batch={"input_ids": _ids()[None]})
    assert np.isfinite(float(jax.device_get(loss)))
    snap = engine.monitor.snapshot()
    assert snap["router"] is None
    assert "moe_dispatch" not in engine.monitor.ledger.totals()["hbm"]


def test_moe_warns_without_hook():
    """moe.enabled against a model with no configure_moe hook warns
    and stays inactive instead of crashing."""
    def loss_fn(p, batch, rngs=None, deterministic=False):
        return jnp.mean((batch["x"] @ p["w"]) ** 2)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=loss_fn,
        model_parameters={"w": np.zeros((4, 4), np.float32)},
        config={"train_batch_size": 8,
                "moe": {"enabled": True},
                "optimizer": {"type": "Adam",
                              "params": {"lr": 1e-3}}})
    assert not engine._moe_active


def test_oom_hints_name_moe_knobs():
    from deepspeed_tpu.monitor.memory import oom_hints
    payload = {"hbm": {"categories": {"moe_dispatch": 800,
                                      "params": 200},
                       "ledger_bytes": 1000,
                       "measured_in_use_per_device": None,
                       "residual_bytes": None},
               "host": {"categories": {}}}
    hints = oom_hints(payload)
    assert any("moe.capacity_factor" in h for h in hints)
    assert any("moe.num_experts" in h for h in hints)


# ----------------------------------------------------------------------
# topology: extensible axis list
# ----------------------------------------------------------------------
def test_topology_grid_keeps_expert_axis():
    from deepspeed_tpu.runtime.pipe.topology import (
        PipelineParallelGrid, topology_from_mesh)
    mesh = build_mesh({"data": -1, "expert": 2})
    topo = topology_from_mesh(mesh)
    assert topo.get_axis_names() == ["pipe", "data", "expert",
                                     "model"]
    assert topo.world_size() == 8
    grid = PipelineParallelGrid(mesh=mesh)
    assert grid.expert_parallel_size == 2
    assert grid.get_expert_parallel_world_size() == 2
    assert grid.get_expert_parallel_rank() == 0
    # the expert coordinate shows up in rank reprs (data/pipe omitted)
    repr4 = topo.get_rank_repr(rank=1)
    assert "expert" in repr4 or "model" in repr4
    # comm-group math covers the new axis
    lists = topo.get_axis_comm_lists("expert")
    assert len(lists) == 4 and all(len(l) == 2 for l in lists)
    # 3-axis meshes unchanged
    grid3 = PipelineParallelGrid(mesh=build_mesh({"data": -1}))
    assert grid3.expert_parallel_size == 1
    assert grid3.get_expert_parallel_rank() == 0
