"""Async dispatch pipeline tests (ISSUE 2).

Covers:
  * device-resident LR schedule parity with the host classes — all four
    schedules swept over 0..5k steps including warmup/decay/cycle
    boundaries;
  * fp16 overflow-skip semantics without a host sync (async vs legacy
    synced trajectories are identical, including the scheduler hold);
  * the NO-HOST-SYNC guard: bf16 and fp16 `train_batch` hot loops with
    `jax.device_get` / `jax.effects_barrier` instrumented must perform
    ZERO per-step calls (and the legacy synced fp16 loop must show the
    per-step device_get the async path deleted);
  * PrefetchLoader collation/staging/termination;
  * backward(release_loss=...) honoring the flag and step() dropping
    the pending-loss reference.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from simple_model import SimpleModel
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.runtime.prefetch import PrefetchLoader


# ----------------------------------------------------------------------
# device-vs-host LR schedule parity
# ----------------------------------------------------------------------
SWEEP_STEPS = 5000

SCHEDULE_CASES = [
    ("WarmupLR",
     {"warmup_min_lr": 1e-5, "warmup_max_lr": 0.1,
      "warmup_num_steps": 1000}),
    ("WarmupDecayLR",
     {"warmup_min_lr": 0.0, "warmup_max_lr": 0.1,
      "warmup_num_steps": 500, "total_num_steps": 3000}),
    ("LRRangeTest",
     {"lr_range_test_min_lr": 1e-3, "lr_range_test_step_size": 100,
      "lr_range_test_step_rate": 0.5,
      "lr_range_test_staircase": False}),
    ("LRRangeTest",
     {"lr_range_test_min_lr": 1e-3, "lr_range_test_step_size": 100,
      "lr_range_test_step_rate": 0.5,
      "lr_range_test_staircase": True}),
    ("OneCycle",
     {"cycle_min_lr": 0.01, "cycle_max_lr": 0.1,
      "cycle_first_step_size": 400, "cycle_second_step_size": 600,
      "decay_step_size": 250, "decay_lr_rate": 0.5,
      "cycle_momentum": False}),
]

_HOST_CLASSES = {
    "WarmupLR": lr_schedules.WarmupLR,
    "WarmupDecayLR": lr_schedules.WarmupDecayLR,
    "LRRangeTest": lr_schedules.LRRangeTest,
    "OneCycle": lr_schedules.OneCycle,
}


@pytest.mark.parametrize("name,params", SCHEDULE_CASES,
                         ids=["warmup", "warmup_decay", "range_cont",
                              "range_stair", "one_cycle"])
def test_device_schedule_matches_host(name, params):
    """device fn at step k == host get_lr() at last_batch_iteration=k
    for every k in the sweep (covers warmup→flat, warmup→decay→0 clamp,
    stair edges, and the cycle→decay transition)."""
    host = _HOST_CLASSES[name](lr_schedules._OptimizerShim(), **params)
    host_lrs = []
    for _ in range(SWEEP_STEPS):
        host.step()
        host_lrs.append(host.get_last_lr()[0])
    dev = lr_schedules.device_schedule_fn(name, params)
    dev_lrs = np.asarray(dev(jnp.arange(SWEEP_STEPS)))
    # fp32 device math vs float64 host math
    np.testing.assert_allclose(dev_lrs, host_lrs, rtol=2e-6, atol=1e-9)


def test_device_schedule_constant_and_none():
    fn = lr_schedules.device_schedule_fn(None, base_lr=3e-4)
    np.testing.assert_allclose(np.asarray(fn(jnp.arange(5))), 3e-4)
    assert lr_schedules.device_schedule_fn(None, base_lr=None) is None
    with pytest.raises(ValueError):
        lr_schedules.device_schedule_fn("NoSuchSchedule", {})


# ----------------------------------------------------------------------
# engine-level async behavior
# ----------------------------------------------------------------------
def _fp16_cfg(async_enabled, **over):
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10000,
        "fp16": {"enabled": True, "loss_scale": 0,
                 "initial_scale_power": 4, "loss_scale_window": 1000,
                 "hysteresis": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_min_lr": 0.0,
                                 "warmup_max_lr": 5e-2,
                                 "warmup_num_steps": 10}},
        "async_dispatch": {"enabled": async_enabled},
    }
    cfg.update(over)
    return cfg


def _make_stacked(seed, bs=16, dim=8, bad=False):
    rng = np.random.RandomState(seed)
    x = rng.randn(bs, dim).astype(np.float32)
    if bad:
        x = np.full((bs, dim), 1e30, np.float32)
    w = np.linspace(-1, 1, dim * dim).reshape(dim, dim).astype(np.float32)
    return {"x": x[None], "y": (x @ w)[None]}


def _run_fp16(async_enabled, plan):
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=_fp16_cfg(async_enabled))
    assert engine.async_dispatch_enabled() == async_enabled
    losses = []
    for seed, bad in plan:
        loss = engine.train_batch(batch=_make_stacked(seed, bad=bad))
        losses.append(float(jax.device_get(loss)))
    return engine, losses


def test_async_overflow_skip_matches_synced_loop():
    """The device-resident schedule must reproduce the legacy host
    rewind exactly: an overflow step advances neither the optimizer nor
    the schedule, and the whole trajectory (losses, params, counters,
    lr) matches the synced loop step for step."""
    plan = [(0, False), (1, False), (2, True), (3, False), (2, True),
            (4, False), (5, False)]
    e_async, l_async = _run_fp16(True, plan)
    e_sync, l_sync = _run_fp16(False, plan)

    np.testing.assert_allclose(l_async, l_sync, rtol=1e-5)
    assert e_async.skipped_steps == e_sync.skipped_steps == 2
    assert int(jax.device_get(e_async.state.global_steps)) == \
        int(jax.device_get(e_sync.state.global_steps)) == 5
    # user-facing lr query syncs the async mirror; the two must agree
    np.testing.assert_allclose(e_async.get_lr(), e_sync.get_lr(),
                               rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(
                        jax.device_get(e_async.fp32_params)),
                    jax.tree_util.tree_leaves(
                        jax.device_get(e_sync.fp32_params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-7)


def test_async_scheduler_trajectory_matches_sync_no_overflow():
    """bf16-free fp32 path: async vs sync with a OneCycle schedule must
    train identically (the lr fed to the update is the same function of
    the step count on both paths)."""
    def run(async_enabled):
        model = SimpleModel(hidden_dim=8)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=model.params,
            config={
                "train_batch_size": 16,
                "steps_per_print": 10000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "scheduler": {"type": "OneCycle",
                              "params": {"cycle_min_lr": 1e-3,
                                         "cycle_max_lr": 5e-2,
                                         "cycle_first_step_size": 5,
                                         "decay_step_size": 5,
                                         "decay_lr_rate": 0.1,
                                         "cycle_momentum": False}},
                "async_dispatch": {"enabled": async_enabled},
            })
        losses = []
        for i in range(12):
            loss = engine.train_batch(batch=_make_stacked(i % 3))
            losses.append(float(jax.device_get(loss)))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-4)


def test_client_scheduler_forces_sync_mode():
    model = SimpleModel(hidden_dim=8)
    client = lr_schedules.WarmupLR(lr_schedules._OptimizerShim(lr=0.0),
                                   warmup_max_lr=1e-2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        lr_scheduler=client,
        config={"train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    assert not engine.async_dispatch_enabled()
    loss = engine.train_batch(batch=_make_stacked(0))
    assert np.isfinite(float(jax.device_get(loss)))
    # sync path advanced the client scheduler on the hot loop
    assert client.last_batch_iteration == 0


# ----------------------------------------------------------------------
# the no-host-sync guard
# ----------------------------------------------------------------------
class _SyncCounters:
    """Count calls to the two host-sync entry points the engine/timers
    use (`jax.device_get`, `jax.effects_barrier`)."""

    def __init__(self, monkeypatch):
        self.device_get = 0
        self.effects_barrier = 0
        real_get, real_barrier = jax.device_get, jax.effects_barrier

        def counting_get(x):
            self.device_get += 1
            return real_get(x)

        def counting_barrier():
            self.effects_barrier += 1
            return real_barrier()

        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "effects_barrier", counting_barrier)

    def reset(self):
        self.device_get = 0
        self.effects_barrier = 0

    @property
    def total(self):
        return self.device_get + self.effects_barrier


def _guard_cfg(mode, async_enabled=True):
    cfg = {
        "train_batch_size": 32,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10000,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupDecayLR",
                      "params": {"warmup_max_lr": 1e-3,
                                 "warmup_num_steps": 10,
                                 "total_num_steps": 100}},
        "async_dispatch": {"enabled": async_enabled},
    }
    if mode == "fp16":
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 4}
    else:
        cfg["bf16"] = {"enabled": True}
    return cfg


def _guard_engine_and_batches(mode, async_enabled=True):
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config=_guard_cfg(mode, async_enabled))
    rng = np.random.RandomState(0)
    w = np.linspace(-1, 1, 64).reshape(8, 8).astype(np.float32)

    def stacked(seed):
        x = rng.randn(2, 16, 8).astype(np.float32)
        return {"x": x, "y": x @ w}

    # pre-staged device batches: the guard measures the STEP loop, not
    # the input pipeline (PrefetchLoader owns that side)
    batches = [engine.stage_batch(stacked(i)) for i in range(8)]
    return engine, batches


@pytest.mark.parametrize("mode", ["bf16", "fp16"])
def test_hot_path_has_zero_host_syncs(mode, monkeypatch):
    """The acceptance guard: after warmup (compile + throughput-window
    open), N async train_batch steps perform ZERO jax.device_get /
    jax.effects_barrier calls."""
    engine, batches = _guard_engine_and_batches(mode)
    # warmup: compile, settle donation, open the tput timer window
    # (its one-time fence at start_step=2)
    for b in batches[:3]:
        engine.train_batch(batch=b)
    counters = _SyncCounters(monkeypatch)
    for b in batches[3:]:
        engine.train_batch(batch=b)
    assert counters.device_get == 0, \
        f"{mode} hot path called jax.device_get {counters.device_get}x"
    assert counters.effects_barrier == 0, \
        f"{mode} hot path called jax.effects_barrier " \
        f"{counters.effects_barrier}x"
    # the loop still trained: reading the loss now is allowed to sync
    assert np.isfinite(float(jax.device_get(engine.losses)))


def test_synced_fp16_loop_does_sync_per_step(monkeypatch):
    """Inverse control for the guard: with async_dispatch disabled the
    legacy fp16 loop performs its per-step device_get(overflow) — this
    is the sync the tentpole deletes, and it proves the counters see
    through to the hot path."""
    engine, batches = _guard_engine_and_batches("fp16",
                                                async_enabled=False)
    for b in batches[:3]:
        engine.train_batch(batch=b)
    counters = _SyncCounters(monkeypatch)
    n = len(batches) - 3
    for b in batches[3:]:
        engine.train_batch(batch=b)
    assert counters.device_get >= n


# ----------------------------------------------------------------------
# PrefetchLoader
# ----------------------------------------------------------------------
def test_prefetch_loader_collates_and_stages():
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={"train_batch_size": 32,
                "gradient_accumulation_steps": 2,
                "steps_per_print": 10000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    rng = np.random.RandomState(0)
    w = np.linspace(-1, 1, 64).reshape(8, 8).astype(np.float32)

    def micro_iter(n):
        for _ in range(n):
            x = rng.randn(16, 8).astype(np.float32)
            yield {"x": x, "y": x @ w}

    # 6 microbatches / gas=2 → exactly 3 steps then StopIteration
    loader = engine.prefetch(micro_iter(6))
    losses = []
    for _ in range(3):
        losses.append(float(jax.device_get(
            engine.train_batch(data_iter=loader))))
    assert np.isfinite(losses).all()
    with pytest.raises(StopIteration):
        engine.train_batch(data_iter=loader)
    loader.close()


def test_prefetch_loader_stacks_like_train_batch():
    micro = [{"x": np.full((4, 2), i, np.float32)} for i in range(4)]
    loader = PrefetchLoader(iter(micro), stage_fn=None, gas=2, depth=2)
    b0 = next(loader)
    b1 = next(loader)
    np.testing.assert_array_equal(np.asarray(b0["x"])[:, 0, 0], [0, 1])
    np.testing.assert_array_equal(np.asarray(b1["x"])[:, 0, 0], [2, 3])
    assert b0["x"].shape == (2, 4, 2)
    with pytest.raises(StopIteration):
        next(loader)
    loader.close()


def test_prefetch_loader_propagates_worker_errors():
    def boom():
        yield {"x": np.zeros((2, 2), np.float32)}
        raise RuntimeError("loader exploded")

    loader = PrefetchLoader(boom(), stage_fn=None, gas=1, depth=2)
    next(loader)
    with pytest.raises(RuntimeError, match="loader exploded"):
        next(loader)
    loader.close()


def test_prefetch_loader_drops_partial_tail():
    micro = [{"x": np.zeros((2,), np.float32)} for _ in range(3)]
    loader = PrefetchLoader(iter(micro), stage_fn=None, gas=2, depth=2)
    next(loader)   # 2 microbatches consumed
    with pytest.raises(StopIteration):   # 1 leftover < gas
        next(loader)
    loader.close()


# ----------------------------------------------------------------------
# backward(release_loss) / step() loss-reference hygiene
# ----------------------------------------------------------------------
def test_release_loss_flag_and_step_drop():
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={"train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    batch = {"x": np.random.RandomState(0).randn(16, 8).astype(np.float32),
             "y": np.zeros((16, 8), np.float32)}

    loss = engine(batch)
    assert engine._pending_loss is not None
    engine.backward(loss)
    # default: the engine keeps the loss reference for engine.losses
    assert engine.losses is loss or \
        float(jax.device_get(engine.losses)) == \
        float(jax.device_get(loss))
    engine.step()
    # step() drops the forward-cached reference so the buffer isn't
    # pinned across steps
    assert engine._pending_loss is None

    loss = engine(batch)
    engine.backward(loss, release_loss=True)
    # release_loss honors the flag: no engine-held reference at all
    assert engine.losses is None
    assert engine._pending_loss is None
    engine.step()
    assert engine._pending_loss is None
