"""Elastic preemption-safe runtime tests (ISSUE 10).

What these pin:
  * the failure taxonomy (`classify_failure`) and runtime-config
    validation;
  * `FaultInjector` sentinel lifecycle: a SIGKILL'd "host" subprocess
    surfaces as exactly one `host_lost` event;
  * `engine.wait_for_checkpoint(timeout=...)` raises a
    `CheckpointWaitTimeout` (with the writer's heartbeat age) instead
    of deadlocking on a hung writer, and `abandon_checkpoint_writers`
    detaches it;
  * checkpoint load retry/backoff and the distinct
    staging-only-vs-nothing error taxonomy;
  * watchdog escalation: consecutive-fire counting, ONE terminal
    `stall_escalated` per episode, re-arm on fence;
  * the supervisor end-to-end on the virtual mesh: lose a host ->
    re-form on the survivors (re-derived micro-batch, re-planned ZeRO
    bytes strictly smaller per remaining device count), resume from
    the last committed tag with loss continuity asserted; capacity
    returns -> grow at the next checkpoint boundary;
  * the CHAOS test (subprocess — the PR-8/9 isolation precedent):
    SIGKILL a sentinel host mid-step, prove the post-resume loss
    trajectory is BIT-IDENTICAL to a clean engine restarted from the
    same checkpoint on the same surviving mesh, and that a scale-up
    restores the original device count at a checkpoint boundary.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.elasticity import ElasticityConfigError
from deepspeed_tpu.elasticity.runtime import (
    CAPACITY_RETURNED, HOST_LOST, HOST_SLOW, STALL, STALL_ESCALATED,
    BatchSpec, ElasticRuntimeConfig, ElasticSupervisor, FaultEvent,
    FaultInjector, classify_failure)
from deepspeed_tpu.runtime import checkpoint as ckpt_io
from deepspeed_tpu.monitor.watchdog import StallWatchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

D, H = 24, 48


def _model_factory():
    rng = np.random.RandomState(0)
    params = {"w1": np.asarray(rng.randn(D, H) * 0.1, np.float32),
              "b1": np.zeros(H, np.float32),
              "w2": np.asarray(rng.randn(H, 1) * 0.1, np.float32)}

    def loss_fn(p, batch, rngs=None, deterministic=False):
        h = jnp.tanh(batch["x"] @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

    return loss_fn, params


def _batch_fn(step, spec):
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(spec.total, D).astype(np.float32)
    y = (x[:, :1] * 0.5).astype(np.float32)
    return {"x": x.reshape(spec.gas, spec.rows, D),
            "y": y.reshape(spec.gas, spec.rows, 1)}


def _ds_config(hosts=4, interval=2, **runtime_over):
    runtime = {"enabled": True, "hosts": hosts,
               "checkpoint_interval": interval,
               "drain_timeout_sec": 5.0, "escalate_after": 2}
    runtime.update(runtime_over)
    return {
        "steps_per_print": 10000,
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "elasticity": {"enabled": True, "max_train_batch_size": 48,
                       "micro_batch_sizes": [2], "version": 0.1,
                       "runtime": runtime},
    }


# ----------------------------------------------------------------------
# failure taxonomy + runtime config
# ----------------------------------------------------------------------
def test_classify_failure_taxonomy():
    # lost dominates the verdict, but a straggler reported in the same
    # batch is dropped too (events are one-shot)
    kind, hosts, ret, n = classify_failure(
        [FaultEvent(HOST_SLOW, host=1), FaultEvent(HOST_LOST, host=2),
         FaultEvent(STALL)], 0, 3)
    assert (kind, hosts, ret, n) == (HOST_LOST, [1, 2], [], 0)
    # slow host is a verdict on its own
    kind, hosts, _, _ = classify_failure(
        [FaultEvent(HOST_SLOW, host=0)], 0, 3)
    assert (kind, hosts) == (HOST_SLOW, [0])
    # transient stalls accumulate, then escalate at the threshold
    kind, _, _, n = classify_failure([FaultEvent(STALL)], 0, 3)
    assert (kind, n) == (STALL, 1)
    kind, _, _, n = classify_failure([FaultEvent(STALL)], 2, 3)
    assert (kind, n) == (STALL_ESCALATED, 0)
    # an explicit watchdog escalation is terminal immediately
    kind, _, _, _ = classify_failure([FaultEvent(STALL_ESCALATED)], 0, 3)
    assert kind == STALL_ESCALATED
    # capacity return rides along with a healthy poll
    kind, _, ret, _ = classify_failure(
        [FaultEvent(CAPACITY_RETURNED, host=3)], 0, 3)
    assert kind is None and ret == [3]


def test_elastic_runtime_config_validation():
    assert not ElasticRuntimeConfig({}).enabled
    cfg = ElasticRuntimeConfig({"enabled": True, "hosts": 4})
    assert cfg.enabled and cfg.hosts == 4
    for bad in ({"hosts": 0}, {"checkpoint_interval": 0},
                {"drain_timeout_sec": 0}, {"load_retries": -1},
                {"max_recoveries": 0}):
        with pytest.raises(ElasticityConfigError):
            ElasticRuntimeConfig(dict({"enabled": True}, **bad))


def test_supervisor_requires_enabled_blocks():
    with pytest.raises(ElasticityConfigError):
        ElasticSupervisor({}, _model_factory, _batch_fn)
    cfg = _ds_config()
    cfg["elasticity"]["runtime"]["enabled"] = False
    with pytest.raises(ElasticityConfigError):
        ElasticSupervisor(cfg, _model_factory, _batch_fn)


def test_supervisor_rejects_model_parallel_mesh():
    """The supervisor re-forms pure data-parallel meshes; a tensor- or
    pipe-parallel mesh config must fail loudly, not silently degrade
    to dp-only."""
    cfg = _ds_config()
    cfg["mesh"] = {"model": 2}
    with pytest.raises(ElasticityConfigError, match="mesh.model"):
        ElasticSupervisor(cfg, _model_factory, _batch_fn)


def test_abandoned_writer_guard_survives_rebuild(tmp_path):
    """The same-tag staging guard must survive the engine rebuild a
    recovery performs: a stale abandoned writer still holding
    global_step2's staging dir blocks the REBUILT engine's replayed
    save of that tag (the next boundary's tag is free)."""

    class _StuckWriter:
        def pending(self):
            return 1

        def tag_in_flight(self, tag):
            return tag == "global_step2"

    inj = FaultInjector()
    sup = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                            save_dir=str(tmp_path / "ckpt"),
                            injector=inj)
    try:
        sup.run(1)
        sup.engine._abandoned_ckpt_writers = [_StuckWriter()]
        inj.mark_host_lost(3)
        sup.run(4)
        save = tmp_path / "ckpt"
        assert not (save / "global_step2").exists(), \
            "rebuilt engine wrote into a staging dir a stale writer owns"
        assert (save / "global_step4").exists()
        assert ckpt_io.read_latest_tag(str(save)) == "global_step4"
    finally:
        sup.close()


def test_batch_spec_rows():
    assert BatchSpec(world=6, micro=2, gas=4, total=48).rows == 12


# ----------------------------------------------------------------------
# fault injector sentinels
# ----------------------------------------------------------------------
def test_fault_injector_sentinel_sigkill_reports_once():
    with FaultInjector() as inj:
        pid = inj.spawn_host(0)
        inj.spawn_host(1)
        assert inj.poll() == []
        inj.sigkill_host(0)
        deadline = time.time() + 5.0
        events = []
        while not events and time.time() < deadline:
            events = inj.poll()
            time.sleep(0.01)
        assert [e.kind for e in events] == [HOST_LOST]
        assert events[0].host == 0 and events[0].info["pid"] == pid
        # reported exactly once; the surviving sentinel stays quiet
        assert inj.poll() == []
    # close() reaped the survivor
    assert inj.poll() == []


def test_fault_injector_respawn_after_death():
    """capacity_returned hosts get re-backed: a dead sentinel is
    evicted on respawn (and the new sentinel's death reports again);
    respawning over a LIVE sentinel is an error."""
    with FaultInjector() as inj:
        inj.spawn_host(0)
        with pytest.raises(ValueError, match="live sentinel"):
            inj.spawn_host(0)
        inj.sigkill_host(0)
        assert inj.wait_host_dead(0)
        deadline = time.time() + 5.0
        while not inj.poll() and time.time() < deadline:
            time.sleep(0.01)
        pid2 = inj.spawn_host(0)
        assert pid2 and not inj.host_dead(0)
        inj.sigkill_host(0)
        assert inj.wait_host_dead(0)
        events = []
        deadline = time.time() + 5.0
        while not events and time.time() < deadline:
            events = inj.poll()
            time.sleep(0.01)
        assert [e.kind for e in events] == [HOST_LOST]


def test_fault_injector_direct_events():
    inj = FaultInjector()
    inj.mark_host_lost(2, reason="preempted")
    inj.mark_host_slow(1)
    inj.inject_stall()
    inj.return_capacity(2)
    kinds = [e.kind for e in inj.poll()]
    assert kinds == [HOST_LOST, HOST_SLOW, STALL, CAPACITY_RETURNED]
    assert inj.poll() == []


# ----------------------------------------------------------------------
# wait_for_checkpoint timeout + abandon (satellite 1)
# ----------------------------------------------------------------------
def _tiny_engine(tmp_path, mesh_devices=8):
    from deepspeed_tpu import initialize
    from deepspeed_tpu.runtime.mesh import build_mesh
    model, params = _model_factory()
    mesh = build_mesh({"pipe": 1, "data": mesh_devices, "model": 1})
    engine, _, _, _ = initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 3,
                "train_batch_size": 2 * 3 * mesh_devices,
                "steps_per_print": 10000,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        mesh=mesh)
    return engine


def test_wait_for_checkpoint_timeout_raises_and_abandon(tmp_path):
    engine = _tiny_engine(tmp_path)
    spec = BatchSpec(world=8, micro=2, gas=3, total=48)
    engine.train_batch(batch=_batch_fn(0, spec))

    real_write = engine._write_checkpoint
    release = {"t": 0.6}

    def slow_write(*a, **kw):
        time.sleep(release["t"])
        return real_write(*a, **kw)

    engine._write_checkpoint = slow_write
    assert engine.save_checkpoint(str(tmp_path), tag="slow",
                                  async_save=True)
    with pytest.raises(ckpt_io.CheckpointWaitTimeout) as ei:
        engine.wait_for_checkpoint(timeout=0.05)
    assert ei.value.pending == 1
    assert "abandon" in str(ei.value)
    # abandon detaches the writer; the engine can keep saving
    writer = engine._ckpt_writer
    assert engine.abandon_checkpoint_writers() == 1
    assert engine._ckpt_writer is None
    # the abandoned writer still commits its tag dir atomically, but
    # must NOT move `latest` — it may be racing a successor engine
    # that already committed newer tags
    writer.wait()
    assert os.path.isdir(tmp_path / "slow")
    assert ckpt_io.read_latest_tag(str(tmp_path)) is None
    # a post-abandon save gets a fresh writer that owns `latest` again
    assert engine.save_checkpoint(str(tmp_path), tag="fresh",
                                  async_save=True)
    engine.wait_for_checkpoint()
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "fresh"
    engine.shutdown()


def test_abandoned_writer_same_tag_save_skipped(tmp_path):
    """A save must refuse to reuse a tag whose staging dir a live
    ABANDONED writer job may still own (two writers in one `<tag>.tmp`
    would commit a torn checkpoint); once that job ends, the tag is
    free again."""
    engine = _tiny_engine(tmp_path)
    spec = BatchSpec(world=8, micro=2, gas=3, total=48)
    engine.train_batch(batch=_batch_fn(0, spec))
    real_write = engine._write_checkpoint

    def slow_write(*a, **kw):
        time.sleep(0.8)
        return real_write(*a, **kw)

    engine._write_checkpoint = slow_write
    assert engine.save_checkpoint(str(tmp_path), tag="t",
                                  async_save=True)
    with pytest.raises(ckpt_io.CheckpointWaitTimeout):
        engine.wait_for_checkpoint(timeout=0.05)
    writer = engine._ckpt_writer
    engine.abandon_checkpoint_writers()
    assert engine.save_checkpoint(str(tmp_path), tag="t",
                                  async_save=True) is False
    writer.wait()
    engine._write_checkpoint = real_write
    assert engine.save_checkpoint(str(tmp_path), tag="t",
                                  async_save=True)
    engine.wait_for_checkpoint()
    assert ckpt_io.read_latest_tag(str(tmp_path)) == "t"
    engine.shutdown()


def test_shutdown_abandons_hung_writer(tmp_path):
    engine = _tiny_engine(tmp_path)
    spec = BatchSpec(world=8, micro=2, gas=3, total=48)
    engine.train_batch(batch=_batch_fn(0, spec))
    real_write = engine._write_checkpoint

    def slow_write(*a, **kw):
        time.sleep(2.0)
        return real_write(*a, **kw)

    engine._write_checkpoint = slow_write
    engine.save_checkpoint(str(tmp_path), tag="hung", async_save=True)
    writer = engine._ckpt_writer
    t0 = time.monotonic()
    engine.shutdown(checkpoint_timeout=0.05)
    assert time.monotonic() - t0 < 1.5, "shutdown blocked on the writer"
    assert engine._ckpt_writer is None
    writer.wait()   # drain so the test leaves no stray thread


# ----------------------------------------------------------------------
# load retry/backoff + error taxonomy (satellite 2)
# ----------------------------------------------------------------------
def test_checkpoint_not_found_vs_staging_only(tmp_path):
    # nothing at all -> CheckpointNotFoundError, never retried (a
    # checkpoint that was never saved cannot appear by waiting)
    t0 = time.monotonic()
    with pytest.raises(ckpt_io.CheckpointNotFoundError):
        ckpt_io.load_checkpoint_flat(str(tmp_path), "never",
                                     retries=5, backoff_sec=0.2)
    assert time.monotonic() - t0 < 0.5
    # tag dir present but manifest missing (mp_rank mismatch /
    # corruption) -> also terminal NotFound, not a burned retry loop
    os.makedirs(tmp_path / "nomanifest")
    t0 = time.monotonic()
    with pytest.raises(ckpt_io.CheckpointNotFoundError,
                       match="manifest"):
        ckpt_io.load_checkpoint_flat(str(tmp_path), "nomanifest",
                                     retries=5, backoff_sec=0.2)
    assert time.monotonic() - t0 < 0.5
    # staging-only (interrupted save) -> distinct actionable error;
    # IS retried (a same-tag resave's two-rename commit window shows
    # the same signature transiently) but stays terminal once the
    # bounded retries exhaust
    os.makedirs(tmp_path / "broken.tmp")
    with pytest.raises(ckpt_io.CheckpointStagingOnlyError) as ei:
        ckpt_io.load_checkpoint_flat(str(tmp_path), "broken")
    assert "interrupted save" in str(ei.value)
    with pytest.raises(ckpt_io.CheckpointStagingOnlyError):
        ckpt_io.load_checkpoint_flat(str(tmp_path), "broken",
                                     retries=2, backoff_sec=0.01)
    # both are FileNotFoundError subclasses (back-compat)
    assert issubclass(ckpt_io.CheckpointNotFoundError, FileNotFoundError)
    assert issubclass(ckpt_io.CheckpointStagingOnlyError,
                      FileNotFoundError)


def test_retry_read_bounded_backoff():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert ckpt_io._retry_read(flaky, retries=3, backoff_sec=0.01,
                               describe="test") == "ok"
    assert calls["n"] == 3
    calls["n"] = 0
    with pytest.raises(OSError):
        ckpt_io._retry_read(flaky, retries=1, backoff_sec=0.01,
                            describe="test")


def test_read_latest_tag_retries(tmp_path, monkeypatch):
    ckpt_io.write_latest_tag(str(tmp_path), "tagA")
    real_open = open
    fails = {"n": 1}

    def flaky_open(path, *a, **kw):
        if str(path).endswith("latest") and fails["n"] > 0 and \
                "r" in (a[0] if a else kw.get("mode", "r")):
            fails["n"] -= 1
            raise OSError("transient NFS flutter")
        return real_open(path, *a, **kw)

    monkeypatch.setattr("builtins.open", flaky_open)
    assert ckpt_io.read_latest_tag(str(tmp_path), retries=2,
                                   backoff_sec=0.01) == "tagA"


# ----------------------------------------------------------------------
# watchdog escalation (satellite 3)
# ----------------------------------------------------------------------
def test_watchdog_escalates_exactly_once_per_episode():
    fired, escalated, emitted = [], [], []
    wd = StallWatchdog(timeout_sec=0.15, on_stall=fired.append,
                       poll_interval=0.03, escalate_after=2,
                       on_escalate=escalated.append,
                       emit=lambda kind, d: emitted.append(kind))
    try:
        wd.arm()
        deadline = time.time() + 5.0
        while len(escalated) < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert len(escalated) == 1, "no escalation"
        assert escalated[0]["consecutive_fires"] == 2
        assert escalated[0]["escalate_after"] == 2
        assert wd.stall_count >= 2
        # terminal: the episode goes quiet after escalating
        n_fired, n_esc = len(fired), wd.escalation_count
        time.sleep(0.5)
        assert len(fired) == n_fired and wd.escalation_count == n_esc
        assert emitted.count("stall_escalated") == 1
        # a fence re-arms: the next episode escalates again
        wd.notify_fence()
        deadline = time.time() + 5.0
        while wd.escalation_count < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert wd.escalation_count == 2
        assert emitted.count("stall_escalated") == 2
    finally:
        wd.stop()


def test_watchdog_default_fires_once_per_episode():
    """escalate_after=0 keeps the pre-existing contract: ONE fire per
    stall episode, no terminal event."""
    fired = []
    wd = StallWatchdog(timeout_sec=0.15, on_stall=fired.append,
                       poll_interval=0.03)
    try:
        wd.arm()
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.02)
        assert len(fired) == 1
        time.sleep(0.5)
        assert len(fired) == 1 and wd.escalation_count == 0
    finally:
        wd.stop()


def test_monitor_config_escalate_after():
    from deepspeed_tpu.monitor.config import (DeepSpeedMonitorConfig,
                                              MonitorConfigError)
    cfg = DeepSpeedMonitorConfig(
        {"monitor": {"enabled": True, "stall_timeout_sec": 5,
                     "stall_escalate_after": 3}})
    assert cfg.stall_escalate_after == 3
    assert DeepSpeedMonitorConfig({}).stall_escalate_after == 0
    with pytest.raises(MonitorConfigError):
        DeepSpeedMonitorConfig(
            {"monitor": {"stall_escalate_after": -1}})


# ----------------------------------------------------------------------
# supervisor end-to-end on the virtual mesh (in-process)
# ----------------------------------------------------------------------
def test_supervisor_lost_host_shrinks_resumes_and_regrows(tmp_path):
    inj = FaultInjector()
    sup = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                            save_dir=str(tmp_path / "ckpt"),
                            injector=inj)
    try:
        sup.run(3)
        assert sup.batch_spec == BatchSpec(world=8, micro=2, gas=3,
                                           total=48)
        plan8 = dict(sup.zero_plan)
        inj.mark_host_lost(3, reason="preemption")
        sup.run(8)
        # re-formed on the 6 survivors with the re-derived micro-batch
        assert sup.batch_spec == BatchSpec(world=6, micro=2, gas=4,
                                           total=48)
        assert len(sup.devices) == 6
        rec = [e for e in sup.events if e["kind"] == "recovery"][0]
        assert rec["cause"] == HOST_LOST and rec["lost_hosts"] == [3]
        assert rec["resumed_from_tag"] == "global_step2"
        assert rec["resumed_step"] == 2
        assert rec["replayed_steps"] == 1   # lost at step 3, ckpt at 2
        assert rec["detect_to_resume_sec"] < 30
        # the re-planned ZeRO state grows per-device when dp shrinks
        # (same total bytes over fewer devices)
        assert rec["zero_plan_bytes"]["opt_state"] > plan8["opt_state"]
        # loss continuity held across the replayed step (asserted
        # inside _note_loss; reaching here means it passed) and the
        # history is contiguous
        assert sorted(sup.loss_history) == list(range(8))
        # capacity returns -> grow at the NEXT checkpoint boundary
        inj.return_capacity(3)
        sup.run(12)
        assert sup.batch_spec.world == 8 and len(sup.devices) == 8
        up = [e for e in sup.events if e["kind"] == "scale_up"][0]
        assert up["world_before"] == 6 and up["world_after"] == 8
        assert up["resumed_step"] % 2 == 0   # boundary-aligned
        assert all(np.isfinite(v) for v in sup.loss_history.values())
    finally:
        sup.close()


def test_supervisor_slow_host_treated_as_lost(tmp_path):
    inj = FaultInjector()
    sup = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                            save_dir=str(tmp_path / "ckpt"),
                            injector=inj)
    try:
        sup.run(2)
        inj.mark_host_slow(0)
        sup.run(4)
        assert sup.batch_spec.world == 6
        rec = [e for e in sup.events if e["kind"] == "recovery"][0]
        assert rec["cause"] == HOST_SLOW and rec["lost_hosts"] == [0]
    finally:
        sup.close()


def test_supervisor_injected_stalls_escalate_to_inplace_recovery(
        tmp_path):
    inj = FaultInjector()
    sup = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                            save_dir=str(tmp_path / "ckpt"),
                            injector=inj)
    try:
        sup.run(4)
        # one transient stall: no recovery
        inj.inject_stall()
        sup.run(5)
        assert not sup.events
        # the stall vote PERSISTS across polls (slow-but-completing
        # steps must not launder a persistent stall): one more single
        # vote in a later poll reaches escalate_after=2 -> in-place
        # recovery
        inj.inject_stall()
        sup.run(8)
        rec = [e for e in sup.events if e["kind"] == "recovery"][0]
        assert rec["cause"] == STALL_ESCALATED
        assert rec["world_before"] == rec["world_after"] == 8
    finally:
        sup.close()


def test_supervisor_batch_fn_failure_recovers(tmp_path):
    """An input-pipeline exception recovers exactly like an engine
    failure instead of killing the supervised loop."""
    boom = {"at": 3}

    def flaky_batch_fn(step, spec):
        if step == boom["at"]:
            boom["at"] = -1   # only once
            raise OSError("data source hiccup")
        return _batch_fn(step, spec)

    sup = ElasticSupervisor(_ds_config(), _model_factory,
                            flaky_batch_fn,
                            save_dir=str(tmp_path / "ckpt"))
    try:
        sup.run(6)
        rec = [e for e in sup.events if e["kind"] == "recovery"][0]
        assert rec["cause"] == "engine_error"
        assert "hiccup" in rec["error"]
        assert sorted(sup.loss_history) == list(range(6))
    finally:
        sup.close()


def test_supervisor_lost_and_returned_in_one_poll(tmp_path):
    """A host reported lost AND returned in the same poll batch must
    first be dropped (recovery on the survivors) and then rejoin at
    the next checkpoint boundary — not be silently eaten."""
    inj = FaultInjector()
    sup = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                            save_dir=str(tmp_path / "ckpt"),
                            injector=inj)
    try:
        sup.run(3)
        inj.mark_host_lost(2)
        inj.return_capacity(2)
        sup.run(8)
        rec = [e for e in sup.events if e["kind"] == "recovery"][0]
        assert rec["cause"] == HOST_LOST and rec["world_after"] == 6
        ups = [e for e in sup.events if e["kind"] == "scale_up"]
        assert ups and ups[0]["world_after"] == 8
        assert sup.batch_spec.world == 8
    finally:
        sup.close()


def test_grow_deferred_until_boundary_save_commits(tmp_path):
    """A grow is voluntary: when the boundary save fails to commit,
    growing must be DEFERRED (not reload an older tag and discard
    work)."""
    inj = FaultInjector()
    sup = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                            save_dir=str(tmp_path / "ckpt"),
                            injector=inj)
    try:
        sup.run(2)
        inj.mark_host_lost(3)
        sup.run(4)
        assert sup.batch_spec.world == 6
        inj.return_capacity(3)
        # break the boundary save: _checkpoint swallows the error, so
        # latest stays at global_step4 and the grow must defer
        sup.engine.save_checkpoint = \
            lambda *a, **kw: (_ for _ in ()).throw(
                RuntimeError("disk full"))
        sup.run(6)
        assert sup.batch_spec.world == 6, \
            "grew despite an uncommitted boundary save"
        assert sup._pending_grow
        assert not [e for e in sup.events if e["kind"] == "scale_up"]
        # saving works again -> the next boundary grows
        del sup.engine.save_checkpoint
        sup.run(8)
        assert sup.batch_spec.world == 8
        up = [e for e in sup.events if e["kind"] == "scale_up"][0]
        assert up["resumed_from_tag"] == "global_step8"
        # no work was lost across the deferral
        assert sorted(sup.loss_history) == list(range(8))
    finally:
        sup.close()


def test_supervisor_restart_adopts_committed_progress(tmp_path):
    """A supervisor restart (the process-death recovery story) resumes
    from the save_dir's committed latest instead of step 0."""
    save = str(tmp_path / "ckpt")
    sup = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                            save_dir=save)
    sup.run(4)
    sup.close()
    sup2 = ElasticSupervisor(_ds_config(), _model_factory, _batch_fn,
                             save_dir=save)
    try:
        sup2.run(6)
        assert sorted(sup2.loss_history) == [4, 5]
        assert sup2.engine.global_steps == 6
    finally:
        sup2.close()


# ----------------------------------------------------------------------
# THE chaos test (subprocess isolation — the PR-8/9 precedent)
# ----------------------------------------------------------------------
CHAOS_SCRIPT = """
import json, os, sys, threading, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", {cache!r})
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))
assert len(jax.devices()) == 8, jax.devices()

from test_elastic_runtime import _batch_fn, _ds_config, _model_factory
from deepspeed_tpu.elasticity.runtime import (ElasticSupervisor,
                                              FaultInjector)
from deepspeed_tpu.runtime.mesh import reform_mesh

save_dir = {save_dir!r}
inj = FaultInjector()
for h in range(4):
    inj.spawn_host(h)

KILL_AT = 2     # SIGKILL mid-step-2: the last committed checkpoint is
END = 8         # global_step2, so the death is detected BEFORE the
                # next boundary and step 2 must be replayed


def batch_fn(step, spec):
    if step == KILL_AT and not inj.host_dead(1):
        # mid-step: the kill lands while this step's batch is being
        # staged/trained, like a real preemption
        threading.Timer(0.0, inj.sigkill_host, args=(1,)).start()
        inj.wait_host_dead(1)   # let the kernel reap the sentinel
    return _batch_fn(step, spec)


sup = ElasticSupervisor(_ds_config(), _model_factory, batch_fn,
                        save_dir=save_dir, injector=inj)
sup.run(END)
rec = [e for e in sup.events if e["kind"] == "recovery"][0]
post = {{s: sup.loss_history[s]
        for s in range(rec["resumed_step"], END)}}
report = sup.report()

# ---- clean restart from the SAME checkpoint on the SAME surviving
# mesh: the bit-identical oracle -------------------------------------
by_id = {{d.id: d for d in jax.devices()}}
devices = [by_id[i] for i in report["device_ids"]]
cfg2 = _ds_config()
cfg2["elasticity"]["runtime"]["hosts"] = 1
sup2 = ElasticSupervisor(cfg2, _model_factory, _batch_fn,
                         save_dir=save_dir, devices=devices)
sup2._build_engine(devices)
sup2.engine.load_checkpoint(save_dir, tag=rec["resumed_from_tag"])
assert int(sup2.engine.global_steps) == rec["resumed_step"]
clean = {{}}
for s in range(rec["resumed_step"], END):
    loss = sup2.engine.train_batch(batch=_batch_fn(s, sup2.batch_spec))
    clean[s] = float(jax.device_get(loss))
sup2.close()

# ---- scale-up: capacity returns, grow at the next boundary ---------
inj.return_capacity(1)
sup.run(END + 4)
grow_world = sup.batch_spec.world
ups = [e for e in sup.events if e["kind"] == "scale_up"]
sup.close()

print(json.dumps({{
    "recovery": rec,
    "post_resume_losses": post,
    "clean_restart_losses": clean,
    "clean_world": sup2.batch_spec.world,
    "grow_world": grow_world,
    "scale_ups": ups,
    "final_losses_finite": all(
        l == l for l in report["losses"].values()),
}}))
"""


MOE_CHAOS_SCRIPT = """
import json, os, sys, threading
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", {cache!r})
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))
assert len(jax.devices()) == 8, jax.devices()

from test_elastic_runtime import (_moe_batch_fn, _moe_ds_config,
                                  _moe_model_factory)
from deepspeed_tpu.elasticity.runtime import (ElasticSupervisor,
                                              FaultInjector)

save_dir = {save_dir!r}
inj = FaultInjector()
for h in range(4):
    inj.spawn_host(h)

KILL_AT = 2
END = 6


def batch_fn(step, spec):
    # kill TWO hosts mid-step: the 4 survivors re-form as data=2 x
    # expert=2 (XLA-CPU's emulated collectives are nondeterministically
    # unstable on the odd data=3 submesh a single-host loss would
    # produce under the expert axis — a backend artifact; the recovery
    # semantics under test are identical)
    if step == KILL_AT and not inj.host_dead(1):
        threading.Timer(0.0, inj.sigkill_host, args=(1,)).start()
        threading.Timer(0.0, inj.sigkill_host, args=(2,)).start()
        inj.wait_host_dead(1)
        inj.wait_host_dead(2)
    return _moe_batch_fn(step, spec)


sup = ElasticSupervisor(_moe_ds_config(), _moe_model_factory, batch_fn,
                        save_dir=save_dir, injector=inj)
sup.run(END)
rec = [e for e in sup.events if e["kind"] == "recovery"][0]
post = {{s: sup.loss_history[s]
        for s in range(rec["resumed_step"], END)}}
report = sup.report()
# the re-formed mesh kept the pinned expert axis; data absorbed the loss
mesh_shape = dict(sup.engine.mesh.shape)
moe_active = bool(sup.engine._moe_active)
zero_plan = sup.zero_plan
sup.close()

print(json.dumps({{
    "recovery": rec,
    "post_resume_losses": post,
    "device_ids": report["device_ids"],
    "mesh_shape": mesh_shape,
    "moe_active": moe_active,
    "zero_plan_nonzero": bool(zero_plan and zero_plan.get("params")),
    "spec": {{"world": sup.batch_spec.world,
             "micro": sup.batch_spec.micro,
             "gas": sup.batch_spec.gas,
             "total": sup.batch_spec.total}},
}}))
"""

# the clean-restart oracle runs in its OWN subprocess: a third engine
# build in the chaos process (8-dev supervisor engine -> 6-dev
# recovered engine -> 6-dev oracle engine) trips nondeterministic
# native-memory corruption in XLA-CPU's emulated collectives with the
# 4-axis mesh's all-to-alls — a backend artifact, not recovery
# semantics; the oracle's own process builds exactly one engine, the
# shape every manual repro of it is stable in
MOE_CHAOS_CLEAN_SCRIPT = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", {cache!r})
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))
assert len(jax.devices()) == 8, jax.devices()

from test_elastic_runtime import (_moe_batch_fn, _moe_ds_config,
                                  _moe_model_factory)
import deepspeed_tpu
from deepspeed_tpu.elasticity.runtime import BatchSpec
from deepspeed_tpu.runtime.mesh import reform_mesh

save_dir = {save_dir!r}
rec = json.loads({rec_json!r})
sp = json.loads({spec_json!r})
spec = BatchSpec(world=sp["world"], micro=sp["micro"],
                 gas=sp["gas"], total=sp["total"])
by_id = {{d.id: d for d in jax.devices()}}
devices = [by_id[i] for i in {device_ids!r}]
# plain engine, NOT a second supervisor: the oracle only needs the
# same mesh + batches + checkpoint — and the supervisor scaffolding
# (watchdog/teardown machinery) is part of what perturbs XLA-CPU's
# fragile emulated-collective runtime this test already retries over
mesh = reform_mesh(devices, {{"expert": 2}})
cfg2 = _moe_ds_config()
cfg2.pop("elasticity", None)
cfg2.pop("mesh", None)
cfg2["train_batch_size"] = spec.total
cfg2["train_micro_batch_size_per_gpu"] = spec.micro
cfg2["gradient_accumulation_steps"] = spec.gas
model, params = _moe_model_factory()
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=params, config=cfg2, mesh=mesh)
engine.load_checkpoint(save_dir, tag=rec["resumed_from_tag"])
assert int(engine.global_steps) == rec["resumed_step"]
clean = {{}}
for s in range(rec["resumed_step"], {end}):
    loss = engine.train_batch(batch=_moe_batch_fn(s, spec))
    clean[s] = float(jax.device_get(loss))
clean_mesh = dict(engine.mesh.shape)

print(json.dumps({{"clean_restart_losses": clean,
                  "clean_mesh": clean_mesh}}))
"""


def _moe_model_factory():
    from deepspeed_tpu.moe import MoEConfig
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM
    import jax as _jax
    import jax.numpy as _jnp
    moe = MoEConfig(num_experts=4, top_k=2, capacity_factor=1.5,
                    every_n_layers=2).validate()
    cfg = GPT2Config(n_layer=2, n_head=2, n_embd=16, n_positions=16,
                     vocab_size=64, dropout=0.0, moe=moe,
                     dtype=_jnp.float32, param_dtype=_jnp.float32)
    model = GPT2ForCausalLM(cfg)
    params = model.module.init(
        _jax.random.PRNGKey(0),
        _jnp.zeros((4, 8), _jnp.int32), True)["params"]
    return model, params


def _moe_batch_fn(step, spec):
    rng = np.random.RandomState(2000 + step)
    ids = rng.randint(0, 64, size=(spec.gas, spec.rows, 8))
    return {"input_ids": ids.astype(np.int32)}


def _moe_ds_config():
    return {
        "steps_per_print": 10000,
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"expert": 2},
        "moe": {"enabled": True, "num_experts": 4, "top_k": 2,
                "capacity_factor": 1.5, "every_n_layers": 2},
        # inline saves: XLA-CPU's emulated collectives corrupt native
        # memory when the async snapshot thread's device_get races the
        # 4-axis mesh's all-to-all steps (a CPU-backend concurrency
        # artifact — bisected sync-save-fixes-it; dense 3-axis chaos
        # runs async saves fine). Real TPU runtimes don't share the
        # emulation path; the chaos contract here is the recovery
        # semantics, not the writer overlap.
        "checkpoint": {"async_save": False},
        "elasticity": {"enabled": True, "max_train_batch_size": 48,
                       "micro_batch_sizes": [2], "version": 0.1,
                       "runtime": {"enabled": True, "hosts": 4,
                                   "checkpoint_interval": 2,
                                   "drain_timeout_sec": 5.0,
                                   "escalate_after": 2}},
    }


@pytest.mark.slow
def test_moe_chaos_sigkill_bit_identical_resume(tmp_path):
    """The MoE twin of the chaos test (ISSUE 15 satellite): SIGKILL
    hosts mid-step under an EXPERT-PARALLEL run — the mesh re-forms
    on the survivors KEEPING the pinned expert axis (data absorbs the
    loss: 4x2 -> 2x2), expert state re-plans and reloads from the
    last committed checkpoint, and the post-resume loss trajectory is
    BIT-IDENTICAL to a clean engine restarted from that same
    checkpoint on the same surviving mesh (its own subprocess — see
    MOE_CHAOS_CLEAN_SCRIPT)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"])

    # Private per-attempt compile cache + bounded retries: XLA-CPU's
    # emulated collectives NONDETERMINISTICALLY corrupt native memory
    # under the 4-axis mesh's all-to-all programs (glibc heap aborts /
    # SIGSEGV; bisected — the dense 3-axis chaos twin never trips it),
    # and a corrupted process can poison a SHARED persistent compile
    # cache for every later run. Each attempt gets a fresh cache under
    # tmp_path; a REAL recovery-semantics regression fails all
    # attempts deterministically.
    attempts = 3
    out = None
    for attempt in range(attempts):
        cache = str(tmp_path / f"jax_cache_{attempt}")
        save_dir = str(tmp_path / f"ckpt_{attempt}")
        script = MOE_CHAOS_SCRIPT.format(repo=REPO, cache=cache,
                                         save_dir=save_dir)
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True,
                              timeout=420)
        if proc.returncode != 0:
            assert attempt < attempts - 1, proc.stderr[-3000:]
            continue
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        # the oracle gets its OWN cache: phase 1's process can be
        # internally corrupted by the emulated-collective bug and
        # serialize poisoned executables the oracle would then replay
        clean_script = MOE_CHAOS_CLEAN_SCRIPT.format(
            repo=REPO, cache=str(tmp_path / f"jax_cache_{attempt}b"),
            save_dir=save_dir,
            rec_json=json.dumps(out["recovery"]),
            spec_json=json.dumps(out["spec"]),
            device_ids=out["device_ids"], end=6)
        proc2 = subprocess.run([sys.executable, "-c", clean_script],
                               env=env, capture_output=True,
                               text=True, timeout=420)
        if proc2.returncode != 0:
            out = None
            assert attempt < attempts - 1, proc2.stderr[-3000:]
            continue
        out.update(json.loads(proc2.stdout.strip().splitlines()[-1]))
        break
    assert out is not None

    rec = out["recovery"]
    assert rec["cause"] == "host_lost"
    assert sorted(rec["lost_hosts"]) == [1, 2]
    assert rec["world_before"] == 8 and rec["world_after"] == 4
    assert rec["resumed_step"] == 2
    # the pinned expert axis survived; data absorbed the host loss
    # (4x2 -> 2x2)
    assert out["mesh_shape"]["expert"] == 2
    assert out["mesh_shape"]["data"] == 2
    assert out["clean_mesh"] == out["mesh_shape"]
    assert out["moe_active"] is True
    # expert state re-planned (the ZeRO plan priced the new world)
    assert out["zero_plan_nonzero"]
    # THE contract: post-resume losses == clean-restart losses, bitwise
    post = out["post_resume_losses"]
    clean = out["clean_restart_losses"]
    assert set(post) == set(clean) and len(post) >= 3
    for step in sorted(post):
        assert post[step] == clean[step], (
            step, post[step], clean[step],
            "MoE post-resume trajectory diverged from a clean restart")


def test_chaos_sigkill_bit_identical_resume(tmp_path):
    """SIGKILL a worker host mid-step: the supervisor must detect it,
    re-form the mesh on the 6 survivors with a re-planned ZeRO
    partition, resume from the last committed checkpoint with a loss
    trajectory BIT-IDENTICAL to a clean restart from that same
    checkpoint, and grow back to 8 devices when capacity returns."""
    cache = os.path.abspath(os.environ.get(
        "JAX_TEST_COMPILATION_CACHE",
        os.path.join(REPO, ".jax_test_cache")))
    script = CHAOS_SCRIPT.format(repo=REPO, cache=cache,
                                 save_dir=str(tmp_path / "ckpt"))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"])
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])

    rec = out["recovery"]
    assert rec["cause"] == "host_lost" and rec["lost_hosts"] == [1]
    assert rec["world_before"] == 8 and rec["world_after"] == 6
    assert rec["resumed_from_tag"] == "global_step2"
    assert rec["resumed_step"] == 2
    # recovery is seconds, not minutes (detect -> engine resumed)
    assert rec["detect_to_resume_sec"] < 60
    assert out["clean_world"] == 6
    # THE contract: post-resume losses == clean-restart losses, bitwise
    post = out["post_resume_losses"]
    clean = out["clean_restart_losses"]
    assert set(post) == set(clean) and len(post) >= 4
    for step in sorted(post):
        assert post[step] == clean[step], (
            step, post[step], clean[step],
            "post-resume trajectory diverged from a clean restart")
    # scale-up restored the original device count at a boundary
    assert out["grow_world"] == 8
    assert out["scale_ups"] and \
        out["scale_ups"][0]["world_after"] == 8
    assert out["final_losses_finite"]
