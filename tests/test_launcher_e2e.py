"""Launcher end-to-end smoke (parity target: ref
`tests/unit/common.py:16-104`, which actually forks distributed
workers): `dstpu` really spawns a training child, and the per-node
launcher really stands up a 2-process `jax.distributed` rendezvous on
the CPU backend with rank env + cross-rank loss agreement.

These spawn subprocesses and pay JAX startup each time -> slow tier.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TRAIN_SCRIPT = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, __REPO__)
    import deepspeed_tpu           # applies DS_TPU_PLATFORM before jax use
    import jax, numpy as np

    dist = os.environ.get("WORLD_SIZE") is not None
    if dist:
        deepspeed_tpu.init_distributed()
    import jax.numpy as jnp
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.tanh(nn.Dense(16)(x)))

    class Model:
        def __init__(self):
            self.net = Net()
            x = np.zeros((4, 8), np.float32)
            self.params = self.net.init(jax.random.PRNGKey(0), x)["params"]
        def loss_fn(self, params, batch, rngs=None, deterministic=False):
            y = self.net.apply({"params": params}, batch["x"])
            return jnp.mean((y - batch["y"]) ** 2)

    m = Model()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m, model_parameters=m.params,
        config={"train_micro_batch_size_per_gpu":
                    8 // max(1, jax.device_count()),
                "gradient_accumulation_steps": 1,
                "steps_per_print": 1000,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-2}}})
    rng = np.random.RandomState(0)
    x = rng.randn(1, 8, 8).astype(np.float32)
    w = np.linspace(-1, 1, 32).reshape(8, 4).astype(np.float32)
    batch = {"x": x, "y": x @ w}
    for i in range(10):
        loss = engine.train_batch(batch=batch)
    print("SMOKE_RESULT:" + json.dumps({
        "rank": os.environ.get("RANK"),
        "world": os.environ.get("WORLD_SIZE"),
        "n_devices": jax.device_count(),
        "loss": round(float(jax.device_get(loss)), 6)}), flush=True)
""")


def _write_script(tmp_path):
    p = tmp_path / "smoke_train.py"
    p.write_text(_TRAIN_SCRIPT.replace("__REPO__", repr(REPO)))
    return str(p)


def _base_env():
    env = dict(os.environ)
    env["DS_TPU_PLATFORM"] = "cpu"
    env.pop("XLA_FLAGS", None)   # 1 real CPU device per process
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   env.get("JAX_TEST_COMPILATION_CACHE",
                           os.path.join(REPO, ".jax_test_cache")))
    return env


def _parse(stdout):
    import json
    for line in stdout.splitlines():
        if line.startswith("SMOKE_RESULT:"):
            return json.loads(line[len("SMOKE_RESULT:"):])
    return None


@pytest.mark.slow
def test_dstpu_spawns_single_node_training(tmp_path):
    """`bin/dstpu script.py` must actually spawn and run the child."""
    script = _write_script(tmp_path)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dstpu"), script],
        capture_output=True, text=True, timeout=600, env=_base_env(),
        cwd=REPO)
    res = _parse(proc.stdout)
    assert proc.returncode == 0 and res, \
        (proc.returncode, proc.stdout[-800:], proc.stderr[-800:])
    assert res["loss"] < 0.5, res


@pytest.mark.slow
def test_launch_two_process_jax_distributed(tmp_path):
    """Two per-node launcher processes rendezvous via jax.distributed
    (CPU backend): both ranks see the 2-device global mesh, train the
    same 10 steps, and report identical losses."""
    from deepspeed_tpu.launcher.runner import encode_world_info
    script = _write_script(tmp_path)
    world = encode_world_info({"nodeA": [0], "nodeB": [0]})
    # free port (a hardcoded one collides across concurrent runs)
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(2):
        env = _base_env()
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
             "--world_info", world, "--node_rank", str(rank),
             "--master_addr", "127.0.0.1", "--master_port", str(port),
             script],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    results = [_parse(o[1]) for o in outs]
    assert all(o[0] == 0 for o in outs) and all(results), \
        [(o[0], o[1][-400:], o[2][-600:]) for o in outs]
    ranks = sorted(r["rank"] for r in results)
    assert ranks == ["0", "1"], results
    assert all(r["world"] == "2" for r in results), results
    assert all(r["n_devices"] == 2 for r in results), results
    # same global data + same program -> identical loss on every rank
    assert abs(results[0]["loss"] - results[1]["loss"]) < 1e-6, results
    assert results[0]["loss"] < 0.5, results


_ZERO2_SCRIPT = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, __REPO__)
    import deepspeed_tpu
    import jax, numpy as np

    if os.environ.get("WORLD_SIZE") is not None and \\
            int(os.environ["WORLD_SIZE"]) > 1:
        deepspeed_tpu.init_distributed()
    import jax.numpy as jnp
    import flax.linen as nn

    CKPT = os.environ["DS_TEST_CKPT_DIR"]
    PHASE = os.environ["DS_TEST_PHASE"]

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(nn.tanh(nn.Dense(32)(x)))

    class Model:
        def __init__(self):
            self.net = Net()
            x = np.zeros((8, 8), np.float32)
            self.params = self.net.init(jax.random.PRNGKey(0), x)["params"]
        def loss_fn(self, params, batch, rngs=None, deterministic=False):
            y = self.net.apply({"params": params}, batch["x"])
            return jnp.mean((y - batch["y"]) ** 2)

    m = Model()
    n_dev = jax.device_count()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=m, model_parameters=m.params,
        config={"train_micro_batch_size_per_gpu": 16 // n_dev,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 1000,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 2e-2}}})
    rng = np.random.RandomState(0)
    x = rng.randn(1, 16, 8).astype(np.float32)
    w = np.linspace(-1, 1, 64).reshape(8, 8).astype(np.float32)
    batch = {"x": x, "y": x @ w}

    if PHASE == "train_save":
        for i in range(5):
            engine.train_batch(batch=batch)
        engine.save_checkpoint(CKPT, tag="ms")
        engine.wait_for_checkpoint()
        # module_state_dict fetches non-fully-addressable arrays via
        # process_allgather (engine._fetch_to_host) — checksum must
        # agree across ranks
        sd = engine.module_state_dict()
        checksum = float(sum(np.abs(np.asarray(l)).sum()
                             for l in jax.tree_util.tree_leaves(sd)))
        loss_next = float(jax.device_get(
            engine.train_batch(batch=batch)))
    else:
        engine.load_checkpoint(CKPT, tag="ms")
        checksum = 0.0
        loss_next = float(jax.device_get(
            engine.train_batch(batch=batch)))

    print("SMOKE_RESULT:" + json.dumps({
        "rank": os.environ.get("RANK", "0"),
        "n_devices": n_dev,
        "checksum": round(checksum, 6),
        "loss_next": round(loss_next, 8)}), flush=True)
""")


@pytest.mark.slow
def test_multiprocess_zero2_checkpoint_respawn(tmp_path):
    """VERDICT r3 #5: 2 processes x 4 CPU devices each run a ZeRO-2
    engine (moments sharded over the 8-device data axis spanning both
    processes), train, save a checkpoint where each process writes
    only its addressable shards, and a DIFFERENT process split (1
    process x 8 devices) reloads it and continues — losses must agree.
    Also executes engine._fetch_to_host's process_allgather
    (module_state_dict on non-fully-addressable arrays)."""
    from deepspeed_tpu.launcher.runner import encode_world_info
    import socket
    script = tmp_path / "zero2_train.py"
    script.write_text(_ZERO2_SCRIPT.replace("__REPO__", repr(REPO)))
    ckpt_dir = tmp_path / "ckpt"

    world = encode_world_info({"nodeA": [0], "nodeB": [0]})
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(2):
        env = _base_env()
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["DS_TEST_CKPT_DIR"] = str(ckpt_dir)
        env["DS_TEST_PHASE"] = "train_save"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
             "--world_info", world, "--node_rank", str(rank),
             "--master_addr", "127.0.0.1", "--master_port", str(port),
             str(script)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=REPO))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    results = [_parse(o[1]) for o in outs]
    assert all(o[0] == 0 for o in outs) and all(results), \
        [(o[0], o[1][-400:], o[2][-800:]) for o in outs]
    assert all(r["n_devices"] == 8 for r in results), results
    # process_allgather produced the same full tree on both ranks
    assert results[0]["checksum"] == results[1]["checksum"], results
    # both ranks agree on the post-checkpoint loss
    assert abs(results[0]["loss_next"] - results[1]["loss_next"]) < 1e-7

    # each process wrote only its addressable shards: with 8 dp
    # ordinals split 4/4, optimizer shard buckets must exist for all 8
    import glob as _glob
    buckets = _glob.glob(str(ckpt_dir / "ms" / "zero_pp_rank_*optim*.npz"))
    assert len(buckets) == 8, sorted(os.path.basename(b) for b in buckets)

    # phase 2: different split (1 process x 8 devices) reloads
    env = _base_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["DS_TEST_CKPT_DIR"] = str(ckpt_dir)
    env["DS_TEST_PHASE"] = "load"
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, timeout=600,
                          env=env, cwd=REPO)
    res = _parse(proc.stdout)
    assert proc.returncode == 0 and res, \
        (proc.returncode, proc.stdout[-400:], proc.stderr[-800:])
    assert res["n_devices"] == 8
    # the reloaded engine's next-step loss matches the saved run's
    assert abs(res["loss_next"] - results[0]["loss_next"]) < 1e-5, \
        (res, results[0])
