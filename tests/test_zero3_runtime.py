"""ZeRO-3 overlapped runtime (ISSUE 9): the explicit gather/release
scheduler (`runtime/zero/stage3.py`) — layer-granular all-gather
prefetched ahead of use, release after fwd/bwd use, reduce-scatter of
gradients into the owning data-axis shard.

What these tests pin:
  * the scheduled apply path computes the SAME function as the plain
    module path — bit-exact loss on identical sharded inputs, grads to
    float roundoff — for GPT-2 and BERT, across prefetch_layers
    settings and the naive up-front baseline;
  * a stage-3 engine's 10-step fp32 training trajectory matches a
    stage-2 engine's (same data, same init) to float roundoff;
  * stage-3 sharded checkpoints round-trip, including reload at a
    DIFFERENT prefetch_layers (the schedule is a trace-time choice,
    not state);
  * the hot loop stays sync-free with the scheduler on (the
    async-dispatch guard, re-run over the scheduled step);
  * the memory ledger's zero3_gather entry obeys the
    (prefetch_layers + 1)-layer bound, and the naive mode records the
    whole stack;
  * the sequential PipelineModule chain and the ZeRO-Offload
    compressed wire compose with the scheduler;
  * config validation raises ValueError carrying the offending value.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config
from deepspeed_tpu.runtime.mesh import build_mesh
from deepspeed_tpu.runtime.zero.partition import ZeroShardingPolicy
from deepspeed_tpu.runtime.zero.stage3 import (Zero3GatherScheduler,
                                               resolve_gather_dtype)


def _mesh():
    return build_mesh({"pipe": 1, "data": len(jax.devices()), "model": 1})


def _gpt2_batch(seed, rows=8, t=32, vocab=256, stacked=False):
    ids = np.random.default_rng(seed).integers(
        0, vocab, (rows, t)).astype(np.int32)
    return {"input_ids": ids[None] if stacked else ids}


def _engine_config(stage, stage3=None, **over):
    zo = {"stage": stage}
    if stage3 is not None:
        zo["stage3"] = stage3
    cfg = {"train_micro_batch_size_per_gpu": 8,
           "gradient_accumulation_steps": 1,
           "steps_per_print": 10000,
           "zero_optimization": zo,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}
    cfg.update(over)
    return cfg


def _build_gpt2_engine(stage, stage3=None, n_layer=4, **over):
    model = GPT2ForCausalLM(tiny_gpt2_config(n_layer=n_layer))
    params = model.init(jax.random.PRNGKey(0), _gpt2_batch(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=_engine_config(stage, stage3, **over))
    return engine, model


def _run(engine, steps, t=32):
    losses = []
    for i in range(steps):
        loss = engine.train_batch(batch=_gpt2_batch(i, t=t, stacked=True))
        losses.append(float(jax.device_get(loss)))
    return np.asarray(losses)


# ----------------------------------------------------------------------
# scheduled path == module path (fixed sharding, strongest invariant)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("prefetch,release", [(1, True), (0, True),
                                              (3, True), (1, False)])
def test_gpt2_scheduled_path_matches_module_path(prefetch, release):
    """Same sharded params + batch through the module path and the
    scheduled path: loss is BIT-EXACT, grads agree to float roundoff
    (the per-layer vjp + reduce-scatter accumulation is a different —
    equally valid — summation program)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = _mesh()
    model = GPT2ForCausalLM(tiny_gpt2_config(n_layer=4))
    batch = _gpt2_batch(7)
    params = model.init(jax.random.PRNGKey(0), batch)
    params = jax.device_put(
        params, ZeroShardingPolicy(mesh, 3).param_shardings(params))
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, PartitionSpec("data", None))), batch)

    def loss(p, b):
        return model.loss_fn(p, b, rngs=None, deterministic=True)

    l0, g0 = jax.jit(jax.value_and_grad(loss))(params, batch)
    model.bind_zero3_scheduler(Zero3GatherScheduler(
        mesh, prefetch_layers=prefetch, release_after_use=release))
    l1, g1 = jax.jit(jax.value_and_grad(loss))(params, batch)
    model.bind_zero3_scheduler(None)

    assert np.array_equal(np.asarray(l0), np.asarray(l1)), (l0, l1)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=2e-6)


def test_bert_scheduled_path_matches_module_path():
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from deepspeed_tpu.models.bert import (BertForPreTrainingLM,
                                           tiny_bert_config)
    mesh = _mesh()
    model = BertForPreTrainingLM(tiny_bert_config(num_hidden_layers=3))
    rng = np.random.default_rng(3)
    batch = {"input_ids": rng.integers(0, 256, (8, 32)).astype(np.int32),
             "attention_mask": np.ones((8, 32), np.int32),
             "masked_lm_labels": rng.integers(
                 0, 256, (8, 32)).astype(np.int32),
             "next_sentence_label": rng.integers(
                 0, 2, (8,)).astype(np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch)
    params = jax.device_put(
        params, ZeroShardingPolicy(mesh, 3).param_shardings(params))
    batch = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(
            mesh, PartitionSpec(*(["data"] + [None] * (x.ndim - 1))))),
        batch)

    def loss(p, b):
        return model.loss_fn(p, b, rngs=None, deterministic=True)

    l0, g0 = jax.jit(jax.value_and_grad(loss))(params, batch)
    model.bind_zero3_scheduler(Zero3GatherScheduler(mesh))
    l1, g1 = jax.jit(jax.value_and_grad(loss))(params, batch)
    model.bind_zero3_scheduler(None)
    assert np.array_equal(np.asarray(l0), np.asarray(l1)), (l0, l1)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=5e-6)


# ----------------------------------------------------------------------
# stage 3 vs stage 2: fp32 10-step training trajectory
# ----------------------------------------------------------------------
def test_stage3_vs_stage2_fp32_loss_parity_10_steps():
    """The satellite acceptance run: an fp32 stage-3 engine (scheduled
    gathers, reduce-scattered grads, sharded params) tracks an fp32
    stage-2 engine bit-for-bit up to float roundoff over 10 optimizer
    steps on the same data. The two engines compile DIFFERENT XLA
    programs whose cross-shard reduction orders differ, so the bound
    is float-roundoff-tight (measured ~5e-7 absolute on a ~5.5 loss),
    not literal bit equality."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    e2, _ = _build_gpt2_engine(2)
    e3, _ = _build_gpt2_engine(3)
    assert e3.zero3_scheduler is not None, \
        "stage-3 engine did not weave the gather scheduler"
    assert e2.zero3_scheduler is None
    l2 = _run(e2, 10)
    l3 = _run(e3, 10)
    np.testing.assert_allclose(l3, l2, rtol=0, atol=5e-6)
    # and training actually progressed identically enough to converge
    # together: final params agree to roundoff
    for a, b in zip(jax.tree_util.tree_leaves(e2.fp32_params),
                    jax.tree_util.tree_leaves(e3.fp32_params)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)),
                                   rtol=0, atol=1e-5)


# ----------------------------------------------------------------------
# sharded checkpoint round-trip (incl. different prefetch_layers)
# ----------------------------------------------------------------------
_ROUNDTRIP_CHILD = r"""
import jax, numpy as np, sys, tempfile
import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config


def build(stage3=None):
    model = GPT2ForCausalLM(tiny_gpt2_config(n_layer=4))
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((8, 32), np.int32)})
    zo = {"stage": 3}
    if stage3:
        zo["stage3"] = stage3
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 10000,
                "zero_optimization": zo,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    return engine


def batch(i):
    ids = np.random.default_rng(i).integers(
        0, 256, (1, 8, 32)).astype(np.int32)
    return {"input_ids": ids}


def run(engine, rng):
    return [float(jax.device_get(engine.train_batch(batch=batch(i))))
            for i in rng]


ref_losses = np.asarray(run(build(), range(6)))
ckpt_dir = tempfile.mkdtemp(prefix="zero3_roundtrip_")
e_a = build()
run(e_a, range(3))
e_a.save_checkpoint(ckpt_dir, tag="s3")
e_a.wait_for_checkpoint()

for stage3 in ({"prefetch_layers": 2}, {"release_after_use": False}):
    e_b = build(stage3)
    assert e_b.zero3_scheduler is not None
    e_b.load_checkpoint(ckpt_dir, tag="s3")
    for a, b in zip(jax.tree_util.tree_leaves(e_a.state.params),
                    jax.tree_util.tree_leaves(e_b.state.params)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))
    resumed = np.asarray(run(e_b, range(3, 6)))
    np.testing.assert_allclose(resumed, ref_losses[3:], rtol=0,
                               atol=5e-6, err_msg=str(stage3))
print("ROUNDTRIP_OK")
"""


def test_stage3_checkpoint_roundtrip_across_prefetch_layers():
    """Save a stage-3 engine mid-training, reload into a fresh stage-3
    engine configured with a DIFFERENT prefetch_layers (and once into
    the naive up-front mode): the schedule is a trace-time choice, so
    restored state must be bit-identical and training must continue on
    the same trajectory as the uninterrupted run.

    Runs in a SUBPROCESS with the persistent compilation cache off:
    this is the one sequence that compiles new donated-buffer programs
    AFTER a checkpoint load, and in-process it reads whatever heap
    damage the suite's persistent-cache writes left behind — a
    pre-existing jaxlib landmine (glibc "corrupted size vs. prev_size"
    -> segfault/NaN, reproduced on the UNMODIFIED pre-PR tree with a
    plain stage-2 save/load/resume). A fresh process with no cache is
    deterministic every run (the memory-ledger OOM test precedent for
    subprocess isolation)."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"])
    proc = subprocess.run(
        [sys.executable, "-c", _ROUNDTRIP_CHILD], env=env,
        capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "ROUNDTRIP_OK" in proc.stdout, proc.stdout[-1000:]


# ----------------------------------------------------------------------
# sync-free hot loop guard (the async-dispatch acceptance, scheduled)
# ----------------------------------------------------------------------
class _SyncCounters:
    """Counts host<->device rendezvous a step loop must not use
    (`jax.device_get`, `jax.effects_barrier`) — the async-dispatch
    guard pattern, pointed at the scheduled stage-3 step."""

    def __init__(self, monkeypatch):
        self.device_get = 0
        self.effects_barrier = 0
        real_get, real_barrier = jax.device_get, jax.effects_barrier

        def counting_get(*a, **k):
            self.device_get += 1
            return real_get(*a, **k)

        def counting_barrier(*a, **k):
            self.effects_barrier += 1
            return real_barrier(*a, **k)

        monkeypatch.setattr(jax, "device_get", counting_get)
        monkeypatch.setattr(jax, "effects_barrier", counting_barrier)


def test_stage3_hot_loop_has_zero_host_syncs(monkeypatch):
    """With the gather scheduler ON, N train_batch steps after warmup
    perform ZERO jax.device_get / jax.effects_barrier calls: the whole
    gather/prefetch/release/reduce-scatter schedule is compiled into
    the step, never coordinated from the host."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    engine, _ = _build_gpt2_engine(
        3, **{"bf16": {"enabled": True},
              "async_dispatch": {"enabled": True}})
    assert engine.zero3_scheduler is not None
    batches = [engine.stage_batch(_gpt2_batch(i, stacked=True))
               for i in range(8)]
    for b in batches[:3]:
        engine.train_batch(batch=b)
    counters = _SyncCounters(monkeypatch)
    for b in batches[3:]:
        engine.train_batch(batch=b)
    assert counters.device_get == 0, \
        f"scheduled stage-3 hot path called jax.device_get " \
        f"{counters.device_get}x"
    assert counters.effects_barrier == 0
    assert np.isfinite(float(jax.device_get(engine.losses)))


# ----------------------------------------------------------------------
# memory-ledger window bound
# ----------------------------------------------------------------------
@pytest.mark.parametrize("prefetch", [0, 1, 2])
def test_ledger_window_bytes_bound(prefetch):
    """zero3_gather in the ledger == gathered embeddings + exactly
    (prefetch_layers + 1) layers' full params — the live-bytes bound
    the tentpole claims. The expectation is computed INDEPENDENTLY
    from the raw param tree, not the scheduler's own bookkeeping."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    n_layer = 4
    engine, _ = _build_gpt2_engine(
        3, stage3={"prefetch_layers": prefetch}, n_layer=n_layer)
    _run(engine, 1)
    sched = engine.zero3_scheduler
    info = sched.stack_info["h"]
    window = min(prefetch, n_layer - 1) + 1
    assert info["window_layers"] == window

    def full_bytes(tree):
        return sum(int(np.prod(l.shape)) * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(tree))

    (_, stacked), = engine.state.params["h"].items()
    per_layer = full_bytes(stacked) // n_layer
    extras = sum(full_bytes(engine.state.params[k])
                 for k in ("wte", "wpe", "ln_f"))
    cats = engine.monitor.ledger.totals()["hbm"]
    assert cats["zero3_gather"] == per_layer * window + extras
    # the bound: window <= (prefetch + 1) layers' worth
    assert per_layer * window <= per_layer * (prefetch + 1)


def test_ledger_naive_mode_records_whole_stack():
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    n_layer = 4
    engine, _ = _build_gpt2_engine(
        3, stage3={"release_after_use": False}, n_layer=n_layer)
    _run(engine, 1)
    info = engine.zero3_scheduler.stack_info["h"]
    assert info["window_layers"] == n_layer


def test_oom_hints_name_prefetch_layers():
    from deepspeed_tpu.monitor.memory import oom_hints
    payload = {"hbm": {
        "categories": {"zero3_gather": 8 << 30, "params": 1 << 30},
        "ledger_bytes": 9 << 30,
        "measured_in_use_per_device": 10 << 30,
        "residual_bytes": 1 << 30}}
    hints = "\n".join(oom_hints(payload))
    assert "stage3.prefetch_layers" in hints


# ----------------------------------------------------------------------
# PipelineModule sequential chain
# ----------------------------------------------------------------------
def test_pipe_sequential_chain_stage3_parity():
    """The unrolled chained-loss path (pipe=1 PipelineModule): layer
    gathers fence on the activation prefetch_layers back, grads
    reduce-scatter through the gather's VJP — trajectory matches
    stage 2 to roundoff."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    import flax.linen as nn
    from deepspeed_tpu.runtime.pipe.module import (LayerSpec,
                                                   PipelineModule)

    class Mid(nn.Module):
        feats: int = 16

        @nn.compact
        def __call__(self, x):
            return nn.tanh(nn.Dense(self.feats)(x))

    mod = PipelineModule(
        layers=[LayerSpec(Mid) for _ in range(4)], num_stages=1,
        loss_fn=lambda x, y: jnp.mean((x - y) ** 2))
    params = mod.init_params(jax.random.PRNGKey(0),
                             np.zeros((2, 16), np.float32))

    def build(stage):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=mod,
            model_parameters=jax.tree_util.tree_map(np.copy, params),
            config=_engine_config(
                stage, gradient_accumulation_steps=2))
        return engine

    def run(engine):
        out = []
        for i in range(5):
            r = np.random.default_rng(i)
            x = r.standard_normal((16, 16)).astype(np.float32)
            out.append(float(jax.device_get(
                engine.train_batch(batch=(x, np.roll(x, 1, 1))))))
        return np.asarray(out)

    e3 = build(3)
    assert e3.zero3_scheduler is not None
    l3 = run(e3)
    l2 = run(build(2))
    np.testing.assert_allclose(l3, l2, rtol=0, atol=5e-6)
    info = e3.zero3_scheduler.stack_info["pipe_chain"]
    assert info["layers"] == 4 and info["window_layers"] == 2


# ----------------------------------------------------------------------
# ZeRO-Offload compressed-wire composition
# ----------------------------------------------------------------------
def test_stage3_composes_with_offload_compressed_wire():
    """stage 3 + cpu_offload + the PR-1 int8 wire: sharded compute
    params run the scheduled gathers while grads ride the compressed
    D2H wire into the host master update — the full composition the
    tentpole names. Loss stays finite and tracks the stage-2 offload
    engine; wire stats show real compression."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    cfg_over = {"zero_optimization": {
        "stage": 3, "cpu_offload": True,
        "offload_wire": {"grad_bits": 8, "param_bits": 8}}}

    def build(stage):
        model = GPT2ForCausalLM(tiny_gpt2_config(n_layer=2))
        params = model.init(jax.random.PRNGKey(0), _gpt2_batch(0))
        over = {k: dict(v, stage=stage) for k, v in cfg_over.items()}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 8,
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 10000,
                    "optimizer": {"type": "AdamW",
                                  "params": {"lr": 1e-3}},
                    **over})
        return engine

    e3 = build(3)
    assert e3.zero3_scheduler is not None
    l3 = _run(e3, 5)
    assert np.isfinite(l3).all()
    assert e3.wire_stats["d2h_bytes"] < 0.3 * \
        e3.wire_stats["d2h_bytes_native"], e3.wire_stats
    l2 = _run(build(2), 5)
    # int8 wire quantization is the same on both; trajectories track
    np.testing.assert_allclose(l3, l2, rtol=0, atol=1e-4)


# ----------------------------------------------------------------------
# config validation / ValueError contract
# ----------------------------------------------------------------------
def test_stage3_config_validation_raises_valueerror():
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
    with pytest.raises(ValueError, match="-3"):
        DeepSpeedZeroConfig({"zero_optimization": {
            "stage": 2, "stage3": {"prefetch_layers": -3}}})
    with pytest.raises(ValueError, match="int4"):
        DeepSpeedZeroConfig({"zero_optimization": {
            "stage": 3, "stage3": {"gather_dtype": "int4"}}})
    cfg = DeepSpeedZeroConfig({"zero_optimization": {
        "stage": 3, "stage3": {"prefetch_layers": 2,
                               "gather_dtype": "bf16"}}})
    assert cfg.stage3_prefetch_layers == 2
    assert cfg.stage3_enabled and cfg.stage3_release_after_use
    assert resolve_gather_dtype(cfg.stage3_gather_dtype) == jnp.bfloat16


def test_sharding_policy_stage_valueerror_names_value():
    """ZeroShardingPolicy rejects a bad stage with ValueError (visible
    under `python -O`, unlike the old bare assert) and the message
    carries the offending value."""
    mesh = _mesh()
    with pytest.raises(ValueError, match="7"):
        ZeroShardingPolicy(mesh, 7)
    with pytest.raises(ValueError, match="three"):
        ZeroShardingPolicy(mesh, "three")


def test_dropout_active_trace_stays_on_module_path():
    """With dropout > 0 and deterministic=False the scheduled path
    stands down (module path, identical dropout streams to the
    unscheduled engine — the ABCorrectnessChecker contract); eval
    traces (deterministic) still schedule."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = _mesh()
    model = GPT2ForCausalLM(tiny_gpt2_config(n_layer=2, dropout=0.1))
    batch = _gpt2_batch(1)
    params = model.init(jax.random.PRNGKey(0), batch)
    rngs = {"dropout": jax.random.PRNGKey(7)}

    l_plain = model.loss_fn(params, batch, rngs=rngs,
                            deterministic=False)
    model.bind_zero3_scheduler(Zero3GatherScheduler(mesh))
    assert not model._zero3_active(deterministic=False)
    assert model._zero3_active(deterministic=True)
    l_sched = model.loss_fn(params, batch, rngs=rngs,
                            deterministic=False)
    model.bind_zero3_scheduler(None)
    # identical dropout masks -> identical loss
    np.testing.assert_array_equal(np.asarray(l_plain),
                                  np.asarray(l_sched))


def test_gather_dtype_bf16_runs():
    """gather_dtype=bf16 on fp32 params: half the gather bytes, loss
    within bf16 tolerance of the fp32-gather run."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    e_ref, _ = _build_gpt2_engine(3, n_layer=2)
    e_bf, _ = _build_gpt2_engine(
        3, stage3={"gather_dtype": "bf16"}, n_layer=2)
    l_ref = _run(e_ref, 3)
    l_bf = _run(e_bf, 3)
    np.testing.assert_allclose(l_bf, l_ref, rtol=2e-2)
    info_ref = e_ref.zero3_scheduler.stack_info["h"]
    info_bf = e_bf.zero3_scheduler.stack_info["h"]
    assert info_bf["per_layer_bytes"] * 2 == \
        info_ref["per_layer_bytes"]
