"""Communication/compute overlap runtime (ISSUE 16): the shared
fence/tie primitives (`deepspeed_tpu/ops/overlap.py`), their
application at the three sites (MoE dispatch/combine, ring-attention
send/recv, ZeRO-3 standalone-leaf gathers), the fused gather-scatter
MoE dispatch kernels, and the autotuner's collective-schedule table.

What these tests pin:
  * the fence is a schedule-only constraint: the jaxpr carries ONE
    optimization_barrier taking value+deps, the fenced value is the
    barrier's output (no-hoist by construction), and values/gradients
    are bit-exact identities;
  * scheduled-vs-unscheduled BIT-EXACT parity at every site — MoE
    forward+grad, the windowed ring permute chain at issue_distance 1
    and 2, and a stage-3 GPT-2 engine step with the ln_f gather fenced
    under the scan;
  * schedule resolution is trace-time host work: tracing with overlap
    on performs zero jax.device_get / jax.effects_barrier calls;
  * the config surface rejects unknown sites, issue_distance < 1, and
    fused_dispatch='on' against an expert-parallel mesh (ValueError
    with the offending value);
  * the collective-schedule autotune entries: candidate spaces per
    site, roundtrip persist/reload (fresh subprocess included),
    never-slower floor, and `schedule(site)` consulting the table only
    in "auto" mode;
  * fused dispatch/combine parity vs the one-hot einsum pair across
    dtypes, odd token counts, capacity overflow, and the interpret
    kernels, forward and VJP.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.moe import MoEConfig, MoEMLP
from deepspeed_tpu.moe.fused_dispatch import (fused_combine,
                                              fused_dispatch,
                                              routing_slots)
from deepspeed_tpu.moe.router import (router_capacity, top_k_gating,
                                      top_k_gating_indexed)
from deepspeed_tpu.ops import autotune, overlap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_overlap(tmp_path):
    overlap.reset()
    autotune.reset()
    autotune.configure(table_path=str(tmp_path / "table.json"))
    yield
    overlap.reset()
    autotune.reset()


# ----------------------------------------------------------------------
# fence/tie primitives
# ----------------------------------------------------------------------
def _walk_eqns(jaxpr):
    """All eqns, recursing through call/custom-vjp sub-jaxprs (the
    barrier sits inside the `_barrier` custom_vjp body)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (tuple, list)) else [val]):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    yield from _walk_eqns(inner)


def _barrier_eqns(jaxpr):
    return [e for e in _walk_eqns(jaxpr.jaxpr)
            if e.primitive.name == "optimization_barrier"]


def test_fence_pins_value_to_deps_in_jaxpr():
    """The fenced value must come OUT of an optimization_barrier whose
    inputs include the dep chain — that is the no-hoist property: XLA
    cannot schedule the value's consumers before the deps exist."""

    def f(a, b):
        v = a * 2.0
        d = b + 1.0
        return overlap.fence(v, d)

    jaxpr = jax.make_jaxpr(f)(jnp.ones(3), jnp.ones(3))
    eqns = _barrier_eqns(jaxpr)
    assert len(eqns) == 1
    # the barrier consumes both the value and the dep
    assert len(eqns[0].invars) == 2


def test_fence_without_live_deps_is_a_passthrough():
    def f(a):
        return overlap.fence(a * 2.0, None)

    jaxpr = jax.make_jaxpr(f)(jnp.ones(3))
    assert not _barrier_eqns(jaxpr)


def test_fence_and_tie_are_bit_exact_identities():
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)),
                    jnp.float32)
    y = jnp.asarray(np.random.default_rng(1).standard_normal((4, 8)),
                    jnp.float32)
    fx = overlap.fence(x, y)
    tx, ty = overlap.tie(x, y)
    cx, cy = overlap.async_collective(x, y)
    for got, want in ((fx, x), (tx, x), (ty, y), (cx, x), (cy, y)):
        assert jnp.array_equal(got, want)


def test_fence_tree_values_and_grads_pass_through():
    """Pytree values through fence/tie; cotangents pass straight
    through the custom-VJP barrier (the lax op has no grad rule)."""

    def f(x, y):
        tree = {"a": x * 3.0, "b": x + 1.0}
        tree = overlap.fence(tree, y * 2.0)
        out, dep = overlap.tie(tree["a"], y)
        return jnp.sum(out) + 0.0 * jnp.sum(dep) + jnp.sum(tree["b"])

    x = jnp.asarray(np.arange(6), jnp.float32)
    y = jnp.ones(6, jnp.float32)
    gx, gy = jax.grad(f, argnums=(0, 1))(x, y)
    assert jnp.array_equal(gx, jnp.full(6, 4.0))
    assert jnp.array_equal(gy, jnp.zeros(6))


def test_stage3_and_overlap_share_one_fence():
    """Satellite (a): the PR-9 barrier helpers were deduped ONTO
    ops/overlap.py — stage3 imports the shared fence by identity."""
    from deepspeed_tpu.runtime.zero import stage3
    assert stage3._fence is overlap.fence
    assert overlap.overlap_fence is overlap.fence


# ----------------------------------------------------------------------
# configuration contract
# ----------------------------------------------------------------------
def test_configure_rejects_unknown_site():
    with pytest.raises(ValueError, match="bogus"):
        overlap.configure(sites=["bogus"])
    with pytest.raises(ValueError, match="bogus"):
        overlap.configure(sites="ring,bogus")


def test_configure_rejects_bad_issue_distance():
    with pytest.raises(ValueError, match="0"):
        overlap.configure(issue_distance=0)


def test_schedule_rejects_unknown_site():
    with pytest.raises(ValueError, match="nope"):
        overlap.schedule("nope")


def test_schedule_resolution_order():
    # default: auto, empty table -> overlap on, distance 1
    sched = overlap.schedule(overlap.SITE_RING)
    assert sched == {"overlap": True, "issue_distance": 1,
                     "granularity": 1}
    # global off beats everything
    overlap.configure(enabled=False)
    assert overlap.schedule(overlap.SITE_RING)["overlap"] is False
    # explicit site list: on exactly those sites, config distance
    overlap.configure(enabled=True, sites=["ring"], issue_distance=3)
    assert overlap.schedule(overlap.SITE_RING) == {
        "overlap": True, "issue_distance": 3, "granularity": 1}
    assert overlap.schedule(overlap.SITE_MOE)["overlap"] is False


def test_overlap_config_block_validation():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                              get_overlap_config)
    assert get_overlap_config({}) == {
        "enabled": True, "sites": "auto", "issue_distance": 1}
    with pytest.raises(DeepSpeedConfigError, match="bogus"):
        get_overlap_config({"overlap": {"sites": ["bogus"]}})
    with pytest.raises(DeepSpeedConfigError, match="0"):
        get_overlap_config({"overlap": {"issue_distance": 0}})


def test_fused_dispatch_on_rejects_expert_mesh():
    from deepspeed_tpu.runtime.mesh import build_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = build_mesh({"data": len(jax.devices()) // 2, "expert": 2})
    with pytest.raises(ValueError, match="expert"):
        MoEConfig(num_experts=4, fused_dispatch="on",
                  mesh=mesh).validate()
    # 'auto' degrades to the einsum pair instead of raising
    from deepspeed_tpu.moe import resolve_fused_dispatch
    assert resolve_fused_dispatch("auto", mesh) is False
    assert resolve_fused_dispatch("off", mesh) is False


def test_moe_fused_dispatch_config_key():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                              get_moe_config)
    assert get_moe_config({})["fused_dispatch"] == "auto"
    assert get_moe_config(
        {"moe": {"fused_dispatch": "off"}})["fused_dispatch"] == "off"
    with pytest.raises(DeepSpeedConfigError, match="maybe"):
        get_moe_config({"moe": {"fused_dispatch": "maybe"}})


# ----------------------------------------------------------------------
# scheduled vs unscheduled: bit-exact at every site
# ----------------------------------------------------------------------
def _moe_grad(enabled):
    overlap.configure(enabled=enabled)
    moe = MoEConfig(num_experts=4, top_k=2,
                    capacity_factor=1.25).validate()
    mlp = MoEMLP(moe=moe, d_model=32, d_ff=64)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (2, 16, 32)), jnp.float32)
    params = mlp.init(jax.random.PRNGKey(0), x)["params"]

    def loss(p):
        y, stats = mlp.apply({"params": p}, x)
        return jnp.sum(y * y) + stats[-1]

    val, grads = jax.jit(jax.value_and_grad(loss))(params)
    return float(val), jax.tree_util.tree_leaves(grads)


def test_moe_site_bit_exact():
    v_on, g_on = _moe_grad(True)
    v_off, g_off = _moe_grad(False)
    assert v_on == v_off
    for a, b in zip(g_on, g_off):
        assert jnp.array_equal(a, b)


def _ring_grad(enabled, issue_distance=1, causal=True):
    from jax.sharding import Mesh
    from deepspeed_tpu.ops.sequence import ring_attention
    overlap.configure(enabled=enabled, issue_distance=issue_distance)
    mesh = Mesh(np.asarray(jax.devices()), ("seq",))
    q = jnp.asarray(np.random.default_rng(1).standard_normal(
        (1, 256, 2, 16)), jnp.float32)

    def loss(qkv):
        o = ring_attention(qkv, qkv, qkv, mesh, causal=causal,
                           use_flash=False)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    val, grad = jax.jit(jax.value_and_grad(loss))(q)
    return float(val), grad


@pytest.mark.parametrize("distance", [1, 2])
def test_ring_site_bit_exact(distance):
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    v_off, g_off = _ring_grad(False)
    # overlapped arm traced LAST: record_inflight is keyed-overwrite,
    # so its window registration must be the survivor we inspect
    v_on, g_on = _ring_grad(True, issue_distance=distance)
    assert v_on == v_off
    assert jnp.array_equal(g_on, g_off)
    # the in-flight window scales with the issue distance (per-device
    # send+recv payload times rotations in flight)
    win = overlap.inflight_bytes()
    assert win > 0 and win % distance == 0


def _zero3_losses(enabled):
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2ForCausalLM,
                                           tiny_gpt2_config)
    overlap.configure(enabled=enabled)
    model = GPT2ForCausalLM(tiny_gpt2_config(n_layer=2))
    ids = np.random.default_rng(0).integers(
        0, 256, (8, 32)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "steps_per_print": 10000,
                "overlap": {"enabled": enabled},
                "zero_optimization": {"stage": 3},
                "optimizer": {"type": "AdamW",
                              "params": {"lr": 1e-3}}})
    assert engine.zero3_scheduler is not None
    losses = []
    for i in range(3):
        ids_i = np.random.default_rng(i).integers(
            0, 256, (1, 8, 32)).astype(np.int32)
        losses.append(float(jax.device_get(
            engine.train_batch(batch={"input_ids": ids_i}))))
    return losses


def test_zero3_leaf_fence_bf16_dep_grads():
    """Regression: a bf16 activation as the gather's `depend=` must
    get bf16 zero cotangents, not float0 — numpy's issubdtype
    misclassifies bfloat16 (ml_dtypes) as non-inexact, which made the
    dep-cotangent add in the backward pass trip jax's aval typematch
    assert the first time the ln_f fence ran under a bf16 engine."""
    from deepspeed_tpu.runtime.mesh import build_mesh
    from deepspeed_tpu.runtime.zero.stage3 import Zero3GatherScheduler
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = build_mesh({"data": len(jax.devices())})
    sched = Zero3GatherScheduler(mesh)
    leaf = {"scale": jnp.ones((16,), jnp.bfloat16)}

    def loss(tree, hidden):
        full = sched.gather(tree, name="leaf", depend=hidden)
        return jnp.sum(full["scale"].astype(jnp.float32)) + \
            jnp.sum(hidden.astype(jnp.float32))

    hidden = jnp.ones((2, 8), jnp.bfloat16)
    gt, gh = jax.grad(loss, argnums=(0, 1))(leaf, hidden)
    assert gt["scale"].dtype == jnp.bfloat16
    # the dep's real gradient path survives the fence's zero cotangent
    assert gh.dtype == jnp.bfloat16
    assert jnp.array_equal(gh, jnp.ones_like(hidden))


@pytest.mark.slow
def test_zero3_leaf_site_bit_exact():
    """A stage-3 engine with the ln_f gather fenced under the scan
    (overlap on) trains bit-exactly like the unfenced baseline."""
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    on = _zero3_losses(True)
    off = _zero3_losses(False)
    assert on == off, (on, off)


def test_trace_time_schedule_has_zero_host_syncs(monkeypatch):
    """Resolving the schedule + tracing the fenced MoE layer performs
    ZERO host<->device rendezvous (the HOTSYNC guard, pointed at the
    overlap runtime's trace path)."""
    overlap.configure(enabled=True)
    moe = MoEConfig(num_experts=4, top_k=2).validate()
    mlp = MoEMLP(moe=moe, d_model=32, d_ff=64)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (2, 16, 32)), jnp.float32)
    params = mlp.init(jax.random.PRNGKey(0), x)["params"]

    counts = {"device_get": 0, "effects_barrier": 0}
    real_get, real_barrier = jax.device_get, jax.effects_barrier
    monkeypatch.setattr(
        jax, "device_get",
        lambda *a, **k: (counts.__setitem__(
            "device_get", counts["device_get"] + 1), real_get(*a, **k))[1])
    monkeypatch.setattr(
        jax, "effects_barrier",
        lambda *a, **k: (counts.__setitem__(
            "effects_barrier", counts["effects_barrier"] + 1),
            real_barrier(*a, **k))[1])

    jax.jit(lambda p: mlp.apply({"params": p}, x)[0]).lower(params)
    assert counts == {"device_get": 0, "effects_barrier": 0}


# ----------------------------------------------------------------------
# autotune collective-schedule table
# ----------------------------------------------------------------------
def test_mesh_shape_class_forms():
    assert autotune.mesh_shape_class(None) == "nomesh"
    assert autotune.mesh_shape_class({"seq": 8}) == "s8"
    from jax.sharding import Mesh
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
    assert autotune.mesh_shape_class(mesh) == \
        f"d{len(jax.devices())}"


def test_collective_candidates_per_site():
    moe = autotune.collective_candidates("moe_dispatch")
    assert {c["granularity"] for c in moe} == {1, 2, 4}
    ring = autotune.collective_candidates("ring")
    assert {c["issue_distance"] for c in ring} == {1, 2}
    leaf = autotune.collective_candidates("zero3_leaf")
    assert [c["overlap"] for c in leaf] == [True, False]


def test_collective_schedule_roundtrip_and_auto_consultation(tmp_path):
    """search -> persist -> reload -> schedule('auto') applies the
    winner; an explicit site pin ignores the table."""
    site, mesh, payload = "moe_dispatch", {"data": 8}, 1 << 20
    fake = {(True, 1): 5e-3, (True, 2): 1e-3, (True, 4): 4e-3,
            (False, 1): 6e-3, (False, 2): 6e-3, (False, 4): 6e-3}
    res = autotune.search_collective_schedule(
        site, mesh, payload,
        measure=lambda p: fake[(p["overlap"], p["granularity"])])
    assert res["params"]["granularity"] == 2
    # fresh module state, same table path: the entry survives
    path = autotune.table_path()
    autotune.reset()
    autotune.configure(table_path=path)
    got = autotune.collective_schedule(site, mesh, payload)
    assert got["granularity"] == 2 and got["overlap"] is True
    # "auto" consults the table...
    sched = overlap.schedule(site, payload_bytes=payload, mesh=mesh)
    assert sched["granularity"] == 2
    # ...an explicit pin does not
    overlap.configure(sites=["moe_dispatch"])
    assert overlap.schedule(site, payload_bytes=payload,
                            mesh=mesh)["granularity"] == 1
    # the persisted document is versioned (v2: collective_schedule
    # entries joined the table)
    doc = json.load(open(path))
    assert doc["version"] == autotune.TABLE_VERSION >= 2


def test_collective_schedule_never_slower():
    """Every variant slower than the un-tuned default -> the default
    (overlap on, distance 1, granularity 1) is the recorded winner."""
    res = autotune.search_collective_schedule(
        "ring", {"seq": 8}, 1 << 20,
        measure=lambda p: (1e-3 if p == autotune.COLLECTIVE_DEFAULT
                           else 9e-3))
    assert res["params"] == autotune.COLLECTIVE_DEFAULT
    assert res["speedup_vs_default"] == 1.0


_SUBPROCESS_RELOAD = r"""
import jax
jax.config.update('jax_platforms', 'cpu')
from deepspeed_tpu.ops import autotune, overlap
autotune.configure(table_path={path!r})
got = autotune.collective_schedule('moe_dispatch', {{'data': 8}}, 1 << 20)
assert got == {{'overlap': True, 'issue_distance': 1,
                'granularity': 2}}, got
sched = overlap.schedule('moe_dispatch', payload_bytes=1 << 20,
                         mesh={{'data': 8}})
assert sched['granularity'] == 2, sched
print('RELOAD_OK')
"""


@pytest.mark.slow
def test_collective_schedule_fresh_subprocess_reload(tmp_path):
    """The persisted table steers a FRESH interpreter (no state shared
    with the searching process) — the acceptance's reload contract."""
    fake = {1: 5e-3, 2: 1e-3, 4: 4e-3}
    autotune.search_collective_schedule(
        "moe_dispatch", {"data": 8}, 1 << 20,
        measure=lambda p: (9e-3 if not p["overlap"]
                           else fake[p["granularity"]]))
    path = autotune.table_path()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", _SUBPROCESS_RELOAD.format(path=path)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "RELOAD_OK" in proc.stdout


# ----------------------------------------------------------------------
# inflight ledger accounting
# ----------------------------------------------------------------------
def test_inflight_bytes_sum_of_per_site_maxima():
    overlap.record_inflight("ring", "a", 100)
    overlap.record_inflight("ring", "b", 300)
    overlap.record_inflight("moe_dispatch", "x", 50)
    assert overlap.inflight_bytes() == 350
    # keyed overwrite: a re-trace replaces, never double-counts
    overlap.record_inflight("ring", "b", 10)
    assert overlap.inflight_bytes() == 150
    overlap.reset_inflight()
    assert overlap.inflight_bytes() == 0


def test_memory_ledger_category_registered():
    from deepspeed_tpu.monitor import memory as mem
    assert mem.CAT_OVERLAP == "overlap_inflight"
    assert mem.CAT_OVERLAP in mem.CATEGORIES
    # the oom hint names the knob
    payload = {"hbm": {"categories": {mem.CAT_OVERLAP: 1 << 30},
                       "ledger_bytes": 1 << 30}}
    hints = mem.oom_hints(payload)
    assert any("overlap.issue_distance" in h for h in hints), hints


# ----------------------------------------------------------------------
# fused dispatch/combine kernels: parity sweep
# ----------------------------------------------------------------------
def _einsum_reference(x, logits, top_k, capacity, se):
    dispatch, combine, _ = top_k_gating(logits, top_k, capacity)
    xe = jnp.einsum("nec,nh->ech", dispatch, x.astype(jnp.float32))
    ye = xe * se[:, None, None]
    return jnp.einsum("nec,ech->nh", combine, ye)


def _fused_path(x, logits, top_k, capacity, experts, se,
                use_pallas=None, interpret=False):
    routing, _ = top_k_gating_indexed(logits, top_k, capacity)
    src, dest = routing_slots(routing, experts, capacity)
    xe = fused_dispatch(x, src, use_pallas=use_pallas,
                        interpret=interpret)
    ye = (xe.astype(jnp.float32) *
          jnp.repeat(se, capacity)[:, None]).astype(x.dtype)
    return fused_combine(ye, dest, routing["keep"], routing["w"],
                         use_pallas=use_pallas, interpret=interpret)


@pytest.mark.parametrize("n,cf,dtype", [
    (64, 1.25, jnp.float32),     # dropless-ish
    (257, 1.25, jnp.float32),    # odd token count
    (128, 0.4, jnp.float32),     # forced capacity overflow -> drops
    (64, 1.25, jnp.bfloat16),
    (96, 0.5, jnp.bfloat16),
])
def test_fused_dispatch_matches_einsum_pair(n, cf, dtype):
    experts, top_k, h = 4, 2, 32
    capacity = router_capacity(n, experts, top_k, cf)
    rng = np.random.default_rng(n)
    x = jnp.asarray(rng.standard_normal((n, h)), dtype)
    logits = jnp.asarray(rng.standard_normal((n, experts)),
                         jnp.float32)
    se = jnp.asarray(1.0 + 0.25 * rng.standard_normal((experts,)),
                     jnp.float32)
    y_ref = _einsum_reference(x, logits, top_k, capacity, se)
    y_fused = _fused_path(x, logits, top_k, capacity, experts, se)
    tol = 1e-5 if dtype == jnp.float32 else 3e-2
    delta = float(jnp.max(jnp.abs(
        y_fused.astype(jnp.float32) - y_ref)) /
        (jnp.max(jnp.abs(y_ref)) + 1e-6))
    assert delta <= tol, (n, cf, dtype, delta)
    # drop semantics: a token with NO kept assignment combines to zero
    routing, stats = top_k_gating_indexed(logits, top_k, capacity)
    fully_dropped = np.asarray(
        jnp.sum(routing["keep"], axis=-1) == 0)
    if cf < 1.0:
        assert float(stats[-2]) > 0.0   # the sweep point really drops
    if fully_dropped.any():
        assert float(jnp.max(jnp.abs(
            y_fused[fully_dropped].astype(jnp.float32)))) == 0.0


def test_fused_dispatch_interpret_matches_xla():
    """The Pallas kernels in interpret mode compute exactly the XLA
    fallback (one VJP, two forward implementations)."""
    experts, top_k, h, n = 4, 2, 16, 48
    capacity = router_capacity(n, experts, top_k, 1.25)
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((n, h)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((n, experts)),
                         jnp.float32)
    routing, _ = top_k_gating_indexed(logits, top_k, capacity)
    src, dest = routing_slots(routing, experts, capacity)
    d_xla = fused_dispatch(x, src, use_pallas=False)
    d_pal = fused_dispatch(x, src, use_pallas=True, interpret=True)
    assert jnp.array_equal(d_xla, d_pal)
    c_xla = fused_combine(d_xla, dest, routing["keep"], routing["w"],
                          use_pallas=False)
    c_pal = fused_combine(d_xla, dest, routing["keep"], routing["w"],
                          use_pallas=True, interpret=True)
    # both accumulate the same k terms in the same order in fp32, but
    # XLA may contract mul+add into an FMA the interpreter doesn't —
    # a 1-ulp budget, not a formulation tolerance
    np.testing.assert_allclose(np.asarray(c_xla), np.asarray(c_pal),
                               rtol=5e-7, atol=1e-7)


def test_fused_dispatch_vjp_matches_einsum_reference():
    """Gradients through the fused path (dx through gather+scatter,
    dwg through the gate-prob chain) match the einsum formulation in
    float64, where identical math leaves no accumulation-order noise."""
    jax.config.update("jax_enable_x64", True)
    try:
        experts, top_k, h, n = 4, 2, 24, 96
        capacity = router_capacity(n, experts, top_k, 1.25)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((n, h)), jnp.float64)
        wg = jnp.asarray(0.1 * rng.standard_normal((h, experts)),
                         jnp.float64)
        se = jnp.asarray(1.0 + 0.5 * rng.standard_normal((experts,)),
                         jnp.float64)

        def loss_ref(x, wg):
            logits = (x @ wg).astype(jnp.float32)
            dispatch, combine, _ = top_k_gating(logits, top_k, capacity)
            xe = jnp.einsum("nec,nh->ech", dispatch.astype(x.dtype), x)
            y = jnp.einsum("nec,ech->nh", combine.astype(x.dtype),
                           xe * se[:, None, None])
            return jnp.sum(y * y)

        def loss_fused(x, wg):
            logits = (x @ wg).astype(jnp.float32)
            routing, _ = top_k_gating_indexed(logits, top_k, capacity)
            src, dest = routing_slots(routing, experts, capacity)
            xe = fused_dispatch(x, src)
            y = fused_combine(xe * jnp.repeat(se, capacity)[:, None],
                              dest, routing["keep"], routing["w"])
            return jnp.sum(y * y)

        l_r, g_r = jax.value_and_grad(loss_ref, argnums=(0, 1))(x, wg)
        l_f, g_f = jax.value_and_grad(loss_fused, argnums=(0, 1))(x, wg)
        assert float(abs(l_f - l_r) / abs(l_r)) <= 1e-12
        for a, b in zip(g_f, g_r):
            rel = float(jnp.max(jnp.abs(a - b)) /
                        (jnp.max(jnp.abs(b)) + 1e-9))
            assert rel <= 1e-9, rel
    finally:
        jax.config.update("jax_enable_x64", False)


def test_routing_slots_invariants():
    """src/dest are mutually consistent: every kept assignment's dest
    row gathers that token back; empty slots carry the N sentinel."""
    experts, top_k, n = 4, 2, 50
    capacity = router_capacity(n, experts, top_k, 1.0)
    rng = np.random.default_rng(11)
    logits = jnp.asarray(rng.standard_normal((n, experts)),
                         jnp.float32)
    routing, _ = top_k_gating_indexed(logits, top_k, capacity)
    src, dest = routing_slots(routing, experts, capacity)
    src, dest = np.asarray(src), np.asarray(dest)
    keep = np.asarray(routing["keep"])
    assert src.shape == (experts * capacity,)
    assert ((src >= 0) & (src <= n)).all()       # n == empty sentinel
    assert ((dest >= 0) & (dest < experts * capacity)).all()
    for tok in range(n):
        for j in range(top_k):
            if keep[tok, j]:
                assert src[dest[tok, j]] == tok, (tok, j)
    # occupied slot count == kept assignment count (slots are unique)
    assert (src < n).sum() == int(keep.sum())
