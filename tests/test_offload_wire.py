"""Compressed-wire ZeRO-Offload tests (ISSUE 1).

Covers: the grad_bits=32 bit-for-bit legacy guarantee, the int8 / 1-bit
convergence A/B against an fp32-wire baseline, the fused quantized
CPU-Adam chunk steps, overflow x error-feedback interaction, the
param-delta shadow invariant, and checkpoint round-trips of wire state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.models.gpt2 import tiny_gpt2_config, GPT2ForCausalLM

BLOCK = 4096


def _engine(wire=None, fp16=False, bf16=True, lr=1e-2, n_layer=1,
            n_embd=32, seq=64):
    cfg = tiny_gpt2_config(n_layer=n_layer, n_embd=n_embd, n_head=4,
                           n_positions=seq, dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(
        0, 256, (8, seq)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    zero = {"stage": 2, "cpu_offload": True}
    if wire is not None:
        zero["offload_wire"] = wire
    ds = {"train_batch_size": 8,
          "zero_optimization": zero,
          "optimizer": {"type": "AdamW",
                        "params": {"lr": lr, "weight_decay": 0.0}}}
    if fp16:
        ds["fp16"] = {"enabled": True, "loss_scale": 0}
    elif bf16:
        ds["bf16"] = {"enabled": True}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds)
    return engine, ids


def _run(engine, ids, steps):
    return [float(jax.device_get(
        engine.train_batch(batch={"input_ids": ids[None]})))
        for _ in range(steps)]


# ----------------------------------------------------------------------
# default-off guarantee
# ----------------------------------------------------------------------
def test_wire_grad32_bit_identical_to_legacy():
    """grad_bits=32/param_bits=32 must reproduce the legacy wire
    bit-for-bit — identical loss sequence, identical masters."""
    e_leg, ids = _engine(wire=None)
    e_32, _ = _engine(wire={"grad_bits": 32, "param_bits": 32})
    l_leg = _run(e_leg, ids, 4)
    l_32 = _run(e_32, ids, 4)
    assert l_leg == l_32, (l_leg, l_32)
    np.testing.assert_array_equal(e_leg._host_master, e_32._host_master)


# ----------------------------------------------------------------------
# convergence A/B (acceptance: >= 20 steps, non-slow)
# ----------------------------------------------------------------------
def test_wire_compressed_convergence_matches_fp32_wire():
    """int8 and 1-bit(after warmup) loss trajectories on tiny GPT-2 stay
    within tolerance of the fp32-wire baseline over 20+ steps. All
    engines run fp32 compute so the ONLY difference is the wire
    format."""
    steps = 20
    lr = 3e-3   # calibrated: at 1e-2 the tiny model's trajectory is
    # chaotic enough that ANY 1-ulp perturbation diverges past 0.5
    base_e, ids = _engine(wire=None, bf16=False, lr=lr)
    base = _run(base_e, ids, steps)
    assert base[-1] < base[0], "baseline failed to descend"

    int8_e, _ = _engine(wire={"grad_bits": 8, "param_bits": 8},
                        bf16=False, lr=lr)
    int8 = _run(int8_e, ids, steps)

    onebit_e, _ = _engine(
        wire={"grad_bits": 1, "warmup_steps": 4}, bf16=False, lr=lr)
    onebit = _run(onebit_e, ids, steps)

    # measured at this seed: int8 max gap 0.069, 1-bit 0.228
    for name, traj, tol in (("int8", int8, 0.12), ("1bit", onebit, 0.35)):
        gaps = [abs(a - b) for a, b in zip(traj, base)]
        assert max(gaps) < tol, (name, max(gaps), traj, base)
        assert traj[-1] < traj[0], (name, "failed to descend", traj)


# ----------------------------------------------------------------------
# quantized host-Adam chunk steps
# ----------------------------------------------------------------------
def _quant_q8(g, block=BLOCK):
    from deepspeed_tpu.runtime.zero.offload import quantize_int8_blocks
    return quantize_int8_blocks(g, block)


def test_step_chunk_q8_matches_dequant_step():
    n = 10_000
    rng = np.random.RandomState(3)
    p_q = rng.randn(n).astype(np.float32)
    p_ref = p_q.copy()
    a = DeepSpeedCPUAdam(n, lr=1e-3, weight_decay=0.01)
    b = DeepSpeedCPUAdam(n, lr=1e-3, weight_decay=0.01)
    for _ in range(3):
        g = rng.randn(n).astype(np.float32)
        q, s = _quant_q8(g)
        gd = q.astype(np.float32) * np.repeat(s, BLOCK)[:n]
        a.begin_step()
        a.step_chunk_q8(0, n, p_q, q, s, BLOCK)
        b.begin_step()
        b.step_chunk(0, n, p_ref, gd)
        np.testing.assert_allclose(p_q, p_ref, atol=1e-7)
    np.testing.assert_allclose(a.exp_avg, b.exp_avg, atol=1e-7)


def test_step_chunk_q1_matches_dequant_step():
    n = 9_000   # not a multiple of 8: exercises the packed tail
    rng = np.random.RandomState(4)
    p_q = rng.randn(n).astype(np.float32)
    p_ref = p_q.copy()
    a = DeepSpeedCPUAdam(n, lr=1e-3)
    b = DeepSpeedCPUAdam(n, lr=1e-3)
    g = rng.randn(n).astype(np.float32)
    nb = -(-n // BLOCK)
    pad = np.zeros(nb * BLOCK, np.float32)
    pad[:n] = g
    s = np.abs(pad.reshape(nb, BLOCK)).mean(axis=1).astype(np.float32)
    bits = (pad >= 0).astype(np.uint8)
    packed = np.packbits(bits, bitorder="little")[: -(-n // 8)]
    gd = np.where(bits[:n] > 0, 1.0, -1.0).astype(np.float32) * \
        np.repeat(s, BLOCK)[:n]
    a.begin_step()
    a.step_chunk_q1(0, n, p_q, packed, s, BLOCK)
    b.begin_step()
    b.step_chunk(0, n, p_ref, gd)
    np.testing.assert_allclose(p_q, p_ref, atol=1e-7)


def test_step_chunk_q8_native_matches_numpy():
    n = 8192 + 100
    rng = np.random.RandomState(5)
    nat = DeepSpeedCPUAdam(n, lr=1e-2, use_native=True)
    if not nat.native:
        pytest.skip("native cpu_adam unavailable")
    ref = DeepSpeedCPUAdam(n, lr=1e-2, use_native=False)
    pn = rng.randn(n).astype(np.float32)
    pr = pn.copy()
    q, s = _quant_q8(rng.randn(n).astype(np.float32))
    nat.begin_step()
    nat.step_chunk_q8(0, n, pn, q, s, BLOCK)
    ref.begin_step()
    ref.step_chunk_q8(0, n, pr, q, s, BLOCK)
    np.testing.assert_allclose(pn, pr, atol=1e-5)


# ----------------------------------------------------------------------
# overflow x error feedback (satellite: dynamic loss scale interaction)
# ----------------------------------------------------------------------
def test_overflow_skips_step_without_polluting_residual():
    """fp16 overflow must skip the step AND leave the 1-bit error-
    feedback residual, masters, and param shadow untouched."""
    e, ids = _engine(wire={"grad_bits": 1, "param_bits": 8}, fp16=True,
                     bf16=False, lr=1e-3)
    _run(e, ids, 2)   # residual now non-trivial
    res_before = np.asarray(jax.device_get(e._offload_grad_residual))
    master_before = e._host_master.copy()
    shadow_before = e._offload_param_shadow.copy()
    scale_before = e._host_scaler.cur_scale
    skipped_before = int(jax.device_get(e.state.skipped))

    # poison the accumulator: the grad-tail norm goes inf -> overflow
    poisoned = jax.tree_util.tree_map(
        lambda x: (x + jnp.inf).astype(x.dtype), e.state.acc_grads)
    e.state = e.state._replace(acc_grads=poisoned)
    assert e._offload_take_step(lr=1e-3) is True

    assert int(jax.device_get(e.state.skipped)) == skipped_before + 1
    assert e._host_scaler.cur_scale < scale_before
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(e._offload_grad_residual)), res_before)
    np.testing.assert_array_equal(e._host_master, master_before)
    np.testing.assert_array_equal(e._offload_param_shadow, shadow_before)
    # recovery: the next (clean) step trains
    loss = _run(e, ids, 1)[0]
    assert np.isfinite(loss)


# ----------------------------------------------------------------------
# param-delta return invariants
# ----------------------------------------------------------------------
def test_param_shadow_tracks_device_flat():
    """Host shadow and the device-resident fp32 param copy integrate the
    SAME dequantized deltas; they agree to float rounding (XLA may fuse
    the dequant multiply-add into an FMA, so per-step drift is <= 1 ulp
    — inside the error-feedback correction loop)."""
    e, ids = _engine(wire={"grad_bits": 8, "param_bits": 8})
    _run(e, ids, 3)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(e._offload_device_flat)),
        e._offload_param_shadow, rtol=0, atol=2e-6)
    # and the shadow is NOT the master (quantized delta is lossy)
    assert not np.array_equal(e._offload_param_shadow, e._host_master)


def test_wire_warmup_runs_uncompressed_then_engages():
    e, ids = _engine(wire={"grad_bits": 1, "param_bits": 8,
                           "warmup_steps": 2})
    _run(e, ids, 1)
    assert e.wire_stats["warmup"] is True
    n = e._host_master.size
    assert e.wire_stats["d2h_bytes"] == 4 * n       # fp32 warmup wire
    _run(e, ids, 2)
    assert e.wire_stats["warmup"] is False
    assert e.wire_stats["d2h_bytes"] < n            # ~n/8 + scales
    # grad_bits=16 honors the warmup window too (fp32 wire, then bf16)
    e16, _ = _engine(wire={"grad_bits": 16, "warmup_steps": 1},
                     bf16=False)
    _run(e16, ids, 1)
    assert e16.wire_stats["warmup"] is True
    assert e16.wire_stats["d2h_bytes"] == 4 * e16._host_master.size
    _run(e16, ids, 1)
    assert e16.wire_stats["warmup"] is False
    assert e16.wire_stats["d2h_bytes"] == 2 * e16._host_master.size
    # shadow still tracks the device copy (to float rounding; see
    # test_param_shadow_tracks_device_flat)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(e._offload_device_flat)),
        e._offload_param_shadow, rtol=0, atol=2e-6)


def test_offload_bounds_alignment():
    from deepspeed_tpu.runtime.zero.offload import ZeroOffloadMixin

    class Probe(ZeroOffloadMixin):
        _OFFLOAD_CHUNK_ELEMS = 1000

    p = Probe()
    n = 10_000
    bounds = p._offload_bounds(n, align=256)
    assert bounds[0][0] == 0 and bounds[-1][1] == n
    for (lo, hi), (lo2, _) in zip(bounds, bounds[1:]):
        assert hi == lo2             # contiguous
        assert lo % 256 == 0         # aligned interior edges
    assert sum(hi - lo for lo, hi in bounds) == n


# ----------------------------------------------------------------------
# checkpoint round-trip of wire state
# ----------------------------------------------------------------------
def test_wire_checkpoint_roundtrip(tmp_ckpt_dir):
    e, ids = _engine(wire={"grad_bits": 1, "param_bits": 8})
    _run(e, ids, 3)
    res = np.asarray(jax.device_get(e._offload_grad_residual))
    shadow = e._offload_param_shadow.copy()
    e.save_checkpoint(tmp_ckpt_dir)
    e.wait_for_checkpoint()

    e2, _ = _engine(wire={"grad_bits": 1, "param_bits": 8})
    e2.load_checkpoint(tmp_ckpt_dir)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(e2._offload_grad_residual)), res)
    np.testing.assert_array_equal(e2._offload_param_shadow, shadow)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(e2._offload_device_flat)), shadow)
    assert np.isfinite(_run(e2, ids, 1)[0])


def test_wire_engine_loads_other_wire_config_checkpoint(tmp_ckpt_dir):
    """A checkpoint saved by an int8-wire engine (wire state present but
    no grad_residual) must zero a 1-bit engine's residual on load, not
    keep the pre-load one."""
    e, ids = _engine(wire={"grad_bits": 8, "param_bits": 8})
    _run(e, ids, 2)
    e.save_checkpoint(tmp_ckpt_dir)
    e.wait_for_checkpoint()

    e2, _ = _engine(wire={"grad_bits": 1})
    _run(e2, ids, 2)   # accumulate a nonzero residual pre-load
    assert float(np.abs(np.asarray(
        jax.device_get(e2._offload_grad_residual))).max()) > 0
    e2.load_checkpoint(tmp_ckpt_dir)
    assert float(np.abs(np.asarray(
        jax.device_get(e2._offload_grad_residual))).max()) == 0.0
    assert np.isfinite(_run(e2, ids, 1)[0])


def test_wire_engine_loads_wireless_checkpoint(tmp_ckpt_dir):
    """A checkpoint saved WITHOUT offload_wire must load into a
    compressed-wire engine: residual restarts at zero, shadow resyncs
    to the restored masters."""
    e, ids = _engine(wire=None)
    _run(e, ids, 2)
    master = e._host_master.copy()
    e.save_checkpoint(tmp_ckpt_dir)
    e.wait_for_checkpoint()

    e2, _ = _engine(wire={"grad_bits": 1, "param_bits": 8})
    e2.load_checkpoint(tmp_ckpt_dir)
    np.testing.assert_allclose(e2._host_master, master)
    assert float(np.abs(np.asarray(
        jax.device_get(e2._offload_grad_residual))).max()) == 0.0
    np.testing.assert_array_equal(e2._offload_param_shadow, master)
    assert np.isfinite(_run(e2, ids, 1)[0])
