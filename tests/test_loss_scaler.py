"""Dynamic loss scale tests (parity with ref
tests/unit/test_dynamic_loss_scale.py: exact halving/raising schedules)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.fp16.loss_scaler import (
    DynamicLossScaler, LossScaler, make_loss_scale_state, update_loss_scale)


def run_automaton(state, overflows, **kw):
    scales = []
    for ov in overflows:
        state = update_loss_scale(state, ov, **kw)
        scales.append(float(state.loss_scale))
    return state, scales


def test_scale_doubles_after_window():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=1)
    _, scales = run_automaton(state, [False] * 4, scale_window=2,
                              delayed_shift=1)
    assert scales == [256.0, 512.0, 512.0, 1024.0]


def test_scale_halves_on_overflow():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=1)
    _, scales = run_automaton(state, [True, True, False], scale_window=1000,
                              delayed_shift=1)
    assert scales[0] == 128.0
    assert scales[1] == 64.0
    assert scales[2] == 64.0


def test_hysteresis_delays_drop():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=2)
    # first overflow burns hysteresis, second drops the scale
    _, scales = run_automaton(state, [True, True], scale_window=1000,
                              delayed_shift=2)
    assert scales[0] == 256.0
    assert scales[1] == 128.0


def test_min_scale_floor():
    state = make_loss_scale_state(init_scale=2.0, delayed_shift=1)
    _, scales = run_automaton(state, [True] * 5, scale_window=1000,
                              min_scale=1.0, delayed_shift=1)
    assert scales[-1] == 1.0


def test_overflow_resets_good_steps():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=1)
    # 1 clean, overflow, then window clean steps must elapse before growth
    state = update_loss_scale(state, False, scale_window=3, delayed_shift=1)
    state = update_loss_scale(state, True, scale_window=3, delayed_shift=1)
    assert float(state.loss_scale) == 128.0
    for _ in range(2):
        state = update_loss_scale(state, False, scale_window=3,
                                  delayed_shift=1)
    assert float(state.loss_scale) == 128.0
    state = update_loss_scale(state, False, scale_window=3, delayed_shift=1)
    assert float(state.loss_scale) == 256.0


def test_update_is_jittable():
    @jax.jit
    def step(state, ov):
        return update_loss_scale(state, ov, scale_window=2, delayed_shift=1)

    state = make_loss_scale_state(init_scale=16.0, delayed_shift=1)
    state = step(state, jnp.asarray(False))
    state = step(state, jnp.asarray(False))
    assert float(state.loss_scale) == 32.0


def test_host_dynamic_scaler_matches_automaton():
    """Host-side class and device automaton agree on a mixed trace."""
    trace = [False, False, True, False, True, True, False, False]
    host = DynamicLossScaler(init_scale=64.0, scale_window=2,
                             delayed_shift=1, min_scale=1)
    dev = make_loss_scale_state(init_scale=64.0, delayed_shift=1)
    for ov in trace:
        host.update_scale(ov)
        dev = update_loss_scale(dev, ov, scale_window=2, min_scale=1,
                                delayed_shift=1)
    assert float(dev.loss_scale) == float(host.cur_scale)


def test_static_scaler():
    s = LossScaler(scale=128.0)
    assert s.loss_scale == 128.0
    s.update_scale(True)
    assert s.loss_scale == 128.0


def test_clean_window_restores_hysteresis():
    """A full overflow-free window restores hysteresis to delayed_shift
    (ref resets cur_hysteresis at every scale raise)."""
    s = make_loss_scale_state(init_scale=2.0**10, delayed_shift=2)
    s = update_loss_scale(s, True, scale_window=4, delayed_shift=2)
    assert int(s.hysteresis) == 1
    for _ in range(4):
        s = update_loss_scale(s, False, scale_window=4, delayed_shift=2)
    assert int(s.hysteresis) == 2
    # a single overflow now only decrements hysteresis, not the scale
    scale_before = float(s.loss_scale)
    s = update_loss_scale(s, True, scale_window=4, delayed_shift=2)
    assert float(s.loss_scale) == scale_before


# ----------------------------------------------------------------------
# ENGINE-level trajectory exactness (ref test_dynamic_loss_scale.py:
# the reference drives a real engine with injected gradients and
# asserts cur_scale after every step; so do we)
# ----------------------------------------------------------------------
class _GradInjector:
    """loss = sum(w * v): grad(w) == batch value, so inf/nan batches
    force overflow exactly like the reference's p.grad.fill_(value)."""

    def init(self, rng, batch):
        return {"w": jnp.ones((4,), jnp.float32)}

    def loss_fn(self, params, batch, rngs=None, deterministic=True, **_):
        return jnp.sum(params["w"] * batch["v"].astype(jnp.float32))


def _scale_engine(initial_scale_power, window):
    import deepspeed_tpu
    from deepspeed_tpu.runtime.mesh import build_mesh
    model = _GradInjector()
    params = model.init(None, None)
    mesh = build_mesh({"pipe": 1, "data": 1, "model": 1},
                      devices=jax.devices()[:1])   # ref world_size=1
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        config={
            "train_batch_size": 1,
            "steps_per_print": 1000,
            "optimizer": {"type": "Adam", "params": {"lr": 1.5e-4}},
            # hysteresis 1 = the reference FUSED optimizer's behavior
            # (halve on every overflow), which is what its trajectory
            # tests assert; the default 2 matches its unfused
            # DynamicLossScaler
            "fp16": {"enabled": True, "loss_scale": 0,
                     "initial_scale_power": initial_scale_power,
                     "loss_scale_window": window,
                     "hysteresis": 1},
        })
    return engine


def _step(engine, value):
    batch = {"v": np.full((1, 4), value, np.float32)}
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    return float(jax.device_get(engine.state.scale.loss_scale))


def test_engine_no_overflow_schedule():
    """Clean steps double the scale every `window` steps (ref
    test_fused_no_overflow)."""
    window = 2
    engine = _scale_engine(initial_scale_power=8, window=window)
    expected = 2.0 ** 8
    assert float(jax.device_get(engine.state.scale.loss_scale)) == expected
    for i, value in enumerate(np.random.uniform(-0.1, 0.1, 10)):
        got = _step(engine, value)
        if (i + 1) % window == 0:
            expected *= 2
        assert got == expected, (i, got, expected)
    assert engine.skipped_steps == 0


def test_engine_all_overflow_schedule():
    """Every overflow halves the scale (floor 1) and skips the step
    (ref test_fused_all_overflow)."""
    engine = _scale_engine(initial_scale_power=4, window=2)
    expected = 2.0 ** 4
    bad = [np.inf, -np.inf] + [np.nan] * 6
    for i, value in enumerate(bad):
        got = _step(engine, value)
        expected = max(expected / 2, 1.0)
        assert got == expected, (i, got, expected)
    assert engine.skipped_steps == len(bad)


def test_engine_some_overflow_schedule():
    """Mixed trace: consecutive overflows halve twice, then
    window+1 clean steps raise once, then one more overflow halves
    (ref test_fused_some_overflow)."""
    window = 2
    engine = _scale_engine(initial_scale_power=8, window=window)
    expected = 2.0 ** 8

    for value in (np.inf, np.nan):
        got = _step(engine, value)
    expected /= 4
    assert got == expected

    for value in np.random.uniform(-0.1, 0.1, window + 1):
        got = _step(engine, value)
    expected *= 2          # exactly one doubling in window+1 steps
    assert got == expected

    got = _step(engine, np.inf)
    expected /= 2
    assert got == expected
