"""Dynamic loss scale tests (parity with ref
tests/unit/test_dynamic_loss_scale.py: exact halving/raising schedules)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.fp16.loss_scaler import (
    DynamicLossScaler, LossScaler, make_loss_scale_state, update_loss_scale)


def run_automaton(state, overflows, **kw):
    scales = []
    for ov in overflows:
        state = update_loss_scale(state, ov, **kw)
        scales.append(float(state.loss_scale))
    return state, scales


def test_scale_doubles_after_window():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=1)
    _, scales = run_automaton(state, [False] * 4, scale_window=2,
                              delayed_shift=1)
    assert scales == [256.0, 512.0, 512.0, 1024.0]


def test_scale_halves_on_overflow():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=1)
    _, scales = run_automaton(state, [True, True, False], scale_window=1000,
                              delayed_shift=1)
    assert scales[0] == 128.0
    assert scales[1] == 64.0
    assert scales[2] == 64.0


def test_hysteresis_delays_drop():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=2)
    # first overflow burns hysteresis, second drops the scale
    _, scales = run_automaton(state, [True, True], scale_window=1000,
                              delayed_shift=2)
    assert scales[0] == 256.0
    assert scales[1] == 128.0


def test_min_scale_floor():
    state = make_loss_scale_state(init_scale=2.0, delayed_shift=1)
    _, scales = run_automaton(state, [True] * 5, scale_window=1000,
                              min_scale=1.0, delayed_shift=1)
    assert scales[-1] == 1.0


def test_overflow_resets_good_steps():
    state = make_loss_scale_state(init_scale=256.0, delayed_shift=1)
    # 1 clean, overflow, then window clean steps must elapse before growth
    state = update_loss_scale(state, False, scale_window=3, delayed_shift=1)
    state = update_loss_scale(state, True, scale_window=3, delayed_shift=1)
    assert float(state.loss_scale) == 128.0
    for _ in range(2):
        state = update_loss_scale(state, False, scale_window=3,
                                  delayed_shift=1)
    assert float(state.loss_scale) == 128.0
    state = update_loss_scale(state, False, scale_window=3, delayed_shift=1)
    assert float(state.loss_scale) == 256.0


def test_update_is_jittable():
    @jax.jit
    def step(state, ov):
        return update_loss_scale(state, ov, scale_window=2, delayed_shift=1)

    state = make_loss_scale_state(init_scale=16.0, delayed_shift=1)
    state = step(state, jnp.asarray(False))
    state = step(state, jnp.asarray(False))
    assert float(state.loss_scale) == 32.0


def test_host_dynamic_scaler_matches_automaton():
    """Host-side class and device automaton agree on a mixed trace."""
    trace = [False, False, True, False, True, True, False, False]
    host = DynamicLossScaler(init_scale=64.0, scale_window=2,
                             delayed_shift=1, min_scale=1)
    dev = make_loss_scale_state(init_scale=64.0, delayed_shift=1)
    for ov in trace:
        host.update_scale(ov)
        dev = update_loss_scale(dev, ov, scale_window=2, min_scale=1,
                                delayed_shift=1)
    assert float(dev.loss_scale) == float(host.cur_scale)


def test_static_scaler():
    s = LossScaler(scale=128.0)
    assert s.loss_scale == 128.0
    s.update_scale(True)
    assert s.loss_scale == 128.0


def test_clean_window_restores_hysteresis():
    """A full overflow-free window restores hysteresis to delayed_shift
    (ref resets cur_hysteresis at every scale raise)."""
    s = make_loss_scale_state(init_scale=2.0**10, delayed_shift=2)
    s = update_loss_scale(s, True, scale_window=4, delayed_shift=2)
    assert int(s.hysteresis) == 1
    for _ in range(4):
        s = update_loss_scale(s, False, scale_window=4, delayed_shift=2)
    assert int(s.hysteresis) == 2
    # a single overflow now only decrements hysteresis, not the scale
    scale_before = float(s.loss_scale)
    s = update_loss_scale(s, True, scale_window=4, delayed_shift=2)
    assert float(s.loss_scale) == scale_before
