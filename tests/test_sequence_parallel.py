"""Ring attention + Ulysses sequence parallelism vs dense reference.

The reference has no sequence parallelism (SURVEY §2.3); these tests
pin the numerics of the TPU-native long-context path against dense
attention on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deepspeed_tpu.ops.sequence import ring_attention, ulysses_attention
from deepspeed_tpu.ops.transformer.flash_attention import dense_attention


@pytest.fixture
def seq_mesh():
    devs = np.asarray(jax.devices()[:8])
    return Mesh(devs, ("seq",))


def qkv(b=2, t=128, h=8, d=32, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
            for _ in range(3)]


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(seq_mesh, causal):
    q, k, v = qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, seq_mesh, axis_name="seq", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(seq_mesh, causal):
    q, k, v = qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, seq_mesh, axis_name="seq",
                            causal=causal, use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grads_match_dense(seq_mesh):
    q, k, v = qkv(t=64)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ulysses_grads_match_dense(seq_mesh):
    q, k, v = qkv(t=64)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, seq_mesh, causal=True,
                                         use_flash=False) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_attention_jit_sharded_input(seq_mesh):
    """jitted end-to-end with sequence-sharded inputs."""
    from jax.sharding import NamedSharding, PartitionSpec
    q, k, v = qkv()
    spec = NamedSharding(seq_mesh, PartitionSpec(None, "seq"))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, seq_mesh, causal=True))(qs, ks, vs)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gpt2_trains_with_sequence_parallel_config():
    """End-to-end: the flagship GPT-2 trains with sequence parallelism
    selected from its config (T sharded over the model axis, ulysses
    all-to-all inside the engine's fused step) and matches the non-SP
    model exactly (same attention math, different layout)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config
    from deepspeed_tpu.runtime.mesh import build_mesh

    # data=1: XLA's in-process CPU communicator deadlocks on SUBGROUP
    # collectives inside while loops (data>1 would split the model axis
    # into cliques); real TPUs have no such limitation
    mesh = build_mesh({"pipe": 1, "data": 1, "model": 8})
    ids = np.random.RandomState(0).randint(
        0, 256, (4, 64)).astype(np.int32)

    def run(sp):
        cfg = tiny_gpt2_config(n_layer=2, n_head=8, dropout=0.0,
                               sequence_parallel=sp,
                               sp_mesh=mesh if sp else None)
        model = GPT2ForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, mesh=mesh,
            config={"train_batch_size": 4, "steps_per_print": 1000,
                    "optimizer": {"type": "Adam",
                                  "params": {"lr": 1e-3}}})
        losses = []
        for _ in range(4):
            loss = engine.train_batch(batch={"input_ids": ids[None]})
            losses.append(float(jax.device_get(loss)))
        return losses

    losses_sp = run("ulysses")
    losses_ref = run(None)
    np.testing.assert_allclose(losses_sp, losses_ref, rtol=2e-4)


def test_gpt2_ring_sequence_parallel_matches():
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config
    from deepspeed_tpu.runtime.mesh import build_mesh

    mesh = build_mesh({"pipe": 1, "data": 1, "model": 8})
    ids = np.random.RandomState(1).randint(
        0, 256, (4, 64)).astype(np.int32)
    cfg = tiny_gpt2_config(n_layer=2, n_head=8, dropout=0.0,
                           sequence_parallel="ring", sp_mesh=mesh)
    model = GPT2ForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        config={"train_batch_size": 4, "steps_per_print": 1000,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    losses = [float(jax.device_get(
        engine.train_batch(batch={"input_ids": ids[None]})))
        for _ in range(6)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


# ----------------------------------------------------------------------
# per-step Pallas flash partials in the ring (VERDICT r4 #4)
# ----------------------------------------------------------------------
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_path_matches_dense(seq_mesh, causal):
    """Flash (out, lse) partials merged across ring steps (interpret
    mode exercises the same kernel code CPU-side): local chunk 128 per
    device, d=64 — the kernel's tiling contract."""
    q, k, v = qkv(b=1, t=1024, h=2, d=64, seed=3)
    ref = dense_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, seq_mesh, causal=causal,
                         use_flash=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_ring_attention_flash_path_grads_match_dense(seq_mesh):
    """Ring grads through the per-step flash partials: the merge
    weights consume each step's lse, so this exercises the lse-cotangent
    delta-shift in the flash backward."""
    q, k, v = qkv(b=1, t=1024, h=2, d=64, seed=5)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh, causal=True,
                                      use_flash=True, interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3, rtol=1e-3)


def test_indivisible_shapes_raise_cleanly(seq_mesh):
    """Indivisible T (or H for Ulysses) must raise a ValueError naming
    the problem, not an opaque shard_map sharding error."""
    q = jnp.zeros((1, 100, 8, 32), jnp.float32)    # 100 % 8 != 0
    with pytest.raises(ValueError, match="sequence length 100"):
        ring_attention(q, q, q, seq_mesh, causal=True)
    with pytest.raises(ValueError, match="sequence length 100"):
        ulysses_attention(q, q, q, seq_mesh, causal=True, use_flash=False)
    q = jnp.zeros((1, 128, 6, 32), jnp.float32)    # 6 heads % 8 != 0
    with pytest.raises(ValueError, match="heads 6 divisible"):
        ulysses_attention(q, q, q, seq_mesh, causal=True, use_flash=False)
