"""CPU-Adam + ZeRO-Offload tests (parity targets: ref
tests/unit/test_cpu_adam.py compares DeepSpeedCPUAdam vs torch.optim.Adam;
the offload engine path mirrors ref test_fp16.py's zero+offload combos)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.models.gpt2 import tiny_gpt2_config, GPT2ForCausalLM


def test_cpu_adam_matches_torch_adamw():
    import torch
    n = 10_000
    rng = np.random.RandomState(0)
    p0 = rng.randn(n).astype(np.float32)
    opt = DeepSpeedCPUAdam(n, lr=1e-3, weight_decay=0.01)
    p = p0.copy()
    tp = torch.tensor(p0.copy(), requires_grad=True)
    topt = torch.optim.AdamW([tp], lr=1e-3, weight_decay=0.01, eps=1e-8)
    for i in range(10):
        g = rng.randn(n).astype(np.float32)
        opt.step(p, g)
        tp.grad = torch.tensor(g)
        topt.step()
    np.testing.assert_allclose(p, tp.detach().numpy(), atol=1e-5)


def test_cpu_adam_native_matches_numpy():
    n = 5_000
    rng = np.random.RandomState(1)
    p0 = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    nat = DeepSpeedCPUAdam(n, lr=1e-2, weight_decay=0.1, use_native=True)
    ref = DeepSpeedCPUAdam(n, lr=1e-2, weight_decay=0.1, use_native=False)
    pn, pr = p0.copy(), p0.copy()
    for _ in range(5):
        nat.step(pn, g)
        ref.step(pr, g)
    np.testing.assert_allclose(pn, pr, atol=1e-5)


def test_cpu_adam_bf16_copy():
    n = 1024
    rng = np.random.RandomState(2)
    p = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    opt = DeepSpeedCPUAdam(n, lr=1e-3)
    out16 = np.zeros(n, np.uint16)
    opt.step(p, g, params_bf16_out=out16)
    expect = np.asarray(jnp.asarray(p, jnp.bfloat16)).view(np.uint16)
    np.testing.assert_array_equal(out16, expect)


def test_cpu_adam_state_roundtrip():
    n = 128
    rng = np.random.RandomState(3)
    p = rng.randn(n).astype(np.float32)
    a = DeepSpeedCPUAdam(n, lr=1e-3)
    for _ in range(3):
        a.step(p, rng.randn(n).astype(np.float32))
    sd = {k: np.array(v) if isinstance(v, np.ndarray) else v
          for k, v in a.state_dict().items()}
    b = DeepSpeedCPUAdam(n, lr=1e-3)
    b.load_state_dict(sd)
    g = rng.randn(n).astype(np.float32)
    pa, pb = p.copy(), p.copy()
    a.step(pa, g)
    b.step(pb, g)
    np.testing.assert_allclose(pa, pb, atol=1e-6)


def _gpt2_engine(offload, lr=1e-2, **cfg_over):
    cfg = tiny_gpt2_config(n_layer=2, dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(0, 256, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    ds = {"train_batch_size": 8,
          "bf16": {"enabled": True},
          "zero_optimization": {"stage": 2, "cpu_offload": offload},
          "optimizer": {"type": "AdamW",
                        "params": {"lr": lr, "weight_decay": 0.0}}}
    ds.update(cfg_over)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds)
    return engine, ids


def test_offload_engine_matches_device_engine():
    """ZeRO-Offload must track the on-device optimizer trajectory
    (same AdamW math, host vs device execution)."""
    e_dev, ids = _gpt2_engine(offload=False)
    e_off, _ = _gpt2_engine(offload=True)
    for i in range(5):
        ld = float(jax.device_get(
            e_dev.train_batch(batch={"input_ids": ids[None]})))
        lo = float(jax.device_get(
            e_off.train_batch(batch={"input_ids": ids[None]})))
        # bf16 recast + host fp32 step accumulate small differences
        assert abs(ld - lo) < 0.05, (i, ld, lo)


def test_offload_checkpoint_roundtrip(tmp_ckpt_dir):
    engine, ids = _gpt2_engine(offload=True)
    for _ in range(3):
        engine.train_batch(batch={"input_ids": ids[None]})
    master_before = engine._host_master.copy()
    engine.save_checkpoint(tmp_ckpt_dir)
    engine2, _ = _gpt2_engine(offload=True)
    engine2.load_checkpoint(tmp_ckpt_dir)
    np.testing.assert_allclose(engine2._host_master, master_before)
    loss = engine2.train_batch(batch={"input_ids": ids[None]})
    assert np.isfinite(float(jax.device_get(loss)))
