"""CPU-Adam + ZeRO-Offload tests (parity targets: ref
tests/unit/test_cpu_adam.py compares DeepSpeedCPUAdam vs torch.optim.Adam;
the offload engine path mirrors ref test_fp16.py's zero+offload combos)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.models.gpt2 import tiny_gpt2_config, GPT2ForCausalLM


def test_cpu_adam_matches_torch_adamw():
    import torch
    n = 10_000
    rng = np.random.RandomState(0)
    p0 = rng.randn(n).astype(np.float32)
    opt = DeepSpeedCPUAdam(n, lr=1e-3, weight_decay=0.01)
    p = p0.copy()
    tp = torch.tensor(p0.copy(), requires_grad=True)
    topt = torch.optim.AdamW([tp], lr=1e-3, weight_decay=0.01, eps=1e-8)
    for i in range(10):
        g = rng.randn(n).astype(np.float32)
        opt.step(p, g)
        tp.grad = torch.tensor(g)
        topt.step()
    np.testing.assert_allclose(p, tp.detach().numpy(), atol=1e-5)


def test_cpu_adam_native_matches_numpy():
    n = 5_000
    rng = np.random.RandomState(1)
    p0 = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    nat = DeepSpeedCPUAdam(n, lr=1e-2, weight_decay=0.1, use_native=True)
    ref = DeepSpeedCPUAdam(n, lr=1e-2, weight_decay=0.1, use_native=False)
    pn, pr = p0.copy(), p0.copy()
    for _ in range(5):
        nat.step(pn, g)
        ref.step(pr, g)
    np.testing.assert_allclose(pn, pr, atol=1e-5)


def test_cpu_adam_bf16_copy():
    n = 1024
    rng = np.random.RandomState(2)
    p = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    opt = DeepSpeedCPUAdam(n, lr=1e-3)
    out16 = np.zeros(n, np.uint16)
    opt.step(p, g, params_bf16_out=out16)
    expect = np.asarray(jnp.asarray(p, jnp.bfloat16)).view(np.uint16)
    np.testing.assert_array_equal(out16, expect)


def test_cpu_adam_state_roundtrip():
    n = 128
    rng = np.random.RandomState(3)
    p = rng.randn(n).astype(np.float32)
    a = DeepSpeedCPUAdam(n, lr=1e-3)
    for _ in range(3):
        a.step(p, rng.randn(n).astype(np.float32))
    sd = {k: np.array(v) if isinstance(v, np.ndarray) else v
          for k, v in a.state_dict().items()}
    b = DeepSpeedCPUAdam(n, lr=1e-3)
    b.load_state_dict(sd)
    g = rng.randn(n).astype(np.float32)
    pa, pb = p.copy(), p.copy()
    a.step(pa, g)
    b.step(pb, g)
    np.testing.assert_allclose(pa, pb, atol=1e-6)


def _gpt2_engine(offload, lr=1e-2, **cfg_over):
    cfg = tiny_gpt2_config(n_layer=2, dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(0, 256, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    ds = {"train_batch_size": 8,
          "bf16": {"enabled": True},
          "zero_optimization": {"stage": 2, "cpu_offload": offload},
          "optimizer": {"type": "AdamW",
                        "params": {"lr": lr, "weight_decay": 0.0}}}
    ds.update(cfg_over)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=ds)
    return engine, ids


def test_offload_engine_matches_device_engine():
    """ZeRO-Offload must track the on-device optimizer trajectory
    (same AdamW math, host vs device execution)."""
    e_dev, ids = _gpt2_engine(offload=False)
    e_off, _ = _gpt2_engine(offload=True)
    for i in range(5):
        ld = float(jax.device_get(
            e_dev.train_batch(batch={"input_ids": ids[None]})))
        lo = float(jax.device_get(
            e_off.train_batch(batch={"input_ids": ids[None]})))
        # bf16 recast + host fp32 step accumulate small differences
        assert abs(ld - lo) < 0.05, (i, ld, lo)


def test_offload_checkpoint_roundtrip(tmp_ckpt_dir):
    engine, ids = _gpt2_engine(offload=True)
    for _ in range(3):
        engine.train_batch(batch={"input_ids": ids[None]})
    master_before = engine._host_master.copy()
    engine.save_checkpoint(tmp_ckpt_dir)
    engine.wait_for_checkpoint()
    engine2, _ = _gpt2_engine(offload=True)
    engine2.load_checkpoint(tmp_ckpt_dir)
    np.testing.assert_allclose(engine2._host_master, master_before)
    loss = engine2.train_batch(batch={"input_ids": ids[None]})
    assert np.isfinite(float(jax.device_get(loss)))


def test_step_chunk_matches_full_step():
    """begin_step + step_chunk over uneven chunks must be bit-identical
    to one full step (explicit-step bias correction shared by chunks)."""
    n = 1000
    rng = np.random.RandomState(7)
    p_full = rng.randn(n).astype(np.float32)
    p_chunk = p_full.copy()
    a = DeepSpeedCPUAdam(n, lr=3e-3, weight_decay=0.01)
    b = DeepSpeedCPUAdam(n, lr=3e-3, weight_decay=0.01)
    bounds = [(0, 100), (100, 637), (637, 1000)]
    for step in range(4):
        g = rng.randn(n).astype(np.float32)
        a.step(p_full, g)
        b.begin_step()
        for lo, hi in bounds:
            b.step_chunk(lo, hi, p_chunk[lo:hi], g[lo:hi])
        np.testing.assert_allclose(p_full, p_chunk, atol=1e-7)
    np.testing.assert_allclose(a.exp_avg, b.exp_avg, atol=1e-7)
    np.testing.assert_allclose(a.exp_avg_sq, b.exp_avg_sq, atol=1e-7)
    assert a.step_count == b.step_count == 4


def test_step_chunk_bf16_out():
    n = 256
    rng = np.random.RandomState(8)
    p = rng.randn(n).astype(np.float32)
    a = DeepSpeedCPUAdam(n, lr=1e-3)
    a.begin_step()
    out = np.empty(n, np.uint16)
    a.step_chunk(0, n, p, rng.randn(n).astype(np.float32),
                 params_bf16_out=out)
    back = np.asarray(jnp.asarray(out).view(jnp.bfloat16), np.float32)
    np.testing.assert_allclose(back, p, rtol=1e-2, atol=1e-2)


def test_offload_multi_chunk_pipeline_matches_device(monkeypatch):
    """Force the chunked D2H/compute/H2D pipeline (tiny chunk size ->
    many chunks) and verify the trajectory still matches the on-device
    engine (the overlap must be a pure scheduling change)."""
    from deepspeed_tpu.runtime.zero.offload import ZeroOffloadMixin
    monkeypatch.setattr(ZeroOffloadMixin, "_OFFLOAD_CHUNK_ELEMS", 1024)
    e_dev, ids = _gpt2_engine(offload=False)
    e_off, _ = _gpt2_engine(offload=True)
    assert len(e_off._offload_bounds(
        e_off._host_master.size)) > 1, "chunking not engaged"
    for i in range(4):
        ld = float(jax.device_get(
            e_dev.train_batch(batch={"input_ids": ids[None]})))
        lo = float(jax.device_get(
            e_off.train_batch(batch={"input_ids": ids[None]})))
        assert abs(ld - lo) < 0.05, (i, ld, lo)


def test_cpu_adam_perf_vs_numpy():
    """Optimizer perf microbenchmark (counterpart of ref
    tests/perf/adam_test.py): the native OpenMP/vectorized kernel must
    beat the numpy reference implementation clearly (round-1 measured
    ~11x; require >=2x to stay robust on a loaded CI host). Skips when
    the native build is unavailable."""
    import time
    n = 2_000_000
    rng = np.random.RandomState(2)
    p0 = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    try:
        nat = DeepSpeedCPUAdam(n, lr=1e-3, use_native=True)
        if not getattr(nat, "native", True):
            pytest.skip("native cpu_adam unavailable")
    except Exception as e:
        pytest.skip(f"native cpu_adam unavailable: {e}")
    ref = DeepSpeedCPUAdam(n, lr=1e-3, use_native=False)
    pn, pr = p0.copy(), p0.copy()

    def best_of(fn, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    nat.step(pn, g)   # warmup (JIT build, page-in)
    ref.step(pr, g)
    t_nat = best_of(lambda: nat.step(pn, g))
    t_ref = best_of(lambda: ref.step(pr, g))
    assert t_ref / t_nat >= 2.0, (
        f"native {t_nat*1e3:.1f} ms vs numpy {t_ref*1e3:.1f} ms "
        f"({t_ref/t_nat:.1f}x)")
