"""Model-level convergence/regression harness (parity target: ref
`tests/model/Megatron_GPT2/run_func_test.py` — run REAL example scripts
under real configs as subprocesses, grep the loss trajectory, and
compare (a) across configs and (b) against checked-in baseline curves).

Runs `examples/gpt2_train.py` / `examples/bert_pretrain.py` on the
8-device virtual CPU mesh (DS_TPU_PLATFORM=cpu). Baselines live in
`tests/model/baselines/*.json`; regenerate with
`python tests/model/test_model_regression.py --regen` after an
intentional numerics change.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BASELINE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baselines")
STEPS = 60

GPT2_BASE_CONFIG = {
    "train_micro_batch_size_per_gpu": 8,
    "gradient_accumulation_steps": 2,
    "steps_per_print": 50,
    "gradient_clipping": 1.0,
    "optimizer": {"type": "AdamW",
                  "params": {"lr": 3e-3, "betas": [0.9, 0.95],
                             "weight_decay": 0.01}},
    "scheduler": {"type": "WarmupLR",
                  "params": {"warmup_min_lr": 0.0,
                             "warmup_max_lr": 3e-3,
                             "warmup_num_steps": 10}},
}

BERT_BASE_CONFIG = {
    "train_micro_batch_size_per_gpu": 8,
    "gradient_accumulation_steps": 2,
    "steps_per_print": 50,
    "optimizer": {"type": "Lamb",
                  "params": {"lr": 2e-3}},
}


def run_example(script, model, config, steps=STEPS, seq_len=64, seed=42,
                tmp_dir="/tmp"):
    """Run an example script as a subprocess; return its loss
    trajectory (list of floats, one per step)."""
    cfg_path = os.path.join(tmp_dir, f"ds_config_{model}.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    env = dict(os.environ)
    env["DS_TPU_PLATFORM"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8")
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   env.get("JAX_TEST_COMPILATION_CACHE",
                           os.path.join(REPO, ".jax_test_cache")))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script),
         "--model", model, "--seq-len", str(seq_len),
         "--steps", str(steps), "--seed", str(seed),
         "--num-batches", "2",   # fixed learnable set -> loss must fall
         "--deepspeed", "--deepspeed_config", cfg_path],
        capture_output=True, text=True, timeout=1200, env=env, cwd=REPO)
    m = re.search(r"LM loss trajectory: ([\d .eE+-]+)", proc.stdout)
    assert proc.returncode == 0 and m, \
        f"{script} failed\nstdout: {proc.stdout[-1500:]}\n" \
        f"stderr: {proc.stderr[-1500:]}"
    return [float(x) for x in m.group(1).split()]


def _check_against_baseline(name, traj):
    path = os.path.join(BASELINE_DIR, name + ".json")
    with open(path) as f:
        base = json.load(f)["trajectory"]
    assert len(traj) == len(base), (len(traj), len(base))
    # convergence shape: start equal (same init), end within tolerance,
    # running means track (pointwise noise from dropout-free synthetic
    # data is tiny; tolerances still leave room for XLA version drift)
    np.testing.assert_allclose(traj[0], base[0], rtol=1e-3)
    assert abs(traj[-1] - base[-1]) < max(0.1 * abs(base[-1]), 0.15), \
        (traj[-1], base[-1])
    m_new = np.mean(traj[len(traj) // 2:])
    m_base = np.mean(base[len(base) // 2:])
    assert abs(m_new - m_base) < max(0.1 * abs(m_base), 0.15), \
        (m_new, m_base)


@pytest.mark.slow
def test_gpt2_func_zero0_converges_and_matches_baseline(tmp_path):
    cfg = dict(GPT2_BASE_CONFIG)
    cfg["zero_optimization"] = {"stage": 0}
    traj = run_example("gpt2_train.py", "gpt2-tiny", cfg,
                       tmp_dir=str(tmp_path))
    assert traj[-1] < traj[0] * 0.7, (traj[0], traj[-1])
    _check_against_baseline("gpt2_tiny_zero0", traj)


@pytest.mark.slow
def test_gpt2_func_zero2_bf16_matches_zero0_fp32_shape(tmp_path):
    """ZeRO-2 + bf16 must follow the same loss curve as ZeRO-0 fp32 at
    model level (bf16 rounding gives pointwise drift; the curve SHAPE
    and endpoint must agree) — the reference's cross-config check
    (run_func_test.py compares ZeRO configs against megatron)."""
    cfg0 = dict(GPT2_BASE_CONFIG)
    cfg0["zero_optimization"] = {"stage": 0}
    t0 = run_example("gpt2_train.py", "gpt2-tiny", cfg0,
                     tmp_dir=str(tmp_path))
    cfg2 = dict(GPT2_BASE_CONFIG)
    cfg2["zero_optimization"] = {"stage": 2}
    cfg2["bf16"] = {"enabled": True}
    t2 = run_example("gpt2_train.py", "gpt2-tiny", cfg2,
                     tmp_dir=str(tmp_path))
    np.testing.assert_allclose(t0[0], t2[0], rtol=5e-2)
    assert abs(t0[-1] - t2[-1]) < max(0.15 * abs(t0[-1]), 0.2), \
        (t0[-1], t2[-1])


@pytest.mark.slow
def test_gpt2_func_bf16_masterless_sr(tmp_path):
    """The bf16 master-less (stochastic rounding) flagship config must
    converge at model level too."""
    cfg = dict(GPT2_BASE_CONFIG)
    cfg["zero_optimization"] = {"stage": 2}
    cfg["bf16"] = {"enabled": True, "master_weights": False}
    traj = run_example("gpt2_train.py", "gpt2-tiny", cfg,
                       tmp_dir=str(tmp_path))
    assert traj[-1] < traj[0] * 0.7, (traj[0], traj[-1])
    _check_against_baseline("gpt2_tiny_sr", traj)


@pytest.mark.slow
def test_bert_func_converges_and_matches_baseline(tmp_path):
    traj = run_example("bert_pretrain.py", "bert-tiny",
                       dict(BERT_BASE_CONFIG), tmp_dir=str(tmp_path))
    assert traj[-1] < traj[0] * 0.9, (traj[0], traj[-1])
    _check_against_baseline("bert_tiny_lamb", traj)


def _regen():
    os.makedirs(BASELINE_DIR, exist_ok=True)
    jobs = []
    cfg = dict(GPT2_BASE_CONFIG)
    cfg["zero_optimization"] = {"stage": 0}
    jobs.append(("gpt2_tiny_zero0", "gpt2_train.py", "gpt2-tiny", cfg))
    cfg = dict(GPT2_BASE_CONFIG)
    cfg["zero_optimization"] = {"stage": 2}
    cfg["bf16"] = {"enabled": True, "master_weights": False}
    jobs.append(("gpt2_tiny_sr", "gpt2_train.py", "gpt2-tiny", cfg))
    jobs.append(("bert_tiny_lamb", "bert_pretrain.py", "bert-tiny",
                 dict(BERT_BASE_CONFIG)))
    for name, script, model, config in jobs:
        traj = run_example(script, model, config)
        with open(os.path.join(BASELINE_DIR, name + ".json"), "w") as f:
            json.dump({"steps": len(traj), "trajectory": traj}, f)
        print(name, "->", traj[0], "...", traj[-1])


if __name__ == "__main__" and "--regen" in sys.argv:
    _regen()
