"""The driver runs `python bench.py` at the end of every round and
records its single JSON line — a bench.py regression silently costs the
round's perf record. This smoke test runs the CPU path (flagship +
TPU-only extras are gated on the backend) in a subprocess and checks
the output contract."""

import json
import pytest
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_proc(*argv, timeout=120, devices=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    if devices is not None:
        flags.append(f"--xla_force_host_platform_device_count={devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import runpy; runpy.run_path("
            f"{os.path.join(REPO, 'bench.py')!r}, run_name='__main__')")
    return subprocess.run(
        [sys.executable, "-c", code] + list(argv),
        capture_output=True, text=True, env=env, cwd=REPO,
        timeout=timeout)


def test_bench_list_prints_legs():
    proc = _bench_proc("--list")
    assert proc.returncode == 0, proc.stderr[-500:]
    legs = proc.stdout.split()
    assert "async_dispatch" in legs and "zero_offload_wire" in legs
    assert "async_checkpoint" in legs
    assert "fused_hot_loop" in legs and "pipe_interleave" in legs
    assert "monitor_overhead" in legs and "numerics_overhead" in legs
    assert "memory_ledger" in legs and "zero3_overlap" in legs
    assert "elastic_recovery" in legs
    assert "serving_throughput" in legs
    assert "serving_observability" in legs
    assert "speculative_decode" in legs
    assert "moe_vs_dense" in legs
    assert "comm_overlap" in legs
    assert "moe_dispatch_kernel" in legs


def test_bench_list_and_only_error_agree_with_the_registry():
    """`--list` and the unknown-`--only` error message must both be
    generated from BENCH_LEGS — the audit (ISSUE 12 satellite) that a
    new leg cannot silently drop out of either surface. Asserted as
    set equality between the two outputs AND against the registry
    itself, so the next added leg is covered automatically."""
    list_proc = _bench_proc("--list")
    assert list_proc.returncode == 0, list_proc.stderr[-500:]
    listed = set(list_proc.stdout.split())

    err_proc = _bench_proc("--only", "definitely_not_a_leg")
    assert err_proc.returncode != 0
    # the error names every valid leg: "valid legs: a, b, c"
    tail = err_proc.stderr.split("valid legs:", 1)
    assert len(tail) == 2, err_proc.stderr[-500:]
    named = {t.strip() for t in tail[1].strip().split(",")}
    assert named == listed, (named ^ listed)

    import runpy
    mod = runpy.run_path(os.path.join(REPO, "bench.py"))
    registry = set(mod["BENCH_LEGS"])
    assert listed == registry, (listed ^ registry)
    # the legs added since PR 5 (the audited five + the serving legs)
    for leg in ("fused_hot_loop", "pipe_interleave",
                "numerics_overhead", "memory_ledger", "zero3_overlap",
                "elastic_recovery", "serving_throughput",
                "serving_observability", "moe_vs_dense",
                "comm_overlap", "moe_dispatch_kernel",
                "speculative_decode"):
        assert leg in registry, leg


def test_bench_only_fused_hot_loop_leg():
    """The fused-epilogue hot-loop A/B (ISSUE 6) via `--only`: fused
    kernels + per-fusion remat vs unfused + full remat, with the parity
    contract asserted hard (fp32 <= 1e-5, bf16 <= 1e-2) and the
    speedup's presence/sign as the smoke contract (the >=1.05x
    acceptance number is read off the recorded bench line)."""
    proc = _bench_proc("--only", "fused_hot_loop", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "fused_hot_loop"
    result = d["result"]
    assert "error" not in result, result
    assert result["parity_ok"] is True, result
    assert result["grad_rel_diff_fp32"] <= 1e-5
    assert result["loss_abs_diff_bf16"] <= 1e-2
    assert result["fused_fwd_bwd_ms"] > 0
    assert result["unfused_fwd_bwd_ms"] > 0
    # both arms' elementwise-sink tables recorded (the roofline guard)
    assert "unfused" in result["top_non_matmul_sinks"]
    assert "fused" in result["top_non_matmul_sinks"]


def test_bench_only_pipe_interleave_leg():
    """The interleaved 1F1B A/B (ISSUE 6) via `--only`: bit-exact loss
    parity is a hard assert; the analytic bubble reduction at p=4, m=8,
    v=2 is schedule math and must hold on any machine; the wall-clock
    ratio's presence is the smoke contract."""
    proc = _bench_proc("--only", "pipe_interleave", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "pipe_interleave"
    result = d["result"]
    assert "error" not in result, result
    assert result["interp_used"] is True
    assert result["loss_parity_diff"] == 0.0
    assert result["loss_parity_diff_after_steps"] == 0.0
    # schedule math: v=2 shrinks both the bubble and the stage-time wall
    assert result["v2_analytic"]["bubble_fraction"] < \
        result["v1_analytic"]["bubble_fraction"]
    assert result["analytic_speedup"] > 1.0
    assert result["plain_1f1b_ms"] > 0 and result["interleaved_ms"] > 0


def test_bench_only_async_checkpoint_leg():
    """The zero-stall checkpointing A/B (ISSUE 3) must run end-to-end
    via `--only` and emit its contract keys; the bit-identical checks
    are hard assertions — a byte of divergence between an async-saved
    and a sync-saved checkpoint is a correctness bug, not noise."""
    proc = _bench_proc("--only", "async_checkpoint", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    d = json.loads(line)
    assert d["leg"] == "async_checkpoint"
    result = d["result"]
    assert "error" not in result, result
    for leg in ("sync", "async"):
        for key in ("steps_per_sec_baseline", "steps_per_sec_with_save",
                    "train_loop_stall_ms", "save_call_blocked_ms"):
            assert key in result[leg], (leg, key, result)
    assert result["bit_identical"] is True
    assert result["offload_wire_bit_identical"] is True
    # the timing ratio is environment-dependent; its presence and sign
    # are the smoke contract (the >=5x acceptance number is read off
    # the recorded TPU/CI bench line, not asserted on a shared box)
    assert result["stall_reduction"] > 0
    assert result["save_call_speedup"] > 1


def test_bench_only_monitor_overhead_leg():
    """The telemetry overhead A/B (ISSUE 5) must run end-to-end via
    `--only`: monitor-on vs monitor-off interleaved windows, the <3%
    overhead contract, and the shared snapshot() schema. This leg is
    load-sensitive — it flaked on the UNMODIFIED tree under concurrent
    load at PR-13 seed — so the smoke pins the ISSUE-14 hardening
    (every paired window is the MEDIAN of N=3 repetitions, and the
    verdict only ever reads medians) and asserts the recorded
    `regressed` contract flag against a catastrophic bound only (the
    numerics_overhead precedent for environment-dependent ratios on a
    shared box; the <3% number is read off the recorded bench line)."""
    proc = _bench_proc("--only", "monitor_overhead", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    d = json.loads(line)
    assert d["leg"] == "monitor_overhead"
    result = d["result"]
    assert "error" not in result, result
    for leg in ("off", "on"):
        assert "steps_per_sec" in result[leg]
        assert "step_ms" in result[leg]
    assert "overhead_pct" in result
    # the median-of-N-repetitions discipline is pinned: the verdict is
    # computed over per-window MEDIANS, never a raw window
    assert result["window_repetitions"] == 3
    assert result["windows_measured"] >= 6
    # the <3% contract lives in the recorded flag; the smoke asserts
    # only a catastrophic-regression bound
    assert "regressed" in result
    assert result["overhead_pct"] < 25.0, result
    # bench extras share the training telemetry schema via snapshot()
    snap = result["snapshot"]
    for key in ("loss", "lr", "samples_per_sec", "tokens",
                "overflow_count"):
        assert key in snap
    # the JSONL sink recorded fences during the measured windows
    assert result["jsonl_metric_events"] > 0


def test_bench_only_numerics_overhead_leg():
    """The numerics-health overhead A/B (ISSUE 7) must run end-to-end
    via `--only`: monitor-on both legs, numerics off vs on, the <3%
    overhead contract, and proof the numerics event stream flowed."""
    proc = _bench_proc("--only", "numerics_overhead", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    d = json.loads(line)
    assert d["leg"] == "numerics_overhead"
    result = d["result"]
    assert "error" not in result, result
    for leg in ("off", "on"):
        assert "steps_per_sec" in result[leg]
        assert "step_ms" in result[leg]
    # the <3% contract lives in the leg's recorded `regressed` flag
    # (read off the recorded bench line, like async_checkpoint's
    # ratios — not asserted on a shared box): paired-window noise here
    # runs to +/-10% per window while an interleaved raw-jitted-step
    # A/B measures the accumulators at ~0, so the smoke asserts only a
    # catastrophic-regression bound on the ratio
    assert "regressed" in result
    assert result["overhead_pct"] < 25.0, result
    assert result["numerics_groups"] > 0
    assert result["jsonl_numerics_events"] > 0
    # a healthy run must not claim a NaN source
    assert result["first_nonfinite"] is None


def test_bench_only_memory_ledger_leg():
    """The memory-ledger plan-vs-measured leg (ISSUE 8) must run
    end-to-end via `--only`: the 13B abstract plan agrees with the
    closed form, the executed scaled run scores plan vs ledger vs
    REAL per-device shard bytes, memory events flowed, and the
    overhead A/B recorded its <3% contract flag (asserted here only
    against a catastrophic bound — the numerics_overhead precedent
    for shared-box noise)."""
    proc = _bench_proc("--only", "memory_ledger", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "memory_ledger"
    result = d["result"]
    assert "error" not in result, result
    plan13 = result["plan_13b"]
    assert plan13["params_b"] > 12
    assert abs(plan13["vs_closed_form_pct"]) < 5.0
    executed = result["executed"]
    for scored in ("plan_vs_ledger", "plan_vs_measured"):
        for comp in ("params", "opt_state"):
            row = executed[scored][comp]
            assert row["planned_bytes"] > 0
            assert abs(row["delta_pct"]) < 15.0, (scored, comp, row)
    assert executed["memory_events"] > 0
    assert executed["ledger_event_plan"] is True
    assert "regressed" in result
    assert result["overhead_pct"] < 25.0, result


def test_bench_only_zero3_overlap_leg():
    """The ZeRO-3 overlapped-runtime A/B (ISSUE 9) via `--only`: the
    windowed gather/release schedule vs the naive up-front gather on
    the same stage-3 model. The MEMORY contract is asserted hard (the
    leg itself asserts the ledger window bound; re-checked here):
    overlapped live gathered bytes == (prefetch_layers + 1) layers,
    naive == the whole stack — and loss parity between the arms. The
    step-time ratio records `overlap_faster`, asserted here only
    against a catastrophic bound (the numerics_overhead precedent for
    environment-dependent ratios on a shared box); the full leg run
    measures ~1.2-1.4x in favor of overlap on this CPU mesh."""
    proc = _bench_proc("--only", "zero3_overlap", timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "zero3_overlap"
    result = d["result"]
    assert "error" not in result, result
    assert result["parity_ok"], result
    assert result["window_bound_ok"], result
    assert result["window_layers"]["overlap"] == 2
    assert result["window_layers"]["naive"] > 2
    assert result["naive_gathered_mb"] > 2 * result["overlap_gathered_mb"]
    # catastrophic-regression bound only: the schedule must not make
    # the step dramatically slower than gather-everything-up-front
    assert result["overlap_speedup"] > 0.7, result


def test_bench_only_elastic_recovery_leg():
    """The elastic chaos leg (ISSUE 10) via `--only`, on an 8-device
    virtual mesh: a SIGKILL'd sentinel host must be detected, the mesh
    re-formed on the survivors (world 8 -> 4 with hosts=2), training
    resumed from the last committed tag with the replayed-step loss
    continuity assert exercised, and capacity return must grow back to
    8 at a checkpoint boundary. The detection->resume wall time is the
    leg's recorded metric; only its presence and a catastrophic bound
    are asserted here (shared-box timing precedent)."""
    proc = _bench_proc("--only", "elastic_recovery", timeout=540,
                       devices=8)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "elastic_recovery"
    result = d["result"]
    assert "error" not in result, result
    assert result["cause"] == "host_lost"
    assert result["world_before"] == 8 and result["world_after"] == 4
    assert result["resumed_from_tag"] == "global_step2"
    assert result["replayed_steps"] >= 1
    assert result["loss_continuity_checked"] is True
    assert result["loss_continuity_ok"] is True
    assert result["losses_finite"] is True
    # detection->resume is the headline: present, positive, and not
    # catastrophically slow even on a loaded shared box
    assert 0 < result["detect_to_resume_ms"] < 120_000
    assert result["kill_to_caught_up_ms"] > 0
    # the re-planned ZeRO partition for the smaller world was recorded
    assert result["zero_plan_bytes_after"]["opt_state"] > 0
    # scale-up restored the original device count at a boundary
    assert result["grow"]["world_restored"] == 8
    assert result["grow"]["at_checkpoint_boundary"] is True


def test_bench_only_serving_throughput_leg():
    """The serving A/B (ISSUE 12) via `--only` on the 8-device virtual
    mesh: continuous batching must clear the >= 2x acceptance bar over
    request-at-a-time serving under the same Poisson arrival stream
    (the advantage is structural — 8 slots decode for the price of
    one step — so unlike raw step-time ratios it holds on a loaded
    shared box), decode-logits parity vs the training forward is
    asserted BIT-exact inside the leg (fp32), the `kv_cache` ledger
    category must equal independent page-pool arithmetic exactly, and
    the int8 weight-quant A/B records its pinned tolerance."""
    proc = _bench_proc("--only", "serving_throughput", timeout=540,
                       devices=8)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "serving_throughput"
    result = d["result"]
    assert "error" not in result, result
    # the correctness contracts are hard asserts
    assert result["parity_bitexact_fp32"] is True
    assert result["kv_ledger_exact"] is True
    assert result["int8_logits_maxdiff"] < 2e-2
    assert result["int8_greedy_match"] is True
    # both legs served every request and recorded the latency tails
    for leg in ("sequential", "continuous"):
        assert result[leg]["requests"] == result["requests"]
        assert result[leg]["tokens_per_sec"] > 0
        assert result[leg]["p99_token_ms"] >= result[leg]["p50_token_ms"]
    assert result["devices"] == 8
    assert result["tokens_per_sec_per_chip"] > 0
    # the acceptance bar: continuous batching >= 2x tokens/s
    assert result["continuous_vs_sequential_speedup"] >= 2.0, result


def test_bench_only_serving_observability_leg():
    """The serving-observability A/B (ISSUE 14) via `--only` on the
    8-device virtual mesh: tracker on vs off with the monitor enabled
    in both legs. The deterministic contracts are asserted INSIDE the
    leg (tracker p50/p99 within one histogram bucket of the
    independently computed request latencies; per-slot trace tracks +
    counter tracks + a working --serving summary), so the smoke
    asserts the mechanism and a catastrophic overhead bound only —
    the <3% contract lives in the recorded `regressed` flag (the
    numerics_overhead precedent)."""
    proc = _bench_proc("--only", "serving_observability", timeout=540,
                       devices=8)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "serving_observability"
    result = d["result"]
    assert "error" not in result, result
    # the fidelity contracts (hard-asserted in-leg; re-checked here)
    for name in ("ttft_p50", "ttft_p99", "token_p50", "token_p99"):
        assert result[f"{name}_agree"] is True, (name, result)
        assert result[f"{name}_ms"] > 0
    # the serving timeline exported: per-slot tracks + counter tracks
    # + the --serving summary over >= one full request set
    assert result["slot_tracks"] >= 1
    assert result["counter_tracks_ok"] is True
    assert result["summary_serving_ok"] is True, result
    assert result["summary_requests"] >= result["requests"]
    assert result["jsonl_serving_slo_events"] > 0
    # the <3% contract flag is recorded; catastrophic bound only here
    assert "regressed" in result
    assert result["overhead_pct"] < 25.0, result


@pytest.mark.slow
def test_bench_only_speculative_decode_leg():
    """The speculative-decoding serving A/B (ISSUE 18) via `--only`:
    draft-propose/flagship-verify vs vanilla decode on the same
    Poisson arrival stream at temperature 0. Losslessness is
    hard-asserted INSIDE the leg every trial (every request's token
    stream bit-identical to vanilla — re-checked here via the recorded
    flag); acceptance and tokens-per-verify are deterministic for the
    damped-blocks model, so they get real bounds. The wall-clock
    speedup is structural (~5 committed tokens per flagship verify at
    1/8-cost draft steps; measures ~1.9x on this CPU mesh) but still a
    timing ratio, so the smoke asserts a conservative floor under the
    shared-box precedent — the >= 1.5x acceptance number is read off
    the recorded bench line."""
    proc = _bench_proc("--only", "speculative_decode", timeout=540,
                       devices=8)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "speculative_decode"
    result = d["result"]
    assert "error" not in result, result
    # temp-0 losslessness: hard-asserted in-leg, recorded here
    assert result["temp0_bitexact"] is True, result
    # deterministic draft-quality numbers for the damped model: high
    # but NOT perfect acceptance, with the rollback path exercised
    assert 0.9 <= result["acceptance_rate"] < 1.0, result
    assert result["rollback_events"] > 0, result
    assert result["tokens_per_verify"] > 3.0, result
    assert result["drafted_tokens"] >= result["accepted_tokens"] > 0
    assert result["vanilla_tokens_per_sec"] > 0
    assert result["speculative_tokens_per_sec"] > 0
    assert "target_1_5x_met" in result
    # conservative shared-box floor; ~1.9x when the box is quiet
    assert result["speculative_speedup"] >= 1.2, result


def test_bench_only_quantized_matmul_leg():
    """The quantized-compute GEMM A/B (ISSUE 13) via `--only`: parity
    is hard-asserted INSIDE the leg (int8 GEMM vs f32 reference +
    engine loss trajectory), so the smoke asserts the mechanism and a
    catastrophic-regression bound only — the 1.15x speedup is an
    environment-dependent contract flag on this shared box (the
    numerics_overhead precedent)."""
    proc = _bench_proc("--only", "quantized_matmul", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "quantized_matmul"
    result = d["result"]
    assert "error" not in result, result
    assert result["parity_ok"] is True, result
    assert result["gemm_rel_err_vs_f32"] <= 0.05
    assert result["engine_loss_max_abs_dev"] <= 0.2
    assert result["bf16_gemm_ms"] > 0
    assert result["quantized_gemm_ms"] > 0
    assert "int8_faster" in result
    # catastrophic bound: the int8 family must never be WAY slower
    assert result["int8_speedup"] >= 0.5, result


def test_bench_only_autotune_flash_leg():
    """The flash block-size autotuner (ISSUE 13) via `--only`: the
    search must complete, the winner must be >= 1.0x vs the
    hand-picked defaults (never-slower by construction), and the
    persisted table must reload across a process restart with the
    traced entry point resolving the winning blocks."""
    proc = _bench_proc("--only", "autotune_flash", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "autotune_flash"
    result = d["result"]
    assert "error" not in result, result
    assert result["never_slower"] is True, result
    assert result["speedup_vs_default"] >= 1.0
    assert result["reloaded_across_restart"] is True
    assert result["candidates_tried"] >= 2
    assert len(result["winning_blocks"]) == 2


def test_bench_only_unknown_leg_fails_with_list():
    proc = _bench_proc("--only", "no_such_leg")
    assert proc.returncode != 0
    err = proc.stderr
    assert "no_such_leg" in err
    # the error must NAME the valid legs, not silently run nothing
    assert "async_dispatch" in err and "gpt2_350m" in err


def test_bench_emits_one_json_line():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    # the container's sitecustomize pins the TPU plugin at interpreter
    # startup regardless of JAX_PLATFORMS; override via jax.config
    # BEFORE the backend initializes (same recipe as __graft_entry__)
    code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import runpy; runpy.run_path("
            f"{os.path.join(REPO, 'bench.py')!r}, run_name='__main__')")
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    d = json.loads(line)
    for key in ("metric", "value", "unit", "mfu", "vs_baseline",
                "extras_path", "extra"):
        assert key in d, (key, line[:200])
    assert d["value"] > 0
    # the stdout line must stay COMPACT (log tails truncated the old
    # everything-inlined line into parsed:null) ...
    assert len(line) < 4096, len(line)
    # ... with the full per-leg extras in the artifacts file
    assert os.path.exists(d["extras_path"]), d["extras_path"]
    with open(d["extras_path"]) as f:
        full = json.load(f)
    try:
        plan = full["extra"]["gpt2_13b_zero3_memory_plan"]
        assert plan["params_b"] > 12 and plan["state_gb_per_device"] < 2
    finally:
        os.unlink(d["extras_path"])


@pytest.mark.slow
def test_bench_only_moe_dispatch_kernel_leg():
    """The fused MoE dispatch/combine vs einsum-pair A/B (ISSUE 16)
    via `--only`. The deterministic contracts are hard-asserted INSIDE
    the leg (float64-oracle fwd/grad parity <= 5e-7 covering both VJP
    chains, fused >= 1.15x over the einsum pair — an asymptotic-MAC
    gap, not a box-speed bet); the smoke re-checks the recorded flags
    and the output contract."""
    proc = _bench_proc("--only", "moe_dispatch_kernel", timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "moe_dispatch_kernel"
    result = d["result"]
    assert "error" not in result, result
    assert result["parity_ok"] is True, result
    assert result["fwd_parity_delta"] <= 5e-7
    assert result["grad_parity_delta"] <= 5e-7
    assert result["fused_speedup"] >= 1.15, result
    assert result["einsum_fwd_bwd_ms"] > 0
    assert result["fused_fwd_bwd_ms"] > 0


@pytest.mark.slow
def test_bench_only_comm_overlap_leg():
    """The communication/compute overlap A/B (ISSUE 16) via `--only`:
    the MoE dispatch/combine pair over a (data=4, expert=2) mesh and
    the windowed ring-attention ppermute chain over seq=8, each traced
    with the discipline on vs off. Bit-exact gradient parity is
    hard-asserted inside the leg (the fences are schedule-only
    identities); the wall-clock `overlap_faster` flag is recorded, not
    asserted — the virtual mesh serializes the collectives, so there
    is no latency to hide here (the zero3_overlap precedent)."""
    proc = _bench_proc("--only", "comm_overlap", timeout=540,
                       devices=8)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "comm_overlap"
    result = d["result"]
    assert "error" not in result, result
    for site in ("moe", "ring"):
        assert result[site]["bit_exact"] is True, result
        assert result[site]["overlap_ms"] > 0
        assert result[site]["baseline_ms"] > 0
        assert result[site]["speedup"] > 0
    assert result["inflight_bytes"] > 0
    assert isinstance(result["overlap_faster"], bool)


@pytest.mark.slow
def test_bench_only_moe_vs_dense_leg():
    """The MoE iso-step-FLOPs A/B (ISSUE 15) via `--only` on the
    8-device virtual mesh. The deterministic contracts are asserted
    INSIDE the leg (grouped-GEMM fwd/grad parity <= 1e-5 vs the
    unpacked per-expert-loop reference, dropless routing at
    cf >= 1.25 at production token counts, moe_dispatch ledger ==
    independent byte math, router-event load fractions summing to 1,
    the <= 1.3x step-time ratio at 8 experts); the smoke re-checks
    the recorded flags and the leg's output contract."""
    proc = _bench_proc("--only", "moe_vs_dense", timeout=540,
                       devices=8)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["leg"] == "moe_vs_dense"
    result = d["result"]
    assert "error" not in result, result
    assert result["parity_ok"] is True, result
    assert result["iso_flops_ok"] is True, result
    assert result["step_time_ratio"] <= 1.3, result
    assert result["dropless_at_8k_tokens"] is True
    assert result["param_multiplier"] > 2.0, result
    router = result["router"]
    assert router["num_experts"] == 8
    assert abs(sum(router["expert_load"]) - 1.0) < 1e-3
