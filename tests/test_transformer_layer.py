"""DeepSpeedTransformerLayer tests (parity target: ref
tests/unit/test_cuda_forward.py sweeps + memory-flag matrix in
docs/_tutorials/transformer_kernel.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)


def make_layer(**over):
    kw = dict(batch_size=2, max_seq_length=128, hidden_size=64,
              intermediate_size=256, heads=4, attn_dropout_ratio=0.0,
              hidden_dropout_ratio=0.0, num_hidden_layers=2,
              initializer_range=0.02, pre_layer_norm=True, training=True)
    kw.update(over)
    cfg = DeepSpeedTransformerConfig(**kw)
    return DeepSpeedTransformerLayer(cfg), cfg


def init_and_apply(layer, b=2, t=128, h=64, mask=None, seed=0,
                   deterministic=True):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(b, t, h), jnp.float32)
    params = layer.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)},
                        x, mask, deterministic)
    out = layer.apply(params, x, mask, deterministic,
                      rngs={"dropout": jax.random.PRNGKey(2)})
    return params, x, out


@pytest.mark.parametrize("pre_ln", [True, False])
def test_forward_shape_and_finite(pre_ln):
    layer, _ = make_layer(pre_layer_norm=pre_ln)
    _, x, out = init_and_apply(layer)
    assert out.shape == x.shape
    assert bool(jnp.all(jnp.isfinite(out)))


def test_attention_mask_changes_output():
    layer, _ = make_layer()
    params, x, out = init_and_apply(layer)
    # additive mask hiding the second half of the sequence
    mask = jnp.zeros((2, 1, 1, 128)).at[:, :, :, 64:].set(-1e9)
    out_masked = layer.apply(params, x, mask, True)
    assert not np.allclose(np.asarray(out), np.asarray(out_masked))


@pytest.mark.parametrize("flags", [
    dict(normalize_invertible=True),
    dict(gelu_checkpoint=True),
    dict(attn_dropout_checkpoint=True),
    dict(normalize_invertible=True, gelu_checkpoint=True,
         attn_dropout_checkpoint=True),
])
def test_memory_flags_preserve_numerics(flags):
    """The remat flags must not change forward values or gradients."""
    base_layer, _ = make_layer()
    remat_layer, _ = make_layer(**flags)
    params, x, out_base = init_and_apply(base_layer)
    out_remat = remat_layer.apply(params, x, None, True)
    np.testing.assert_allclose(np.asarray(out_base), np.asarray(out_remat),
                               atol=1e-5, rtol=1e-5)

    def loss(layer_, p):
        return jnp.sum(layer_.apply(p, x, None, True).astype(jnp.float32)**2)

    g_base = jax.grad(lambda p: loss(base_layer, p))(params)
    g_remat = jax.grad(lambda p: loss(remat_layer, p))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_base),
                    jax.tree_util.tree_leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_gradients_flow_to_all_params():
    layer, _ = make_layer()
    params, x, _ = init_and_apply(layer)

    def loss(p):
        return jnp.sum(layer.apply(p, x, None, True).astype(jnp.float32)**2)

    grads = jax.grad(loss)(params)
    for path, leaf in jax.tree_util.tree_leaves_with_path(grads):
        assert float(jnp.max(jnp.abs(leaf))) > 0, \
            f"zero gradient at {jax.tree_util.keystr(path)}"


def test_dropout_is_stochastic_in_training():
    layer, _ = make_layer(hidden_dropout_ratio=0.3, attn_dropout_ratio=0.1)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 128, 64), jnp.float32)
    params = layer.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(1)}, x, None, False)
    o1 = layer.apply(params, x, None, False,
                     rngs={"dropout": jax.random.PRNGKey(2)})
    o2 = layer.apply(params, x, None, False,
                     rngs={"dropout": jax.random.PRNGKey(3)})
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    # deterministic mode: no dropout, reproducible
    e1 = layer.apply(params, x, None, True)
    e2 = layer.apply(params, x, None, True)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
