"""bf16 master-less training (bf16 {"master_weights": false}): moments
in bf16, fp32 update math, stochastic-rounded param writes
(runtime/bf16_optimizer.py). Validates rounding unbiasedness, engine
integration (no master, bf16 opt state, loss descent), trajectory
parity against the fp32-master mixed-precision path, and bf16-state
checkpoint round-trip (the npz bf16 encoding in runtime/checkpoint.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, tiny_gpt2_config
from deepspeed_tpu.runtime.bf16_optimizer import (adamw_bf16,
                                                  stochastic_round_bf16)


def test_stochastic_round_unbiased():
    """E[sr(x)] == x for x strictly between two bf16 grid points, and
    sr only ever returns one of the two neighbours."""
    lo = jnp.bfloat16(1.0)
    hi = jnp.nextafter(jnp.bfloat16(1.0), jnp.bfloat16(2.0))
    frac = 0.25
    x = (np.float32(lo) * (1 - frac) + np.float32(hi) * frac)
    xs = jnp.full((20000,), x, jnp.float32)
    out = stochastic_round_bf16(xs, jax.random.PRNGKey(0))
    vals = np.unique(np.asarray(out, np.float32))
    assert set(vals) <= {np.float32(lo), np.float32(hi)}, vals
    p_hi = float((np.asarray(out, np.float32) == np.float32(hi)).mean())
    assert abs(p_hi - frac) < 0.02, p_hi
    mean = np.asarray(out, np.float32).mean()
    assert abs(mean - x) < (np.float32(hi) - np.float32(lo)) * 0.03


def test_stochastic_round_exact_and_specials():
    xs = jnp.asarray([1.0, -2.0, 0.0, np.inf, -np.inf, np.nan],
                     jnp.float32)
    out = np.asarray(stochastic_round_bf16(xs, jax.random.PRNGKey(1)),
                     np.float32)
    np.testing.assert_array_equal(out[:3], [1.0, -2.0, 0.0])
    assert np.isinf(out[3]) and out[3] > 0
    assert np.isinf(out[4]) and out[4] < 0
    assert np.isnan(out[5])


def test_adamw_bf16_states_are_bf16_and_math_matches_fp32():
    """One step of adamw_bf16 from zero moments must equal fp32 adamw
    exactly (zero moments encode exactly; first-step math is identical
    modulo the bf16 re-encode of the new moments)."""
    params = {"w": jnp.asarray([[0.5, -0.25], [1.0, 2.0]], jnp.bfloat16)}
    grads = {"w": jnp.asarray([[0.1, -0.2], [0.3, 0.4]], jnp.float32)}
    tx = adamw_bf16(learning_rate=1e-2, weight_decay=0.1)
    state = tx.init(params)
    assert state.inner_state.mu["w"].dtype == jnp.bfloat16
    assert state.inner_state.nu["w"].dtype == jnp.bfloat16
    updates, _ = tx.update(grads, state, params)

    import optax
    ref = optax.inject_hyperparams(optax.adamw)(
        learning_rate=1e-2, weight_decay=0.1)
    p32 = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32), params)
    rstate = ref.init(p32)
    rupdates, _ = ref.update(grads, rstate, p32)
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               np.asarray(rupdates["w"]),
                               rtol=1e-5, atol=1e-8)


def _gpt2_engine(master_weights, seed=0, lr=1e-3):
    cfg = tiny_gpt2_config(dtype=jnp.bfloat16)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(seed).randint(
        0, cfg.vocab_size, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(seed), {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_batch_size": 8,
            "steps_per_print": 1000,
            "bf16": {"enabled": True, "master_weights": master_weights},
            "optimizer": {"type": "AdamW", "params": {"lr": lr}},
        })
    return engine, ids


def test_engine_sr_mode_state_layout():
    engine, _ = _gpt2_engine(master_weights=False)
    assert engine.bf16_sr_mode
    assert engine.state.master is None
    mu = engine.state.opt_state.inner_state.mu
    for leaf in jax.tree_util.tree_leaves(mu):
        assert leaf.dtype == jnp.bfloat16
    for leaf in jax.tree_util.tree_leaves(engine.state.params):
        assert leaf.dtype == jnp.bfloat16


def test_engine_sr_mode_loss_descends():
    engine, ids = _gpt2_engine(master_weights=False, lr=5e-3)
    losses = []
    for i in range(25):
        loss = engine.train_batch(batch={"input_ids": ids[None]})
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.7, losses[::6]


def test_sr_trajectory_matches_fp32_master():
    """Loss trajectories of the master-less path and the fp32-master
    path must stay close over 20 steps (SR noise is below gradient
    scale at lr=1e-3 on a memorization task)."""
    e_sr, ids = _gpt2_engine(master_weights=False)
    e_ref, _ = _gpt2_engine(master_weights=True)
    l_sr, l_ref = [], []
    for i in range(20):
        l_sr.append(float(jax.device_get(
            e_sr.train_batch(batch={"input_ids": ids[None]}))))
        l_ref.append(float(jax.device_get(
            e_ref.train_batch(batch={"input_ids": ids[None]}))))
    # same starting loss, similar descent
    assert abs(l_sr[0] - l_ref[0]) < 0.05, (l_sr[0], l_ref[0])
    assert abs(l_sr[-1] - l_ref[-1]) < max(0.15 * abs(l_ref[-1]), 0.3), \
        (l_sr[-1], l_ref[-1])


def test_sr_mode_gas2_checkpoint_resume(tmp_path):
    """SR mode with gradient_accumulation_steps > 1 must survive a
    load_checkpoint: the accumulator rebuild used to reference the
    fp32 tree that only the master-weights branches bind (round-3
    advisor finding — NameError on resume)."""
    cfg = tiny_gpt2_config(dtype=jnp.bfloat16)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 8, 64)).astype(np.int32)

    def make():
        params = model.init(jax.random.PRNGKey(0),
                            {"input_ids": ids[0]})
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={
                "train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 2,
                "steps_per_print": 1000,
                "bf16": {"enabled": True, "master_weights": False},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            })
        return engine

    engine = make()
    for _ in range(2):
        engine.train_batch(batch={"input_ids": ids})
    engine.save_checkpoint(str(tmp_path), tag="t2")
    engine.wait_for_checkpoint()
    ref_next = float(jax.device_get(
        engine.train_batch(batch={"input_ids": ids})))

    e2 = make()
    e2.load_checkpoint(str(tmp_path), tag="t2")
    got_next = float(jax.device_get(
        e2.train_batch(batch={"input_ids": ids})))
    assert abs(got_next - ref_next) < 1e-2, (got_next, ref_next)


def test_sr_mode_pad_plan_on_dp_mesh():
    """On a multi-device data mesh, SR mode must build the ZeRO pad
    plan (round-3 advisor finding: moments silently replicated) and
    shard the bf16 moments for non-divisible leaves."""
    from deepspeed_tpu.runtime.mesh import build_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = build_mesh({"pipe": 1, "data": len(jax.devices()),
                       "model": 1})
    cfg = tiny_gpt2_config(dtype=jnp.bfloat16, n_embd=100, n_head=4)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mesh=mesh,
        config={
            "train_batch_size": 8,
            "steps_per_print": 1000,
            "bf16": {"enabled": True, "master_weights": False},
            "zero_optimization": {"stage": 2},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        })
    assert engine.bf16_sr_mode
    assert engine._zero_pad_plan, "expected padded leaves at n_embd=100"
    # every padded moment leaf must actually carry a data-axis sharding
    keys = sorted(engine._zero_pad_plan, key=len, reverse=True)
    flat, _ = jax.tree_util.tree_flatten_with_path(
        engine.state.opt_state.inner_state.mu)
    n_checked = 0
    for path, leaf in flat:
        ks = jax.tree_util.keystr(path)
        if any(ks.endswith(k) for k in keys):
            spec = leaf.sharding.spec
            assert any(ax == "data" for ax in spec if ax is not None), \
                (ks, spec)
            n_checked += 1
    assert n_checked, "pad-plan leaves not found in moment tree"
    # and a step still runs + descends
    l0 = float(jax.device_get(
        engine.train_batch(batch={"input_ids": ids[None]})))
    for _ in range(5):
        l = float(jax.device_get(
            engine.train_batch(batch={"input_ids": ids[None]})))
    assert np.isfinite(l) and l < l0 * 1.5


def test_sr_mode_checkpoint_roundtrip(tmp_path):
    """Save/load with bf16 params + bf16 moments: dtypes must survive
    the npz encoding and training must resume bit-compatibly."""
    engine, ids = _gpt2_engine(master_weights=False)
    for _ in range(3):
        engine.train_batch(batch={"input_ids": ids[None]})
    engine.save_checkpoint(str(tmp_path), tag="t3")
    engine.wait_for_checkpoint()
    ref_next = float(jax.device_get(
        engine.train_batch(batch={"input_ids": ids[None]})))

    e2, _ = _gpt2_engine(master_weights=False, seed=1)
    e2.load_checkpoint(str(tmp_path), tag="t3")
    mu = e2.state.opt_state.inner_state.mu
    for leaf in jax.tree_util.tree_leaves(mu):
        assert leaf.dtype == jnp.bfloat16
    got_next = float(jax.device_get(
        e2.train_batch(batch={"input_ids": ids[None]})))
    assert abs(got_next - ref_next) < 1e-2, (got_next, ref_next)
