"""Launcher CLI tests (parity target: ref tests/unit/test_run.py —
hostfile parsing + include/exclude filtering)."""

import os
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher.runner import (fetch_hostfile,
                                           parse_inclusion_exclusion,
                                           encode_world_info,
                                           decode_world_info,
                                           parse_args)


@pytest.fixture
def hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text(
        "worker-0 slots=4\n"
        "worker-1 slots=4\n"
        "# comment line\n"
        "\n"
        "worker-2 slots=2\n")
    return str(p)


def test_fetch_hostfile(hostfile):
    pool = fetch_hostfile(hostfile)
    assert pool == {"worker-0": 4, "worker-1": 4, "worker-2": 2}


def test_fetch_hostfile_missing():
    assert fetch_hostfile("/nonexistent/hostfile") is None


def test_fetch_hostfile_bad_format(tmp_path):
    p = tmp_path / "bad"
    p.write_text("worker-0 slots=four\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_fetch_hostfile_duplicate(tmp_path):
    p = tmp_path / "dup"
    p.write_text("worker-0 slots=4\nworker-0 slots=2\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_include_filter(hostfile):
    pool = fetch_hostfile(hostfile)
    active = parse_inclusion_exclusion(pool, "worker-0:0,2@worker-1", "")
    assert active == {"worker-0": [0, 2], "worker-1": [0, 1, 2, 3]}


def test_exclude_filter(hostfile):
    pool = fetch_hostfile(hostfile)
    active = parse_inclusion_exclusion(pool, "", "worker-1@worker-0:1")
    assert active == {"worker-0": [0, 2, 3], "worker-2": [0, 1]}


def test_include_exclude_mutually_exclusive(hostfile):
    pool = fetch_hostfile(hostfile)
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, "worker-0", "worker-1")


def test_unknown_host_rejected(hostfile):
    pool = fetch_hostfile(hostfile)
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, "worker-9", "")


def test_bad_slot_rejected(hostfile):
    pool = fetch_hostfile(hostfile)
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, "worker-2:0,3", "")


def test_world_info_roundtrip():
    info = {"worker-0": [0, 1], "worker-1": [0]}
    assert decode_world_info(encode_world_info(info)) == info


def test_parse_args_remainder():
    args = parse_args(["--num_nodes", "2", "train.py",
                       "--deepspeed", "--lr", "0.1"])
    assert args.user_script == "train.py"
    assert args.user_args == ["--deepspeed", "--lr", "0.1"]
    assert args.num_nodes == 2


def test_env_report_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.env_report"],
        capture_output=True, text=True, timeout=180, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "cpu_adam" in out.stdout
    assert "jax version" in out.stdout


def test_ds_elastic_cli(tmp_path):
    cfg = tmp_path / "ds.json"
    cfg.write_text("""{
      "elasticity": {"enabled": true, "max_train_batch_size": 2000,
                     "micro_batch_sizes": [2, 4],
                     "min_gpus": 1, "max_gpus": 64,
                     "min_time": 20, "version": 0.1}
    }""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.elasticity",
         "-c", str(cfg), "-w", "8"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "final_batch_size" in out.stdout


# ----------------------------------------------------------------------
# failure propagation (ref launch.py:128-167: any child failure kills
# the group and propagates the exit code)
# ----------------------------------------------------------------------
def _launch_cmd(world_info, script_path):
    import sys
    from deepspeed_tpu.launcher.runner import encode_world_info
    return [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
            "--world_info", encode_world_info(world_info),
            "--node_rank", "0", str(script_path)]


def test_launch_propagates_child_failure(tmp_path):
    import subprocess
    script = tmp_path / "fail.py"
    script.write_text("import sys; sys.exit(3)\n")
    proc = subprocess.run(
        _launch_cmd({"localhost": [0]}, script),
        capture_output=True, timeout=60)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-500:])


def test_launch_sigterm_terminates_child(tmp_path):
    """SIGTERM to the launcher must terminate the training child and
    exit 128+15 (ref launch.py:128-167 group kill)."""
    import os
    import signal
    import subprocess
    import time
    pid_file = tmp_path / "child.pid"
    script = tmp_path / "spin.py"
    script.write_text(
        "import os, time, pathlib\n"
        f"pathlib.Path({str(pid_file)!r}).write_text(str(os.getpid()))\n"
        "time.sleep(300)\n")
    proc = subprocess.Popen(_launch_cmd({"localhost": [0]}, script),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.time() + 30
    while not pid_file.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert pid_file.exists(), "child never started"
    child_pid = int(pid_file.read_text())
    proc.send_signal(signal.SIGTERM)
    assert proc.wait(timeout=30) == 128 + signal.SIGTERM
    # the child must be gone (allow a moment for termination delivery)
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            os.kill(child_pid, 0)
            time.sleep(0.1)
        except ProcessLookupError:
            break
    else:
        os.kill(child_pid, signal.SIGKILL)
        raise AssertionError("child survived launcher SIGTERM")
