"""Launcher CLI tests (parity target: ref tests/unit/test_run.py —
hostfile parsing + include/exclude filtering)."""

import os
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher.runner import (fetch_hostfile,
                                           parse_inclusion_exclusion,
                                           encode_world_info,
                                           decode_world_info,
                                           parse_args)


@pytest.fixture
def hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text(
        "worker-0 slots=4\n"
        "worker-1 slots=4\n"
        "# comment line\n"
        "\n"
        "worker-2 slots=2\n")
    return str(p)


def test_fetch_hostfile(hostfile):
    pool = fetch_hostfile(hostfile)
    assert pool == {"worker-0": 4, "worker-1": 4, "worker-2": 2}


def test_fetch_hostfile_missing():
    assert fetch_hostfile("/nonexistent/hostfile") is None


def test_fetch_hostfile_bad_format(tmp_path):
    p = tmp_path / "bad"
    p.write_text("worker-0 slots=four\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_fetch_hostfile_duplicate(tmp_path):
    p = tmp_path / "dup"
    p.write_text("worker-0 slots=4\nworker-0 slots=2\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_include_filter(hostfile):
    pool = fetch_hostfile(hostfile)
    active = parse_inclusion_exclusion(pool, "worker-0:0,2@worker-1", "")
    assert active == {"worker-0": [0, 2], "worker-1": [0, 1, 2, 3]}


def test_exclude_filter(hostfile):
    pool = fetch_hostfile(hostfile)
    active = parse_inclusion_exclusion(pool, "", "worker-1@worker-0:1")
    assert active == {"worker-0": [0, 2, 3], "worker-2": [0, 1]}


def test_include_exclude_mutually_exclusive(hostfile):
    pool = fetch_hostfile(hostfile)
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, "worker-0", "worker-1")


def test_unknown_host_rejected(hostfile):
    pool = fetch_hostfile(hostfile)
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, "worker-9", "")


def test_bad_slot_rejected(hostfile):
    pool = fetch_hostfile(hostfile)
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(pool, "worker-2:0,3", "")


def test_world_info_roundtrip():
    info = {"worker-0": [0, 1], "worker-1": [0]}
    assert decode_world_info(encode_world_info(info)) == info


def test_parse_args_remainder():
    args = parse_args(["--num_nodes", "2", "train.py",
                       "--deepspeed", "--lr", "0.1"])
    assert args.user_script == "train.py"
    assert args.user_args == ["--deepspeed", "--lr", "0.1"]
    assert args.num_nodes == 2


def test_env_report_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.env_report"],
        capture_output=True, text=True, timeout=180, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "cpu_adam" in out.stdout
    assert "jax version" in out.stdout


def test_ds_elastic_cli(tmp_path):
    cfg = tmp_path / "ds.json"
    cfg.write_text("""{
      "elasticity": {"enabled": true, "max_train_batch_size": 2000,
                     "micro_batch_sizes": [2, 4],
                     "min_gpus": 1, "max_gpus": 64,
                     "min_time": 20, "version": 0.1}
    }""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.elasticity",
         "-c", str(cfg), "-w", "8"],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "final_batch_size" in out.stdout
