"""FLOPS profiler tests (parity target: ref tests/unit/test_flops_profiler.py
asserts flops/params within tolerance of analytic values)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.profiling.flops_profiler import (FlopsProfiler,
                                                    get_model_profile)
from deepspeed_tpu.profiling.flops_profiler.profiler import num_params


def test_cost_analysis_matmul():
    prof = FlopsProfiler()
    n = 256
    x = jnp.ones((n, n), jnp.float32)
    prof.start_profile()
    cost = prof.profile_jitted(lambda a: a @ a, x)
    prof.stop_profile()
    # 2*n^3 flops for a matmul
    assert abs(cost["flops"] - 2 * n ** 3) / (2 * n ** 3) < 0.05


def test_get_model_profile_flax():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(64)(x)
            x = nn.relu(x)
            return nn.Dense(16)(x)

    flops, macs, params = get_model_profile(
        model=MLP(), args=(np.zeros((4, 32), np.float32),),
        print_profile=False, as_string=False)
    expect_params = 32 * 64 + 64 + 64 * 16 + 16
    assert params == expect_params
    # fwd flops >= the two matmuls
    assert flops >= 2 * 4 * 32 * 64 + 2 * 4 * 64 * 16


def test_engine_profile_step_runs(capsys):
    from deepspeed_tpu.models.gpt2 import tiny_gpt2_config, GPT2ForCausalLM
    cfg = tiny_gpt2_config(n_layer=2, dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(0, 256, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 2}})
    for _ in range(3):
        engine.train_batch(batch={"input_ids": ids[None]})
    # the profiler logged at step 2 without crashing; params counted
    assert num_params(engine.state.params) > 0
