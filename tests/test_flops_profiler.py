"""FLOPS profiler tests (parity target: ref tests/unit/test_flops_profiler.py
asserts flops/params within tolerance of analytic values)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.profiling.flops_profiler import (FlopsProfiler,
                                                    get_model_profile)
from deepspeed_tpu.profiling.flops_profiler.profiler import num_params


def test_cost_analysis_matmul():
    prof = FlopsProfiler()
    n = 256
    x = jnp.ones((n, n), jnp.float32)
    prof.start_profile()
    cost = prof.profile_jitted(lambda a: a @ a, x)
    prof.stop_profile()
    # 2*n^3 flops for a matmul
    assert abs(cost["flops"] - 2 * n ** 3) / (2 * n ** 3) < 0.05


def test_get_model_profile_flax():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(64)(x)
            x = nn.relu(x)
            return nn.Dense(16)(x)

    flops, macs, params = get_model_profile(
        model=MLP(), args=(np.zeros((4, 32), np.float32),),
        print_profile=False, as_string=False)
    expect_params = 32 * 64 + 64 + 64 * 16 + 16
    assert params == expect_params
    # fwd flops >= the two matmuls
    assert flops >= 2 * 4 * 32 * 64 + 2 * 4 * 64 * 16


def test_calls_re_splits_condition_and_body():
    # a while line lists its callees unbraced and comma-separated; the
    # unbraced alternative must stop at the name (a greedy capture would
    # swallow ", body" into the condition's name and drop the body)
    from deepspeed_tpu.profiling.flops_profiler.profiler import (_CALLS_RE,
                                                                 _TRIP_RE)
    line = ('%while.1 = (f32[8,8]{1,0}, s32[]) while(%tuple.1), '
            'condition=%cond_comp.2, body=%body_comp.3, '
            'backend_config={"known_trip_count":{"n":"7"}}')
    names = []
    for m in _CALLS_RE.finditer(line):
        got = m.group(1) if m.group(1) is not None else m.group(2)
        names += [t.strip().lstrip("%") for t in got.split(",") if t.strip()]
    assert names == ["cond_comp.2", "body_comp.3"]
    t = _TRIP_RE.search(line)
    assert t and int(t.group(1)) == 7
    # braced form (branch_computations) still splits on commas
    braced = ('%cond.9 = f32[] conditional(%p.0), '
              'branch_computations={%br_a.1, %br_b.2}')
    bnames = []
    for m in _CALLS_RE.finditer(braced):
        got = m.group(1) if m.group(1) is not None else m.group(2)
        bnames += [t.strip().lstrip("%") for t in got.split(",") if t.strip()]
    assert bnames == ["br_a.1", "br_b.2"]


def test_per_fusion_costs_scan_trip_count_multiplier():
    # a scanned matmul lowers to a while loop whose body XLA annotates
    # with known_trip_count; the body's dot/fusion rows must be scaled
    # by the trip count, not counted once
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        per_fusion_costs
    steps, n = 6, 64

    def fn(x, w):
        def body(carry, _):
            return jnp.tanh(carry @ w), None
        out, _ = jax.lax.scan(body, x, None, length=steps)
        return out

    x = jnp.ones((n, n), jnp.float32)
    w = jnp.ones((n, n), jnp.float32)
    rows = per_fusion_costs(fn, x, w, peak_flops=1e12, hbm_gbps=100.0)
    assert rows, "expected at least one fusion/dot row"
    per_step = 2 * n ** 3
    flop_rows = [r for r in rows if r["flops"] > 0]
    assert flop_rows, "expected a row with visible dot flops"
    total_flops = sum(r["flops"] for r in flop_rows)
    # all `steps` iterations must be accounted for (the unfixed parser
    # dropped the while body entirely, leaving at most one step's flops)
    assert total_flops >= steps * per_step * 0.9, \
        f"scan body under-counted: {total_flops} < {steps}*{per_step}"
    assert any(r["calls"] >= steps for r in flop_rows)


def test_per_fusion_costs_dus_carry_not_inflated():
    # stacking ys in a scan lowers to a loop fusion whose ROOT
    # dynamic-update-slices the stacked buffer (aliased in place, one
    # slice touched per trip); charging the full buffer x trip_count
    # would let this near-free carry update out-rank the real matmuls
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        per_fusion_costs
    steps, n = 8, 64

    def fn(x, w):
        def body(c, _):
            c = jnp.tanh(c @ w)
            return c, c
        return jax.lax.scan(body, x, None, length=steps)

    x = jnp.ones((n, n), jnp.float32)
    w = jnp.ones((n, n), jnp.float32)
    rows = per_fusion_costs(fn, x, w, peak_flops=1e12, hbm_gbps=100.0)
    stack_bytes = steps * n * n * 4
    for r in rows:
        if r["flops"]:
            continue
        # flopless loop fusions (the ys-stacking DUS) must stay at
        # slice-traffic scale: well under a few x the stacked buffer,
        # nowhere near trip_count x full-buffer (= steps * stack_bytes)
        assert r["bytes"] <= 4 * stack_bytes, \
            f"DUS fusion bytes inflated: {r}"


def test_engine_profile_step_runs(capsys):
    from deepspeed_tpu.models.gpt2 import tiny_gpt2_config, GPT2ForCausalLM
    cfg = tiny_gpt2_config(n_layer=2, dropout=0.0)
    model = GPT2ForCausalLM(cfg)
    ids = np.random.RandomState(0).randint(0, 256, (8, 64)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 2}})
    for _ in range(3):
        engine.train_batch(batch={"input_ids": ids[None]})
    # the profiler logged at step 2 without crashing; params counted
    assert num_params(engine.state.params) > 0


def test_custom_call_kernel_labeling():
    """Pallas custom-calls must be attributable by kernel name in the
    per-fusion table, not an opaque "custom-call" (ISSUE 6 satellite).
    TPU lowering cannot run on CPU CI, so the labeling logic is pinned
    on representative HLO text through the same text-level path
    per_fusion_costs uses."""
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        _custom_call_label, per_fusion_costs_from_text)
    line = ('%custom-call.7 = f32[128,256]{1,0} custom-call('
            'f32[128,256]{1,0} %p0), '
            'custom_call_target="tpu_custom_call", '
            'metadata={op_name="jit(step)/fused_bias_residual_layernorm'
            '/pallas_call[name=fused_bias_residual_layernorm_fwd]" '
            'source_file="fused_ops.py" source_line=1}')
    assert _custom_call_label(line) == \
        "fused_bias_residual_layernorm_fwd"
    # no pallas metadata -> the call target is the label
    bare = ('%cc = f32[8,128]{1,0} custom-call(f32[8,128]{1,0} %a), '
            'custom_call_target="my_target"')
    assert _custom_call_label(bare) == "my_target"

    # end to end through the text parser: the row carries the kernel
    text = """HloModule m

ENTRY %main (p0: f32[128,256]) -> f32[128,256] {
  %p0 = f32[128,256]{1,0} parameter(0)
  ROOT %custom-call.7 = f32[128,256]{1,0} custom-call(f32[128,256]{1,0} %p0), custom_call_target="tpu_custom_call", metadata={op_name="jit(step)/fused_bias_residual_layernorm/pallas_call[name=fused_bias_residual_layernorm_fwd]"}
}
"""
    rows = per_fusion_costs_from_text(text, peak_flops=1e12,
                                      hbm_gbps=100.0)
    cc = [r for r in rows if r["kind"] == "custom-call"]
    assert cc and cc[0]["kernel"] == "fused_bias_residual_layernorm_fwd"


def test_fused_chain_rows_attributable():
    """A jitted fused epilogue chain's rows carry the op's named scope
    in their op_name attribution on ANY backend (the named_scope the
    fused_ops wrappers open), so the roofline table names the fused
    chains instead of anonymous elementwise fusions."""
    from deepspeed_tpu.ops.transformer.fused_ops import (
        fused_bias_gelu, fused_bias_residual_layernorm)
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        per_fusion_costs

    def f(y, b, r, g, bet):
        out, s = fused_bias_residual_layernorm(y, b, r, g, bet,
                                               eps=1e-5, impl="xla")
        return fused_bias_gelu(out, bet, impl="xla").sum() + \
            (s ** 2).sum()

    h = 256
    args = [jnp.ones((64, h)), jnp.ones((h,)), jnp.ones((64, h)),
            jnp.ones((h,)), jnp.ones((h,))]
    rows = per_fusion_costs(jax.grad(f, argnums=(0, 1, 2, 3, 4)), *args)
    assert rows
    ops = " ".join(r["op"] for r in rows)
    assert "fused_bias_residual_layernorm" in ops
