"""Activation-checkpointing subsystem tests.

Mirrors the reference's `test_activation_checkpointing.py` intent: the
checkpointed computation must be numerically identical to the plain one
under every config combination, and the config flags must actually
change the compiled program (recompute flops / saved-residual sharding /
host placement).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ck
from deepspeed_tpu.runtime.mesh import build_mesh


@pytest.fixture(autouse=True)
def _reset_config():
    yield
    ck._configure_defaults()
    ck._mesh = None
    ck._policy_name = None


def _mlp(params, x):
    h = jnp.tanh(x @ params["w1"])
    h = jnp.tanh(h @ params["w2"])
    return h @ params["w3"]


def _make(n=64):
    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(size=(n, 4 * n)) * 0.05, jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(4 * n, 4 * n)) * 0.05,
                          jnp.float32),
        "w3": jnp.asarray(rng.normal(size=(4 * n, n)) * 0.05, jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(8, n)), jnp.float32)
    return params, x


def _loss(params, x, use_ckpt):
    def blk(p, h):
        return _mlp(p, h)
    if use_ckpt:
        out = ck.checkpoint(blk, params, x)
    else:
        out = blk(params, x)
    return jnp.sum(out ** 2)


@pytest.mark.parametrize("flags", [
    {},
    {"partition_activations": True},
    {"cpu_checkpointing": True},
    {"partition_activations": True, "cpu_checkpointing": True},
    {"contiguous_memory_optimization": True,
     "synchronize_checkpoint_boundary": True},
])
def test_checkpoint_numerics_match_dense(flags):
    mesh = build_mesh({"pipe": 1, "data": 1, "model": 8})
    ck.configure(None, deepspeed_config={
        "train_micro_batch_size_per_gpu": 1,
        "activation_checkpointing": flags}, mesh=mesh)
    params, x = _make()

    g_ref = jax.grad(lambda p: _loss(p, x, False))(params)
    g_ck = jax.grad(lambda p: _loss(p, x, True))(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_ref[k]),
                                   np.asarray(g_ck[k]), rtol=1e-5,
                                   atol=1e-5)


def test_checkpoint_recomputes_forward():
    """Full remat re-runs the forward matmuls in the backward: the grad
    jaxpr must contain more dot_generals than the unchendpointed one."""
    ck.configure(None)
    params, x = _make()

    def jaxpr_str(use_ckpt):
        return str(jax.make_jaxpr(jax.grad(
            lambda p: _loss(p, x, use_ckpt)))(params))

    plain, ck_str = jaxpr_str(False), jaxpr_str(True)
    assert "remat" in ck_str and "remat" not in plain
    assert ck_str.count("dot_general") >= plain.count("dot_general") + 2


def test_policy_escape_hatch():
    """checkpoint_policy selects a jax.checkpoint_policies entry."""
    ck.configure(None, checkpoint_policy="everything_saveable")
    params, x = _make()
    g_pol = jax.grad(lambda p: _loss(p, x, True))(params)
    g_ref = jax.grad(lambda p: _loss(p, x, False))(params)
    for k in params:
        # atol floor: remat changes XLA's fusion/reduction order, which
        # legitimately moves fp32 grads by ~1 ulp on some XLA versions
        np.testing.assert_allclose(np.asarray(g_pol[k]),
                                   np.asarray(g_ref[k]), rtol=1e-5,
                                   atol=1e-6)


def test_partition_activations_shards_saved_inputs():
    """With partition_activations the staged residuals are sharded over
    the model axis: the compiled backward regathers them (the reference
    all-gathers in get_full_inputs, checkpointing.py:282-312)."""
    mesh = build_mesh({"pipe": 1, "data": 1, "model": 8})
    ck.configure(None, partition_activations=True, mesh=mesh)
    params, x = _make()

    spec = ck._partition_spec(x, mesh)
    # last divisible dim preferred (leading dim is usually the already-
    # data-sharded batch dim)
    assert spec[1] == "model" and spec[0] is None

    # end-to-end: grads still exact on the mesh
    g_ref = jax.grad(lambda p: _loss(p, x, False))(params)
    g_ck = jax.grad(lambda p: _loss(p, x, True))(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_ref[k]),
                                   np.asarray(g_ck[k]), rtol=1e-5,
                                   atol=1e-5)


def test_cpu_checkpointing_without_mesh():
    """Reference-parity configure() has no mesh argument; offload must
    not crash when none was provided."""
    ck.configure(None, checkpoint_in_cpu=True)
    params, x = _make()
    g_ref = jax.grad(lambda p: _loss(p, x, False))(params)
    g_ck = jax.grad(lambda p: _loss(p, x, True))(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_ref[k]),
                                   np.asarray(g_ck[k]), rtol=1e-5,
                                   atol=1e-5)


def test_engine_configures_subsystem(mesh8):
    """The JSON activation_checkpointing block reaches configure()
    through the engine (ref engine wiring)."""
    import flax.linen as nn
    from deepspeed_tpu import initialize

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    class Wrapper:
        def __init__(self):
            self.module = Tiny()

        def init(self, rng, batch):
            return self.module.init(rng, batch["x"])

        def loss_fn(self, params, batch, rngs=None, deterministic=False):
            out = self.module.apply(params, batch["x"])
            return jnp.mean(out ** 2)

    m = Wrapper()
    params = m.init(jax.random.PRNGKey(0), {"x": np.zeros((8, 4),
                                                          np.float32)})
    initialize(model=m, model_parameters=params, config={
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "activation_checkpointing": {"partition_activations": True},
    }, mesh=mesh8)
    assert ck.is_configured()
    assert ck.PARTITION_ACTIVATIONS


def test_rng_tracker_streams():
    key = ck.model_parallel_manual_seed(1234, model_parallel_rank=0)
    assert key is not None
    tracker = ck.get_rng_tracker()
    with tracker.fork() as k1:
        pass
    with tracker.fork() as k2:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    # distinct ranks get distinct model-parallel streams
    ck.model_parallel_manual_seed(1234, model_parallel_rank=1)
    with ck.get_rng_tracker().fork() as k3:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k3))
    with pytest.raises(Exception):
        tracker.fork("missing").__enter__()
