"""BERT family + module injection tests (parity targets: ref vendored
modeling.py BERT comparisons and module_inject tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.bert import (BertForPreTrainingLM, BertModel,
                                       tiny_bert_config, bert_config)
from deepspeed_tpu.module_inject import (convert_bert_layer_params,
                                         revert_bert_layer_params,
                                         replace_transformer_layer,
                                         revert_transformer_layer)
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)


def make_batch(bs=8, t=64, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, (bs, t)).astype(np.int32)
    labels = np.where(rng.rand(bs, t) < 0.15, ids, -100).astype(np.int32)
    return {"input_ids": ids,
            "attention_mask": np.ones((bs, t), np.int32),
            "token_type_ids": np.zeros((bs, t), np.int32),
            "masked_lm_labels": labels,
            "next_sentence_label": rng.randint(0, 2, (bs,)).astype(np.int32)}


def test_bert_pretraining_trains():
    cfg = tiny_bert_config()
    model = BertForPreTrainingLM(cfg)
    batch = make_batch()
    params = model.init(jax.random.PRNGKey(0), batch)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    losses = []
    for i in range(8):
        loss = engine.train_batch(batch={k: v[None] for k, v in
                                         batch.items()})
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], losses


def test_bert_attention_mask_matters():
    cfg = tiny_bert_config()
    module = BertModel(cfg)
    ids = np.random.RandomState(0).randint(0, 256, (2, 64)).astype(np.int32)
    mask = np.ones((2, 64), np.int32)
    params = module.init({"params": jax.random.PRNGKey(0)}, ids, mask,
                         deterministic=True)
    seq_full, _ = module.apply(params, ids, mask, deterministic=True)
    mask2 = mask.copy()
    mask2[:, 32:] = 0
    seq_masked, _ = module.apply(params, ids, mask2, deterministic=True)
    assert not np.allclose(np.asarray(seq_full), np.asarray(seq_masked))


def _fake_hf_bert_layer(h=64, inter=128, seed=0):
    rng = np.random.RandomState(seed)

    def dense(i, o):
        return {"kernel": jnp.asarray(rng.randn(i, o) * 0.02, jnp.float32),
                "bias": jnp.zeros((o,), jnp.float32)}

    def ln(n):
        return {"scale": jnp.ones((n,), jnp.float32),
                "bias": jnp.zeros((n,), jnp.float32)}

    return {
        "attention": {
            "self": {"query": dense(h, h), "key": dense(h, h),
                     "value": dense(h, h)},
            "output": {"dense": dense(h, h), "LayerNorm": ln(h)},
        },
        "intermediate": {"dense": dense(h, inter)},
        "output": {"dense": dense(inter, h), "LayerNorm": ln(h)},
    }


def test_convert_revert_roundtrip():
    hf = _fake_hf_bert_layer()
    ds = convert_bert_layer_params(hf)
    assert ds["core"]["attn_qkvw"]["kernel"].shape == (64, 192)
    back = revert_bert_layer_params(ds)
    for a, b in zip(jax.tree_util.tree_leaves(hf),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_converted_layer_matches_hf_math():
    """The fused layer with converted params must reproduce the HF BERT
    layer computation (the criterion of ref test_cuda_forward.py)."""
    h, nh, inter, t = 64, 4, 128, 64
    hf = _fake_hf_bert_layer(h, inter)
    ds_params = convert_bert_layer_params(hf)
    cfg = DeepSpeedTransformerConfig(
        hidden_size=h, intermediate_size=inter, heads=nh,
        attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
        num_hidden_layers=1, pre_layer_norm=False, training=False,
        layer_norm_eps=1e-12)
    layer = DeepSpeedTransformerLayer(cfg)
    x = jnp.asarray(np.random.RandomState(1).randn(2, t, h), jnp.float32)
    out = layer.apply({"params": ds_params}, x, None, True)

    # reference HF-style post-LN BERT layer math
    def d(p, v):
        return v @ p["kernel"] + p["bias"]

    def lnorm(p, v, eps=1e-12):
        mu = v.mean(-1, keepdims=True)
        var = ((v - mu) ** 2).mean(-1, keepdims=True)
        return (v - mu) / np.sqrt(var + eps) * p["scale"] + p["bias"]

    q = d(hf["attention"]["self"]["query"], x).reshape(2, t, nh, h // nh)
    k = d(hf["attention"]["self"]["key"], x).reshape(2, t, nh, h // nh)
    v = d(hf["attention"]["self"]["value"], x).reshape(2, t, nh, h // nh)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(h // nh)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(2, t, h)
    attn = lnorm(hf["attention"]["output"]["LayerNorm"],
                 x + d(hf["attention"]["output"]["dense"], ctx))
    mlp = d(hf["output"]["dense"],
            jax.nn.gelu(d(hf["intermediate"]["dense"], attn),
                        approximate=False))
    ref = lnorm(hf["output"]["LayerNorm"], attn + mlp)

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_replace_transformer_layer_tree_walk():
    tree = {
        "embeddings": {"word": {"kernel": jnp.zeros((10, 64))}},
        "encoder": {"layer": {
            "0": _fake_hf_bert_layer(seed=0),
            "1": _fake_hf_bert_layer(seed=1),
        }},
    }
    cfg, new_tree, count = replace_transformer_layer(
        params=tree, bert_config=None)
    assert count == 2
    assert "attn_qkvw" in new_tree["encoder"]["layer"]["0"]["core"]
    assert "word" in new_tree["embeddings"]  # untouched
    reverted, rcount = revert_transformer_layer(new_tree)
    assert rcount == 2
    assert "query" in reverted["encoder"]["layer"]["0"]["attention"]["self"]
