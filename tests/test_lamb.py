"""FusedLamb parity tests (VERDICT r1 #10): our LAMB must implement the
reference update rule — clipped per-tensor trust ratio
(`csrc/lamb/fused_lamb_cuda_kernel.cu:279-306`, defaults from
`deepspeed/ops/lamb/fused_lamb.py:48-49`)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.ops.lamb.fused_lamb import lamb, FusedLamb
from simple_model import SimpleModel


def numpy_lamb_reference(w, grads, steps, lr=1e-2, b1=0.9, b2=0.999,
                         eps=1e-8, wd=0.0, max_coeff=10.0, min_coeff=0.01):
    """Direct transcription of the CUDA kernel update
    (lamb_cuda_kernel_part2/3: u = m_hat/(sqrt(v_hat)+eps) + decay*w,
    coeff = clip(||w||/||u||), w -= lr*coeff*u)."""
    w = w.astype(np.float64).copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t, g in zip(range(1, steps + 1), grads):
        g = g.astype(np.float64)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        v_hat = v / (1 - b2 ** t)
        u = m_hat / (np.sqrt(v_hat) + eps) + wd * w
        w_norm = np.linalg.norm(w)
        u_norm = np.linalg.norm(u)
        coeff = 1.0
        if w_norm != 0 and u_norm != 0:
            coeff = np.clip(w_norm / u_norm, min_coeff, max_coeff)
        w = w - lr * coeff * u
    return w


def test_lamb_matches_reference_formula():
    rng = np.random.RandomState(0)
    w0 = rng.randn(8, 8).astype(np.float32)
    grads = [rng.randn(8, 8).astype(np.float32) * 0.1 for _ in range(5)]

    opt = lamb(learning_rate=1e-2, weight_decay=0.01)
    params = {"w": jnp.asarray(w0)}
    state = opt.init(params)
    for g in grads:
        updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)

    expected = numpy_lamb_reference(w0, grads, 5, lr=1e-2, wd=0.01)
    np.testing.assert_allclose(np.asarray(params["w"]), expected,
                               rtol=1e-5, atol=1e-6)


def test_trust_ratio_is_clipped():
    """Tiny gradients after warm moments → raw ratio far above
    max_coeff; the reference clips it to 10.0 (optax.lamb would not)."""
    w0 = np.full((16,), 100.0, np.float32)   # huge weight norm
    g = np.full((16,), 1e-3, np.float32)
    opt = lamb(learning_rate=1.0, max_coeff=10.0)
    params = {"w": jnp.asarray(w0)}
    state = opt.init(params)
    updates, _ = opt.update({"w": jnp.asarray(g)}, state, params)
    # u ~= 1 elementwise (m_hat/sqrt(v_hat) with b1=b2 bias-corrected),
    # ||w||/||u|| = 100 -> must clip to 10: update = -lr*10*u
    upd = np.asarray(updates["w"])
    assert np.all(np.abs(upd) < 10.5), upd.max()
    assert np.all(np.abs(upd) > 5.0), upd.max()


def test_zero_norm_weight_uses_unit_coeff():
    opt = lamb(learning_rate=1e-2)
    params = {"w": jnp.zeros((4,))}
    state = opt.init(params)
    updates, _ = opt.update({"w": jnp.ones((4,)) * 0.1}, state, params)
    # coeff = 1.0 when ||w|| == 0 (ref kernel keeps lamb_coeff = 1.0)
    np.testing.assert_allclose(np.asarray(updates["w"]),
                               -1e-2 * np.ones(4), rtol=1e-4)


def test_engine_lamb_trains_and_uses_scheduler():
    model = SimpleModel(hidden_dim=16)
    cfg = {
        "train_batch_size": 16,
        "steps_per_print": 1000,
        "optimizer": {"type": "Lamb",
                      "params": {"lr": 0.1, "weight_decay": 0.01,
                                 "max_coeff": 10.0, "min_coeff": 0.01}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params, config=cfg)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 16).astype(np.float32)
    w = np.linspace(-1, 1, 256).reshape(16, 16).astype(np.float32)
    losses = []
    for _ in range(30):
        loss = engine.train_batch(batch={"x": x[None], "y": (x @ w)[None]})
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0] * 0.5, losses


def test_fused_lamb_facade():
    opt = FusedLamb(lr=1e-2, betas=(0.9, 0.999), max_coeff=5.0)
    params = {"w": jnp.ones((4,))}
    state = opt.init(params)
    updates, state = opt.update({"w": jnp.ones((4,)) * 0.1}, state, params)
    assert np.isfinite(np.asarray(updates["w"])).all()
