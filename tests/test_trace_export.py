"""Perfetto trace export (ISSUE 7 tentpole a).

Covers:
  * Chrome trace-event schema validation: every emitted event carries
    the required keys, "X" durations are non-negative, `ts` is
    monotonic within a track, and any B/E events pair up (we emit only
    X/i/C/M — the validator enforces the rule anyway);
  * TraceExporter unit behavior: tracks, bounded buffer, atomic write;
  * the acceptance run: a p=4 / m=8 / v=2 interleaved pipeline on the
    virtual mesh exports a trace with one track per stage, per-
    microbatch/per-chunk events, and a computed bubble fraction
    matching the schedule's analytic (p-1)/(v·m+p-1);
  * bin/ds_trace merge + summary via the CLI entry point;
  * span tracks riding trace export without wall_clock_breakdown.
"""

import json
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.monitor.trace_export import (
    TraceExporter, analytic_bubble_fraction, load_trace, merge_traces,
    summarize_trace, tables_bubble_fraction)
from deepspeed_tpu.runtime.pipe.interp import build_clock_tables
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

DIN, DOUT = 16, 8


def mse_loss(pred, labels):
    return jnp.mean((pred.astype(jnp.float32) -
                     labels.astype(jnp.float32)) ** 2)


# ----------------------------------------------------------------------
# schema validation helper (the contract every exported file meets)
# ----------------------------------------------------------------------
REQUIRED_KEYS = ("name", "ph", "pid", "tid")


def validate_chrome_trace(doc):
    """Assert `doc` is a valid Chrome trace-event object: required keys
    per event, numeric non-negative durations, monotonic `ts` within
    each (pid, tid) track, matched B/E pairs per track."""
    assert isinstance(doc, dict) and "traceEvents" in doc
    last_ts = {}
    open_b = {}
    for ev in doc["traceEvents"]:
        for key in REQUIRED_KEYS:
            assert key in ev, (key, ev)
        ph = ev["ph"]
        track = (ev["pid"], ev["tid"])
        if ph == "M":
            continue
        assert isinstance(ev.get("ts"), (int, float)), ev
        assert ev["ts"] >= last_ts.get(track, float("-inf")), \
            f"ts not monotonic within track {track}: {ev}"
        last_ts[track] = ev["ts"]
        if ph == "X":
            assert isinstance(ev.get("dur"), (int, float)) and \
                ev["dur"] >= 0, ev
        elif ph == "B":
            open_b.setdefault(track, []).append(ev["name"])
        elif ph == "E":
            stack = open_b.get(track) or []
            assert stack, f"E without B on track {track}: {ev}"
            stack.pop()
        elif ph in ("i", "I"):
            assert ev.get("s", "t") in ("t", "p", "g"), ev
        elif ph == "C":
            assert isinstance(ev.get("args"), dict) and ev["args"], ev
    for track, stack in open_b.items():
        assert not stack, f"unmatched B events on {track}: {stack}"


# ----------------------------------------------------------------------
# exporter unit behavior
# ----------------------------------------------------------------------
def test_exporter_events_validate_and_tracks_are_named():
    ex = TraceExporter(rank=3, max_events=100)
    ex.complete("host/forward", "forward", 1.0, 0.25)
    ex.complete("host/forward", "forward", 2.0, 0.5,
                args={"step": 1})
    ex.instant("fences", "fence step 1")
    ex.counter("fences", "metrics", {"loss": 1.5})
    doc = ex.to_dict()
    validate_chrome_trace(doc)
    assert all(ev["pid"] == 3 for ev in doc["traceEvents"])
    names = {ev["args"]["name"] for ev in doc["traceEvents"]
             if ev["ph"] == "M"}
    assert {"host/forward", "fences"} <= names


def test_exporter_buffer_is_bounded():
    ex = TraceExporter(max_events=10)
    for i in range(50):
        ex.complete("t", f"e{i}", float(i), 0.1)
    doc = ex.to_dict()
    xs = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    assert len(xs) == 10
    assert xs[0]["name"] == "e40"      # retains the LAST window


def test_exporter_atomic_write(tmp_path):
    ex = TraceExporter()
    ex.complete("t", "e", 1.0, 0.1)
    path = str(tmp_path / "sub" / "trace.json")
    out = ex.write(path)
    assert out == path and os.path.exists(path)
    assert not [n for n in os.listdir(tmp_path / "sub")
                if ".tmp" in n]
    validate_chrome_trace(load_trace(path))


# ----------------------------------------------------------------------
# pipeline timeline from clock tables
# ----------------------------------------------------------------------
@pytest.mark.parametrize("m,S,v", [(8, 4, 2), (8, 4, 1), (8, 2, 4)])
def test_pipeline_events_match_tables_and_bubble(m, S, v):
    tables = build_clock_tables(m, S, num_virtual_stages=v)
    ex = TraceExporter()
    meta = {"stages": S, "micro_batches": m, "num_virtual_stages": v}
    ex.add_pipeline_step(tables, meta, 10.0, 11.0, step=1)
    doc = ex.to_dict()
    validate_chrome_trace(doc)
    xs = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    busy = int((tables["fwd_mb"] >= 0).sum() +
               (tables["bwd_mb"] >= 0).sum())
    assert len(xs) == busy
    # every (chunk, mb) fwd+bwd appears exactly once, args intact
    seen_f = {(e["args"]["chunk"], e["args"]["mb"]) for e in xs
              if e["name"].startswith("F ")}
    assert seen_f == {(q, mb) for q in range(S * v) for mb in range(m)}
    # the metadata's computed bubble equals the table bubble, near the
    # schedule's analytic number
    pipe = doc["otherData"]["pipeline"]
    assert pipe["bubble_fraction"] == pytest.approx(
        tables_bubble_fraction(tables), abs=1e-6)
    assert pipe["analytic_bubble_fraction"] == pytest.approx(
        analytic_bubble_fraction(S, m, v), abs=1e-6)
    # and the summary recomputed FROM EVENTS agrees
    summary = summarize_trace(doc)
    assert summary["pipeline"]["stages"] == S
    assert summary["pipeline"]["bubble_fraction"] == pytest.approx(
        tables_bubble_fraction(tables), abs=0.02)


# ----------------------------------------------------------------------
# acceptance: p=4/m=8/v=2 engine run -> trace -> bubble vs analytic
# ----------------------------------------------------------------------
def _pipe_engine(tmp_path, v=2, gas=8, pipe=4):
    layers = [LayerSpec(nn.Dense, 32), jnp.tanh, LayerSpec(nn.Dense, 32),
              LayerSpec(nn.Dense, 32), LayerSpec(nn.Dense, 32), jnp.tanh,
              LayerSpec(nn.Dense, 32), LayerSpec(nn.Dense, DOUT)]
    module = PipelineModule(layers, num_stages=pipe, loss_fn=mse_loss,
                            partition_method="uniform")
    rng = np.random.RandomState(0)
    example = jnp.asarray(rng.randn(4, DIN), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(0), example)
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"pipe": pipe, "data": 8 // pipe, "model": 1},
        "pipeline": {"num_virtual_stages": v},
        "monitor": {"enabled": True, "sinks": [],
                    "output_path": str(tmp_path),
                    "trace": {"enabled": True}},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=module, model_parameters=params, config=cfg)
    return engine


def _pipe_batch(gas, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(8 * gas, DIN).astype(np.float32)
    w = np.linspace(-1, 1, DIN * DOUT).reshape(DIN, DOUT) \
        .astype(np.float32)
    return {"x": x, "y": x @ w}


def test_interleaved_pipeline_run_exports_valid_trace(tmp_path):
    """The acceptance criterion: a p=4/m=8/v=2 virtual-mesh pipeline
    run exports trace-event JSON that validates, carries per-stage
    tracks with microbatch/chunk events, and whose computed bubble
    matches the schedule's analytic (p-1)/(v·m+p-1)."""
    p, m, v = 4, 8, 2
    engine = _pipe_engine(tmp_path, v=v, gas=m, pipe=p)
    for i in range(3):
        engine.train_batch(batch=_pipe_batch(m, i))
    path = engine.monitor.export_trace()
    engine.monitor.close()
    assert path and os.path.exists(path)

    doc = load_trace(path)
    validate_chrome_trace(doc)
    names = {ev["args"]["name"] for ev in doc["traceEvents"]
             if ev["ph"] == "M"}
    assert {f"pipe/stage{s}" for s in range(p)} <= names
    xs = [ev for ev in doc["traceEvents"]
          if ev["ph"] == "X" and ev.get("cat", "").startswith("pipe")]
    assert xs, "no pipeline events in the trace"
    assert all({"mb", "chunk", "step"} <= set(e["args"]) for e in xs)
    chunks = {e["args"]["chunk"] for e in xs}
    mbs = {e["args"]["mb"] for e in xs}
    assert chunks == set(range(p * v))
    assert mbs == set(range(m))

    analytic = analytic_bubble_fraction(p, m, v)    # 3/19 ~ 0.158
    summary = summarize_trace(doc)
    measured = summary["pipeline"]["bubble_fraction"]
    assert measured == pytest.approx(analytic, abs=0.05), \
        (measured, analytic)
    assert doc["otherData"]["pipeline"]["analytic_bubble_fraction"] \
        == pytest.approx(analytic, abs=1e-6)


def test_span_tracks_ride_trace_export_without_breakdown(tmp_path):
    """monitor.trace.enabled alone records the step spans as slices —
    no wall_clock_breakdown flag required."""
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from simple_model import SimpleModel
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.params,
        config={
            "train_batch_size": 16, "steps_per_print": 10000,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "monitor": {"enabled": True, "sinks": [],
                        "output_path": str(tmp_path),
                        "trace": {"enabled": True}},
        })
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    for _ in range(3):
        engine.train_batch(batch={"x": x[None], "y": (x * 0.5)[None]})
    doc = engine.monitor.trace_export.to_dict()
    engine.monitor.close()
    validate_chrome_trace(doc)
    step_slices = [ev for ev in doc["traceEvents"]
                   if ev["ph"] == "X" and ev["name"] == "step"]
    assert len(step_slices) == 3


# ----------------------------------------------------------------------
# ds_trace CLI: merge + summary
# ----------------------------------------------------------------------
def test_ds_trace_merge_and_summary(tmp_path, capsys):
    tables = build_clock_tables(8, 4, num_virtual_stages=2)
    meta = {"stages": 4, "micro_batches": 8, "num_virtual_stages": 2}
    paths = []
    for rank in range(2):
        ex = TraceExporter(rank=rank)
        ex.add_pipeline_step(tables, meta, 10.0, 11.0, step=1)
        ex.complete("host/step", "step", 10.0, 0.9)
        paths.append(ex.write(str(tmp_path / f"trace_rank{rank}.json")))

    merged = merge_traces([load_trace(path) for path in paths])
    validate_chrome_trace(merged)
    assert merged["otherData"]["merged_ranks"] == 2
    assert {ev["pid"] for ev in merged["traceEvents"]} == {0, 1}

    from deepspeed_tpu.monitor.trace_cli import main
    out = str(tmp_path / "merged.json")
    assert main(["merge", *paths, "-o", out]) == 0
    printed = capsys.readouterr().out
    assert "merged 2 shard(s)" in printed
    assert "bubble_fraction" in printed
    validate_chrome_trace(load_trace(out))

    assert main(["summary", out]) == 0
    printed = capsys.readouterr().out
    assert "pipe/stage0" in printed
    assert "schedule analytic" in printed
