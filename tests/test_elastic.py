"""Elasticity tests (parity with ref tests/unit/test_elastic.py)."""

import pytest

from deepspeed_tpu import elasticity
from deepspeed_tpu.version import __version__

base_ds_config = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def test_basic_10k():
    final_batch_size, valid_gpus = elasticity.compute_elastic_config(
        ds_config=base_ds_config, target_deepspeed_version=__version__)
    for gpu_num in valid_gpus:
        assert final_batch_size % gpu_num == 0
        batch_per_gpu = final_batch_size // gpu_num
        found_valid_mbsize = any(
            batch_per_gpu % mb == 0
            for mb in base_ds_config["elasticity"]["micro_batch_sizes"])
        assert found_valid_mbsize, f"No valid mb for gpu count {gpu_num}"


def test_candidate_batch_sizes_hcn():
    # base 1 scales to the largest HCN <= ceiling
    assert elasticity.get_candidate_batch_sizes([1], 720) == [720]
    # base 2 -> 2*48=96; base 3 -> 3*24=72 (3*36 exceeds 100)
    assert set(elasticity.get_candidate_batch_sizes([2, 3], 100)) == {96, 72}


def test_valid_gpus_divisors():
    gpus = elasticity.get_valid_gpus(24, [2, 3], 1, 100)
    # batch 24, micro 2 -> q=12: 1,2,3,4,6,12; micro 3 -> q=8: 1,2,4,8
    assert gpus == [1, 2, 3, 4, 6, 8, 12]


def test_world_size_picks_micro_batch():
    ds_config = {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 2000,
            "micro_batch_sizes": [2, 4],
            "min_gpus": 1,
            "max_gpus": 100,
            "version": 0.1,
        }
    }
    fbs, valid, micro = elasticity.compute_elastic_config(
        ds_config=ds_config, target_deepspeed_version=__version__,
        world_size=4)
    assert 4 in valid
    assert (fbs // 4) % micro == 0


def test_disabled_raises():
    cfg = {"elasticity": {"enabled": False}}
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.compute_elastic_config(
            ds_config=cfg, target_deepspeed_version=__version__)


def test_missing_block_raises():
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.compute_elastic_config(
            ds_config={}, target_deepspeed_version=__version__)


def test_invalid_version_raises():
    cfg = {"elasticity": dict(base_ds_config["elasticity"], version=0.2)}
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.compute_elastic_config(
            ds_config=cfg, target_deepspeed_version=__version__)


def test_old_deepspeed_version_raises():
    with pytest.raises(elasticity.ElasticityError):
        elasticity.compute_elastic_config(
            ds_config=base_ds_config, target_deepspeed_version="0.2.0")


def test_incompatible_world_size():
    with pytest.raises(elasticity.ElasticityIncompatibleWorldSize):
        elasticity.compute_elastic_config(
            ds_config=base_ds_config,
            target_deepspeed_version=__version__,
            world_size=31)  # below min_gpus


def test_config_missing_fields():
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.ElasticityConfig({"enabled": True})


def test_config_bad_micro_batches():
    with pytest.raises(elasticity.ElasticityConfigError):
        elasticity.ElasticityConfig({
            "enabled": True, "max_train_batch_size": 100,
            "micro_batch_sizes": [0, 2]})


# ----------------------------------------------------------------------
# elasticity x ZeRO compatibility (ISSUE 10 satellite): every device
# count the elastic config declares valid must admit a valid ZeRO
# partition plan whose per-device bytes shrink with the device count.
# ----------------------------------------------------------------------
class _PlanMesh:
    """Stand-in exposing just the `.shape` mapping that
    ZeroShardingPolicy's metadata math reads — the compat sweep covers
    device counts far beyond the 8 virtual devices."""

    def __init__(self, data):
        self.shape = {"pipe": 1, "data": int(data), "model": 1}


def test_every_valid_device_count_admits_a_zero_plan():
    import jax
    import numpy as np
    from deepspeed_tpu.runtime.zero.partition import ZeroShardingPolicy
    from deepspeed_tpu.version import __version__ as ver

    fbs, valid = elasticity.compute_elastic_config(
        ds_config=base_ds_config, target_deepspeed_version=ver)
    assert len(valid) >= 4, valid
    # GPT-ish large leaves: every numel >= 2 * max valid count, so no
    # leaf silently flips to replicated mid-sweep (which would break
    # per-device monotonicity by design, not by bug)
    shapes = {
        "wte": jax.ShapeDtypeStruct((32768, 1024), np.float32),
        "w_qkv": jax.ShapeDtypeStruct((1024, 3072), np.float32),
        "w_mlp": jax.ShapeDtypeStruct((1024, 4099), np.float32),
    }
    total = sum(int(np.prod(s.shape))
                for s in jax.tree_util.tree_leaves(shapes))
    assert min(int(np.prod(s.shape))
               for s in jax.tree_util.tree_leaves(shapes)) >= \
        2 * max(valid)

    prev = None
    for g in valid:                       # ascending
        policy = ZeroShardingPolicy(_PlanMesh(g), stage=3)
        plan = policy.memory_plan(shapes, compute_bytes=2)
        # a valid partition: every category planned, and the g shards
        # cover the full state (>= because pad-plan rounding pads up)
        assert plan["params"] > 0 and plan["master"] > 0 and \
            plan["opt_state"] > 0, (g, plan)
        assert plan["master"] * g >= total * 4, (g, plan)
        assert plan["opt_state"] * g >= total * 8, (g, plan)
        # the elastic batch math stays coherent at this count: same
        # final batch size, and a micro-batch divides the per-device
        # share
        fbs_g, _, micro = elasticity.compute_elastic_config(
            ds_config=base_ds_config, target_deepspeed_version=ver,
            world_size=g)
        assert fbs_g == fbs and (fbs // g) % micro == 0, (g, micro)
        # the ZeRO-partitioned state (masters + moments, stored in the
        # pad-plan encoded layout, so it ALWAYS shards) shrinks
        # monotonically per device with device count. Compute-dtype
        # params are exempt: at awkward counts (e.g. dp=34) a leaf
        # with no divisible dim legitimately stays replicated.
        if prev is not None:
            assert plan["master"] <= prev["master"], (g, plan, prev)
            assert plan["opt_state"] <= prev["opt_state"], \
                (g, plan, prev)
        prev = plan

    # the sweep genuinely shrank state end-to-end
    first = ZeroShardingPolicy(_PlanMesh(valid[0]), stage=3) \
        .memory_plan(shapes, compute_bytes=2)
    assert prev["opt_state"] < first["opt_state"]
    assert prev["master"] < first["master"]
