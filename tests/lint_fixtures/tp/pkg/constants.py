"""CFGKEY fixture constants: GOOD_KEY is read+documented; DEAD_KEY is
declared but never referenced; UNDOC_KEY is read but undocumented."""
GOOD_KEY = "good_key"
GOOD_KEY_DEFAULT = 1
DEAD_KEY = "dead_key"
DEAD_KEY_DEFAULT = 0
UNDOC_KEY = "undocumented_key"
UNDOC_KEY_DEFAULT = 0
