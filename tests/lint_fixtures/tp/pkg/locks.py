"""LOCKBLOCK fixture: fsync and a blocking queue put under a lock."""
import os
import threading


class Writer:
    def __init__(self, queue):
        self._lock = threading.Lock()
        self._queue = queue

    def bad_fsync(self, fd):
        with self._lock:
            os.fsync(fd)              # LOCKBLOCK finding

    def bad_put(self, item):
        with self._lock:
            self._queue.put(item)     # LOCKBLOCK finding
