"""HOTSYNC + TRACECTL true-positive fixture.

`train_step` is the declared hot entrypoint, `fence` the declared
fence site. `helper` syncs outside the fence (HOTSYNC); `traced_body`
branches on a traced value inside a jitted function (TRACECTL).
"""
import jax
import jax.numpy as jnp


def train_step(x):
    y = helper(x)          # reaches a device_get outside the fence
    s = jnp.sum(y)
    v = float(s)           # host conversion of a devicey value
    fence()
    return y, v


def helper(x):
    return jax.device_get(x)      # HOTSYNC finding


def fence():
    # declared fence site: this sync is the contract
    return jax.device_get(jnp.zeros(()))


def traced_body(x):
    if jnp.any(x > 0):            # TRACECTL finding
        return x * 2
    return x


traced_jit = jax.jit(traced_body)
