"""BROADEXC fixture: silent swallow (finding), plus the three passing
forms (re-raise / traceback log / annotation)."""
import logging

logger = logging.getLogger(__name__)


def work():
    raise RuntimeError("boom")


def swallows():
    try:
        work()
    except Exception:
        pass          # BROADEXC finding


def reraises():
    try:
        work()
    except Exception:
        raise


def logs_traceback():
    try:
        work()
    except Exception:
        logger.exception("work failed")


def annotated():
    try:
        work()
    except Exception:  # ds-lint: allow[BROADEXC] fixture: deliberately ignored
        pass
