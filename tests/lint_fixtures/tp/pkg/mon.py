"""EVTSCHEMA fixture: `boom` emits `alpha` (documented) and `beta`
(undocumented -> finding); the doc also lists a `ghost` kind no code
emits (-> finding)."""
import time

SCHEMA_VERSION = 1


def base_event(kind, step):
    return {"v": SCHEMA_VERSION, "ts": time.time(), "kind": kind,
            "step": step}


def emit_boom(sink, step):
    ev = base_event("boom", step)
    ev["alpha"] = 1
    ev["beta"] = 2
    sink(ev)
