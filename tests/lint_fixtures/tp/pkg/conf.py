from pkg.constants import GOOD_KEY, GOOD_KEY_DEFAULT, UNDOC_KEY


def get_scalar_param(d, key, default):
    return d.get(key, default)


def parse(param_dict):
    a = get_scalar_param(param_dict, GOOD_KEY, GOOD_KEY_DEFAULT)
    b = get_scalar_param(param_dict, "literal_key", 2)   # CFGKEY: literal read
    c = get_scalar_param(param_dict, UNDOC_KEY, 0)       # CFGKEY: no doc row
    return a, b, c
