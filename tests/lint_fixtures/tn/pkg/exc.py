"""BROADEXC clean fixture."""
import logging

logger = logging.getLogger(__name__)


def work():
    raise RuntimeError("boom")


def narrow():
    try:
        work()
    except RuntimeError:
        pass


def logs_traceback():
    try:
        work()
    except Exception:
        logger.warning("work failed", exc_info=True)
