"""LOCKBLOCK clean fixture: durability work outside the lock; queue
ops with escape hatches."""
import os
import threading


class Writer:
    def __init__(self, queue):
        self._lock = threading.Lock()
        self._queue = queue
        self._buf = []

    def good_fsync(self, fd):
        with self._lock:
            buf = list(self._buf)     # in-memory work only
        os.fsync(fd)                  # durability outside the lock
        return buf

    def good_put(self, item):
        with self._lock:
            self._queue.put(item, block=False)

    def string_replace_is_fine(self, s):
        with self._lock:
            return s.replace("a", "b")
