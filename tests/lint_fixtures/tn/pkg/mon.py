"""EVTSCHEMA clean fixture: emitted keys == documented keys."""
import time

SCHEMA_VERSION = 1


def base_event(kind, step):
    return {"v": SCHEMA_VERSION, "ts": time.time(), "kind": kind,
            "step": step}


def emit_boom(sink, step):
    ev = base_event("boom", step)
    ev["alpha"] = 1
    sink(ev)
