"""HOTSYNC + TRACECTL true-negative fixture: same shape, clean."""
import jax
import jax.numpy as jnp


def train_step(x):
    y = helper(x)
    fence()
    return y


def helper(x):
    return x * 2                  # no sync: clean


def fence():
    # declared fence site: the one allowed rendezvous
    return jax.device_get(jnp.zeros(()))


def traced_body(x):
    return jnp.where(jnp.any(x > 0), x * 2, x)   # lax-native select


traced_jit = jax.jit(traced_body)
