"""CFGKEY clean fixture constants."""
GOOD_KEY = "good_key"
GOOD_KEY_DEFAULT = 1
