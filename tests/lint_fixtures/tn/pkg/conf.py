from pkg.constants import GOOD_KEY, GOOD_KEY_DEFAULT


def get_scalar_param(d, key, default):
    return d.get(key, default)


def parse(param_dict):
    return get_scalar_param(param_dict, GOOD_KEY, GOOD_KEY_DEFAULT)
