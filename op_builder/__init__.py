from op_builder.builder import OpBuilder, get_default_compute_capabilities
from op_builder.cpu_adam import CPUAdamBuilder

# Registry of all native ops (ref `op_builder/__init__.py:11-21`). The
# CUDA builders of the reference (fused_adam/lamb/transformer/
# sparse_attn) have no native artifact here: their roles are filled by
# XLA/Pallas kernels compiled at trace time, which ds_report reports.
ALL_OPS = {
    "cpu_adam": CPUAdamBuilder,
}

__all__ = ["OpBuilder", "CPUAdamBuilder", "ALL_OPS",
           "get_default_compute_capabilities"]
