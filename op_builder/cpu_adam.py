"""CPU-Adam builder (ref `op_builder/cpu_adam.py`)."""

import ctypes
import os

from op_builder.builder import OpBuilder, REPO_ROOT


class CPUAdamBuilder(OpBuilder):
    BUILD_VAR = "DS_BUILD_CPU_ADAM"
    NAME = "cpu_adam"

    def sources(self):
        return [os.path.join(REPO_ROOT, "csrc", "adam", "cpu_adam.cpp")]

    def _declare(self, lib):
        f32p = ctypes.POINTER(ctypes.c_float)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.ds_adam_create.argtypes = [
            ctypes.c_int, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int]
        lib.ds_adam_create.restype = ctypes.c_int
        lib.ds_adam_destroy.argtypes = [ctypes.c_int]
        lib.ds_adam_destroy.restype = ctypes.c_int
        lib.ds_adam_step.argtypes = [
            ctypes.c_int, ctypes.c_int64, f32p, f32p, f32p, f32p,
            ctypes.c_float]
        lib.ds_adam_step.restype = ctypes.c_int64
        lib.ds_adam_step_copy_bf16.argtypes = [
            ctypes.c_int, ctypes.c_int64, f32p, f32p, f32p, f32p, u16p,
            ctypes.c_float]
        lib.ds_adam_step_copy_bf16.restype = ctypes.c_int64
        lib.ds_adam_step_chunk.argtypes = [
            ctypes.c_int, ctypes.c_int64, ctypes.c_int64, f32p, f32p,
            f32p, f32p, u16p, ctypes.c_float]
        lib.ds_adam_step_chunk.restype = ctypes.c_int64
        i8p = ctypes.POINTER(ctypes.c_int8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.ds_adam_step_chunk_q8.argtypes = [
            ctypes.c_int, ctypes.c_int64, ctypes.c_int64, f32p, i8p,
            f32p, ctypes.c_int64, f32p, f32p, u16p, ctypes.c_float]
        lib.ds_adam_step_chunk_q8.restype = ctypes.c_int64
        lib.ds_adam_step_chunk_q1.argtypes = [
            ctypes.c_int, ctypes.c_int64, ctypes.c_int64, f32p, u8p,
            f32p, ctypes.c_int64, f32p, f32p, u16p, ctypes.c_float]
        lib.ds_adam_step_chunk_q1.restype = ctypes.c_int64
        lib.ds_adam_get_step.argtypes = [ctypes.c_int]
        lib.ds_adam_get_step.restype = ctypes.c_int
        lib.ds_adam_set_step.argtypes = [ctypes.c_int, ctypes.c_int64]
        lib.ds_adam_set_step.restype = ctypes.c_int
        lib.ds_num_threads.restype = ctypes.c_int
