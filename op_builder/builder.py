"""Native-op build/load system.

Counterpart of `op_builder/builder.py:78-220`: the reference JIT-compiles
CUDA extensions through torch's cpp_extension + ninja; here native ops
are plain C++ shared libraries compiled with g++ on first use, cached by
source hash, and loaded with ctypes (no pybind11 in the image — SURVEY
env notes). Per-op DS_BUILD_* env gates are honored the same way
(`DS_BUILD_CPU_ADAM=0` disables the native path and the Python wrapper
falls back to numpy).
"""

import ctypes
import hashlib
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUILD_DIR = os.environ.get(
    "DS_BUILD_DIR", os.path.join(REPO_ROOT, "build", "ops"))


def get_default_compute_capabilities():
    """API parity shim (ref builder.py:223-304 computes CUDA CCs); TPU
    builds have no compute-capability concept."""
    return ""


class OpBuilder:
    BUILD_VAR = None     # e.g. "DS_BUILD_CPU_ADAM"
    NAME = "op"

    def __init__(self):
        self._lib = None

    # -- config ----------------------------------------------------------
    def sources(self):
        raise NotImplementedError

    def include_paths(self):
        return []

    def cxx_args(self):
        args = ["-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp"]
        if os.uname().machine in ("x86_64", "amd64"):
            args.append("-march=native")
        return args

    def libraries_args(self):
        return []

    # -- availability ----------------------------------------------------
    def is_enabled(self):
        if self.BUILD_VAR is None:
            return True
        return os.environ.get(self.BUILD_VAR, "1") not in ("0", "false",
                                                           "False")

    def is_compatible(self):
        from shutil import which
        return which("g++") is not None

    def installed(self):
        return os.path.exists(self._lib_path())

    # -- build/load ------------------------------------------------------
    def _source_hash(self):
        h = hashlib.sha256()
        for src in self.sources():
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.cxx_args()).encode())
        # -march=native binaries are host-specific: key the cache on the
        # CPU's feature flags so a cache dir shared across machines (or
        # accidentally committed) never serves a foreign-ISA .so
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    if line.startswith("flags"):
                        h.update(line.encode())
                        break
        except OSError:
            import platform
            h.update(platform.processor().encode())
        return h.hexdigest()[:16]

    def _lib_path(self):
        return os.path.join(DEFAULT_BUILD_DIR,
                            f"{self.NAME}_{self._source_hash()}.so")

    def build(self, verbose=False):
        lib = self._lib_path()
        if os.path.exists(lib):
            return lib
        os.makedirs(DEFAULT_BUILD_DIR, exist_ok=True)
        cmd = ["g++"] + self.cxx_args()
        for inc in self.include_paths():
            cmd.append(f"-I{inc}")
        cmd += self.sources() + ["-o", lib] + self.libraries_args()
        if verbose:
            print(f"[op_builder] {' '.join(cmd)}", file=sys.stderr)
        subprocess.run(cmd, check=True, capture_output=not verbose)
        return lib

    def load(self, verbose=False):
        """Compile (if needed) and dlopen; returns the ctypes CDLL."""
        if self._lib is not None:
            return self._lib
        if not self.is_enabled():
            raise RuntimeError(
                f"{self.NAME} disabled via {self.BUILD_VAR}=0")
        if not self.is_compatible():
            raise RuntimeError(f"{self.NAME}: no g++ in PATH")
        lib_path = self.build(verbose=verbose)
        self._lib = ctypes.CDLL(lib_path)
        self._declare(self._lib)
        return self._lib

    def _declare(self, lib):
        """Subclasses declare argtypes/restypes."""
