#!/usr/bin/env python
"""Minimal GPT-2 pretraining with deepspeed_tpu — the Megatron-GPT2
example shape from DeepSpeedExamples, TPU-native.

Run (single host):
    python examples/gpt2_train.py --deepspeed \
        --deepspeed_config examples/ds_config_gpt2.json

Multi-host (pod): launch with `bin/dstpu --hostfile ... examples/gpt2_train.py ...`
and the engine picks up jax.distributed from the launcher env.
"""

import argparse
import os
import sys

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the container pins the TPU plugin at interpreter startup; honor
    # the env override before the backend initializes
    jax.config.update("jax_platforms", "cpu")
import numpy as np

# runnable from a source checkout without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models.gpt2 import GPT2ForCausalLM, gpt2_config


def get_args():
    parser = argparse.ArgumentParser(description="GPT-2 pretraining")
    parser.add_argument("--model", default="gpt2-125m",
                        help="gpt2-tiny .. gpt2-13b")
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--save-dir", default=None,
                        help="checkpoint dir (omit to skip saving)")
    parser.add_argument("--num-batches", type=int, default=0,
                        help="cycle a FIXED set of N synthetic batches "
                             "(learnable; the model harness uses this) "
                             "instead of an endless random stream")
    parser = deepspeed_tpu.add_config_arguments(parser)
    return parser.parse_args()


def synthetic_batches(vocab, micro_bs, gas, seq, seed, num_batches=0):
    rng = np.random.default_rng(seed)
    fixed = [{"input_ids": rng.integers(
        0, vocab, (gas, micro_bs, seq)).astype(np.int32)}
        for _ in range(num_batches)] if num_batches else None
    i = 0
    while True:
        if fixed is not None:
            yield fixed[i % len(fixed)]
            i += 1
        else:
            yield {"input_ids": rng.integers(
                0, vocab, (gas, micro_bs, seq)).astype(np.int32)}


def main():
    args = get_args()
    # Selective remat (save matmul outputs) is the throughput sweet spot
    # up to ~1B params; beyond that the saved activations exceed HBM and
    # full remat (policy None) is required. bf16 param STORAGE likewise
    # becomes mandatory at flagship scale (see ds_config_gpt2_1.5b.json);
    # the compute dtype is bf16 at every size.
    import jax.numpy as jnp
    big = args.model in ("gpt2-1.5b", "gpt2-2.7b", "gpt2-6.7b", "gpt2-13b")
    cfg = gpt2_config(args.model, n_positions=args.seq_len, dropout=0.0,
                      remat=True,
                      remat_policy=(None if big else
                                    "dots_with_no_batch_dims_saveable"),
                      **({"param_dtype": jnp.bfloat16} if big else {}))
    model = GPT2ForCausalLM(cfg)
    example = {"input_ids": np.zeros((1, args.seq_len), np.int32)}
    params = model.init(jax.random.PRNGKey(args.seed), example)

    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, model=model, model_parameters=params)

    micro = engine.train_micro_batch_size_per_gpu()
    gas = engine.gradient_accumulation_steps()
    data = synthetic_batches(cfg.vocab_size, micro, gas, args.seq_len,
                             args.seed, args.num_batches)
    losses = []
    for step in range(args.steps):
        loss = engine.train_batch(batch=next(data))
        losses.append(loss)    # fetched after the loop — no per-step sync
        if step % engine.steps_per_print() == 0:
            deepspeed_tpu.log_dist(
                f"step {step}: loss {float(jax.device_get(loss)):.4f}",
                ranks=[0])
    # full trajectory in one greppable line (the model-level regression
    # harness parses this; ref run_func_test.py greps "LM loss:")
    traj = [round(float(jax.device_get(l)), 6) for l in losses]
    print("LM loss trajectory:", " ".join(f"{x:.6f}" for x in traj),
          flush=True)
    if args.save_dir:
        engine.save_checkpoint(args.save_dir)
        # commit barrier: the save is async by default
        engine.wait_for_checkpoint()


if __name__ == "__main__":
    main()
