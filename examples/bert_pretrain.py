#!/usr/bin/env python
"""BERT pretraining (MLM + NSP) with the fused DeepSpeedTransformerLayer
— the bing_bert example shape from DeepSpeedExamples, TPU-native.

Run:
    python examples/bert_pretrain.py --deepspeed \
        --deepspeed_config examples/ds_config_bert.json
"""

import argparse
import os
import sys

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the container pins the TPU plugin at interpreter startup; honor
    # the env override before the backend initializes
    jax.config.update("jax_platforms", "cpu")
import numpy as np

# runnable from a source checkout without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.models.bert import BertForPreTrainingLM, bert_config


def get_args():
    parser = argparse.ArgumentParser(description="BERT pretraining")
    parser.add_argument("--model", default="bert-large",
                        help="bert-tiny | bert-base | bert-large")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--save-dir", default=None,
                        help="checkpoint dir (omit to skip saving)")
    parser.add_argument("--num-batches", type=int, default=0,
                        help="cycle a FIXED set of N synthetic batches "
                             "(learnable; the model harness uses this) "
                             "instead of an endless random stream")
    parser = deepspeed_tpu.add_config_arguments(parser)
    return parser.parse_args()


def synthetic_batches(vocab, micro_bs, gas, seq, seed, num_batches=0):
    rng = np.random.default_rng(seed)

    def make():
        ids = rng.integers(0, vocab, (gas, micro_bs, seq)).astype(np.int32)
        labels = np.where(rng.random((gas, micro_bs, seq)) < 0.15,
                          ids, -100).astype(np.int32)
        return {"input_ids": ids, "masked_lm_labels": labels,
                "next_sentence_label": rng.integers(
                    0, 2, (gas, micro_bs)).astype(np.int32)}

    fixed = [make() for _ in range(num_batches)] if num_batches else None
    i = 0
    while True:
        if fixed is not None:
            yield fixed[i % len(fixed)]
            i += 1
        else:
            yield make()


def main():
    args = get_args()
    cfg = bert_config(args.model, max_position_embeddings=args.seq_len,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0, bf16=True)
    model = BertForPreTrainingLM(cfg)
    example = {"input_ids": np.zeros((1, args.seq_len), np.int32)}
    params = model.init(jax.random.PRNGKey(args.seed), example)

    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, model=model, model_parameters=params)

    data = synthetic_batches(cfg.vocab_size,
                             engine.train_micro_batch_size_per_gpu(),
                             engine.gradient_accumulation_steps(),
                             args.seq_len, args.seed, args.num_batches)
    losses = []
    for step in range(args.steps):
        loss = engine.train_batch(batch=next(data))
        losses.append(loss)    # fetched after the loop — no per-step sync
        if step % engine.steps_per_print() == 0:
            deepspeed_tpu.log_dist(
                f"step {step}: loss {float(jax.device_get(loss)):.4f}",
                ranks=[0])
    traj = [round(float(jax.device_get(l)), 6) for l in losses]
    print("LM loss trajectory:", " ".join(f"{x:.6f}" for x in traj),
          flush=True)
    if args.save_dir:
        engine.save_checkpoint(args.save_dir)
        # commit barrier: the save is async by default
        engine.wait_for_checkpoint()


if __name__ == "__main__":
    main()
