#!/usr/bin/env python
"""3D-parallel pipeline training: PipelineModule on a pipe x data x
model mesh — the shape of the reference's Megatron+pipeline examples
(`PipeModelDataParallelTopology`, ref topology.py:246-249), TPU-native.

The compiled 1F1B executor clock-aligns the TrainSchedule instruction
streams into one SPMD program; stage parameters live in flat [S, F]
buffers sharded over (pipe, model), so parameter/optimizer memory
divides by pipe*model (*data for ZeRO-sharded state).

Run on the 8-device virtual CPU mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_3d_train.py
On a real slice, drop the env vars and size the mesh to the chips.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.runtime.pipe.module import (LayerSpec,  # noqa: E402
                                               PipelineModule)


def get_args():
    p = argparse.ArgumentParser(description="3D pipeline training")
    p.add_argument("--pipe", type=int, default=2)
    p.add_argument("--model-par", type=int, default=2)
    p.add_argument("--data", type=int, default=-1)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--gas", type=int, default=4,
                   help="microbatches per step (>= pipe stages for "
                        "pipeline overlap; gas=1 with pipe>1 is refused)")
    p = deepspeed_tpu.add_config_arguments(p)
    return p.parse_args()


def main():
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the container pins the TPU plugin at interpreter startup;
        # honor the env override before the backend initializes
        jax.config.update("jax_platforms", "cpu")
    import flax.linen as nn
    import jax.numpy as jnp

    args = get_args()
    h = args.hidden

    def mse(pred, labels):
        return jnp.mean((pred.astype(jnp.float32) -
                         labels.astype(jnp.float32)) ** 2)

    # heterogeneous on purpose: widths differ per stage, one paramless
    # callable in the chain — the case the 1F1B interpreter exists for
    module = PipelineModule(
        layers=[LayerSpec(nn.Dense, h),
                jnp.tanh,
                LayerSpec(nn.Dense, 2 * h),
                LayerSpec(nn.Dense, h // 2)],
        num_stages=args.pipe,
        loss_fn=mse,
        partition_method="parameters")

    rng = np.random.RandomState(0)
    example = jnp.asarray(rng.randn(4, h), jnp.float32)
    params = module.init_params(jax.random.PRNGKey(0), example)

    config = {
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": args.gas,
        "steps_per_print": 5,
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"pipe": args.pipe, "data": args.data,
                 "model": args.model_par},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, model=module, model_parameters=params, config=config)

    w = np.linspace(-1, 1, h * (h // 2)).reshape(h, h // 2)
    bs = 8 * args.gas
    for step in range(args.steps):
        x = rng.randn(bs, h).astype(np.float32)
        loss = engine.train_batch(batch={"x": x, "y": (x @ w)})
        if step % 5 == 0:
            print(f"step {step}: loss {float(jax.device_get(loss)):.4f}",
                  flush=True)

    # show the memory partitioning the mesh bought
    for dt, buf in engine.state.params["flat"].items():
        shard = buf.addressable_shards[0].data.shape
        print(f"flat[{dt}] global {tuple(buf.shape)} -> per-device "
              f"{tuple(shard)} (pipe x model partitioned)", flush=True)


if __name__ == "__main__":
    main()
