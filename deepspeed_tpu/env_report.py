"""`ds_report` — environment and native-op compatibility report.

Counterpart of `deepspeed/env_report.py:23-105`: per-op
compatible/installed matrix (our ops are the C++ builders in op_builder/
plus the trace-time Pallas kernels), framework versions, and device
inventory. Run as `python -m deepspeed_tpu.env_report`."""

import os
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[FAIL]{END}"
INFO = "[INFO]"

COLUMNS = ["op name", "installed", "compatible"]


def op_report():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from op_builder import ALL_OPS

    max_dots = 23
    print("-" * 64)
    print("DeepSpeed-TPU C++ op report")
    print("-" * 64)
    print("native ops compile with g++ on first use (JIT), cached by "
          "source hash")
    print("-" * 64)
    print("op name", "." * max_dots, "installed", "..", "compatible")
    print("-" * 64)
    for name, builder_cls in ALL_OPS.items():
        builder = builder_cls()
        installed = SUCCESS if builder.installed() else "[NO]"
        compatible = SUCCESS if builder.is_compatible() else FAIL
        dots = "." * (max_dots - len(name))
        print(name, dots, installed, "..", compatible)
    print("-" * 64)
    print("trace-time kernels (no prebuild needed):")
    print("  flash_attention ......... Pallas (TPU) / interpret (CPU)")
    print("  block_sparse_attention .. Pallas masked-flash")
    print("  fused train step ........ XLA fusion of loss/grad/update")
    print("-" * 64)


def debug_report():
    import jax
    import jaxlib

    report = [("jax version", jax.__version__),
              ("jaxlib version", jaxlib.__version__)]
    try:
        import flax
        report.append(("flax version", flax.__version__))
    except ImportError:
        pass
    try:
        import optax
        report.append(("optax version", optax.__version__))
    except ImportError:
        pass
    try:
        devices = jax.devices()
        report.append(("platform", devices[0].platform))
        report.append(("backend", jax.default_backend()))
        report.append(("device count", len(devices)))
        report.append(("device kind", devices[0].device_kind))
        from deepspeed_tpu.utils.timer import device_memory_stats
        mem = device_memory_stats()
        if mem["device_count"]:
            gib = 1024 ** 3
            report.append((
                "device memory",
                f"{mem['in_use_bytes'] / gib:.2f} GiB in use, "
                f"{mem['peak_bytes'] / gib:.2f} GiB peak "
                f"({mem['device_count']} local devices)"))
        else:
            report.append(("device memory",
                           "allocator stats unavailable on this backend"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        report.append(("devices", f"unavailable: {e}"))
    import deepspeed_tpu
    report.append(("deepspeed_tpu version", deepspeed_tpu.__version__))
    report.append(("deepspeed_tpu install path",
                   os.path.dirname(deepspeed_tpu.__file__)))

    print("DeepSpeed-TPU general environment info:")
    for name, value in report:
        print(f"{name} {'.' * (28 - len(name))} {value}")


def feature_report():
    """Runtime feature availability: monitor sinks, native CPU-Adam,
    Pallas flash attention."""
    rows = []
    try:
        from deepspeed_tpu.monitor.sinks import VALID_SINKS
        rows.append(("monitor sinks",
                     f"{SUCCESS} {', '.join(VALID_SINKS)} "
                     "(dependency-free: no torch/tensorflow)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("monitor sinks", f"{FAIL} {e}"))
    try:
        from op_builder import CPUAdamBuilder
        native = CPUAdamBuilder().is_compatible()
        rows.append(("native CPU-Adam",
                     SUCCESS if native else
                     f"{WARNING} numpy fallback (no C++ toolchain)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("native CPU-Adam", f"{WARNING} {e}"))
    try:
        import jax
        from jax.experimental import pallas  # noqa: F401
        on_tpu = jax.devices()[0].platform == "tpu"
        rows.append(("Pallas flash attention",
                     SUCCESS if on_tpu else
                     f"{SUCCESS} interpret mode (no TPU attached)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("Pallas flash attention", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.ops.transformer.fused_ops import \
            fused_ops_available
        ok, mode = fused_ops_available()
        rows.append(("Pallas fused ops",
                     f"{SUCCESS} {mode} (bias+residual+LayerNorm, "
                     "bias+GeLU)" if ok else f"{FAIL} {mode}"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("Pallas fused ops", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.ops.transformer.quantized_matmul import \
            resolve_quantized_compute
        active = resolve_quantized_compute("auto")
        rows.append((
            "quantized compute",
            f"{SUCCESS} int8 GEMM epilogue family "
            f"({'Pallas MXU path' if active else 'XLA fallback'}; "
            "quantized_compute block; docs/quantized-compute.md)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("quantized compute", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.ops import autotune as _autotune
        rows.append((
            "kernel autotuner",
            f"{SUCCESS} block-size table at "
            f"{_autotune.table_path()} (autotune block; "
            "bench.py --only autotune_flash)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("kernel autotuner", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.monitor.trace_export import TraceExporter  # noqa: F401
        rows.append(("trace export",
                     f"{SUCCESS} Perfetto/Chrome trace events "
                     "(monitor.trace + bin/ds_trace)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("trace export", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.monitor.flight import FlightRecorder  # noqa: F401
        rows.append(("flight recorder",
                     f"{SUCCESS} crash/stall dumps "
                     "(monitor.flight, flight_<ts>.json)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("flight recorder", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.monitor import numerics  # noqa: F401
        rows.append(("numerics health",
                     f"{SUCCESS} device-side per-layer accumulators "
                     "(monitor.numerics)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("numerics health", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.monitor.memory import MemoryLedger  # noqa: F401,E501
        rows.append(("memory ledger",
                     f"{SUCCESS} HBM/host byte attribution + OOM "
                     "forensics (monitor.memory, default on)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("memory ledger", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.runtime.zero.stage3 import \
            Zero3GatherScheduler  # noqa: F401
        rows.append((
            "ZeRO-3 overlap",
            f"{SUCCESS} layer-granular gather prefetch + "
            "reduce-scatter grads (zero_optimization.stage3; GPT-2/"
            "BERT stacks + sequential pipe chains)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("ZeRO-3 overlap", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.elasticity.runtime import \
            ElasticSupervisor  # noqa: F401
        rows.append((
            "elastic runtime",
            f"{SUCCESS} fault-injecting supervisor: mesh re-form + "
            "ZeRO re-plan + resharded resume (elasticity.runtime; "
            "docs/elasticity.md)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("elastic runtime", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.inference import InferenceEngine  # noqa: F401
        rows.append((
            "inference engine",
            f"{SUCCESS} AOT prefill+decode, paged KV cache, "
            "continuous batching, int8 weights (inference block; "
            "docs/inference.md)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("inference engine", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.monitor.serving import ServingTracker  # noqa: F401,E501
        rows.append((
            "serving observability",
            f"{SUCCESS} per-request lifecycle traces, SLO "
            "histograms, serving forensics (inference.observability; "
            "ds_trace summary --serving)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("serving observability", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.inference.speculative import build_verify_step  # noqa: F401,E501
        rows.append((
            "speculative decoding",
            f"{SUCCESS} draft propose + batched verify, lossless "
            "acceptance sampling, paged-KV rollback, adaptive k "
            "(inference.speculative; bench.py --only "
            "speculative_decode; docs/inference.md)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("speculative decoding", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.moe import MoEMLP  # noqa: F401
        rows.append((
            "mixture of experts",
            f"{SUCCESS} expert-parallel top-k routing, all-to-all "
            "dispatch, grouped-GEMM FFNs composed with ZeRO-3 + "
            "elasticity (moe block + mesh expert axis; docs/moe.md)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("mixture of experts", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.ops import overlap as _overlap
        rows.append((
            "comm/compute overlap",
            f"{SUCCESS} async-collective scheduling at "
            f"{', '.join(_overlap.SITES)} (overlap block; "
            "bench.py --only comm_overlap; docs/overlap.md)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("comm/compute overlap", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.moe.fused_dispatch import fused_dispatch  # noqa: F401,E501
        rows.append((
            "fused MoE dispatch",
            f"{SUCCESS} Pallas gather-scatter dispatch/combine "
            "kernels over capacity-indexed rows (moe.fused_dispatch; "
            "bench.py --only moe_dispatch_kernel)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("fused MoE dispatch", f"{FAIL} {e}"))
    try:
        from deepspeed_tpu.analysis.rules import ALL_RULES
        from deepspeed_tpu.analysis import baseline as _bl
        bl_path = _bl.default_path(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        try:
            n_baselined = len(_bl.load(bl_path))
        except (ValueError, OSError):
            n_baselined = 0
        rows.append((
            "static analysis",
            f"{SUCCESS} ds_lint: {len(ALL_RULES)} rules "
            f"({', '.join(ALL_RULES)}), {n_baselined} baselined "
            "finding(s) (bin/ds_lint; docs/static-analysis.md)"))
    except Exception as e:  # ds-lint: allow[BROADEXC] environment probe: the failure text IS the report row
        rows.append(("static analysis", f"{FAIL} {e}"))

    print("-" * 64)
    print("runtime feature report")
    print("-" * 64)
    for name, value in rows:
        print(f"{name} {'.' * (28 - len(name))} {value}")
    print("-" * 64)


def main():
    op_report()
    feature_report()
    debug_report()


cli_main = main

if __name__ == "__main__":
    main()
