"""deepspeed_tpu — a TPU-native training framework with the capabilities of
DeepSpeed (reference v0.3.11), built on JAX/XLA/Pallas.

Public API parity with `deepspeed/__init__.py`:
    initialize(), add_config_arguments(), init_distributed,
    DeepSpeedTransformerLayer/Config re-exports, PipelineModule re-export,
    checkpointing module.
"""

import argparse
import os

# Platform override hook: DS_TPU_PLATFORM=cpu forces the JAX backend
# before any device use — needed by subprocess harnesses (tests/model/,
# launcher smoke) on machines whose sitecustomize pins a TPU plugin
# (plain JAX_PLATFORMS env is applied before the pin and loses).
if os.environ.get("DS_TPU_PLATFORM"):
    import jax as _jax
    _jax.config.update("jax_platforms", os.environ["DS_TPU_PLATFORM"])

from deepspeed_tpu.version import __version__
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments
from deepspeed_tpu.utils.distributed import init_distributed
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.runtime.correctness import (ABCorrectnessChecker,
                                               DivergenceError)

__version_info__ = tuple(int(p) for p in __version__.split("."))
__git_hash__ = "unknown"
__git_branch__ = "unknown"


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               mesh=None):
    """Initialize the DeepSpeed-TPU engine (ref `__init__.py:50`).

    Returns a tuple of ``(engine, optimizer, training_dataloader,
    lr_scheduler)`` — same shape as the reference. If the model is a
    PipelineModule, a PipelineEngine is constructed instead
    (ref `__init__.py:109-131`).
    """
    log_dist(f"DeepSpeed-TPU info: version={__version__}", ranks=[0])

    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    is_pipelined_protocol = hasattr(model, "stage_module") and \
        hasattr(model, "loss_fn")
    if isinstance(model, PipelineModule) or is_pipelined_protocol:
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=model.mpu() if hasattr(model, "mpu")
                                else mpu,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config=config,
                                config_params=config_params,
                                mesh=mesh)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn,
                                 config=config,
                                 config_params=config_params,
                                 mesh=mesh)

    return_items = [
        engine, engine.optimizer, engine.training_dataloader,
        engine.lr_scheduler
    ]
    return tuple(return_items)


def _add_core_arguments(parser):
    """--deepspeed family of args (ref `__init__.py:142-175`)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag to user code)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Discover launch info from MPI environment")
    return parser


def add_config_arguments(parser):
    """Update an argument parser with DeepSpeed's args (ref
    `__init__.py:193`)."""
    parser = _add_core_arguments(parser)
    return parser


# Top-level re-exports (ref `__init__.py`: DeepSpeedTransformerLayer and
# DeepSpeedTransformerConfig live at package root).
from deepspeed_tpu.ops.transformer import (DeepSpeedTransformerLayer,
                                           DeepSpeedTransformerConfig)
# `deepspeed.checkpointing` module alias (ref exposes the activation-
# checkpointing module at package level).
from deepspeed_tpu.runtime.activation_checkpointing import \
    checkpointing  # noqa: F401

# Backwards compatibility with the old `deepspeed.pt` module structure
# (ref `__init__.py:37-47`): alias runtime modules under a dummy `pt`
# submodule so `import deepspeed_tpu.pt.deepspeed_utils` etc. resolve.
import sys as _sys
import types as _types

from deepspeed_tpu.runtime import config as _config_mod
from deepspeed_tpu.runtime import utils as _utils_mod
from deepspeed_tpu.runtime.fp16 import loss_scaler as _loss_scaler_mod

pt = _types.ModuleType("pt", "dummy pt module for backwards compatability")
pt.deepspeed_utils = _utils_mod
pt.deepspeed_config = _config_mod
pt.loss_scaler = _loss_scaler_mod
_sys.modules[__name__ + ".pt"] = pt
_sys.modules[__name__ + ".pt.deepspeed_utils"] = _utils_mod
_sys.modules[__name__ + ".pt.deepspeed_config"] = _config_mod
_sys.modules[__name__ + ".pt.loss_scaler"] = _loss_scaler_mod
