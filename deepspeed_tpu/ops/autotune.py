"""Block-size autotuner for the repo's Pallas kernels.

Every Pallas kernel in the tree (flash attention, packed flash, the
fused epilogue family, the quantized GEMM) hand-picks its grid/block
shapes from one sweep on one chip generation (`_DEFAULT_BLOCK = 1024`
in flash_attention.py was swept on v5e at the flagship shape).  Those
constants are wrong the moment the backend, dtype, or shape class
changes — the autotuner replaces them with a measured, persisted
table:

  * `search(...)` enumerates grid/block candidates per
    (kernel, backend, dtype, shape-class), measures each with the
    bench-harness timing discipline (warmup, interleaved best-of-N
    windows so load drift hits every candidate equally), and keeps the
    winner ONLY if it beats the hand-picked default — the table is
    never-slower by construction.
  * The winning table persists as a versioned JSON next to the jax
    compile cache (`autotune.table_path` overrides).  Each entry
    records the SHA-256 of the defining kernel module's source; a
    kernel edit invalidates its entries on load (they fall back to
    defaults with one warning — no silent reuse of measurements taken
    on different kernel code).
  * `lookup(...)` is consulted transparently at trace time by the
    kernel entry points (flash `_normalize_flash_args`, the fused-ops
    row-block launchers, the quantized GEMM) whenever the caller did
    not pass explicit block sizes.  A corrupt or stale table degrades
    to the defaults with a single warning, never a crash.

Monitor events: `autotune_search` per completed search and
`autotune_hit` once per (kernel, shape-class) the first time a traced
entry point picks up a tuned shape (attach a monitor via
`configure(monitor=...)`; the engine does this when the monitor is
enabled).  Both are rows in the EVTSCHEMA table (docs/monitoring.md).

Lookups are pure host-side dict reads after one lazy table load — no
device sync ever happens on this path (the kernel entry points are
declared HOTSYNC hot entrypoints).
"""

import hashlib
import json
import os
import threading
import time

from deepspeed_tpu.utils.logging import logger

# v2: adds the collective-schedule family (overlap on/off, issue
# distance, dispatch granularity per site/mesh/payload class) and the
# fused MoE dispatch kernel family. v1 tables are ignored with one
# warning and repopulate on the next search.
TABLE_VERSION = 2
TABLE_BASENAME = f"autotune_table_v{TABLE_VERSION}.json"

# kernel family -> defining module (its source hash invalidates the
# family's entries). Import lazily: this module must stay importable
# without pulling every kernel module in.
KERNEL_MODULES = {
    "flash_fwd": "deepspeed_tpu.ops.transformer.flash_attention",
    "flash_fwd_packed": "deepspeed_tpu.ops.transformer.flash_attention",
    "fused_ln": "deepspeed_tpu.ops.transformer.fused_ops",
    "fused_gelu": "deepspeed_tpu.ops.transformer.fused_ops",
    "quantized_matmul":
        "deepspeed_tpu.ops.transformer.quantized_matmul",
    "moe_dispatch": "deepspeed_tpu.moe.fused_dispatch",
    # collective-schedule entries describe the overlap runtime's
    # behavior, so its module source is the invalidation key
    "collective_schedule": "deepspeed_tpu.ops.overlap",
}

_lock = threading.Lock()
_state = {
    "enabled": True,
    "path": None,          # explicit table path (configure/config key)
    "table": None,         # loaded entries dict
    "loaded_from": None,   # path the current table came from
    "monitor": None,
    "dirty_warned": set(),  # one warning per failure class
    "hit_emitted": set(),   # one autotune_hit event per key
}


def configure(enabled=None, table_path=None, monitor=None):
    """Engine/bench wiring: toggle lookups, point at a table file, and
    attach a monitor for `autotune_search`/`autotune_hit` events
    (monitor=False detaches — a later engine without telemetry must
    not leave events flowing to a closed monitor). Changing the path
    drops the in-memory table so the next lookup reloads."""
    with _lock:
        if enabled is not None:
            _state["enabled"] = bool(enabled)
        if table_path is not None:
            path = table_path or None
            if path != _state["path"]:
                _state["path"] = path
                _state["table"] = None
                _state["loaded_from"] = None
                _state["hit_emitted"] = set()
        if monitor is False:
            _state["monitor"] = None
        elif monitor is not None:
            _state["monitor"] = monitor


def reset(drop_monitor=True):
    """Test hook: forget the loaded table, warnings, and config."""
    with _lock:
        _state["enabled"] = True
        _state["path"] = None
        _state["table"] = None
        _state["loaded_from"] = None
        _state["dirty_warned"] = set()
        _state["hit_emitted"] = set()
        if drop_monitor:
            _state["monitor"] = None


def table_path():
    """Resolution order: configure()/autotune.table_path config key >
    DS_TPU_AUTOTUNE_TABLE env > next to the jax compile cache >
    ~/.cache/deepspeed_tpu."""
    if _state["path"]:
        return _state["path"]
    env = os.environ.get("DS_TPU_AUTOTUNE_TABLE")
    if env:
        return env
    cache_dir = None
    try:
        import jax
        cache_dir = jax.config.jax_compilation_cache_dir
    except Exception:  # ds-lint: allow[BROADEXC] no jax / unreadable config -> fall through to the home cache dir
        cache_dir = None
    if not cache_dir:
        cache_dir = os.path.expanduser("~/.cache/deepspeed_tpu")
    return os.path.join(cache_dir, TABLE_BASENAME)


def _backend():
    try:
        import jax
        return jax.default_backend()
    except Exception:  # ds-lint: allow[BROADEXC] backend probe for a cache key; "cpu" is the safe default
        return "cpu"


def kernel_source_hash(kernel):
    """SHA-256 of the kernel family's defining module source — the
    cache-invalidation key. Unknown families hash their own name (so
    tests can register synthetic families)."""
    import importlib
    mod_name = KERNEL_MODULES.get(kernel)
    if mod_name is None:
        return hashlib.sha256(kernel.encode()).hexdigest()
    try:
        import inspect
        mod = importlib.import_module(mod_name)
        src = inspect.getsource(mod)
    except Exception:  # ds-lint: allow[BROADEXC] unreadable source (zipapp, stripped install): hash the module name — entries then never validate stale
        src = mod_name
    return hashlib.sha256(src.encode()).hexdigest()


def pow2_bucket(n):
    """Shape-class bucketing: next power of two >= n (floor 1), so one
    measured entry covers the whole bucket instead of every exact row
    count re-searching."""
    n = max(int(n), 1)
    b = 1
    while b < n:
        b *= 2
    return b


def _dtype_str(dtype):
    """Canonical dtype spelling for keys: np.dtype collapses jnp type
    objects, np dtypes and strings onto one name ("float32",
    "bfloat16", ...)."""
    import numpy as _np
    try:
        return str(_np.dtype(dtype))
    except TypeError:
        return str(dtype)


def entry_key(kernel, shape_class, dtype, backend=None):
    backend = backend or _backend()
    return f"{kernel}|{backend}|{_dtype_str(dtype)}|{shape_class}"


def _warn_once(tag, msg):
    if tag in _state["dirty_warned"]:
        return
    _state["dirty_warned"] = _state["dirty_warned"] | {tag}
    logger.warning(msg)


def _load_table_locked():
    """Load + validate the JSON table (call with _lock held). Any
    failure — unreadable file, bad JSON, wrong version, non-dict
    schema — degrades to an empty table with ONE warning."""
    if _state["table"] is not None:
        return _state["table"]
    path = table_path()
    entries = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict) or \
                    not isinstance(doc.get("entries"), dict):
                raise ValueError("not an autotune table document")
            if doc.get("version") != TABLE_VERSION:
                _warn_once(
                    "version",
                    f"autotune table {path} has version "
                    f"{doc.get('version')!r} != {TABLE_VERSION}; "
                    "ignoring it (kernels use default block sizes "
                    "until a new search repopulates it)")
            else:
                entries = doc["entries"]
        except Exception as e:  # ds-lint: allow[BROADEXC] corrupt table must degrade to defaults with one warning, never crash a training trace
            _warn_once(
                "corrupt",
                f"autotune table {path} is unreadable "
                f"({type(e).__name__}: {e}); kernels use default "
                "block sizes")
            entries = {}
    _state["table"] = entries
    _state["loaded_from"] = path
    return entries


def lookup(kernel, shape_class, dtype, backend=None):
    """Tuned params dict for (kernel, backend, dtype, shape_class), or
    None (no entry / autotune disabled / stale source hash). Consulted
    at trace time by the kernel entry points; one `autotune_hit` event
    per key when a monitor is attached."""
    if not _state["enabled"]:
        return None
    key = entry_key(kernel, shape_class, dtype, backend)
    with _lock:
        entries = _load_table_locked()
        entry = entries.get(key)
        if entry is None:
            return None
        if entry.get("source_hash") != kernel_source_hash(kernel):
            # the kernel changed since the measurement: measurements on
            # old kernel code must not silently steer the new one
            del entries[key]
            _warn_once(
                f"stale:{kernel}",
                f"autotune entries for kernel {kernel!r} were measured "
                "on different kernel source; using default block sizes "
                "until a new search runs")
            return None
        params = dict(entry.get("params") or {})
        first_hit = key not in _state["hit_emitted"]
        if first_hit:
            _state["hit_emitted"] = _state["hit_emitted"] | {key}
        mon = _state["monitor"]
    if first_hit and mon is not None:
        mon.event("autotune_hit", kernel=kernel,
                  shape_class=shape_class, dtype=_dtype_str(dtype),
                  backend=backend or _backend(), params=params)
    return params or None


def record(kernel, shape_class, dtype, params, best_us, default_us,
           candidates_tried, backend=None, persist=True):
    """Store a search result and (optionally) persist the table
    atomically (tmp + os.replace, no partial table ever visible)."""
    key = entry_key(kernel, shape_class, dtype, backend)
    entry = {
        "params": dict(params),
        "best_us": round(float(best_us), 3),
        "default_us": round(float(default_us), 3),
        "candidates_tried": int(candidates_tried),
        "source_hash": kernel_source_hash(kernel),
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    with _lock:
        entries = _load_table_locked()
        entries[key] = entry
        path = _state["loaded_from"] or table_path()
        doc = {"version": TABLE_VERSION, "entries": dict(entries)}
    if persist:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    return entry


def measure_callable(fn, warmup=2, reps=3, inner=1):
    """Bench-harness timing for one candidate: warm the compile +
    donated-buffer layouts, then best-of-`reps` windows of `inner`
    calls (jax.block_until_ready on the result). Returns seconds per
    call."""
    import jax
    r = None
    for _ in range(max(warmup, 1)):
        r = fn()
    jax.block_until_ready(r)
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        for _ in range(inner):
            r = fn()
        jax.block_until_ready(r)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best


def search(kernel, shape_class, dtype, candidates, default_params,
           measure=None, build=None, warmup=2, reps=3, backend=None,
           persist=True):
    """Enumerate `candidates` (list of params dicts; `default_params`
    is measured too and acts as the floor), measure each, keep the
    winner ONLY if it beats the default — so applying the table is
    never slower than the hand-picked shapes.

    Measurement comes either from `measure(params) -> seconds` or from
    `build(params) -> zero-arg jitted callable` timed by
    `measure_callable`. Candidate rounds INTERLEAVE (round-robin over
    candidates, best-of-`reps` per candidate) so machine-load drift
    lands on every candidate equally — the bench harness's interleaved
    A/B discipline.

    Returns {params, best_us, default_us, speedup_vs_default,
    candidates_tried}."""
    if measure is None and build is None:
        raise ValueError("search() needs measure= or build=")
    all_params = [dict(default_params)] + \
        [dict(c) for c in candidates
         if dict(c) != dict(default_params)]
    if measure is not None:
        times = [measure(p) for p in all_params]
    else:
        fns = [build(p) for p in all_params]
        # warm every candidate first, then interleave the timed reps
        times = [float("inf")] * len(fns)
        for fn in fns:
            measure_callable(fn, warmup=warmup, reps=1, inner=1)
        import jax
        for _ in range(max(reps, 1)):
            for i, fn in enumerate(fns):
                t0 = time.perf_counter()
                r = fn()
                jax.block_until_ready(r)
                times[i] = min(times[i], time.perf_counter() - t0)
    default_s = times[0]
    best_i = min(range(len(all_params)), key=lambda i: times[i])
    best_params, best_s = all_params[best_i], times[best_i]
    if best_s > default_s:   # never-slower floor
        best_params, best_s = all_params[0], default_s
    entry = record(kernel, shape_class, dtype, best_params,
                   best_s * 1e6, default_s * 1e6, len(all_params),
                   backend=backend, persist=persist)
    result = {
        "params": best_params,
        "best_us": entry["best_us"],
        "default_us": entry["default_us"],
        "speedup_vs_default": round(default_s / max(best_s, 1e-12), 4),
        "candidates_tried": len(all_params),
    }
    mon = _state["monitor"]
    if mon is not None:
        mon.event("autotune_search", kernel=kernel,
                  shape_class=shape_class, dtype=_dtype_str(dtype),
                  backend=backend or _backend(),
                  params=best_params,
                  best_us=result["best_us"],
                  default_us=result["default_us"],
                  speedup_vs_default=result["speedup_vs_default"],
                  candidates_tried=result["candidates_tried"])
    return result


# ----------------------------------------------------------------------
# kernel-family helpers: shape classes + candidate enumeration. The
# kernel entry points call the *_params lookups at trace time; the
# bench legs / operators call the *_candidates enumerators to search.
# ----------------------------------------------------------------------
def flash_shape_class(t, d, causal, packed):
    return f"t{t}_d{d}_{'causal' if causal else 'bidir'}" + \
        ("_packed" if packed else "")


def flash_block_candidates(t):
    """(block_q, block_k) grid candidates: power-of-two tiles in
    [128, 1024] that divide t."""
    sizes = [b for b in (128, 256, 512, 1024) if b <= t and t % b == 0]
    return [{"block_q": bq, "block_k": bk}
            for bq in sizes for bk in sizes]


def flash_blocks(t, d, causal, packed, dtype):
    """Tuned (block_q, block_k) for a flash launch, or None."""
    kernel = "flash_fwd_packed" if packed else "flash_fwd"
    params = lookup(kernel, flash_shape_class(t, d, causal, packed),
                    dtype)
    if not params:
        return None
    bq, bk = params.get("block_q"), params.get("block_k")
    if not bq or not bk or t % int(bq) or t % int(bk):
        return None    # table entry from an incompatible shape class
    return int(bq), int(bk)


def row_kernel_shape_class(n, h_padded):
    return f"rows{pow2_bucket(n)}_h{h_padded}"


def row_block_candidates(n):
    """Row-block targets for the fused epilogue kernels (the
    `_row_block` launcher argument)."""
    return [{"row_block": rb} for rb in (64, 128, 256, 512, 1024)
            if rb <= max(n, 64)]


def row_block_target(kernel, n, h_padded, dtype):
    """Tuned row-block target for a fused epilogue launch, or None."""
    params = lookup(kernel, row_kernel_shape_class(n, h_padded), dtype)
    if not params:
        return None
    rb = params.get("row_block")
    return int(rb) if rb else None


def qmm_shape_class(m, k, n):
    return f"m{pow2_bucket(m)}_k{k}_n{n}"


def qmm_block_candidates(m, n):
    """(block_m, block_n) tile candidates for the quantized GEMM."""
    bms = [b for b in (128, 256, 512) if b <= max(m, 128)]
    bns = [b for b in (128, 256, 512) if b <= max(n, 128)]
    return [{"block_m": bm, "block_n": bn} for bm in bms for bn in bns]


def qmm_blocks(m, k, n, dtype):
    """Tuned (block_m, block_n) for the quantized GEMM, or None."""
    params = lookup("quantized_matmul", qmm_shape_class(m, k, n), dtype)
    if not params:
        return None
    bm, bn = params.get("block_m"), params.get("block_n")
    if not bm or not bn:
        return None
    return int(bm), int(bn)


# ----------------------------------------------------------------------
# collective-schedule family: per-(site, mesh-shape, payload-bytes)
# overlap variants, searched with the same never-slower discipline and
# persisted in the same versioned table as the block shapes. Consulted
# by ops/overlap.py `schedule()` when `overlap.sites == "auto"`.
# ----------------------------------------------------------------------
# entries are schedules, not kernels: this string fills the key's
# dtype slot (_dtype_str passes non-dtypes through verbatim)
COLLECTIVE_DTYPE = "schedule"

COLLECTIVE_DEFAULT = {"overlap": True, "issue_distance": 1,
                      "granularity": 1}


def mesh_shape_class(mesh):
    """Axis-signature string for a mesh ("p1.d8.e1.m1"); accepts a jax
    Mesh, a {name: size} dict, or None ("nomesh")."""
    if mesh is None:
        return "nomesh"
    try:
        items = list(mesh.shape.items())
    except AttributeError:
        items = list(dict(mesh).items())
    return ".".join(f"{str(n)[:1]}{int(s)}" for n, s in items) or "nomesh"


def collective_shape_class(site, mesh, payload_bytes):
    """Shape class for a collective site: mesh axis signature plus the
    pow2 KiB bucket of the per-shard payload."""
    kb = pow2_bucket(max(int(payload_bytes), 1024) // 1024)
    return f"{site}|{mesh_shape_class(mesh)}|kb{kb}"


def collective_candidates(site):
    """Schedule candidates per site. MoE varies dispatch granularity,
    ring varies how many permutes stay in flight, the ZeRO-3 leaf
    fence is a pure on/off decision."""
    if site == "moe_dispatch":
        return [{"overlap": o, "issue_distance": 1, "granularity": g}
                for o in (True, False) for g in (1, 2, 4)]
    if site == "ring":
        return [{"overlap": o, "issue_distance": d, "granularity": 1}
                for o in (True, False) for d in (1, 2)]
    return [{"overlap": o, "issue_distance": 1, "granularity": 1}
            for o in (True, False)]


def collective_schedule(site, mesh, payload_bytes):
    """Tuned schedule params for a collective site, or None."""
    return lookup("collective_schedule",
                  collective_shape_class(site, mesh, payload_bytes),
                  COLLECTIVE_DTYPE)


def search_collective_schedule(site, mesh, payload_bytes, measure,
                               backend=None, persist=True):
    """Search the schedule variants for one site with `measure(params)
    -> seconds`. The un-tuned behavior (overlap on, distance 1,
    granularity 1) is the default and the never-slower floor."""
    return search("collective_schedule",
                  collective_shape_class(site, mesh, payload_bytes),
                  COLLECTIVE_DTYPE, collective_candidates(site),
                  dict(COLLECTIVE_DEFAULT), measure=measure,
                  backend=backend, persist=persist)
