"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference snapshot predates sequence parallelism entirely (SURVEY
§2.3: its long-sequence story is block-sparse attention + activation
checkpointing); later DeepSpeed added Ulysses (all-to-all head/sequence
swap) and the community added ring attention. Both are first-class here
because they shape the long-context design:

  ring_attention    — Q stays put; KV blocks rotate around the `seq`
                      mesh axis via `ppermute` (ICI neighbor hops),
                      merging per-block softmax partials with the
                      online (m, l) recurrence. HBM per device is
                      O(T/S · d); total T is unbounded by chip memory.
  ulysses_attention — `all_to_all` swaps the sequence shard for a head
                      shard so every device runs *full-sequence*
                      attention on H/S heads (DeepSpeed-Ulysses
                      semantics), then swaps back. Cheaper collectives
                      for moderate T; requires heads % seq_par == 0.

Both run under `shard_map` over the `seq` axis and are transparent to
autodiff (the transpose of ppermute/all_to_all is the reverse
ppermute/all_to_all), so the backward pass is itself a ring/all-to-all
schedule — no hand-written backward communication.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from deepspeed_tpu.runtime.compat import shard_map

from deepspeed_tpu.ops import overlap as _overlap
from deepspeed_tpu.ops.transformer.flash_attention import (NEG_INF,
                                                           dense_attention)


def _ring_overlap_setup(k, v, axis_name, s_size, overlap_sched=None):
    """Resolve the `ring` overlap schedule and build the pre-rotated
    KV window (ops/overlap.py discipline).

    Returns (sched, win): `win` is None when the site is not
    overlapped (the caller keeps the baseline merge-then-permute
    scan); otherwise win[j] holds the block j hops back — the block
    step i+j consumes at step i — so each scan step issues ONE 1-hop
    `ppermute` of the window's deepest entry BEFORE the held block's
    merge consumes (`issue_distance` = window depth = permutes in
    flight; d-1 extra prologue rotations build the stagger). The merge
    order and block contents are identical to the baseline —
    scheduled-vs-unscheduled outputs are bit-exact (test-pinned)."""
    payload = 2 * int(np.prod(k.shape)) * np.dtype(k.dtype).itemsize
    sched = overlap_sched if overlap_sched is not None else \
        _overlap.schedule(_overlap.SITE_RING, payload_bytes=payload,
                          mesh={axis_name: s_size})
    if not sched["overlap"]:
        _overlap.record_inflight(_overlap.SITE_RING, axis_name, 0)
        return sched, None
    dist = min(max(int(sched["issue_distance"]), 1), s_size)
    win = [(k, v)]
    for j in range(1, dist):
        pj = [(i, (i + j) % s_size) for i in range(s_size)]
        win.append((jax.lax.ppermute(k, axis_name, pj),
                    jax.lax.ppermute(v, axis_name, pj)))
    # the send/recv window: `dist` (K, V) block pairs in flight
    _overlap.record_inflight(_overlap.SITE_RING, axis_name,
                             dist * payload)
    return sched, tuple(win)


def _block_attn_partial(q, k, v, sm_scale, mask=None):
    """Unmerged attention partial of one KV block: returns (numerator
    [B,Tq,H,D], m [B,H,Tq,1], l [B,H,Tq,1]) for online-softmax merging.

    XLA fallback path (scores materialize per ring step) — used when
    the local chunk doesn't meet the flash kernel's tiling contract;
    the primary path runs the Pallas flash kernel per ring step and
    merges normalized (out, lse) partials (`_ring_local_flash`)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)              # [B,H,Tq,1]
    # fully-masked rows: exp(NEG_INF - NEG_INF) would be 1; clamp m
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)              # [B,H,Tq,1]
    num = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return num.astype(jnp.float32), m_safe, l


def _merge(acc, num, m_new, l_new):
    """Merge one block partial into the running (num, m, l)."""
    num_acc, m_acc, l_acc = acc
    m = jnp.maximum(m_acc, m_new)
    a1 = jnp.exp(m_acc - m)          # [B,H,Tq,1]
    a2 = jnp.exp(m_new - m)
    # broadcast [B,H,Tq,1] -> [B,Tq,H,1] for the numerator layout
    def bhq1_to_bqh1(x):
        return x.transpose(0, 2, 1, 3)
    num_out = num_acc * bhq1_to_bqh1(a1) + num * bhq1_to_bqh1(a2)
    l_out = l_acc * a1 + l_new * a2
    return num_out, m, l_out


def _ring_local_flash(q, k, v, axis_name, causal=True, sm_scale=None,
                      interpret=None, head_packing="auto",
                      overlap_sched=None):
    """Per-device ring body on the Pallas flash kernel: each ring step
    folds the held KV block into the running (out, lse) carry via
    `flash_attention_merge` — the softmax-partial merge
    (m = max(lse1, lse2); w_i = exp2(lse_i − m)) happens IN THE KERNEL
    EPILOGUE, so the per-step partial never round-trips HBM through an
    XLA elementwise merge chain (it previously cost ~5 extra passes
    over [B,Tl,H,D] fp32 per ring step).  Chunk-level causality picks
    the kernel variant per step: the diagonal chunk runs the causal
    kernel, strictly-lower chunks the non-causal one, upper chunks
    pass the carry through untouched (no kernel launch at all)."""
    from deepspeed_tpu.ops.transformer.flash_attention import \
        flash_attention_merge
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    s_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape

    o0 = jnp.zeros((b, tl, h, d), jnp.float32)
    lse0 = jnp.full((b, h, tl, 1), NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % s_size) for i in range(s_size)]

    def merged(kb, vb, o, lse, step_causal):
        return flash_attention_merge(
            q, kb, vb, o, lse, causal=step_causal, sm_scale=sm_scale,
            interpret=interpret, head_packing=head_packing)

    def fold(kb, vb, o, lse, step_idx):
        src = (my_idx - step_idx) % s_size
        if causal:
            def diag(args):
                return merged(*args, True)

            def full(args):
                return merged(*args, False)

            def none(args):
                return args[2], args[3]

            branch = jnp.where(src == my_idx, 0,
                               jnp.where(src < my_idx, 1, 2))
            return jax.lax.switch(branch, [diag, full, none],
                                  (kb, vb, o, lse))
        return merged(kb, vb, o, lse, False)

    _sched, win = _ring_overlap_setup(k, v, axis_name, s_size,
                                      overlap_sched)
    if win is None:
        def step(carry, step_idx):
            o, lse, kb, vb = carry
            o, lse = fold(kb, vb, o, lse, step_idx)
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
            return (o, lse, kb, vb), None

        (o, _, _, _), _ = jax.lax.scan(
            step, (o0, lse0, k, v), jnp.arange(s_size))
    else:
        def step(carry, step_idx):
            o, lse, blocks = carry
            kb, vb = blocks[0]
            nk = jax.lax.ppermute(blocks[-1][0], axis_name, perm)
            nv = jax.lax.ppermute(blocks[-1][1], axis_name, perm)
            # issue-early: chunk k+1's permute must be in flight
            # before chunk k's flash-merge consumes the held block
            kb, vb = _overlap.fence((kb, vb), (nk, nv))
            o, lse = fold(kb, vb, o, lse, step_idx)
            return (o, lse, blocks[1:] + ((nk, nv),)), None

        (o, _, _), _ = jax.lax.scan(
            step, (o0, lse0, win), jnp.arange(s_size))
    return o.astype(q.dtype)


def ring_attention_local(q, k, v, axis_name, causal=True, sm_scale=None,
                         overlap_sched=None):
    """Per-device body (inside shard_map): local Q [B,Tl,H,D] attends to
    the full sequence as KV blocks rotate around `axis_name`."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    s_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape

    num0 = jnp.zeros((b, tl, h, d), jnp.float32)
    m0 = jnp.full((b, h, tl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tl, 1), jnp.float32)

    perm = [(i, (i + 1) % s_size) for i in range(s_size)]

    def fold(kb, vb, acc, step_idx):
        # kv block currently held originated at device (my_idx - step)
        src = (my_idx - step_idx) % s_size
        if causal:
            # chunk-causal: attend iff src < my_idx; diagonal chunk uses
            # the in-chunk triangular mask
            rows = jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 1)
            tri = rows >= cols
            full = jnp.ones((tl, tl), bool)
            none = jnp.zeros((tl, tl), bool)
            mask2d = jnp.where(src == my_idx, tri,
                               jnp.where(src < my_idx, full, none))
            mask = mask2d[None, None, :, :]
        else:
            mask = None
        blk_num, blk_m, blk_l = _block_attn_partial(q, kb, vb, sm_scale,
                                                    mask)
        return _merge(acc, blk_num, blk_m, blk_l)

    _sched, win = _ring_overlap_setup(k, v, axis_name, s_size,
                                      overlap_sched)
    if win is None:
        def step(carry, step_idx):
            num, m, l, kb, vb = carry
            num, m, l = fold(kb, vb, (num, m, l), step_idx)
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
            return (num, m, l, kb, vb), None

        (num, m, l, _, _), _ = jax.lax.scan(
            step, (num0, m0, l0, k, v), jnp.arange(s_size))
    else:
        def step(carry, step_idx):
            num, m, l, blocks = carry
            kb, vb = blocks[0]
            nk = jax.lax.ppermute(blocks[-1][0], axis_name, perm)
            nv = jax.lax.ppermute(blocks[-1][1], axis_name, perm)
            # issue-early: the next hop's send is in flight before the
            # held block's merge consumes
            kb, vb = _overlap.fence((kb, vb), (nk, nv))
            num, m, l = fold(kb, vb, (num, m, l), step_idx)
            return (num, m, l, blocks[1:] + ((nk, nv),)), None

        (num, m, l, _), _ = jax.lax.scan(
            step, (num0, m0, l0, win), jnp.arange(s_size))
    l = jnp.maximum(l, 1e-30)
    out = num / l.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def _mesh_targets_tpu(mesh):
    """Whether the MESH's devices are TPUs. The auto-selection keys on
    this rather than jax.default_backend() so ahead-of-time lowering for
    a TPU target from a CPU host process still picks the flash body —
    default_backend() reports the HOST's backend at trace time, which
    silently chose the XLA fallback under cross-backend AOT."""
    try:
        return mesh.devices.flat[0].platform == "tpu"
    except Exception:  # ds-lint: allow[BROADEXC] AbstractMesh / device-less mesh variants have no .devices; fall back to the host backend
        return jax.default_backend() == "tpu"


def ring_attention(q, k, v, mesh: Mesh, axis_name="seq", causal=True,
                   sm_scale=None, use_flash=None, interpret=None,
                   head_packing="auto"):
    """Ring attention over [B, T, H, D] with T sharded on `axis_name`.

    use_flash=None auto-selects the per-step Pallas flash body when the
    mesh's devices are TPUs (keyed on the MESH target, not
    jax.default_backend()) and the LOCAL chunk meets the kernel's
    tiling contract (chunk length a multiple of 128, head dim a
    multiple of 64); otherwise the XLA online-softmax fallback runs.
    The flash body merges each step's (out, lse) partial in the kernel
    epilogue (`flash_attention_merge`) and packs d=64 head pairs into
    K=128 contractions per `head_packing` ("auto"|"packed"|"off").

    **Cross-backend AOT lowering (CPU host → TPU target): pass
    `use_flash=True` explicitly.** The auto-selection inspects the
    mesh's devices AT TRACE TIME; device-bearing meshes resolve the
    TPU target correctly even from a CPU host process, but abstract /
    device-less meshes (e.g. `jax.sharding.AbstractMesh` under
    `jax.export`-style lowering) fall back to the HOST backend and
    would silently pick the XLA body for a TPU executable.  interpret
    forwards to the kernel so CPU tests exercise the same code path.
    (Same selection and the same AOT caveat apply to
    `ulysses_attention`.)"""
    from deepspeed_tpu.ops.transformer.flash_attention import \
        flash_attention_usable

    s_size = mesh.shape[axis_name]
    b, t, h, d = q.shape
    if t % s_size:
        raise ValueError(
            f"sequence length {t} must be divisible by the '{axis_name}' "
            f"axis size {s_size} (pad the sequence; shard_map would "
            "otherwise fail with an opaque sharding error)")
    local_example = jax.ShapeDtypeStruct((b, t // s_size, h, d), q.dtype)
    if use_flash is None:
        use_flash = (_mesh_targets_tpu(mesh) or bool(interpret)) \
            and flash_attention_usable(local_example, True)
    if use_flash:
        body = functools.partial(_ring_local_flash, axis_name=axis_name,
                                 causal=causal, sm_scale=sm_scale,
                                 interpret=interpret,
                                 head_packing=head_packing)
    else:
        body = functools.partial(ring_attention_local, axis_name=axis_name,
                                 causal=causal, sm_scale=sm_scale)
    spec = PartitionSpec(None, axis_name, None, None)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ulysses_attention_local(q, k, v, axis_name, causal=True, sm_scale=None,
                            attn_fn=None):
    """Per-device body: all-to-all swaps the local sequence shard for a
    head shard, runs full-sequence attention on H/S heads, swaps back
    (DeepSpeed-Ulysses dataflow)."""
    s_size = jax.lax.psum(1, axis_name)
    b, tl, h, d = q.shape
    assert h % s_size == 0, \
        f"heads {h} must be divisible by seq-parallel degree {s_size}"

    def seq_to_head(x):
        # [B, Tl, H, D] -> [B, Tl*S, H/S, D]: trade head shards for the
        # full sequence (source devices concatenate in ring order, which
        # is global sequence order)
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def head_to_seq(x):
        # [B, T, H/S, D] -> [B, Tl, H, D]: the inverse swap
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qg, kg, vg = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    if attn_fn is None:
        attn_fn = functools.partial(dense_attention, causal=causal,
                                    sm_scale=sm_scale)
    out = attn_fn(qg, kg, vg)                    # [B, T, H/S, D]
    return head_to_seq(out)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name="seq", causal=True,
                      sm_scale=None, use_flash=None, head_packing="auto"):
    """Ulysses sequence-parallel attention over [B, T, H, D] with T
    sharded on `axis_name`.

    Cross-backend AOT lowering (CPU host → TPU target) must pass
    `use_flash=True` explicitly — see `ring_attention`'s note: the
    auto-selection keys on the mesh's devices at trace time and a
    device-less mesh falls back to the host backend."""
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention, flash_attention_usable)

    s_size = mesh.shape[axis_name]
    b, t, h, d = q.shape
    if t % s_size:
        raise ValueError(
            f"sequence length {t} must be divisible by the '{axis_name}' "
            f"axis size {s_size} (pad the sequence)")
    if h % s_size:
        raise ValueError(
            f"ulysses_attention needs heads {h} divisible by the "
            f"'{axis_name}' axis size {s_size} (the all-to-all trades "
            "a head shard for the sequence shard); use ring_attention "
            "for indivisible head counts")

    attn_fn = None
    if use_flash is None:
        # keyed on the mesh target, not default_backend() — see
        # _mesh_targets_tpu (cross-backend AOT lowering)
        use_flash = _mesh_targets_tpu(mesh)
    if use_flash:
        def attn_fn(qg, kg, vg):
            if flash_attention_usable(qg, True):
                return flash_attention(qg, kg, vg, causal=causal,
                                       sm_scale=sm_scale,
                                       head_packing=head_packing)
            return dense_attention(qg, kg, vg, causal=causal,
                                   sm_scale=sm_scale)

    spec = PartitionSpec(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(ulysses_attention_local, axis_name=axis_name,
                          causal=causal, sm_scale=sm_scale,
                          attn_fn=attn_fn),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
