from deepspeed_tpu.ops.sequence.ring_attention import (
    ring_attention, ulysses_attention)

__all__ = ["ring_attention", "ulysses_attention"]
