"""DeepSpeedCPUAdam — host-side AdamW over flat numpy buffers.

Counterpart of `deepspeed/ops/adam/cpu_adam.py:12` + `csrc/adam/
cpu_adam.cpp`. The optimizer half of ZeRO-Offload: fp32 master params
and both moments live in host RAM; each step consumes device gradients
and produces updated parameters (optionally cast to bf16 in the same
native pass, mirroring the fused fp16-param copy of ref
`stage2.py:1416-1427`).

Falls back to a numpy implementation when the native library is
unavailable (no g++, or DS_BUILD_CPU_ADAM=0), with identical numerics.
"""

import itertools

import numpy as np

from deepspeed_tpu.utils.logging import logger

_id_counter = itertools.count()


def _load_native():
    try:
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))))
        from op_builder.cpu_adam import CPUAdamBuilder
        return CPUAdamBuilder().load()
    except Exception:  # pragma: no cover - depends on toolchain
        logger.warning("cpu_adam native build unavailable; falling "
                       "back to numpy", exc_info=True)
        return None


class DeepSpeedCPUAdam:
    """Flat-buffer host AdamW (API shape follows ref cpu_adam.py:12)."""

    def __init__(self, num_elements, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True, use_native=True):
        self.opt_id = next(_id_counter)
        self.num_elements = int(num_elements)
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0

        self.exp_avg = np.zeros(self.num_elements, np.float32)
        self.exp_avg_sq = np.zeros(self.num_elements, np.float32)

        self._lib = _load_native() if use_native else None
        if self._lib is not None:
            self._lib.ds_adam_create(
                self.opt_id, float(lr), float(betas[0]), float(betas[1]),
                float(eps), float(weight_decay), int(adamw_mode))

    @property
    def native(self):
        return self._lib is not None

    def step(self, params, grads, lr=None, params_bf16_out=None):
        """In-place AdamW over flat fp32 `params` given fp32 `grads`.
        If `params_bf16_out` (uint16 view of bf16) is given, the native
        path also writes the downcast params in the same pass."""
        import ctypes
        assert params.dtype == np.float32 and grads.dtype == np.float32
        assert params.size == self.num_elements == grads.size
        lr_eff = -1.0 if lr is None else float(lr)

        if self._lib is not None:
            f32p = ctypes.POINTER(ctypes.c_float)
            u16p = ctypes.POINTER(ctypes.c_uint16)
            if params_bf16_out is not None:
                step = self._lib.ds_adam_step_copy_bf16(
                    self.opt_id, params.size,
                    params.ctypes.data_as(f32p),
                    grads.ctypes.data_as(f32p),
                    self.exp_avg.ctypes.data_as(f32p),
                    self.exp_avg_sq.ctypes.data_as(f32p),
                    params_bf16_out.ctypes.data_as(u16p),
                    lr_eff)
            else:
                step = self._lib.ds_adam_step(
                    self.opt_id, params.size,
                    params.ctypes.data_as(f32p),
                    grads.ctypes.data_as(f32p),
                    self.exp_avg.ctypes.data_as(f32p),
                    self.exp_avg_sq.ctypes.data_as(f32p),
                    lr_eff)
            self.step_count = int(step)
            return params

        # numpy fallback: one full-range chunk (identical math)
        self.step_count += 1
        return self.step_chunk(0, self.num_elements, params, grads,
                               lr=lr, params_bf16_out=params_bf16_out)

    def begin_step(self):
        """Open a chunked optimizer step: advances the bias-correction
        counter ONCE; subsequent step_chunk calls share it. Pairs with
        the offload driver's D2H/compute/H2D pipelining."""
        self.step_count += 1
        if self._lib is not None:
            self._lib.ds_adam_set_step(self.opt_id, self.step_count)

    def step_chunk(self, lo, hi, params, grads, lr=None,
                   params_bf16_out=None):
        """AdamW over elements [lo, hi) at the step opened by
        begin_step. `params`/`grads` are the CHUNK arrays (len hi-lo);
        moments are sliced internally."""
        import ctypes
        assert self.step_count >= 1, \
            "step_chunk requires begin_step() first (step 0 would " \
            "divide by a zero bias correction)"
        assert params.dtype == np.float32 and grads.dtype == np.float32
        assert params.size == hi - lo == grads.size
        lr_eff = -1.0 if lr is None else float(lr)
        m = self.exp_avg[lo:hi]
        v = self.exp_avg_sq[lo:hi]

        if self._lib is not None:
            f32p = ctypes.POINTER(ctypes.c_float)
            u16p = ctypes.POINTER(ctypes.c_uint16)
            bf16 = params_bf16_out.ctypes.data_as(u16p) \
                if params_bf16_out is not None else \
                ctypes.cast(None, u16p)
            self._lib.ds_adam_step_chunk(
                self.opt_id, self.step_count, hi - lo,
                params.ctypes.data_as(f32p),
                grads.ctypes.data_as(f32p),
                m.ctypes.data_as(f32p), v.ctypes.data_as(f32p),
                bf16, lr_eff)
            return params

        # numpy fallback (identical math, explicit step)
        lr_v = self.lr if lr is None else lr
        b1, b2 = self.betas
        g = grads
        if not self.adamw_mode and self.weight_decay:
            g = g + self.weight_decay * params
        m *= b1
        m += (1 - b1) * g
        v *= b2
        v += (1 - b2) * g * g
        bias1 = 1 - b1 ** self.step_count
        bias2 = 1 - b2 ** self.step_count
        denom = np.sqrt(v) / np.sqrt(bias2) + self.eps
        update = (lr_v / bias1) * (m / denom)
        if self.adamw_mode and self.weight_decay:
            update = update + lr_v * self.weight_decay * params
        params -= update
        if params_bf16_out is not None:
            import jax.numpy as jnp
            bf = jnp.asarray(params, jnp.bfloat16)
            params_bf16_out[:] = np.asarray(bf).view(np.uint16)
        return params

    def step_chunk_q8(self, lo, hi, params, qgrads, scales, block,
                      lr=None, params_bf16_out=None):
        """step_chunk with int8 gradients + one fp32 scale per `block`
        elements (ZeRO-Offload compressed wire). The chunk must start on
        a block boundary; scales[i // block] covers chunk element i.
        Native path dequantizes inside the fused AdamW loop."""
        import ctypes
        assert self.step_count >= 1, "step_chunk_q8 requires begin_step()"
        assert params.dtype == np.float32 and qgrads.dtype == np.int8
        assert scales.dtype == np.float32
        assert params.size == hi - lo == qgrads.size
        assert scales.size * block >= hi - lo
        if self._lib is not None:
            f32p = ctypes.POINTER(ctypes.c_float)
            i8p = ctypes.POINTER(ctypes.c_int8)
            u16p = ctypes.POINTER(ctypes.c_uint16)
            bf16 = params_bf16_out.ctypes.data_as(u16p) \
                if params_bf16_out is not None else \
                ctypes.cast(None, u16p)
            m = self.exp_avg[lo:hi]
            v = self.exp_avg_sq[lo:hi]
            self._lib.ds_adam_step_chunk_q8(
                self.opt_id, self.step_count, hi - lo,
                params.ctypes.data_as(f32p),
                np.ascontiguousarray(qgrads).ctypes.data_as(i8p),
                np.ascontiguousarray(scales).ctypes.data_as(f32p),
                block, m.ctypes.data_as(f32p), v.ctypes.data_as(f32p),
                bf16, -1.0 if lr is None else float(lr))
            return params
        # numpy fallback: dequantize, then the shared chunk math
        g = qgrads.astype(np.float32) * \
            np.repeat(scales, block)[: hi - lo]
        return self.step_chunk(lo, hi, params, g, lr=lr,
                               params_bf16_out=params_bf16_out)

    def step_chunk_q1(self, lo, hi, params, packed, scales, block,
                      lr=None, params_bf16_out=None):
        """step_chunk with 1-bit gradients: sign bits packed LSB-first
        8-per-byte (`pack_signs` layout, runtime/fp16/onebit_adam.py)
        with one fp32 scale per `block` elements; g = ±scale."""
        import ctypes
        assert self.step_count >= 1, "step_chunk_q1 requires begin_step()"
        assert params.dtype == np.float32 and packed.dtype == np.uint8
        assert scales.dtype == np.float32
        n = hi - lo
        assert params.size == n and packed.size >= -(-n // 8)
        assert scales.size * block >= n
        if self._lib is not None:
            f32p = ctypes.POINTER(ctypes.c_float)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            u16p = ctypes.POINTER(ctypes.c_uint16)
            bf16 = params_bf16_out.ctypes.data_as(u16p) \
                if params_bf16_out is not None else \
                ctypes.cast(None, u16p)
            m = self.exp_avg[lo:hi]
            v = self.exp_avg_sq[lo:hi]
            self._lib.ds_adam_step_chunk_q1(
                self.opt_id, self.step_count, n,
                params.ctypes.data_as(f32p),
                np.ascontiguousarray(packed).ctypes.data_as(u8p),
                np.ascontiguousarray(scales).ctypes.data_as(f32p),
                block, m.ctypes.data_as(f32p), v.ctypes.data_as(f32p),
                bf16, -1.0 if lr is None else float(lr))
            return params
        bits = np.unpackbits(packed, bitorder="little")[:n]
        g = np.where(bits > 0, 1.0, -1.0).astype(np.float32) * \
            np.repeat(scales, block)[:n]
        return self.step_chunk(lo, hi, params, g, lr=lr,
                               params_bf16_out=params_bf16_out)

    def state_dict(self):
        return {"exp_avg": self.exp_avg, "exp_avg_sq": self.exp_avg_sq,
                "step": self.step_count}

    def load_state_dict(self, sd):
        self.exp_avg[:] = sd["exp_avg"]
        self.exp_avg_sq[:] = sd["exp_avg_sq"]
        self.step_count = int(sd["step"])
        if self._lib is not None:
            self._lib.ds_adam_set_step(self.opt_id, self.step_count)

    def __del__(self):
        try:
            if getattr(self, "_lib", None) is not None:
                self._lib.ds_adam_destroy(self.opt_id)
        except Exception:  # ds-lint: allow[BROADEXC] __del__ during interpreter teardown: modules/ctypes may already be torn down
            pass
