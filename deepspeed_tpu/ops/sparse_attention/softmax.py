"""Standalone block-sparse Softmax over the compact block format.

Counterpart of the reference's Triton sparse softmax
(`deepspeed/ops/sparse_attention/softmax.py:17-304`): normalizes each
QUERY ROW across every visible key block of that row in a
[batch, nnz, block, block] tensor, with the same optional masks —
relative position embedding, key padding mask [B, seq], attention mask
[seq, seq], each in 'add' or 'mul' mode.

TPU-native form: a row's blocks are scattered along the nnz axis, so
the row-wise max/sum become `segment_max`/`segment_sum` keyed by
(head, block_row) — the XLA analogue of the reference's LUT-driven
reduction (`make_lut`, `softmax.py:66-86`). Pure jax: autodiff supplies
the backward (the reference hand-writes the y*(dy - sum(y*dy)) kernel,
`softmax.py:157-183`)."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.matmul import _layout_indices

_NEG = -1e30


class Softmax:
    """Block-sparse softmax over a fixed layout (ref `softmax.py:219`)."""

    def __init__(self, layout, block):
        self.layout = np.asarray(layout)
        self.block = int(block)
        self.spdims = self.layout.shape
        self._h, self._r, self._c = _layout_indices(self.layout)

    def __call__(self, x, scale=1.0, rpe=None, key_padding_mask=None,
                 attn_mask=None, key_padding_mask_mode="add",
                 attn_mask_mode="add"):
        """x: [B, nnz, block, block] scores in compact block format.

        scale multiplies x first; rpe (broadcastable to x, compact
        format) adds; key_padding_mask [B, seq_k] and attn_mask
        [seq_q, seq_k] apply per their mode ('add' before softmax, or
        'mul' zeroing: 0-entries become -inf). Rows with no surviving
        entries return 0 probabilities (not NaN)."""
        bs = self.block
        H, R, C = self.spdims
        h, r, c = self._h, self._r, self._c
        xs = x.astype(jnp.float32) * scale
        if rpe is not None:
            xs = xs + rpe.astype(jnp.float32)

        if key_padding_mask is not None:
            # gather each block's key columns: [B, nnz, bs]
            kpm = key_padding_mask.astype(jnp.float32)
            kcols = kpm.reshape(kpm.shape[0], C, bs)[:, c]
            if key_padding_mask_mode == "add":
                xs = xs + kcols[:, :, None, :]
            else:
                xs = jnp.where(kcols[:, :, None, :] == 0, _NEG, xs)
        if attn_mask is not None:
            am = attn_mask.astype(jnp.float32)
            blocks = am.reshape(R, bs, C, bs).transpose(0, 2, 1, 3)[r, c]
            if attn_mask_mode == "add":
                xs = xs + blocks[None]
            else:
                xs = jnp.where(blocks[None] == 0, _NEG, xs)

        # row-wise softmax across this row's blocks (segment over nnz)
        seg = jnp.asarray(h.astype(np.int64) * R + r)
        G = H * R
        rowmax = jnp.max(xs, axis=-1)                       # [B, z, bs]
        gmax = jax.ops.segment_max(jnp.moveaxis(rowmax, 1, 0), seg,
                                   num_segments=G)          # [G, B, bs]
        gmax = jnp.maximum(gmax, _NEG)   # empty/all-masked rows
        p = jnp.exp(xs - jnp.moveaxis(gmax, 0, 1)[:, seg][..., None])
        # entries pushed to -inf by a mask contribute 0 probability even
        # when the whole row is masked (gmax saturates at _NEG there and
        # exp(0) would otherwise resurrect them)
        p = jnp.where(xs > _NEG / 2, p, 0.0)
        rowsum = jnp.sum(p, axis=-1)                        # [B, z, bs]
        gsum = jax.ops.segment_sum(jnp.moveaxis(rowsum, 1, 0), seg,
                                   num_segments=G)
        denom = jnp.moveaxis(gsum, 0, 1)[:, seg][..., None]
        p = jnp.where(denom > 0, p / jnp.maximum(denom, 1e-30), 0.0)
        return p.astype(x.dtype)
