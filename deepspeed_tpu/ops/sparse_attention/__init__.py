from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
    VariableSparsityConfig, BigBirdSparsityConfig,
    BSLongformerSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
    block_sparse_attention, layout_to_dense_mask)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention, BertSparseSelfAttention)
from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
    SparseAttentionUtils)
from deepspeed_tpu.ops.sparse_attention.matmul import (MatMul, to_sparse,
                                                       to_dense)
from deepspeed_tpu.ops.sparse_attention.softmax import Softmax

__all__ = [
    "SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
    "VariableSparsityConfig", "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig", "block_sparse_attention",
    "layout_to_dense_mask", "SparseSelfAttention",
    "BertSparseSelfAttention", "SparseAttentionUtils",
    "MatMul", "Softmax", "to_sparse", "to_dense",
]
