"""Block-sparse flash attention: layout-gated Pallas kernels.

TPU replacement for the reference's Triton SDD/DSD/DDS matmul + sparse
softmax pipeline (`ops/sparse_attention/matmul.py:16-750`,
`softmax.py:17-304`, `trsrc/*.tr`). Where Triton gathers irregular block
lists through lookup tables (`sdd_segment`, `csrc/sparse_attention/
utils.cpp:117`), the TPU kernel keeps the dense flash-attention grid and
*predicates* each K-block tile on the boolean layout: invisible blocks
skip their matmuls entirely (the MXU sees only visible tiles), so FLOPs
scale with layout density while the memory-access pattern stays the
regular streaming one the hardware wants (SURVEY §7: irregular gathers
are TPU-hostile; predicated-dense is the splash-attention-style answer).

The layout block size doubles as the kernel tile size (128 = one MXU
tile; the reference's 16-wide Triton blocks would starve the MXU).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import (NEG_INF, _on_tpu,
                                                           dense_attention)


def _causal_visible(qi, ki, block):
    return ki * block <= qi * block + block - 1


def _bs_fwd_kernel(head_map_ref, layout_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale, causal, block,
                   num_heads):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    h_idx = jax.lax.rem(pl.program_id(0), num_heads)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    nq_l = pl.num_programs(1)
    lay_h = head_map_ref[h_idx]
    visible = layout_ref[(lay_h * nq_l + qi) * nq_l + ki] != 0
    if causal:
        visible = jnp.logical_and(visible,
                                  _causal_visible(qi, ki, block))

    @pl.when(visible)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, :1] = m_new
        l_scr[:, :1] = l_new

    @pl.when(ki == nk - 1)
    def _():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:, :1] + jnp.log(l)


def _bs_bwd_dkv_kernel(head_map_ref, layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                       delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                       sm_scale, causal, block, num_heads):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)
    h_idx = jax.lax.rem(pl.program_id(0), num_heads)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    nq_l = pl.num_programs(1)
    lay_h = head_map_ref[h_idx]
    visible = layout_ref[(lay_h * nq_l + qi) * nq_l + ki] != 0
    if causal:
        visible = jnp.logical_and(visible,
                                  _causal_visible(qi, ki, block))

    @pl.when(visible)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bs_bwd_dq_kernel(head_map_ref, layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dq_scr, *, sm_scale, causal,
                      block, num_heads):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    h_idx = jax.lax.rem(pl.program_id(0), num_heads)

    @pl.when(ki == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    nq_l = pl.num_programs(1)
    lay_h = head_map_ref[h_idx]
    visible = layout_ref[(lay_h * nq_l + qi) * nq_l + ki] != 0
    if causal:
        visible = jnp.logical_and(visible,
                                  _causal_visible(qi, ki, block))

    @pl.when(visible)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dedup_layout(layout):
    """[H, nq, nk] concrete layout -> (head_map [H], flat unique
    layouts) for SMEM scalar prefetch. Heads sharing a layout (the
    default for every shipped SparsityConfig:
    different_layout_per_head=False) collapse to ONE stored copy — at
    16k context a per-head table would be H*nq*nk*4 = 4 MB of SMEM,
    past the hardware limit, while the deduped table is
    nq*nk*4 = 64 KB. Must be called on concrete (numpy) layouts, so it
    runs once at the public entry point and the deduped arrays thread
    through the custom-VJP residuals."""
    lay = np.asarray(layout, np.int32)
    unique, inverse = np.unique(lay, axis=0, return_inverse=True)
    return (jnp.asarray(inverse.reshape(-1), jnp.int32),
            jnp.asarray(unique, jnp.int32).reshape(-1))


def _bs_fwd(q, k, v, head_map, lay_flat, sm_scale, causal, block,
            interpret):
    b, t, h, d = q.shape
    bh = b * h
    nq = t // block

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    kernel = functools.partial(_bs_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block=block, num_heads=h)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, nq),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, ki, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, 1), lambda bhi, qi, ki, *_: (bhi, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(head_map, lay_flat, to_bht(q), to_bht(k), to_bht(v))
    return out, lse


def _bs_bwd(sm_scale, causal, block, interpret, res, g):
    q, k, v, out, lse, head_map, lay_flat = res
    b, t, h, d = q.shape
    bh = b * h
    nq = t // block

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    def from_bht(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    qt, kt, vt, dot_ = to_bht(q), to_bht(k), to_bht(v), to_bht(g)
    ot = to_bht(out)
    delta = jnp.sum(dot_.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dkv_kernel = functools.partial(_bs_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block=block, num_heads=h)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, nq),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda bhi, ki, qi, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, ki, qi, *_: (bhi, ki, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, ki, qi, *_: (bhi, ki, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, ki, qi, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, 1), lambda bhi, ki, qi, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, 1), lambda bhi, ki, qi, *_: (bhi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda bhi, ki, qi, *_: (bhi, ki, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, ki, qi, *_: (bhi, ki, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        interpret=interpret,
    )(head_map, lay_flat, qt, kt, vt, dot_, lse, delta)

    dq_kernel = functools.partial(_bs_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block=block, num_heads=h)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq, nq),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, ki, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, ki, 0)),
            pl.BlockSpec((1, block, d), lambda bhi, qi, ki, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, 1), lambda bhi, qi, ki, *_: (bhi, qi, 0)),
            pl.BlockSpec((1, block, 1), lambda bhi, qi, ki, *_: (bhi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d),
                               lambda bhi, qi, ki, *_: (bhi, qi, 0)),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(head_map, lay_flat, qt, kt, vt, dot_, lse, delta)

    return from_bht(dq), from_bht(dk), from_bht(dv), None, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _bs_flash(q, k, v, head_map, lay_flat, sm_scale, causal, block,
              interpret):
    out, _ = _bs_fwd(q, k, v, head_map, lay_flat, sm_scale, causal,
                     block, interpret)
    b, t, h, d = q.shape
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _bs_flash_fwd(q, k, v, head_map, lay_flat, sm_scale, causal, block,
                  interpret):
    out, lse = _bs_fwd(q, k, v, head_map, lay_flat, sm_scale, causal,
                       block, interpret)
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out_bthd, (q, k, v, out_bthd, lse, head_map, lay_flat)


_bs_flash.defvjp(_bs_flash_fwd, _bs_bwd)


def layout_to_dense_mask(layout, seq_len, block):
    """[H, nq, nk] block layout -> [H, T, T] boolean mask (the XLA
    fallback path and the ground truth for kernel tests)."""
    lay = np.asarray(layout, bool)
    return np.kron(lay, np.ones((block, block), dtype=bool))


def block_sparse_attention(q, k, v, layout, block, causal=False,
                           sm_scale=None, interpret=None):
    """Block-sparse attention over [B, T, H, D].

    layout: [H, T/block, T/block] 0/1 matrix from a SparsityConfig.
    """
    b, t, h, d = q.shape
    if isinstance(layout, jax.core.Tracer):
        raise ValueError(
            "block_sparse_attention requires a CONCRETE layout (it is "
            "deduplicated host-side for SMEM prefetch); build the "
            "layout outside jit — SparsityConfig.make_layout returns "
            "numpy and layouts are static per (config, seq_len)")
    layout = np.asarray(layout)
    assert layout.shape == (h, t // block, t // block), \
        (layout.shape, (h, t // block, t // block))
    assert t % block == 0
    # every query block must see at least one key block (the diagonal in
    # all shipped patterns) or its softmax is over the empty set
    if causal:
        diag = layout[:, np.arange(t // block), np.arange(t // block)]
        assert diag.all(), "causal layouts must include the diagonal"
    else:
        assert (layout.sum(-1) > 0).all(), \
            "every query block needs >= 1 visible key block"
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = not _on_tpu()
    head_map, lay_flat = _dedup_layout(layout)
    return _bs_flash(q, k, v, head_map, lay_flat,
                     float(sm_scale), bool(causal), int(block),
                     bool(interpret))


def block_sparse_attention_dense_fallback(q, k, v, layout, block,
                                          causal=False, sm_scale=None):
    """Dense reference: same math via an expanded additive mask."""
    t = q.shape[1]
    mask = layout_to_dense_mask(layout, t, block)         # [H, T, T]
    additive = np.where(mask, 0.0, NEG_INF).astype(np.float32)
    return dense_attention(q, k, v, mask=jnp.asarray(additive)[None],
                           causal=causal, sm_scale=sm_scale)
