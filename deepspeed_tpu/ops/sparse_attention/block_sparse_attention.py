"""Block-sparse flash attention: index-compacted Pallas kernels.

TPU replacement for the reference's Triton SDD/DSD/DDS matmul + sparse
softmax pipeline (`ops/sparse_attention/matmul.py:16-750`,
`softmax.py:17-304`, `trsrc/*.tr`). The reference compiles per-layout
lookup tables (`sdd_segment`, `csrc/sparse_attention/utils.cpp:117`)
that enumerate the visible blocks; the TPU kernels do the same thing
with scalar-prefetch index tables: for each query row-block the table
lists exactly the visible key blocks (causality already folded in at
block granularity), and the grid's inner dimension runs over THAT list
— `kmax` steps instead of `nq`. Work therefore scales with layout
density (a 16k-context window layout with ~6 visible blocks per row
runs a 128x6 grid, not 128x128), while every step is still one dense
128x128 MXU tile from a regular streaming access pattern.

The layout block size doubles as the kernel tile size (128 = one MXU
tile; the reference's 16-wide Triton blocks would starve the MXU).
Tables dedupe identical per-head layouts (the default for every shipped
SparsityConfig) so the SMEM footprint is ~U*nq*kmax*4 bytes, a few KB.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import (NEG_INF, _on_tpu,
                                                           dense_attention)


# ----------------------------------------------------------------------
# layout -> visible-block index tables
# ----------------------------------------------------------------------
def _build_tables(layout, causal):
    """Concrete [H, nq, nk] layout -> scalar-prefetch tables:

      head_map [H]          head -> unique-layout index u
      kidx [U*nq*kmax]      visible key blocks per query row (padded)
      kcnt [U*nq]           count of visible key blocks per query row
      qidx [U*nq*qmax]      visible query blocks per key column (padded)
      qcnt [U*nq]           count per key column

    Causality is folded in at block granularity (ki <= qi), so the
    kernels iterate ONLY over genuinely visible tiles — the TPU analog
    of the reference's sdd_segment lookup tables. Padding repeats index
    0; padded steps are skipped by the count predicate."""
    lay = np.asarray(layout, np.int32)
    unique, inverse = np.unique(lay, axis=0, return_inverse=True)
    U, nq, nk = unique.shape
    vis = unique != 0
    if causal:
        vis = vis & np.tril(np.ones((nq, nk), bool))[None]

    kcnt = vis.sum(axis=2).astype(np.int32)               # [U, nq]
    qcnt = vis.sum(axis=1).astype(np.int32)               # [U, nk]
    kmax = max(1, int(kcnt.max()))
    qmax = max(1, int(qcnt.max()))
    kidx = np.zeros((U, nq, kmax), np.int32)
    qidx = np.zeros((U, nk, qmax), np.int32)
    for u in range(U):
        for qi in range(nq):
            cols = np.where(vis[u, qi])[0]
            kidx[u, qi, :len(cols)] = cols
        for ki in range(nk):
            rows = np.where(vis[u, :, ki])[0]
            qidx[u, ki, :len(rows)] = rows
    # head-group size: the largest power of two (<=8) dividing H whose
    # groups are layout-uniform — grouped heads ride one grid step
    hm = inverse.reshape(-1)
    H = hm.size
    g = 1
    for cand in (8, 4, 2):
        if H % cand == 0 and \
                (hm.reshape(H // cand, cand) ==
                 hm.reshape(H // cand, cand)[:, :1]).all():
            g = cand
            break
    return (jnp.asarray(hm, jnp.int32),
            jnp.asarray(kidx.reshape(-1)), jnp.asarray(kcnt.reshape(-1)),
            jnp.asarray(qidx.reshape(-1)), jnp.asarray(qcnt.reshape(-1)),
            kmax, qmax, g)


def _row(hm_ref, bhi, qi, nq, num_heads):
    u = hm_ref[jax.lax.rem(bhi, num_heads)]
    return u * nq + qi


# ----------------------------------------------------------------------
# kernels (grid inner dim = visible-block list position)
# ----------------------------------------------------------------------
def _bs_fwd_kernel(hm_ref, kidx_ref, kcnt_ref, q_ref, k_ref, v_ref,
                   o_ref, lse_ref, m_scr, l_scr, acc_scr, *, sm_scale,
                   causal, block, num_heads, nq, kmax, g):
    # blocks carry G heads per grid step (legal because grouped heads
    # share one layout row): fewer, fatter steps amortize the per-step
    # grid/DMA overhead that starves 128-row single-head tiles
    qi = pl.program_id(1)
    st = pl.program_id(2)
    row = _row(hm_ref, pl.program_id(0) * g, qi, nq, num_heads)

    @pl.when(st == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(st < kcnt_ref[row])
    def _():
        ki = kidx_ref[row * kmax + st]
        q = q_ref[...]
        k = k_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale   # [G, B, B]
        if causal:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where((rows >= cols)[None], s, NEG_INF)

        m_prev = m_scr[:, :, :1]
        l_prev = l_scr[:, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[...]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, :, :1] = m_new
        l_scr[:, :, :1] = l_new

    @pl.when(st == kmax - 1)
    def _():
        l = jnp.maximum(l_scr[:, :, :1], 1e-30)
        o_ref[...] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[...] = m_scr[:, :, :1] + jnp.log(l)


def _bs_bwd_dkv_kernel(hm_ref, qidx_ref, qcnt_ref, q_ref, k_ref, v_ref,
                       do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                       dk_scr, dv_scr, *, sm_scale, causal, block,
                       num_heads, nq, qmax, g):
    ki = pl.program_id(1)
    st = pl.program_id(2)
    row = _row(hm_ref, pl.program_id(0) * g, ki, nq, num_heads)

    @pl.when(st == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(st < qcnt_ref[row])
    def _():
        qi = qidx_ref[row * qmax + st]
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]
        delta = delta_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale   # [G, Bq, Bk]
        if causal:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where((rows >= cols)[None], s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(st == qmax - 1)
    def _():
        dk_ref[...] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[:].astype(dv_ref.dtype)


def _bs_bwd_dq_kernel(hm_ref, kidx_ref, kcnt_ref, q_ref, k_ref, v_ref,
                      do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *,
                      sm_scale, causal, block, num_heads, nq, kmax, g):
    qi = pl.program_id(1)
    st = pl.program_id(2)
    row = _row(hm_ref, pl.program_id(0) * g, qi, nq, num_heads)

    @pl.when(st == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(st < kcnt_ref[row])
    def _():
        ki = kidx_ref[row * kmax + st]
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]
        delta = delta_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            cols = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where((rows >= cols)[None], s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(st == kmax - 1)
    def _():
        dq_ref[...] = dq_scr[:].astype(dq_ref.dtype)


# ----------------------------------------------------------------------
# pallas_call plumbing
# ----------------------------------------------------------------------
def _k_lookup(nq, kmax, num_heads, g):
    """BlockSpec index fn for k/v: the key block comes from the table."""
    def idx(grp, qi, st, hm_ref, kidx_ref, kcnt_ref):
        row = _row(hm_ref, grp * g, qi, nq, num_heads)
        return (grp, kidx_ref[row * kmax + st], 0)
    return idx


def _q_lookup(nq, qmax, num_heads, g):
    def idx(grp, ki, st, hm_ref, qidx_ref, qcnt_ref):
        row = _row(hm_ref, grp * g, ki, nq, num_heads)
        return (grp, qidx_ref[row * qmax + st], 0)
    return idx


def _bs_fwd(q, k, v, head_map, kidx, kcnt, sm_scale, causal, block,
            interpret, kmax, g):
    b, t, h, d = q.shape
    bh = b * h
    nq = t // block

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    kernel = functools.partial(_bs_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block=block, num_heads=h,
                               nq=nq, kmax=kmax, g=g)
    fixed = lambda grp, qi, st, *_: (grp, qi, 0)
    kv = _k_lookup(nq, kmax, h, g)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(bh // g, nq, kmax),
        in_specs=[
            pl.BlockSpec((g, block, d), fixed),
            pl.BlockSpec((g, block, d), kv),
            pl.BlockSpec((g, block, d), kv),
        ],
        out_specs=[
            pl.BlockSpec((g, block, d), fixed),
            pl.BlockSpec((g, block, 1), fixed),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, block, 128), jnp.float32),
            pltpu.VMEM((g, block, 128), jnp.float32),
            pltpu.VMEM((g, block, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(head_map, kidx, kcnt, to_bht(q), to_bht(k), to_bht(v))
    return out, lse


def _bs_bwd(sm_scale, causal, block, interpret, kmax, qmax, g_grp, res,
            g):
    q, k, v, out, lse, head_map, kidx, kcnt, qidx, qcnt = res
    b, t, h, d = q.shape
    bh = b * h
    nq = t // block

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    def from_bht(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    qt, kt, vt, dot_ = to_bht(q), to_bht(k), to_bht(v), to_bht(g)
    ot = to_bht(out)
    delta = jnp.sum(dot_.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1, keepdims=True)

    fixed1 = lambda grp, ki, st, *_: (grp, ki, 0)
    qv = _q_lookup(nq, qmax, h, g_grp)
    dkv_kernel = functools.partial(_bs_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block=block,
                                   num_heads=h, nq=nq, qmax=qmax,
                                   g=g_grp)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(bh // g_grp, nq, qmax),
        in_specs=[
            pl.BlockSpec((g_grp, block, d), qv),      # q from table
            pl.BlockSpec((g_grp, block, d), fixed1),  # k at ki
            pl.BlockSpec((g_grp, block, d), fixed1),  # v at ki
            pl.BlockSpec((g_grp, block, d), qv),      # do from table
            pl.BlockSpec((g_grp, block, 1), qv),      # lse from table
            pl.BlockSpec((g_grp, block, 1), qv),      # delta from table
        ],
        out_specs=[
            pl.BlockSpec((g_grp, block, d), fixed1),
            pl.BlockSpec((g_grp, block, d), fixed1),
        ],
        scratch_shapes=[
            pltpu.VMEM((g_grp, block, d), jnp.float32),
            pltpu.VMEM((g_grp, block, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        interpret=interpret,
    )(head_map, qidx, qcnt, qt, kt, vt, dot_, lse, delta)

    fixed = lambda grp, qi, st, *_: (grp, qi, 0)
    kv = _k_lookup(nq, kmax, h, g_grp)
    dq_kernel = functools.partial(_bs_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block=block,
                                  num_heads=h, nq=nq, kmax=kmax,
                                  g=g_grp)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(bh // g_grp, nq, kmax),
        in_specs=[
            pl.BlockSpec((g_grp, block, d), fixed),
            pl.BlockSpec((g_grp, block, d), kv),
            pl.BlockSpec((g_grp, block, d), kv),
            pl.BlockSpec((g_grp, block, d), fixed),
            pl.BlockSpec((g_grp, block, 1), fixed),
            pl.BlockSpec((g_grp, block, 1), fixed),
        ],
        out_specs=pl.BlockSpec((g_grp, block, d), fixed),
        scratch_shapes=[pltpu.VMEM((g_grp, block, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(head_map, kidx, kcnt, qt, kt, vt, dot_, lse, delta)

    return (from_bht(dq), from_bht(dk), from_bht(dv),
            None, None, None, None, None)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(8, 9, 10, 11, 12, 13, 14))
def _bs_flash(q, k, v, head_map, kidx, kcnt, qidx, qcnt, sm_scale,
              causal, block, interpret, kmax, qmax, g):
    out, _ = _bs_fwd(q, k, v, head_map, kidx, kcnt, sm_scale, causal,
                     block, interpret, kmax, g)
    b, t, h, d = q.shape
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _bs_flash_fwd(q, k, v, head_map, kidx, kcnt, qidx, qcnt, sm_scale,
                  causal, block, interpret, kmax, qmax, g):
    out, lse = _bs_fwd(q, k, v, head_map, kidx, kcnt, sm_scale, causal,
                       block, interpret, kmax, g)
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out_bthd, (q, k, v, out_bthd, lse, head_map, kidx, kcnt,
                      qidx, qcnt)


def _bs_flash_bwd(sm_scale, causal, block, interpret, kmax, qmax, g_grp,
                  res, g):
    return _bs_bwd(sm_scale, causal, block, interpret, kmax, qmax,
                   g_grp, res, g)


_bs_flash.defvjp(_bs_flash_fwd, _bs_flash_bwd)


def layout_to_dense_mask(layout, seq_len, block):
    """[H, nq, nk] block layout -> [H, T, T] boolean mask (the XLA
    fallback path and the ground truth for kernel tests)."""
    lay = np.asarray(layout, bool)
    return np.kron(lay, np.ones((block, block), dtype=bool))


def block_sparse_attention(q, k, v, layout, block, causal=False,
                           sm_scale=None, interpret=None):
    """Block-sparse attention over [B, T, H, D].

    layout: [H, T/block, T/block] 0/1 matrix from a SparsityConfig.
    """
    b, t, h, d = q.shape
    if isinstance(layout, jax.core.Tracer):
        raise ValueError(
            "block_sparse_attention requires a CONCRETE layout (it is "
            "compiled into visible-block index tables host-side); build "
            "the layout outside jit — SparsityConfig.make_layout "
            "returns numpy and layouts are static per (config, seq_len)")
    layout = np.asarray(layout)
    assert layout.shape == (h, t // block, t // block), \
        (layout.shape, (h, t // block, t // block))
    assert t % block == 0
    # every query block must see at least one key block (the diagonal in
    # all shipped patterns) or its softmax is over the empty set
    if causal:
        diag = layout[:, np.arange(t // block), np.arange(t // block)]
        assert diag.all(), "causal layouts must include the diagonal"
    else:
        assert (layout.sum(-1) > 0).all(), \
            "every query block needs >= 1 visible key block"
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = not _on_tpu()
    head_map, kidx, kcnt, qidx, qcnt, kmax, qmax, g = _build_tables(
        layout, causal)
    assert h % g == 0 and (b * h) % g == 0  # _build_tables guarantees
    # VMEM tile budget: the f32 score tile is g*block*block*4 bytes;
    # keep g*block <= 2048 (16 MB VMEM, double-buffered operands)
    while g > 1 and g * block > 2048:
        g //= 2
    return _bs_flash(q, k, v, head_map, kidx, kcnt, qidx, qcnt,
                     float(sm_scale), bool(causal), int(block),
                     bool(interpret), kmax, qmax, g)


def block_sparse_attention_dense_fallback(q, k, v, layout, block,
                                          causal=False, sm_scale=None):
    """Dense reference: same math via an expanded additive mask."""
    t = q.shape[1]
    mask = layout_to_dense_mask(layout, t, block)         # [H, T, T]
    additive = np.where(mask, 0.0, NEG_INF).astype(np.float32)
    return dense_attention(q, k, v, mask=jnp.asarray(additive)[None],
                           causal=causal, sm_scale=sm_scale)
