"""Block-sparse flash attention: index-compacted Pallas kernels.

TPU replacement for the reference's Triton SDD/DSD/DDS matmul + sparse
softmax pipeline (`ops/sparse_attention/matmul.py:16-750`,
`softmax.py:17-304`, `trsrc/*.tr`). The reference compiles per-layout
lookup tables (`sdd_segment`, `csrc/sparse_attention/utils.cpp:117`)
that enumerate the visible blocks; the TPU kernels do the same thing
with scalar-prefetch index tables: for each q SUPER-ROW (qt adjacent
layout rows — the kernel's q tile is qt*block rows) the table lists the
union of visible key blocks, with a per-entry bitmask gating each
member row; causality is folded in at block granularity. The grid's
inner dimension runs over THAT list — `kmax` steps instead of `nq` —
so work scales with layout density, while each step is one fat
(g heads x qt*block x block) MXU tile from a regular streaming access
pattern; head-grouping and super-rows exist to amortize per-grid-step
overhead.

Tables dedupe identical per-head layouts (the default for every shipped
SparsityConfig); SMEM holds ~3*U*(nq/qt)*kmax int32 entries (indices,
counts, masks) plus the transpose tables — a few KB.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import (NEG_INF, _on_tpu,
                                                           dense_attention)

# f32 score-tile budget per grid step and the matching Mosaic
# scoped-vmem ceiling (default 16 MB refuses ~18 MB stacks; the chip
# has 128 MB of VMEM)
_SCORE_TILE_BUDGET = 4 * 1024 * 1024
_VMEM_LIMIT = 64 * 1024 * 1024
_FWD_MIN_OUTER = 8


def _element_spec(shape, index_map):
    """All-Element BlockSpec (every index_map coordinate is an ELEMENT
    offset). Spelled `pl.Element` per dim on modern pallas; older
    releases (jax 0.4.x) express the same thing as a whole-spec
    Unblocked indexing mode."""
    if hasattr(pl, "Element"):
        return pl.BlockSpec(tuple(pl.Element(s) for s in shape),
                            index_map)
    return pl.BlockSpec(tuple(shape), index_map,
                        indexing_mode=pl.Unblocked())


def _compiler_params(kind):
    # Measured on v5e at the 16k bench point: the BACKWARD kernels want
    # ("parallel","parallel","arbitrary") (+40% over default), while
    # the forward's online-softmax carry pipelines better with Mosaic's
    # own scheduling (declared semantics cost it ~25%).
    sem = ("parallel", "parallel", "arbitrary") if kind == "bwd" else None
    # CompilerParams was TPUCompilerParams before jax 0.6 (same fields)
    cls = getattr(pltpu, "CompilerParams",
                  getattr(pltpu, "TPUCompilerParams", None))
    return cls(dimension_semantics=sem, vmem_limit_bytes=_VMEM_LIMIT)


# ----------------------------------------------------------------------
# layout -> visible-block index tables
# ----------------------------------------------------------------------
def _build_tables(layout, causal, qt):
    """Concrete [H, nq, nk] layout -> scalar-prefetch tables over
    SUPER-ROWS of `qt` consecutive layout rows (the kernel's q tile is
    qt*block rows — bigger MXU tiles, fewer grid steps):

      head_map [H]            head -> unique-layout index u
      kidx [U*nqs*kmax]       visible key blocks per q super-row (union
                              over member rows, padded)
      kcnt [U*nqs]            count per q super-row
      kmask [U*nqs*kmax]      per-entry bitmask: which of the qt member
                              rows actually sees that key block
      qidx/qcnt/qmask         the transpose (visible q super-rows per
                              key column) for the dK/dV kernel

    Causality is folded in at block granularity (ki <= qi), so the
    kernels iterate ONLY over genuinely visible tiles — the TPU analog
    of the reference's sdd_segment lookup tables. Padding repeats index
    0 with an all-zero mask."""
    lay = np.asarray(layout, np.int32)
    unique, inverse = np.unique(lay, axis=0, return_inverse=True)
    U, nq, nk = unique.shape
    assert nq % qt == 0
    nqs = nq // qt
    vis = unique != 0
    if causal:
        vis = vis & np.tril(np.ones((nq, nk), bool))[None]

    vis_s = vis.reshape(U, nqs, qt, nk)
    union = vis_s.any(axis=2)                              # [U, nqs, nk]
    bits = (vis_s.astype(np.int32) <<
            np.arange(qt)[None, None, :, None]).sum(axis=2)  # [U,nqs,nk]

    kcnt = union.sum(axis=2).astype(np.int32)              # [U, nqs]
    qcnt = union.sum(axis=1).astype(np.int32)              # [U, nk]
    kmax = max(1, int(kcnt.max()))
    qmax = max(1, int(qcnt.max()))
    kidx = np.zeros((U, nqs, kmax), np.int32)
    kmask = np.zeros((U, nqs, kmax), np.int32)
    qidx = np.zeros((U, nk, qmax), np.int32)
    qmask = np.zeros((U, nk, qmax), np.int32)
    for u in range(U):
        for R in range(nqs):
            cols = np.where(union[u, R])[0]
            kidx[u, R, :len(cols)] = cols
            kmask[u, R, :len(cols)] = bits[u, R, cols]
        for ki in range(nk):
            rows = np.where(union[u, :, ki])[0]
            qidx[u, ki, :len(rows)] = rows
            qmask[u, ki, :len(rows)] = bits[u, rows, ki]
    # head-group size: the largest power of two (<=8) dividing H whose
    # groups are layout-uniform — grouped heads ride one grid step
    hm = inverse.reshape(-1)
    H = hm.size
    g = 1
    for cand in (8, 4, 2):
        if H % cand == 0 and \
                (hm.reshape(H // cand, cand) ==
                 hm.reshape(H // cand, cand)[:, :1]).all():
            g = cand
            break
    return (jnp.asarray(hm, jnp.int32),
            jnp.asarray(kidx.reshape(-1)), jnp.asarray(kcnt.reshape(-1)),
            jnp.asarray(kmask.reshape(-1)),
            jnp.asarray(qidx.reshape(-1)), jnp.asarray(qcnt.reshape(-1)),
            jnp.asarray(qmask.reshape(-1)),
            kmax, qmax, g)


def _row(hm_ref, bhi, qi, nq, num_heads):
    u = hm_ref[jax.lax.rem(bhi, num_heads)]
    return u * nq + qi


# ----------------------------------------------------------------------
# kernels (grid inner dim = visible-block list position)
# ----------------------------------------------------------------------
def _visible_mask(mbits, R, ki, qt, block, causal):
    """[qt*block, block] bool: which score entries are visible — the
    per-member-row layout bit, intersected with the causal triangle in
    GLOBAL coordinates when causal."""
    qtb = qt * block
    rows = jax.lax.broadcasted_iota(jnp.int32, (qtb, block), 0)
    visible = ((mbits >> (rows // block)) & 1) == 1
    if causal:
        grows = R * qtb + rows
        cols = ki * block + jax.lax.broadcasted_iota(
            jnp.int32, (qtb, block), 1)
        visible = visible & (grows >= cols)
    return visible


def _bs_fwd_kernel(hm_ref, kidx_ref, kcnt_ref, kmask_ref, q_ref, k_ref,
                   v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                   sm_scale, causal, block, num_heads, nqs, kmax, g, qt,
                   lse2d):
    # blocks carry G heads x QT layout rows per grid step (legal because
    # grouped heads share one layout row): fewer, fatter steps amortize
    # the per-step grid/DMA overhead that starves small tiles; the
    # bitmask gates each member row on its own layout visibility
    R = pl.program_id(1)
    st = pl.program_id(2)
    row = _row(hm_ref, pl.program_id(0) * g, R, nqs, num_heads)
    qtb = qt * block

    @pl.when(st == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(st < kcnt_ref[row])
    def _():
        ki = kidx_ref[row * kmax + st]
        mbits = kmask_ref[row * kmax + st]
        q = q_ref[...]
        k = k_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale  # [G, QTB, B]
        s = jnp.where(
            _visible_mask(mbits, R, ki, qt, block, causal)[None],
            s, NEG_INF)

        m_prev = m_scr[:, :, :1]
        l_prev = l_scr[:, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # rows with no visible block this step keep m=-inf; guard exp
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        p = jnp.exp(s - m_safe)
        alpha = jnp.exp(jnp.minimum(m_prev - m_safe, 0.0))
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[...]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, :, :1] = m_new
        l_scr[:, :, :1] = l_new

    @pl.when(st == kmax - 1)
    def _():
        l = l_scr[:, :, :1]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[...] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # A member row with ZERO visible entries (possible inside a
        # super-row whose union has blocks only for sibling rows) must
        # export lse=+inf, not NEG_INF+log(1e-30): the backward kernels
        # compute p=exp(s-lse) and only +inf sends every masked score to
        # exactly 0 (delta=0 does not cancel the dp term).
        # lse rides [g, qtb] when the head group allows it — t in the
        # MINOR dim (a [.., t, 1] layout pads the 1-wide minor to full
        # 128-lane tiles: 128x the write bytes)
        lse_val = jnp.where(l > 0.0, m_scr[:, :, :1] + jnp.log(l_safe),
                            jnp.inf)
        if lse2d:
            lse_ref[...] = lse_val[:, :, 0]
        else:
            lse_ref[...] = lse_val


def _bs_bwd_dkv_kernel(hm_ref, qidx_ref, qcnt_ref, qmask_ref, q_ref,
                       k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                       dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                       block, num_heads, nqs, qmax, g, qt, lse2d):
    ki = pl.program_id(1)
    st = pl.program_id(2)
    # the q-side tables for dK/dV are indexed by KEY column: nk == nq
    # rows in the flat [U, nk] layout (square layouts asserted)
    row = _row(hm_ref, pl.program_id(0) * g, ki, nqs * qt, num_heads)
    qtb = qt * block

    @pl.when(st == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(st < qcnt_ref[row])
    def _():
        R = qidx_ref[row * qmax + st]
        mbits = qmask_ref[row * qmax + st]
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][..., None] if lse2d else lse_ref[...]
        delta = delta_ref[...][..., None] if lse2d else delta_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale  # [G,QTB,B]
        s = jnp.where(
            _visible_mask(mbits, R, ki, qt, block, causal)[None],
            s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(st == qmax - 1)
    def _():
        dk_ref[...] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[:].astype(dv_ref.dtype)


def _bs_bwd_dq_kernel(hm_ref, kidx_ref, kcnt_ref, kmask_ref, q_ref,
                      k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                      dq_scr, *, sm_scale, causal, block, num_heads,
                      nqs, kmax, g, qt, lse2d):
    R = pl.program_id(1)
    st = pl.program_id(2)
    row = _row(hm_ref, pl.program_id(0) * g, R, nqs, num_heads)
    qtb = qt * block

    @pl.when(st == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(st < kcnt_ref[row])
    def _():
        ki = kidx_ref[row * kmax + st]
        mbits = kmask_ref[row * kmax + st]
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...][..., None] if lse2d else lse_ref[...]
        delta = delta_ref[...][..., None] if lse2d else delta_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale
        s = jnp.where(
            _visible_mask(mbits, R, ki, qt, block, causal)[None],
            s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(st == kmax - 1)
    def _():
        dq_ref[...] = dq_scr[:].astype(dq_ref.dtype)


# ----------------------------------------------------------------------
# pallas_call plumbing
# ----------------------------------------------------------------------
def _k_lookup(nqs, kmax, num_heads, g):
    """BlockSpec index fn for k/v: the key block comes from the table."""
    def idx(grp, R, st, hm_ref, kidx_ref, kcnt_ref, kmask_ref):
        row = _row(hm_ref, grp * g, R, nqs, num_heads)
        return (grp, kidx_ref[row * kmax + st], 0)
    return idx


def _q_lookup(nk, qmax, num_heads, g):
    def idx(grp, ki, st, hm_ref, qidx_ref, qcnt_ref, qmask_ref):
        row = _row(hm_ref, grp * g, ki, nk, num_heads)
        return (grp, qidx_ref[row * qmax + st], 0)
    return idx


def _bs_fwd(q, k, v, head_map, kidx, kcnt, kmask, sm_scale, causal,
            block, interpret, kmax, g, qt, allow_lse2d=True):
    b, t, h, d = q.shape
    bh = b * h
    nqs = t // block // qt
    qtb = qt * block

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    lse2d = (g % 8 == 0) and allow_lse2d   # 2-D lse needs sublane-divisible g
    kernel = functools.partial(_bs_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block=block, num_heads=h,
                               nqs=nqs, kmax=kmax, g=g, qt=qt,
                               lse2d=lse2d)
    fixed = lambda grp, R, st, *_: (grp, R, 0)
    fixed2 = lambda grp, R, st, *_: (grp, R)
    kv = _k_lookup(nqs, kmax, h, g)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(bh // g, nqs, kmax),
        in_specs=[
            pl.BlockSpec((g, qtb, d), fixed),
            pl.BlockSpec((g, block, d), kv),
            pl.BlockSpec((g, block, d), kv),
        ],
        out_specs=[
            pl.BlockSpec((g, qtb, d), fixed),
            pl.BlockSpec((g, qtb), fixed2) if lse2d else
            pl.BlockSpec((g, qtb, 1), fixed),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, qtb, 128), jnp.float32),
            pltpu.VMEM((g, qtb, 128), jnp.float32),
            pltpu.VMEM((g, qtb, d), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        compiler_params=_compiler_params("fwd"),
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t) if lse2d else (bh, t, 1),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(head_map, kidx, kcnt, kmask, to_bht(q), to_bht(k), to_bht(v))
    return out, lse


def _bs_bwd(sm_scale, causal, block, interpret, kmax, qmax, g_grp, qt,
            res, g):
    (q, k, v, out, lse, head_map, kidx, kcnt, kmask, qidx, qcnt,
     qmask) = res
    b, t, h, d = q.shape
    bh = b * h
    nk = t // block
    nqs = nk // qt
    qtb = qt * block

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    def from_bht(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    qt_, kt, vt, dot_ = to_bht(q), to_bht(k), to_bht(v), to_bht(g)
    ot = to_bht(out)
    lse2d = (lse.ndim == 2)
    delta = jnp.sum(dot_.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1, keepdims=not lse2d)

    fixed1 = lambda grp, ki, st, *_: (grp, ki, 0)
    qv = _q_lookup(nk, qmax, h, g_grp)
    qv2 = lambda grp, ki, st, *refs: qv(grp, ki, st, *refs)[:2]
    dkv_kernel = functools.partial(_bs_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block=block,
                                   num_heads=h, nqs=nqs, qmax=qmax,
                                   g=g_grp, qt=qt, lse2d=lse2d)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(bh // g_grp, nk, qmax),
        in_specs=[
            pl.BlockSpec((g_grp, qtb, d), qv),      # q super-row
            pl.BlockSpec((g_grp, block, d), fixed1),  # k at ki
            pl.BlockSpec((g_grp, block, d), fixed1),  # v at ki
            pl.BlockSpec((g_grp, qtb, d), qv),      # do super-row
            (pl.BlockSpec((g_grp, qtb), qv2) if lse2d else
             pl.BlockSpec((g_grp, qtb, 1), qv)),    # lse super-row
            (pl.BlockSpec((g_grp, qtb), qv2) if lse2d else
             pl.BlockSpec((g_grp, qtb, 1), qv)),    # delta super-row
        ],
        out_specs=[
            pl.BlockSpec((g_grp, block, d), fixed1),
            pl.BlockSpec((g_grp, block, d), fixed1),
        ],
        scratch_shapes=[
            pltpu.VMEM((g_grp, block, d), jnp.float32),
            pltpu.VMEM((g_grp, block, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=dkv_spec,
        compiler_params=_compiler_params("bwd"),
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v.dtype),
        ],
        interpret=interpret,
    )(head_map, qidx, qcnt, qmask, qt_, kt, vt, dot_, lse, delta)

    fixed = lambda grp, R, st, *_: (grp, R, 0)
    kv = _k_lookup(nqs, kmax, h, g_grp)
    dq_kernel = functools.partial(_bs_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block=block,
                                  num_heads=h, nqs=nqs, kmax=kmax,
                                  g=g_grp, qt=qt, lse2d=lse2d)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(bh // g_grp, nqs, kmax),
        in_specs=[
            pl.BlockSpec((g_grp, qtb, d), fixed),
            pl.BlockSpec((g_grp, block, d), kv),
            pl.BlockSpec((g_grp, block, d), kv),
            pl.BlockSpec((g_grp, qtb, d), fixed),
            (pl.BlockSpec((g_grp, qtb), lambda grp, R, st, *_: (grp, R))
             if lse2d else pl.BlockSpec((g_grp, qtb, 1), fixed)),
            (pl.BlockSpec((g_grp, qtb), lambda grp, R, st, *_: (grp, R))
             if lse2d else pl.BlockSpec((g_grp, qtb, 1), fixed)),
        ],
        out_specs=pl.BlockSpec((g_grp, qtb, d), fixed),
        scratch_shapes=[pltpu.VMEM((g_grp, qtb, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=dq_spec,
        compiler_params=_compiler_params("bwd"),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(head_map, kidx, kcnt, kmask, qt_, kt, vt, dot_, lse, delta)

    return (from_bht(dq), from_bht(dk), from_bht(dv),
            None, None, None, None, None, None, None)


# ----------------------------------------------------------------------
# band + global fast path (Longformer/Fixed-class layouts)
# ----------------------------------------------------------------------
def _band_decompose(layout, causal, max_globals=64, max_band_blocks=64):
    """Causal-folded layout -> ("sliding"|"aligned", w, global_cols)
    when it is EXACTLY a width-w block window (sliding band, or
    window-ALIGNED block-diagonal groups — the reference Fixed
    pattern's "local" attention, `sparsity_config.py:94`) plus a set
    of globally-visible block columns; None otherwise (BigBird random
    blocks, per-head layouts).

    BSLongformer decomposes as sliding, Fixed as aligned; the fast
    forward then replaces the per-visible-block table walk with ONE
    contiguous band/window fetch + regular tiles over the gathered
    global columns — far fewer, far fatter grid steps."""
    lay = np.asarray(layout, np.int32)
    if lay.ndim == 3:
        if not (lay == lay[:1]).all():
            return None            # per-head layouts: table path
        lay = lay[0]
    vis = lay != 0
    nq = vis.shape[0]
    if causal:
        vis = vis & np.tril(np.ones_like(vis, dtype=bool))
    rows_i, cols_j = np.nonzero(vis)
    # global columns: visible from EVERY (causal-)eligible row
    gcols = []
    for j in range(nq):
        rows_seeing = vis[:, j]
        expect = np.arange(nq) >= j if causal else np.ones(nq, bool)
        if (rows_seeing == expect).all():
            gcols.append(j)
    gset = set(gcols)
    if len(gcols) > max_globals:
        return None
    off_band = [(i, j) for i, j in zip(rows_i, cols_j) if j not in gset]
    ii = np.arange(nq)[:, None]
    jj = np.arange(nq)[None, :]
    tril = np.tril(np.ones_like(vis, dtype=bool))

    def matches(base):
        expected = base.copy()
        for j in gcols:
            expected[:, j] |= (np.arange(nq) >= j) if causal else True
        if causal:
            expected &= tril
        return np.array_equal(vis, expected)

    # (a) sliding band of width w
    w = max((i - j + 1 for i, j in off_band), default=1)
    if w <= max_band_blocks:
        band = (jj <= ii) & (jj >= ii - w + 1) if causal else \
            (np.abs(ii - jj) < w)
        if matches(band):
            return "sliding", int(w), tuple(int(j) for j in gcols)
    # (b) window-aligned block-diagonal of width w: row i sees cols of
    # its own window floor(i/w) (the Fixed pattern's local part). The
    # minimal candidate w comes from the same max-offset statistic.
    for wa in range(max(w, 1), max_band_blocks + 1):
        aligned = (ii // wa) == (jj // wa)
        if matches(aligned):
            return "aligned", int(wa), tuple(int(j) for j in gcols)
    return None


def _band_fwd_kernel(q_ref, kb_ref, vb_ref, kg_ref, vg_ref, pos_ref,
                     o_ref, lse_ref, m_scr, l_scr, acc_scr, *, sm_scale,
                     block, qt, w, n_steps, tk, g, lse2d, causal, nq,
                     BW, aligned, max_live=None):
    R = pl.program_id(1)
    st = pl.program_id(2)
    qtb = qt * block

    def online_update(s, vv):
        m_prev = m_scr[:, :, :1]
        l_prev = l_scr[:, :, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        p = jnp.exp(s - m_safe)
        alpha = jnp.exp(jnp.minimum(m_prev - m_safe, 0.0))
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vv.dtype), vv, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:, :, :1] = m_new
        l_scr[:, :, :1] = l_new

    @pl.when(st == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        s = jax.lax.dot_general(
            q_ref[...], kb_ref[...], (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale
        # band/window start (block units) — must mirror the index map
        if aligned:
            S = jnp.clip((R * qt) // w * w, 0, nq - BW)
        else:
            S = jnp.clip(R * qt - (w - 1), 0, nq - BW)
        rows = jax.lax.broadcasted_iota(jnp.int32, (qtb, BW * block), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (qtb, BW * block), 1)
        gp = R * qtb + rows
        kp = S * block + cols
        if aligned:
            # window-aligned local attention (Fixed): same w-window only
            visible = (kp // block // w) == (gp // block // w)
            if causal:
                visible = visible & (kp <= gp)
        else:
            visible = (kp // block) >= (gp // block - (w - 1))
            if causal:
                visible = visible & (kp <= gp)
            else:
                visible = visible & \
                    ((kp // block) <= (gp // block + (w - 1)))
        s = jnp.where(visible[None], s, NEG_INF)
        online_update(s, vb_ref[...])

    # causal: gathered global columns are position-sorted, so a tile
    # whose FIRST position exceeds the super-row's last query position
    # is fully invisible — skip its matmul outright (for the Fixed
    # pattern the per-row visible-summary count grows with position,
    # and this turns the global sweep's triangular waste into skipped
    # steps, ~halving global work at long T). With the regular-globals
    # index clamp (`max_live`) the liveness MUST come from the closed
    # form: dead steps re-fetch the last LIVE tile (so Pallas elides
    # the DMA), whose pos entries would wrongly pass the runtime test.
    tile_live = True
    if causal:
        if max_live is not None:
            tile_live = st - 1 <= max_live(R)
        else:
            tile_live = pos_ref[0, 0] <= (R + 1) * qtb - 1

    @pl.when(jnp.logical_and(st > 0, tile_live))
    def _():
        s = jax.lax.dot_general(
            q_ref[...], kg_ref[...], (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale
        pos = pos_ref[0, :]                       # [tk] source positions
        rows = jax.lax.broadcasted_iota(jnp.int32, (qtb, tk), 0)
        gp = R * qtb + rows
        # exclude entries the band/window step already covered (double
        # count) and the zero-K padding tail (pos is 2**30 there —
        # without the bound it would pass the non-causal test and add
        # phantom mass)
        valid = pos[None, :] < nq * block
        if aligned:
            other_window = (pos[None, :] // block // w) != \
                (gp // block // w)
            if causal:
                visible = other_window & (pos[None, :] <= gp) & valid
            else:
                visible = other_window & valid
        elif causal:
            visible = ((pos[None, :] // block) < (gp // block - (w - 1))) \
                & (pos[None, :] <= gp) & valid
        else:
            diff = pos[None, :] // block - gp // block
            visible = ((diff < -(w - 1)) | (diff > (w - 1))) & valid
        s = jnp.where(visible[None], s, NEG_INF)
        online_update(s, vg_ref[...])

    @pl.when(st == n_steps - 1)
    def _():
        l = l_scr[:, :, :1]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[...] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_val = jnp.where(l > 0.0, m_scr[:, :, :1] + jnp.log(l_safe),
                            jnp.inf)
        if lse2d:
            lse_ref[...] = lse_val[:, :, 0]
        else:
            lse_ref[...] = lse_val


def _band_fwd(q, k, v, band, sm_scale, causal, block, interpret, qt,
              allow_lse2d=True):
    """(out [bh,t,d], lse) via the band+global forward. allow_lse2d:
    the BACKWARD (table kernels, head group g_bwd) must also be able to
    address a 2-D lse — callers pass g_bwd's sublane divisibility."""
    kind, w, gcols = band
    aligned = kind == "aligned"
    b, t, h, d = q.shape
    bh = b * h
    nq = t // block
    nqs = nq // qt
    qtb = qt * block
    if aligned:
        # caller guarantees qt % w == 0 or w % qt == 0, so a q
        # super-row's member windows span exactly max(w, qt) block cols
        assert qt % w == 0 or w % qt == 0, (qt, w)
        BW = min(nq, max(w, qt))
    else:
        BW = min(nq, (w + qt - 1) if causal else (2 * w + qt - 2))

    def to_bht(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    qb, kb, vb = to_bht(q), to_bht(k), to_bht(v)

    # gathered global columns (+1 tile of padding when empty); positions
    # beyond t mask to invisible
    tk = min(1024, max(block, 512))
    if gcols:
        gidx = np.concatenate(
            [np.arange(block) + j * block for j in gcols])
        pos = gidx.astype(np.int32)
    else:
        gidx = np.zeros((0,), np.int64)
        pos = np.zeros((0,), np.int32)
    ng = len(gidx)
    pad = (-ng) % tk if ng else tk
    n_steps = 1 + (ng + pad) // tk if ng else 1
    kg = jnp.pad(kb[:, gidx, :], ((0, 0), (0, pad), (0, 0))) if ng else \
        jnp.zeros((bh, tk, d), kb.dtype)
    vg = jnp.pad(vb[:, gidx, :], ((0, 0), (0, pad), (0, 0))) if ng else \
        jnp.zeros((bh, tk, d), vb.dtype)
    pos = jnp.asarray(
        np.pad(pos, (0, pad if ng else tk),
               constant_values=np.int32(2**30)))[None, :]   # [1, NGB]

    # head group: fattest that fits the band score tile (<= ~20 MB under
    # the raised scoped-vmem limit); prefer sublane-divisible g for the
    # 2-D lse layout
    g = 1
    while (g * 2 <= 8 and bh % (g * 2) == 0 and
           g * 2 * qtb * BW * block * 4 <= 24 * 1024 * 1024):
        g *= 2
    lse2d = (g % 8 == 0) and allow_lse2d

    # Regularly-spaced globals (the Fixed pattern: one summary column
    # per w-block window => gcols is the stride-w progression ending
    # each window) admit a CLOSED FORM for "last live global tile of
    # super-row R" under causality: tile sti's first source position is
    # sti*(tk//block)*w*block + (w-1)*block. Clamping the index maps to
    # that bound makes dead steps refetch the PREVIOUS tile — which
    # Pallas elides as a revisit — so causally dead tiles cost neither
    # MXU nor DMA (review r4: the in-kernel guard alone still streamed
    # g*tk*d*2 bytes of K and V per dead step).
    regular_globals = bool(
        causal and gcols and tk % block == 0 and
        tuple(gcols) == tuple(w - 1 + m * w for m in range(len(gcols))))
    blocks_per_tile = tk // block if tk % block == 0 else 0

    def max_live_tile(R):
        # largest sti with first_pos(sti) <= (R+1)*qtb - 1, in 0-based
        # global-tile units (st = sti + 1 in the grid)
        return ((R + 1) * qtb - 1 - (w - 1) * block) // \
            (blocks_per_tile * w * block)

    kernel = functools.partial(
        _band_fwd_kernel, sm_scale=sm_scale, block=block, qt=qt, w=w,
        n_steps=n_steps, tk=tk, g=g, lse2d=lse2d, causal=causal, nq=nq,
        BW=BW, aligned=aligned,
        max_live=max_live_tile if regular_globals else None)

    def band_idx(grp, R, st):
        # all-Element spec (Mosaic rejects mixed Element/Blocked dims):
        # every coordinate is an ELEMENT offset
        if aligned:
            start = jnp.clip((R * qt) // w * w, 0, nq - BW)
        else:
            start = jnp.clip(R * qt - (w - 1), 0, nq - BW)
        return (grp * g, start * block, 0)

    def gtile(R, st):
        sti = jnp.maximum(st - 1, 0)
        if regular_globals:
            sti = jnp.clip(sti, 0, jnp.maximum(max_live_tile(R), 0))
        return sti

    def gtile_idx(grp, R, st):
        return (grp, gtile(R, st), 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh // g, nqs, n_steps),
        in_specs=[
            pl.BlockSpec((g, qtb, d), lambda grp, R, st: (grp, R, 0)),
            _element_spec((g, BW * block, d), band_idx),
            _element_spec((g, BW * block, d), band_idx),
            pl.BlockSpec((g, tk, d), gtile_idx),
            pl.BlockSpec((g, tk, d), gtile_idx),
            pl.BlockSpec((1, tk), lambda grp, R, st: (0, gtile(R, st))),
        ],
        out_specs=[
            pl.BlockSpec((g, qtb, d), lambda grp, R, st: (grp, R, 0)),
            (pl.BlockSpec((g, qtb), lambda grp, R, st: (grp, R))
             if lse2d else
             pl.BlockSpec((g, qtb, 1), lambda grp, R, st: (grp, R, 0))),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, qtb, 128), jnp.float32),
            pltpu.VMEM((g, qtb, 128), jnp.float32),
            pltpu.VMEM((g, qtb, d), jnp.float32),
        ],
        compiler_params=_compiler_params("fwd"),
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t) if lse2d else (bh, t, 1),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb, kg, vg, pos)
    return out, lse


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(10, 11, 12, 13, 14, 15, 16, 17, 18))
def _bs_flash(q, k, v, head_map, kidx, kcnt, kmask, qidx, qcnt, qmask,
              sm_scale, causal, block, interpret, kmax, qmax, g, qt,
              band):
    if band is not None:
        out, _ = _band_fwd(q, k, v, band, sm_scale, causal, block,
                           interpret, qt, allow_lse2d=(g[1] % 8 == 0))
    else:
        out, _ = _bs_fwd(q, k, v, head_map, kidx, kcnt, kmask, sm_scale,
                         causal, block, interpret, kmax, g[0], qt,
                         allow_lse2d=(g[1] % 8 == 0))
    b, t, h, d = q.shape
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _bs_flash_fwd(q, k, v, head_map, kidx, kcnt, kmask, qidx, qcnt,
                  qmask, sm_scale, causal, block, interpret, kmax, qmax,
                  g, qt, band):
    if band is not None:
        out, lse = _band_fwd(q, k, v, band, sm_scale, causal, block,
                             interpret, qt, allow_lse2d=(g[1] % 8 == 0))
    else:
        out, lse = _bs_fwd(q, k, v, head_map, kidx, kcnt, kmask,
                           sm_scale, causal, block, interpret, kmax,
                           g[0], qt, allow_lse2d=(g[1] % 8 == 0))
    b, t, h, d = q.shape
    out_bthd = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out_bthd, (q, k, v, out_bthd, lse, head_map, kidx, kcnt,
                      kmask, qidx, qcnt, qmask)


def _bs_flash_bwd(sm_scale, causal, block, interpret, kmax, qmax, g_grp,
                  qt, band, res, g):
    # the backward always runs the table kernels — they are fast (short
    # carries, fat tiles) and layout-general; only the forward has a
    # band+global specialization
    return _bs_bwd(sm_scale, causal, block, interpret, kmax, qmax,
                   g_grp[1], qt, res, g)


_bs_flash.defvjp(_bs_flash_fwd, _bs_flash_bwd)


def layout_to_dense_mask(layout, seq_len, block):
    """[H, nq, nk] block layout -> [H, T, T] boolean mask (the XLA
    fallback path and the ground truth for kernel tests)."""
    lay = np.asarray(layout, bool)
    return np.kron(lay, np.ones((block, block), dtype=bool))


def block_sparse_attention(q, k, v, layout, block, causal=False,
                           sm_scale=None, interpret=None,
                           head_packing="auto"):
    """Block-sparse attention over [B, T, H, D].

    layout: [H, T/block, T/block] 0/1 matrix from a SparsityConfig.

    head_packing: accepted for signature parity with the dense flash
    kernel ("auto"|"packed"|"off") but the sparse kernels ALWAYS run
    unpacked — the index-compacted tables are per-head (each head has
    its own visible-block list), so pairing two heads into one K=128
    contraction would force both onto the union of their layouts.
    "auto"/"off" silently take the unpacked sparse kernel; "packed"
    raises (use the dense kernel for packed d=64 attention).
    """
    b, t, h, d = q.shape
    if head_packing in ("packed", True, 1):
        raise ValueError(
            "head_packing='packed' is not supported by the block-sparse "
            "kernels (per-head visible-block tables don't pair); use "
            "'auto'/'off', or the dense flash kernel for packed "
            "attention")
    if head_packing not in ("auto", "off", None, False, 0):
        raise ValueError(
            f"head_packing={head_packing!r}: expected 'auto' or 'off'")
    if isinstance(layout, jax.core.Tracer):
        raise ValueError(
            "block_sparse_attention requires a CONCRETE layout (it is "
            "compiled into visible-block index tables host-side); build "
            "the layout outside jit — SparsityConfig.make_layout "
            "returns numpy and layouts are static per (config, seq_len)")
    layout = np.asarray(layout)
    assert layout.shape == (h, t // block, t // block), \
        (layout.shape, (h, t // block, t // block))
    assert t % block == 0
    # every query block must see at least one key block (the diagonal in
    # all shipped patterns) or its softmax is over the empty set
    if causal:
        diag = layout[:, np.arange(t // block), np.arange(t // block)]
        assert diag.all(), "causal layouts must include the diagonal"
    else:
        assert (layout.sum(-1) > 0).all(), \
            "every query block needs >= 1 visible key block"
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = not _on_tpu()
    # q super-tile: target ~512 query rows per grid step; must divide
    # the block-row count. Head-group g then fits the VMEM tile budget.
    nq = t // block
    qt = max(1, min(4, 512 // block, nq))
    while nq % qt != 0:
        qt -= 1
    # VMEM tile budget: the f32 score tile is g*qt*block*block*4 bytes
    # and operands are double-buffered; the pallas_calls raise the
    # Mosaic scoped-vmem limit (_VMEM_LIMIT) so fat head-groups fit —
    # bigger tiles amortize the per-grid-step fixed cost that dominates
    # short visible-block lists. qt shrinks before the tables are built
    # (tables are qt-dependent); g shrinks after.
    while qt > 1 and qt * block * block * 4 > _SCORE_TILE_BUDGET:
        qt -= 1
    while qt > 1 and nq % qt != 0:
        qt -= 1
    band = _band_decompose(layout, causal)
    if band is not None and band[0] == "aligned":
        # the aligned-window kernel needs super-rows that tile whole
        # windows (or windows that tile super-rows)
        w = band[1]
        while qt > 1 and not (qt % w == 0 or w % qt == 0):
            qt -= 1
        while qt > 1 and nq % qt != 0:
            qt -= 1
        if not (qt % w == 0 or w % qt == 0):
            band = None           # qt=1 divides everything; defensive
    (head_map, kidx, kcnt, kmask, qidx, qcnt, qmask, kmax, qmax,
     g) = _build_tables(layout, causal, qt)
    assert h % g == 0 and (b * h) % g == 0  # _build_tables guarantees
    while g > 1 and g * qt * block * block * 4 > _SCORE_TILE_BUDGET:
        g //= 2
    # The fwd kernel's online-softmax carry serializes its inner loop,
    # so it wants OUTER parallelism (many small head-groups keep the
    # pipeline full at small batch); the bwd kernels have shorter
    # carries and prefer the fattest tiles. Any divisor of g keeps
    # layout-uniform groups, so the two passes pick independently
    # (measured at the 16k bench point: fwd g=2 + bwd g=8 is ~20%
    # faster than a shared g).
    g_fwd = g
    while g_fwd > 1 and (b * h) // g_fwd < _FWD_MIN_OUTER:
        g_fwd //= 2
    return _bs_flash(q, k, v, head_map, kidx, kcnt, kmask, qidx, qcnt,
                     qmask, float(sm_scale), bool(causal), int(block),
                     bool(interpret), kmax, qmax, (g_fwd, g), qt, band)


def block_sparse_attention_dense_fallback(q, k, v, layout, block,
                                          causal=False, sm_scale=None):
    """Dense reference: same math via an expanded additive mask."""
    t = q.shape[1]
    mask = layout_to_dense_mask(layout, t, block)         # [H, T, T]
    additive = np.where(mask, 0.0, NEG_INF).astype(np.float32)
    return dense_attention(q, k, v, mask=jnp.asarray(additive)[None],
                           causal=causal, sm_scale=sm_scale)
