"""Block-sparsity layout configs: Dense, Fixed, Variable, BigBird,
BSLongformer.

Parity with `deepspeed/ops/sparse_attention/sparsity_config.py:9,63,94,
243,421,544`: each config builds a boolean layout matrix
[num_heads, T/block, T/block] marking which key blocks each query block
attends to. The patterns are re-derived from their papers (Sparse
Transformers fixed pattern, BigBird random+window+global, Longformer
sliding+dilated+global) rather than ported line-by-line.

TPU note (SURVEY §7): the reference's 16/32-wide Triton blocks are
MXU-hostile; the default block here is 128 so each layout block is one
MXU-shaped flash-attention tile.
"""

import random

import numpy as np


class SparsityConfig:
    """Base class (ref `sparsity_config.py:9`).

    Args:
        num_heads: attention heads (layouts may differ per head).
        block: sparsity block size — layout entries gate block x block
            score tiles (128 on TPU vs the reference's 16).
        different_layout_per_head: give each head its own pattern where
            the pattern has per-head structure.
    """

    def __init__(self, num_heads, block=128, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block "
                f"size {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks),
                        dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks visible (ref `sparsity_config.py:63`) — for testing."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers 'fixed' pattern (ref `sparsity_config.py:94`):
    each block attends to its local window of `num_local_blocks` and to
    'summary' block columns — the last `num_global_blocks` block(s) of
    each preceding local window."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only unidirectional or bidirectional attention is "
                "supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional "
                "attention")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and \
                not different_layout_per_head:
            raise ValueError(
                "different global patterns require "
                "different_layout_per_head")
        if num_different_global_patterns > \
                num_local_blocks // num_global_blocks:
            raise ValueError(
                f"only {num_local_blocks // num_global_blocks} different "
                "global patterns are possible")
        self.num_different_global_patterns = num_different_global_patterns

    def _global_block_indices(self, head, window_start):
        """Summary (global) block columns inside one local window."""
        # head h uses the h-th pattern: the global blocks slide within
        # the window across heads (ref fixed pattern's per-head offsets)
        pattern = head % self.num_different_global_patterns
        first = window_start + self.num_local_blocks - \
            (pattern + 1) * self.num_global_blocks
        return range(first, first + self.num_global_blocks)

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        for h in range(self.num_layout_heads):
            # local windows
            for start in range(0, num_blocks, self.num_local_blocks):
                end = min(start + self.num_local_blocks, num_blocks)
                for q in range(start, end):
                    if self.attention == "unidirectional":
                        layout[h, q, start:q + 1] = 1
                    else:
                        layout[h, q, start:end] = 1
            # global/summary columns
            for start in range(0, num_blocks, self.num_local_blocks):
                for g in self._global_block_indices(h, start):
                    if not 0 <= g < num_blocks:
                        continue
                    if self.horizontal_global_attention:
                        layout[h, g, :] = 1
                    if self.attention == "unidirectional":
                        # queries after this window see the summary block
                        layout[h, g + 1:, g] = 1
                    else:
                        layout[h, :, g] = 1
        layout = self.check_and_propagate_first_head_layout(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Custom local windows + explicit global blocks
    (ref `sparsity_config.py:243`): local window sizes may vary
    (`num_local_blocks` is a list), and `global_block_indices` /
    `global_block_end_indices` pick arbitrary global columns."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices \
            if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(global_block_end_indices) != \
                    len(self.global_block_indices):
                raise ValueError(
                    "global_block_end_indices must pair with "
                    "global_block_indices")
            for start, end in zip(self.global_block_indices,
                                  global_block_end_indices):
                if start >= end:
                    raise ValueError(
                        "global block end must exceed its start")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only unidirectional or bidirectional attention is "
                "supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional "
                "attention (full global rows attend to future blocks)")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def _set_local(self, layout, h, num_blocks):
        start = 0
        window_idx = 0
        while start < num_blocks:
            size = self.local_window_blocks[
                min(window_idx, len(self.local_window_blocks) - 1)]
            end = min(start + size, num_blocks)
            for q in range(start, end):
                if self.attention == "unidirectional":
                    layout[h, q, start:q + 1] = 1
                else:
                    layout[h, q, start:end] = 1
            start = end
            window_idx += 1

    def _set_global(self, layout, h, num_blocks):
        cols = []
        if self.global_block_end_indices is None:
            cols = [i for i in self.global_block_indices if i < num_blocks]
        else:
            for start, end in zip(self.global_block_indices,
                                  self.global_block_end_indices):
                cols.extend(range(start, min(end, num_blocks)))
        for g in cols:
            if self.horizontal_global_attention:
                layout[h, g, :] = 1
            if self.attention == "unidirectional":
                layout[h, g:, g] = 1
            else:
                layout[h, :, g] = 1

    def _set_random(self, layout, h, num_blocks, rng):
        for q in range(num_blocks):
            hi = q + 1 if self.attention == "unidirectional" else num_blocks
            if hi <= 0:
                continue
            for _ in range(self.num_random_blocks):
                layout[h, q, rng.randrange(hi)] = 1

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        rng = random.Random(0)  # deterministic layouts across processes
        for h in range(self.num_layout_heads):
            self._set_local(layout, h, num_blocks)
            self._set_global(layout, h, num_blocks)
            if self.num_random_blocks:
                self._set_random(layout, h, num_blocks, rng)
        layout = self.check_and_propagate_first_head_layout(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding-window + global blocks
    (ref `sparsity_config.py:421`)."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only unidirectional or bidirectional attention is "
                "supported")
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        rng = random.Random(0)
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for q in range(num_blocks):
                # sliding window
                lo = max(0, q - w)
                hi = min(num_blocks, q + w + 1)
                if self.attention == "unidirectional":
                    hi = min(hi, q + 1)
                layout[h, q, lo:hi] = 1
                # random blocks
                rand_hi = q + 1 if self.attention == "unidirectional" \
                    else num_blocks
                for _ in range(self.num_random_blocks):
                    layout[h, q, rng.randrange(max(rand_hi, 1))] = 1
            # global: first num_global_blocks rows+cols
            g = min(self.num_global_blocks, num_blocks)
            if self.attention == "unidirectional":
                layout[h, :, :g] = 1
                layout[h, :g, :] = np.tril(
                    np.ones((g, num_blocks), dtype=np.int64))
            else:
                layout[h, :, :g] = 1
                layout[h, :g, :] = 1
        layout = self.check_and_propagate_first_head_layout(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding (+dilated) window + global
    (ref `sparsity_config.py:544`)."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices \
            if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(global_block_end_indices) != \
                    len(self.global_block_indices):
                raise ValueError(
                    "global_block_end_indices must pair with "
                    "global_block_indices")
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for q in range(num_blocks):
                lo = max(0, q - w)
                hi = min(num_blocks, q + w + 1)
                if self.attention == "unidirectional":
                    hi = min(hi, q + 1)
                layout[h, q, lo:hi] = 1
            cols = []
            if self.global_block_end_indices is None:
                cols = [i for i in self.global_block_indices
                        if i < num_blocks]
            else:
                for start, end in zip(self.global_block_indices,
                                      self.global_block_end_indices):
                    cols.extend(range(start, min(end, num_blocks)))
            for g in cols:
                if self.attention == "unidirectional":
                    layout[h, g:, g] = 1        # vertical, causal half
                    layout[h, g, :g + 1] = 1    # horizontal, causal half
                else:
                    layout[h, :, g] = 1
                    layout[h, g, :] = 1
        layout = self.check_and_propagate_first_head_layout(layout)
        return layout
