"""SparseSelfAttention + BertSparseSelfAttention modules.

Parity with `deepspeed/ops/sparse_attention/sparse_self_attention.py:14-164`
and `bert_sparse_self_attention.py:9`. The reference assembles QKᵀ (sdd)
→ scaled masked softmax → ·V (dsd) from Triton block ops with a
per-seq-len layout cache; here the whole chain is one layout-gated
Pallas flash kernel (`block_sparse_attention.py`), with the same
layout-cache keyed on sequence length.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig, FixedSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
    block_sparse_attention, block_sparse_attention_dense_fallback, NEG_INF,
    layout_to_dense_mask)


class SparseSelfAttention:
    """Applies block-sparse scaled-dot-product attention
    (ref `sparse_self_attention.py:14`).

    Call with q, k, v of shape [B, T, H, D] (the reference uses
    [B, H, T, D]; BTHD is this framework's native layout).
    """

    # layout cache shared across instances (ref `master_layout` caching)
    _layout_cache = {}

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul", max_seq_length=2048,
                 head_packing="auto"):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(
            num_heads=4)
        assert key_padding_mask_mode in ("add", "mul")
        assert attn_mask_mode in ("add", "mul")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        # forwarded to block_sparse_attention; the sparse kernels run
        # unpacked regardless (per-head layouts don't pair) — this only
        # validates/forwards the knob so model configs can plumb one
        # value everywhere
        self.head_packing = head_packing

    def get_layout(self, seq_len):
        key = (id(type(self.sparsity_config)),
               self.sparsity_config.num_heads, self.sparsity_config.block,
               seq_len, repr(sorted(self.sparsity_config.__dict__.items(),
                                    key=lambda kv: kv[0])))
        if key not in self._layout_cache:
            self._layout_cache[key] = \
                self.sparsity_config.make_layout(seq_len)
        return self._layout_cache[key]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None, causal=False):
        assert query.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)
        b, t, h, d = query.shape
        layout = self.get_layout(t)
        block = self.sparsity_config.block

        uses_masks = (rpe is not None or key_padding_mask is not None or
                      attn_mask is not None)
        on_tpu = jax.default_backend() == "tpu"
        if not uses_masks:
            return block_sparse_attention(
                query, key, value, layout, block, causal=causal,
                interpret=not on_tpu, head_packing=self.head_packing)

        # masked path: fold masks into an additive bias and run the
        # dense-fallback math with the layout mask (exact, but O(T^2)
        # memory — the reference's mask support has the same cost in
        # its sparse softmax, `softmax.py:17-304`)
        scale = 1.0 / np.sqrt(d)
        scores = jnp.einsum("bqhd,bkhd->bhqk", query, key).astype(
            jnp.float32) * scale
        lay_mask = layout_to_dense_mask(layout, t, block)
        scores = jnp.where(jnp.asarray(lay_mask)[None], scores, NEG_INF)
        if causal:
            tri = np.tril(np.ones((t, t), dtype=bool))
            scores = jnp.where(jnp.asarray(tri)[None, None], scores,
                               NEG_INF)
        if rpe is not None:
            scores = scores + rpe.astype(jnp.float32)
        if key_padding_mask is not None:
            kp = key_padding_mask.astype(jnp.float32)[:, None, None, :]
            if self.key_padding_mask_mode == "add":
                scores = scores + kp
            else:
                scores = jnp.where(kp != 0, scores, NEG_INF)
        if attn_mask is not None:
            am = attn_mask.astype(jnp.float32)
            while am.ndim < 4:
                am = am[None]
            if self.attn_mask_mode == "add":
                scores = scores + am
            else:
                scores = jnp.where(am != 0, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(value.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, value)


class BertSparseSelfAttention(nn.Module):
    """BERT-style self-attention block with block-sparse scores
    (ref `bert_sparse_self_attention.py:9`)."""
    hidden_size: int
    num_attention_heads: int
    sparsity_config: Any = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None):
        h = self.hidden_size
        nh = self.num_attention_heads
        assert h % nh == 0
        hd = h // nh
        b, t, _ = hidden_states.shape

        def dense(name):
            return nn.Dense(h, dtype=self.dtype, name=name)

        q = dense("query")(hidden_states).reshape(b, t, nh, hd)
        k = dense("key")(hidden_states).reshape(b, t, nh, hd)
        v = dense("value")(hidden_states).reshape(b, t, nh, hd)
        sparse_attn = SparseSelfAttention(
            sparsity_config=self.sparsity_config or
            FixedSparsityConfig(num_heads=nh),
            key_padding_mask_mode="add", attn_mask_mode="mul")
        ctx = sparse_attn(q, k, v, attn_mask=attention_mask)
        return ctx.reshape(b, t, h)
