"""Standalone block-sparse MatMul — the reusable primitive behind the
fused attention kernel.

Counterpart of the reference's Triton block-sparse matmul
(`deepspeed/ops/sparse_attention/matmul.py:16-750`): same three modes
over the same data format —

    sdd   sparse = dense  x dense
    dsd   dense  = sparse x dense
    dds   dense  = dense  x sparse

with dense tensors shaped [batch, heads, M, N] and sparse tensors in
the compact block format [batch, nnz, block, block], where nnz
enumerates `layout.nonzero()` in (head, block_row, block_col)
lexicographic order (the reference's LUT order).

TPU-native form: instead of compiling Triton LUT kernels, the nonzero
blocks become ONE batched einsum over a gathered [batch, nnz, ...]
operand (every block is an MXU tile), and dense outputs reduce with
`segment_sum` over the nnz axis. Everything is plain jax — autodiff
provides the dA/dB programs that the reference hand-assembles from
`make_dxx_lut`/`make_sdd_lut` tables, and `jit` caches the compiled
kernels the way the reference caches LUTs. Gather/scatter indices are
numpy constants baked at trace time (layouts are static per config).
"""

import jax
import jax.numpy as jnp
import numpy as np


def _layout_indices(layout):
    """layout [H, R, C] -> (h_idx, r_idx, c_idx) in the reference's
    lexicographic nonzero order."""
    lay = np.asarray(layout)
    if lay.ndim != 3:
        raise ValueError(f"layout must be [heads, rows, cols] 3-D, got "
                         f"shape {lay.shape}")
    h, r, c = np.nonzero(lay)
    return (h.astype(np.int32), r.astype(np.int32), c.astype(np.int32))


def _seg_sum(data, seg_ids, num_segments):
    """segment_sum over axis 1 (the nnz axis) of [B, nnz, ...]."""
    moved = jnp.moveaxis(data, 1, 0)
    out = jax.ops.segment_sum(moved, jnp.asarray(seg_ids),
                              num_segments=num_segments)
    return jnp.moveaxis(out, 0, 1)


def to_sparse(dense, layout, block):
    """[B, H, R*block, C*block] dense -> [B, nnz, block, block] compact
    (the inverse of `to_dense`; test/interop helper)."""
    h, r, c = _layout_indices(layout)
    b = dense.shape[0]
    H, R, C = np.asarray(layout).shape
    x = dense.reshape(b, H, R, block, C, block)
    return x.transpose(0, 1, 2, 4, 3, 5)[:, h, r, c]


def to_dense(sparse, layout, block, fill=0.0):
    """[B, nnz, block, block] compact -> [B, H, R*block, C*block]."""
    h, r, c = _layout_indices(layout)
    H, R, C = np.asarray(layout).shape
    b = sparse.shape[0]
    out = jnp.full((b, H * R * C, block, block), fill, sparse.dtype)
    flat_idx = (h.astype(np.int64) * R * C + r.astype(np.int64) * C +
                c.astype(np.int64))
    out = out.at[:, flat_idx].set(sparse)
    out = out.reshape(b, H, R, C, block, block)
    return out.transpose(0, 1, 2, 4, 3, 5).reshape(
        b, H, R * block, C * block)


class MatMul:
    """Block-sparse matmul over a fixed layout (ref `matmul.py:616`).

    Arguments match the reference: layout [heads, blocks, blocks] 0/1;
    block size; mode in {'sdd','dsd','dds'}; trans_a/trans_b transpose
    the corresponding operand (for the sparse operand this transposes
    each block AND swaps its row/column placement — the layout the
    caller passes is always the layout of the UNtransposed operand)."""

    def __init__(self, layout, block, mode, trans_a=False, trans_b=False):
        if mode not in ("sdd", "dsd", "dds"):
            raise NotImplementedError("Supported modes are: sdd, dsd, dds")
        self.layout = np.asarray(layout)
        self.block = int(block)
        self.mode = mode
        self.trans_a = trans_a
        self.trans_b = trans_b
        self.spdims = self.layout.shape
        self._h, self._r, self._c = _layout_indices(self.layout)

    # -- gathers ---------------------------------------------------------
    def _dense_rows(self, x, h, r):
        """x [B, H, M, K] -> [B, nnz, block, K] (block-rows r of head h)."""
        b, H, m, k = x.shape
        xr = x.reshape(b, H, m // self.block, self.block, k)
        return xr[:, h, r]

    def _dense_cols(self, x, h, c):
        """x [B, H, K, N] -> [B, nnz, K, block] (block-cols c of head h)."""
        b, H, k, n = x.shape
        xc = x.reshape(b, H, k, n // self.block, self.block)
        return jnp.moveaxis(xc, 3, 2)[:, h, c]

    def __call__(self, a, b):
        bs = self.block
        H, R, C = self.spdims
        h, r, c = self._h, self._r, self._c

        if self.mode == "sdd":
            ad = jnp.swapaxes(a, -1, -2) if self.trans_a else a
            bd = jnp.swapaxes(b, -1, -2) if self.trans_b else b
            a_r = self._dense_rows(ad, h, r)           # [B, z, bs, K]
            b_c = self._dense_cols(bd, h, c)           # [B, z, K, bs]
            return jnp.einsum("bzik,bzkj->bzij", a_r, b_c,
                              preferred_element_type=a_r.dtype)

        if self.mode == "dsd":
            # a sparse [B, nnz, bs, bs]; out rows follow a's layout rows
            # (or cols when trans_a)
            blk = jnp.swapaxes(a, -1, -2) if self.trans_a else a
            row, col = (c, r) if self.trans_a else (r, c)
            nrows = C if self.trans_a else R
            bd = jnp.swapaxes(b, -1, -2) if self.trans_b else b
            b_r = self._dense_rows(bd, h, col)         # [B, z, bs, N]
            prod = jnp.einsum("bzij,bzjn->bzin", blk, b_r,
                              preferred_element_type=blk.dtype)
            out = _seg_sum(prod, h.astype(np.int64) * nrows + row,
                           H * nrows)                  # [B, H*nr, bs, N]
            bsz, _, _, n = prod.shape
            return out.reshape(bsz, H, nrows * bs, n)

        # dds: b sparse; out cols follow b's layout cols (or rows when
        # trans_b)
        blk = jnp.swapaxes(b, -1, -2) if self.trans_b else b
        row, col = (c, r) if self.trans_b else (r, c)
        ncols = R if self.trans_b else C
        ad = jnp.swapaxes(a, -1, -2) if self.trans_a else a
        a_c = self._dense_cols(ad, h, row)             # [B, z, M, bs]
        prod = jnp.einsum("bzmi,bzin->bzmn", a_c, blk,
                          preferred_element_type=a_c.dtype)
        out = _seg_sum(prod, h.astype(np.int64) * ncols + col,
                       H * ncols)                      # [B, H*nc, M, bs]
        bsz, _, m, _ = prod.shape
        out = out.reshape(bsz, H, ncols, m, bs)
        return jnp.moveaxis(out, 2, 3).reshape(bsz, H, m, ncols * bs)
