"""Model-surgery helpers for sparse attention
(ref `sparse_attention_utils.py:13-225`): pad sequences to a block
multiple, extend position embeddings for longer contexts."""

import jax.numpy as jnp
import numpy as np


class SparseAttentionUtils:
    @staticmethod
    def extend_position_embedding(pos_embedding, max_position):
        """Tile an existing [old_max, H] position embedding out to
        max_position rows (ref `:34-76` repeats the learned table)."""
        old_max, hidden = np.asarray(pos_embedding).shape
        assert max_position > old_max, \
            "new max_position must exceed the original"
        reps = int(np.ceil(max_position / old_max))
        extended = np.tile(np.asarray(pos_embedding), (reps, 1))
        return jnp.asarray(extended[:max_position])

    @staticmethod
    def pad_to_block_size(block_size, input_ids=None, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id=0,
                          model_embeddings=None):
        """Right-pad sequence tensors to a multiple of block_size
        (ref `:156-225`). Returns (pad_len, *padded tensors in the same
        order)."""
        ref = input_ids if input_ids is not None else inputs_embeds
        seq_len = ref.shape[1]
        pad_len = (block_size - seq_len % block_size) % block_size

        def pad_tokens(x, value=0):
            if x is None or pad_len == 0:
                return x
            widths = [(0, 0), (0, pad_len)] + \
                [(0, 0)] * (np.ndim(x) - 2)
            return jnp.pad(jnp.asarray(x), widths, constant_values=value)

        input_ids = pad_tokens(input_ids, pad_token_id)
        attention_mask = pad_tokens(attention_mask, 0)
        token_type_ids = pad_tokens(token_type_ids, 0)
        position_ids = pad_tokens(position_ids, 0)
        if inputs_embeds is not None and pad_len > 0:
            if model_embeddings is not None:
                pad_ids = jnp.full((inputs_embeds.shape[0], pad_len),
                                   pad_token_id, jnp.int32)
                pad_embeds = model_embeddings[pad_ids]
            else:
                pad_embeds = jnp.zeros(
                    (inputs_embeds.shape[0], pad_len,
                     inputs_embeds.shape[2]), inputs_embeds.dtype)
            inputs_embeds = jnp.concatenate([inputs_embeds, pad_embeds],
                                            axis=1)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Drop the padding rows added by pad_to_block_size (ref `:227`)."""
        if pad_len > 0:
            return sequence_output[:, :-pad_len]
        return sequence_output
