"""Alias of the module-injection API under `ops` — the reference ships
the older copy-based injection twice (`deepspeed/ops/module_inject.py`
duplicating `deepspeed/module_inject/`); here the ops-path module simply
re-exports the single implementation."""

from deepspeed_tpu.module_inject.replace_module import (  # noqa: F401
    replace_transformer_layer, revert_transformer_layer, replace_module)
