"""Reference-parity LAMB optimizer.

Counterpart of `deepspeed/ops/lamb/fused_lamb.py:38` and the CUDA
kernel's update rule (`csrc/lamb/fused_lamb_cuda_kernel.cu:279-306`):

    m = b1*m + (1-b1)*g ;  v = b2*v + (1-b2)*g^2
    u = m_hat / (sqrt(v_hat) + eps) + weight_decay * w     (eps mode 1)
    coeff = ||w|| / ||u||   clipped to [min_coeff, max_coeff],
            1.0 when either norm is zero
    w <- w - lr * coeff * u

optax.lamb differs in one observable way — it never clips the trust
ratio (the reference clips to [0.01, 10.0] by default,
`ops/lamb/fused_lamb.py:48-49`), which changes early-training behavior
when moments are tiny — so the engine wires THIS transformation for
`"type": "Lamb"`. On TPU the whole update fuses into the train step;
the per-tensor norm reductions XLA emits are the analogue of the CUDA
kernel's two-pass block reduction.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class LambState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates


def _lamb(learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8,
          weight_decay=0.0, max_coeff=10.0, min_coeff=0.01,
          bias_correction=True):
    def init_fn(params):
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), params)
        return LambState(count=jnp.zeros([], jnp.int32),
                         mu=zeros(), nu=zeros())

    def update_fn(updates, state, params=None):
        assert params is not None, "lamb requires params for trust ratio"
        count = state.count + 1
        mu = jax.tree_util.tree_map(
            lambda m, g: b1 * m + (1 - b1) * g, state.mu, updates)
        nu = jax.tree_util.tree_map(
            lambda v, g: b2 * v + (1 - b2) * g * g, state.nu, updates)
        if bias_correction:
            c1 = 1 - b1 ** count.astype(jnp.float32)
            c2 = 1 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = 1.0

        def one(m, v, p):
            u = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay:
                u = u + weight_decay * p
            w_norm = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
            u_norm = jnp.sqrt(jnp.sum(u ** 2))
            coeff = jnp.clip(w_norm / jnp.where(u_norm == 0, 1.0, u_norm),
                             min_coeff, max_coeff)
            coeff = jnp.where((w_norm == 0) | (u_norm == 0), 1.0, coeff)
            return -learning_rate * coeff * u

        new_updates = jax.tree_util.tree_map(one, mu, nu, params)
        return new_updates, LambState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def lamb(learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8,
         weight_decay=0.0, max_coeff=10.0, min_coeff=0.01,
         bias_correction=True):
    """Scheduler-injectable reference-parity LAMB (only learning_rate is
    a traced hyperparam; the rest stay static so Python-level gating on
    weight_decay/bias_correction remains legal)."""
    return optax.inject_hyperparams(
        _lamb, static_args=('b1', 'b2', 'eps', 'weight_decay',
                            'max_coeff', 'min_coeff', 'bias_correction'))(
        learning_rate=learning_rate, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, max_coeff=max_coeff,
        min_coeff=min_coeff, bias_correction=bias_correction)


class FusedLamb:
    """Class-style facade mirroring ref `ops/lamb/fused_lamb.py:38`."""

    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0.0, max_grad_norm=0.0, max_coeff=10.0,
                 min_coeff=0.01, amsgrad=False):
        if amsgrad:
            raise RuntimeError('FusedLamb does not support the AMSGrad '
                               'variant.')
        if eps_inside_sqrt:
            raise NotImplementedError(
                "eps_inside_sqrt (adam mode 0) is not implemented; the "
                "reference default (mode 1) is used")
        self.transformation = lamb(
            learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps,
            weight_decay=weight_decay, max_coeff=max_coeff,
            min_coeff=min_coeff, bias_correction=bias_correction)

    def init(self, params):
        return self.transformation.init(params)

    def update(self, grads, state, params=None):
        return self.transformation.update(grads, state, params)
