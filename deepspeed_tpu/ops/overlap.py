"""Communication/compute overlap runtime: the ONE home of the
overlap discipline.

The repo's collectives are declarative (GSPMD sharding constraints
lower to all-to-all / all-gather; `ppermute` inside shard_map is the
ring hop), which leaves *when* a collective issues entirely to the
XLA latency-hiding scheduler.  PR-9 taught one site — the ZeRO-3
gather — to phrase its schedule explicitly with
`jax.lax.optimization_barrier`: issue the collective early (tied so it
cannot hoist above the producer that makes issuing legal), consume it
late (tied so the consumer cannot sink the issue to just before the
use).  This module generalizes that pattern into shared primitives and
applies one consistent discipline at every overlap site:

  * ``tie(*trees)`` / ``async_collective(collective, compute)`` — one
    `optimization_barrier` across all leaves: every output depends on
    every input, so an issued async collective and the compute meant
    to hide it reach the scheduler as one co-scheduled group.  XLA
    starts the collective, runs the compute while it flies, and only
    then releases either result downstream.
  * ``fence(value, *deps)`` (alias ``overlap_fence``) — the one-way
    form: ``value`` cannot be hoisted above any dep; the deps' barrier
    outputs are discarded.  This is the exact PR-9 ZeRO-3 fence —
    `runtime/zero/stage3.py` and `runtime/pipe/engine.py` now import
    it from here rather than each open-coding the barrier.

Both are bit-exact identities on values: the barrier constrains the
schedule, never the math.  Every parity test in tests/test_overlap.py
asserts bit-exact equality between scheduled and unscheduled runs.

Sites (the names accepted by ``overlap.sites`` and keyed in the
autotune collective-schedule table):

  * ``moe_dispatch`` — the MoE all-to-all pair (moe/dispatch.py): the
    dispatch all-to-all is co-scheduled with the router stats/aux
    epilogue so it issues while the gate epilogue computes; the
    combine all-to-all is fenced so the post-expert residual can
    overlap it.  ``granularity`` > 1 splits the dispatch/combine
    einsum along the capacity axis into that many independently
    scheduled chunks (bit-exact: the token contraction is untouched).
  * ``ring`` — ring-attention send/recv (ops/sequence/): chunk k+1's
    `ppermute` issues before chunk k's flash-merge consumes, with
    ``issue_distance`` controlling how many rotations stay in flight.
  * ``zero3_leaf`` — ZeRO-3 standalone-leaf gathers (ln_f in the
    models' loss closures): gathered with ``depend=`` on the embedded
    activations so the gather issues under the first scan layers
    instead of serializing up-front.

Schedule resolution (``schedule(site, ...)``, a pure host-side dict
read at trace time — no device sync, HOTSYNC-safe):

  1. global ``overlap.enabled`` off -> overlap off everywhere;
  2. explicit ``overlap.sites`` list -> overlap on exactly those
     sites, with the configured ``overlap.issue_distance``;
  3. ``sites="auto"`` (default) -> consult the autotune
     collective-schedule table (per site / mesh shape / payload-bytes
     bucket, never-slower by construction — see ops/autotune.py),
     falling back to overlap ON with the configured issue distance.

In-flight byte accounting: each site registers its per-device staging
window (``record_inflight``) at trace time; the engine exposes the sum
of per-site maxima as the ``overlap_inflight`` memory-ledger category
(docs/monitoring.md) so `oom_hints` can name ``overlap.issue_distance``
when the in-flight window dominates.
"""

import threading

import jax

SITE_MOE = "moe_dispatch"
SITE_RING = "ring"
SITE_ZERO3_LEAF = "zero3_leaf"
SITES = (SITE_MOE, SITE_RING, SITE_ZERO3_LEAF)

DEFAULT_ISSUE_DISTANCE = 1

_lock = threading.Lock()
_state = {
    "enabled": True,
    "sites": "auto",     # "auto" | frozenset of SITES members
    "issue_distance": DEFAULT_ISSUE_DISTANCE,
    "inflight": {},      # (site, key) -> per-device staging bytes
}


# ----------------------------------------------------------------------
# primitives
# ----------------------------------------------------------------------
@jax.custom_vjp
def _barrier(leaves):
    """optimization_barrier behind a pass-through VJP: the lax op has
    no differentiation rule, and the fences sit on differentiated loss
    paths (the MoE dispatch tie). The barrier is an identity, so the
    cotangents pass straight through — the *backward* schedule is
    constrained by its own sites' fences, not by replaying forward
    ones."""
    return jax.lax.optimization_barrier(leaves)


def _barrier_fwd(leaves):
    return _barrier(leaves), None


def _barrier_bwd(_res, cts):
    return (cts,)


_barrier.defvjp(_barrier_fwd, _barrier_bwd)


def tie(*trees):
    """One `optimization_barrier` across every leaf of every tree:
    each returned tree depends on ALL inputs, so XLA can neither hoist
    one past the others nor sink any input's producer below the group.
    Bit-exact identity on values. Returns the tied trees (a single
    tree when called with one argument, else a tuple)."""
    flat, treedef = jax.tree_util.tree_flatten(tuple(trees))
    if not flat:
        return trees[0] if len(trees) == 1 else trees
    out = _barrier(tuple(flat))
    tied = jax.tree_util.tree_unflatten(treedef, out)
    return tied[0] if len(trees) == 1 else tied


def fence(value, *deps):
    """One-way fence: `value`'s returned copy cannot be hoisted above
    any of `deps` (the deps' barrier outputs are discarded, so their
    own consumers are unconstrained). None deps are ignored; with no
    live deps the value passes through untouched. This is the PR-9
    ZeRO-3 gather fence, shared."""
    live = [d for d in deps if d is not None]
    if not live:
        return value
    v_leaves, v_def = jax.tree_util.tree_flatten(value)
    d_leaves, _ = jax.tree_util.tree_flatten(tuple(live))
    if not v_leaves or not d_leaves:
        return value
    out = _barrier(tuple(v_leaves) + tuple(d_leaves))
    return jax.tree_util.tree_unflatten(v_def, out[:len(v_leaves)])


# The issue's spelling for the same primitive: sites that phrase their
# schedule as "this may not start before that" use the fence name.
overlap_fence = fence


def async_collective(collective, compute):
    """Co-schedule an issued collective with the compute meant to hide
    it: returns ``(collective', compute')`` mutually tied, so the
    collective is issued no later than the compute group and neither
    result releases downstream until both exist. The async collective
    flies while the compute runs — issue-early/consume-late in one
    call. Bit-exact identity on both values."""
    return tie(collective, compute)


# ----------------------------------------------------------------------
# configuration (engine wiring; autotune-style process-global state)
# ----------------------------------------------------------------------
def _normalize_sites(sites):
    if isinstance(sites, str):
        if sites == "auto":
            return "auto"
        sites = [s.strip() for s in sites.split(",") if s.strip()]
    names = tuple(sites)
    for s in names:
        if s not in SITES:
            raise ValueError(
                f"overlap.sites: unknown site {s!r} "
                f"(valid: {', '.join(SITES)}, or 'auto')")
    return frozenset(names)


def configure(enabled=None, sites=None, issue_distance=None):
    """Engine wiring: toggle the discipline, pin the overlapped site
    set ('auto' = autotuned per site), and set the default issue
    distance (how many collective windows may stay in flight)."""
    if sites is not None:
        sites = _normalize_sites(sites)
    if issue_distance is not None:
        issue_distance = int(issue_distance)
        if issue_distance < 1:
            raise ValueError(
                "overlap.issue_distance must be >= 1, got "
                f"{issue_distance}")
    with _lock:
        if enabled is not None:
            _state["enabled"] = bool(enabled)
        if sites is not None:
            _state["sites"] = sites
        if issue_distance is not None:
            _state["issue_distance"] = issue_distance


def reset():
    """Test hook: restore defaults and drop in-flight accounting."""
    with _lock:
        _state["enabled"] = True
        _state["sites"] = "auto"
        _state["issue_distance"] = DEFAULT_ISSUE_DISTANCE
        _state["inflight"] = {}


def enabled():
    return _state["enabled"]


def schedule(site, payload_bytes=0, mesh=None):
    """Resolve the overlap schedule for one site at trace time (pure
    host-side dict reads — no device sync on this path). Returns
    ``{"overlap": bool, "issue_distance": int, "granularity": int}``.

    Explicit config wins over the autotune table: a pinned
    ``overlap.sites`` list means the user decided; only ``"auto"``
    consults the measured collective-schedule entries."""
    if site not in SITES:
        raise ValueError(
            f"unknown overlap site {site!r} (valid: {', '.join(SITES)})")
    base = {
        "overlap": True,
        "issue_distance": _state["issue_distance"],
        "granularity": 1,
    }
    if not _state["enabled"]:
        base["overlap"] = False
        return base
    sites = _state["sites"]
    if sites != "auto":
        base["overlap"] = site in sites
        return base
    from deepspeed_tpu.ops import autotune
    params = autotune.collective_schedule(site, mesh, payload_bytes)
    if params:
        for k in ("overlap", "issue_distance", "granularity"):
            if k in params:
                base[k] = params[k]
        base["overlap"] = bool(base["overlap"])
        base["issue_distance"] = max(int(base["issue_distance"]), 1)
        base["granularity"] = max(int(base["granularity"]), 1)
    return base


# ----------------------------------------------------------------------
# in-flight byte accounting (the `overlap_inflight` ledger category)
# ----------------------------------------------------------------------
def record_inflight(site, key, nbytes):
    """Trace-time registration of one site's per-device in-flight
    staging bytes (MoE dispatch staging, the ring send/recv window,
    ...). Keyed so re-traces overwrite rather than double-count."""
    with _lock:
        _state["inflight"][(str(site), str(key))] = int(nbytes)


def inflight_bytes():
    """Ledger callback: in-flight collective bytes = the sum over
    sites of the largest single registered window (layers execute one
    at a time within a site; distinct sites can be in flight
    together)."""
    with _lock:
        items = list(_state["inflight"].items())
    per_site = {}
    for (site, _key), nbytes in items:
        per_site[site] = max(per_site.get(site, 0), int(nbytes))
    return int(sum(per_site.values()))


def reset_inflight():
    with _lock:
        _state["inflight"] = {}
