"""Fused non-attention epilogue kernels: the transformer hot loop's
elementwise chains as single Pallas launches.

TPU-native rebuild of the reference's fused transformer kernel scope
(`csrc/transformer/ds_transformer_cuda.cpp` — `launch_bias_add`,
`launch_bias_gelu`, `launch_fused_add2` + `normalize_kernels.cu`): the
two chains the PR-4 fusion roofline (`top_fusion_sinks`) ranks as the
largest non-matmul sinks of the GPT-2/BERT step are

  (a) bias + residual-add + LayerNorm   (the block epilogue)
  (b) bias + GeLU                       (the MLP activation; exact-erf
                                         form per the reference kernel,
                                         plus the tanh approximation
                                         GPT-2 uses)

XLA compiles each chain into several fusions with HBM-materialized
intermediates (the LayerNorm reductions split the fusion); the Pallas
forward kernel streams one row block through VMEM and writes exactly
two tensors — the normalized output and the residual sum.  The custom
VJP runs a single backward kernel per chain (dX / d_bias / d_gamma /
d_beta in one pass, cross-block accumulators in VMEM scratch) instead
of XLA's autodiff chain.

Remat contract (the per-fusion policy, mirroring the
`_flash_apply` split in flash_attention.py): the forward kernel runs on
`stop_gradient` inputs and its outputs carry `checkpoint_name`
annotations —

    "fused_ln_out"    LN output           (feeds the next matmul)
    "fused_ln_sum"    bias+residual sum   (the residual stream AND the
                                           only backward residual)
    "fused_gelu_sum"  bias+input sum      (the only GeLU bwd residual)
    "fused_gelu_out"  GeLU output

so the `save_fused_epilogues` policy
(runtime/activation_checkpointing/checkpointing.py) saves the kernels'
outputs and the rematted backward never re-runs a fused forward: every
backward residual is either a saved named output or recomputed from one
with cheap reductions (mu/rstd from the saved sum).  The GeLU OUTPUT is
deliberately NOT in the policy (it is `4·H` wide — the roofline's
bytes verdict; it recomputes from the saved sum with one transcendental
pass).

`impl="auto"` lowers to the Pallas kernels on real TPU and to a fused
jnp formulation (same custom VJP, same saved set) elsewhere —
CPU CI validates the kernel logic itself via `impl="interpret"`.
Every entry point runs inside a `jax.named_scope` carrying the op name,
which is what the flops profiler's per-fusion table uses to attribute
the custom-calls/fusions (`per_fusion_costs` kernel labeling).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# names the save_fused_epilogues remat policy saves (fused_gelu_out is
# named but EXCLUDED from the policy: 4·H bytes/token vs a one-erf
# recompute from the saved sum)
FUSED_LN_OUT = "fused_ln_out"
FUSED_LN_SUM = "fused_ln_sum"
FUSED_GELU_SUM = "fused_gelu_sum"
FUSED_GELU_OUT = "fused_gelu_out"
FUSED_EPILOGUE_SAVE_NAMES = (FUSED_LN_OUT, FUSED_LN_SUM, FUSED_GELU_SUM)

_SQRT_2 = 1.4142135623730951
_SQRT_2_OVER_PI = 0.7978845608028654   # sqrt(2/pi), the tanh-gelu const
_INV_SQRT_2PI = 0.3989422804014327     # 1/sqrt(2*pi)
_GELU_C = 0.044715

_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))
_COMPILER_PARAMS = None if _CompilerParams is None else \
    _CompilerParams(vmem_limit_bytes=64 * 1024 * 1024)


def _on_tpu():
    return jax.default_backend() == "tpu"


def resolve_fused_ops(mode, dropout_inactive=True):
    """`fused_ops` config value -> bool.  "auto" enables the fused path
    on real TPU when dropout does not sit inside the chain (dropout
    between the bias add and the residual would change semantics) — the
    same backend-keyed auto convention as `head_packing` and
    `mlm_head_in_compute_dtype`, so CPU numerics stay bit-identical by
    default.  "on" forces it on any backend (XLA-fallback off-TPU) and
    refuses dropout loudly; "off" disables."""
    if mode in ("off", False, 0, None):
        return False
    if mode in ("on", True, 1):
        if not dropout_inactive:
            raise ValueError(
                "fused_ops='on' requires inactive dropout (deterministic "
                "or rate 0): dropout sits between the bias add and the "
                "residual, which the fused chain cannot express; use "
                "'auto' to fall back automatically")
        return True
    if mode == "auto":
        return bool(dropout_inactive) and _on_tpu()
    raise ValueError(
        f"fused_ops={mode!r}: expected 'auto', 'on' or 'off'")


def _resolve_impl(impl):
    """impl -> (use_pallas, interpret)."""
    if impl in ("auto", None):
        return (True, False) if _on_tpu() else (False, False)
    if impl == "pallas":
        return True, False
    if impl == "interpret":
        return True, True
    if impl == "xla":
        return False, False
    raise ValueError(
        f"impl={impl!r}: expected 'auto', 'pallas', 'xla' or 'interpret'")


def _row_block(n, target=256):
    """Largest power-of-two row-block <= target dividing n (floor 1)."""
    blk = min(target, n)
    while blk > 1 and n % blk:
        blk //= 2
    return max(blk, 1)


_DEFAULT_ROW_BLOCK = 256


def _tuned_row_block(kernel, n, hp, dtype):
    """Row-block for one launch: the autotune table's winner for this
    (kernel, backend, dtype, shape-class) when one exists, else the
    hand-picked 256 target. Pure host-side dict lookup at trace time
    (no device sync)."""
    from deepspeed_tpu.ops import autotune
    target = autotune.row_block_target(kernel, n, hp, dtype)
    return _row_block(n, target or _DEFAULT_ROW_BLOCK)


# ----------------------------------------------------------------------
# shared math (the kernels and the XLA fallback use the SAME formulas,
# so interpret-mode parity tests pin the kernel logic itself)
# ----------------------------------------------------------------------
def _ln_stats(s, h_valid, h_padded):
    """fp32 row mean / rstd over the last axis, masking pad lanes when
    the wrapper padded H up to a lane multiple.  Mirrors flax
    LayerNorm's fast-variance formula (E[x^2] - E[x]^2, clamped)."""
    if h_valid == h_padded:
        mu = jnp.mean(s, axis=-1, keepdims=True)
        mu2 = jnp.mean(s * s, axis=-1, keepdims=True)
    else:
        mu = jnp.sum(s, axis=-1, keepdims=True) / h_valid
        mu2 = jnp.sum(s * s, axis=-1, keepdims=True) / h_valid
    var = jnp.maximum(mu2 - mu * mu, 0.0)
    return mu, var


def _ln_fwd_math(y, bias, residual, gamma, beta, eps, h_valid):
    """fp32 chain: s = (y + bias) + residual; out = LN(s)*gamma+beta."""
    s = (y.astype(jnp.float32) + bias.astype(jnp.float32)) + \
        residual.astype(jnp.float32)
    h_padded = s.shape[-1]
    if h_valid != h_padded:
        lane = jax.lax.broadcasted_iota(jnp.int32, s.shape, s.ndim - 1)
        s = jnp.where(lane < h_valid, s, 0.0)
    mu, var = _ln_stats(s, h_valid, h_padded)
    rstd = jax.lax.rsqrt(var + eps)
    out = (s - mu) * rstd * gamma.astype(jnp.float32) + \
        beta.astype(jnp.float32)
    if h_valid != h_padded:
        lane = jax.lax.broadcasted_iota(jnp.int32, out.shape, out.ndim - 1)
        out = jnp.where(lane < h_valid, out, 0.0)
    return out, s


def _ln_bwd_math(s, gamma, d_out, d_sum, eps, h_valid):
    """One-pass LN backward off the saved sum `s` (mu/rstd recomputed —
    cheap reductions instead of saved tensors).  Returns
    (ds_total, d_gamma_rows, d_beta_rows) where ds_total is the shared
    cotangent of y, bias (row-summed by the caller) and residual."""
    s = s.astype(jnp.float32)
    d_out = d_out.astype(jnp.float32)
    h_padded = s.shape[-1]
    if h_valid != h_padded:
        lane = jax.lax.broadcasted_iota(jnp.int32, s.shape, s.ndim - 1)
        valid = lane < h_valid
        s = jnp.where(valid, s, 0.0)
        d_out = jnp.where(valid, d_out, 0.0)
    mu, var = _ln_stats(s, h_valid, h_padded)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (s - mu) * rstd
    dxhat = d_out * gamma.astype(jnp.float32)
    if h_valid != h_padded:
        dxhat = jnp.where(valid, dxhat, 0.0)
        mean_dxhat = jnp.sum(dxhat, -1, keepdims=True) / h_valid
        mean_dxhat_x = jnp.sum(dxhat * xhat, -1, keepdims=True) / h_valid
    else:
        mean_dxhat = jnp.mean(dxhat, -1, keepdims=True)
        mean_dxhat_x = jnp.mean(dxhat * xhat, -1, keepdims=True)
    ds = rstd * (dxhat - mean_dxhat - xhat * mean_dxhat_x)
    if d_sum is not None:
        ds = ds + d_sum.astype(jnp.float32)
    if h_valid != h_padded:
        ds = jnp.where(valid, ds, 0.0)
    d_gamma_rows = d_out * xhat
    return ds, d_gamma_rows, d_out


def _gelu_fwd_math(x, bias, approximate):
    """fp32 s = x + bias; out = gelu(s) (erf exact or tanh approx —
    same formulas as jax.nn.gelu, so unfused parity is roundoff)."""
    s = x.astype(jnp.float32) + bias.astype(jnp.float32)
    # association order mirrors jax.nn.gelu exactly (s * cdf), so the
    # fused/unfused fp32 forward is bit-identical
    if approximate:
        cdf = 0.5 * (1.0 + jnp.tanh(_SQRT_2_OVER_PI *
                                    (s + _GELU_C * (s ** 3))))
        out = s * cdf
    else:
        out = s * (jax.lax.erf(s / _SQRT_2) + 1.0) / 2.0
    return out, s


def _gelu_bwd_math(s, d_out, approximate):
    """d gelu(s)/ds * d_out off the saved sum."""
    s = s.astype(jnp.float32)
    d_out = d_out.astype(jnp.float32)
    if approximate:
        inner = _SQRT_2_OVER_PI * (s + _GELU_C * s * s * s)
        t = jnp.tanh(inner)
        dinner = _SQRT_2_OVER_PI * (1.0 + 3.0 * _GELU_C * s * s)
        grad = 0.5 * (1.0 + t) + 0.5 * s * (1.0 - t * t) * dinner
    else:
        grad = 0.5 * (1.0 + jax.lax.erf(s / _SQRT_2)) + \
            s * jnp.exp(-0.5 * s * s) * _INV_SQRT_2PI
    return d_out * grad


# ----------------------------------------------------------------------
# Pallas kernels — one row block per grid step, H on the lanes
# ----------------------------------------------------------------------
def _ln_fwd_kernel(y_ref, bias_ref, res_ref, gamma_ref, beta_ref,
                   out_ref, sum_ref, *, eps, h_valid):
    out, s = _ln_fwd_math(y_ref[...], bias_ref[...], res_ref[...],
                          gamma_ref[...], beta_ref[...], eps, h_valid)
    out_ref[...] = out.astype(out_ref.dtype)
    sum_ref[...] = s.astype(sum_ref.dtype)


def _ln_bwd_kernel(s_ref, gamma_ref, dout_ref, dsum_ref, dx_ref,
                   dbias_ref, dgamma_ref, dbeta_ref,
                   db_scr, dg_scr, dbeta_scr, *, eps, h_valid,
                   has_dsum):
    i = pl.program_id(0)
    n = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        db_scr[...] = jnp.zeros_like(db_scr)
        dg_scr[...] = jnp.zeros_like(dg_scr)
        dbeta_scr[...] = jnp.zeros_like(dbeta_scr)

    dsum = dsum_ref[...] if has_dsum else None
    ds, dg_rows, dbeta_rows = _ln_bwd_math(
        s_ref[...], gamma_ref[...], dout_ref[...], dsum, eps, h_valid)
    dx_ref[...] = ds.astype(dx_ref.dtype)
    db_scr[...] += jnp.sum(ds, axis=0, keepdims=True)
    dg_scr[...] += jnp.sum(dg_rows, axis=0, keepdims=True)
    dbeta_scr[...] += jnp.sum(dbeta_rows, axis=0, keepdims=True)

    @pl.when(i == n - 1)
    def _():
        dbias_ref[...] = db_scr[...].astype(dbias_ref.dtype)
        dgamma_ref[...] = dg_scr[...].astype(dgamma_ref.dtype)
        dbeta_ref[...] = dbeta_scr[...].astype(dbeta_ref.dtype)


def _gelu_fwd_kernel(x_ref, bias_ref, out_ref, sum_ref, *, approximate):
    out, s = _gelu_fwd_math(x_ref[...], bias_ref[...], approximate)
    out_ref[...] = out.astype(out_ref.dtype)
    sum_ref[...] = s.astype(sum_ref.dtype)


def _gelu_bwd_kernel(s_ref, dout_ref, dx_ref, dbias_ref, db_scr, *,
                     approximate):
    i = pl.program_id(0)
    n = pl.num_programs(0)

    @pl.when(i == 0)
    def _():
        db_scr[...] = jnp.zeros_like(db_scr)

    dx = _gelu_bwd_math(s_ref[...], dout_ref[...], approximate)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    db_scr[...] += jnp.sum(dx, axis=0, keepdims=True)

    @pl.when(i == n - 1)
    def _():
        dbias_ref[...] = db_scr[...].astype(dbias_ref.dtype)


def _pad_lanes(x, h_padded):
    h = x.shape[-1]
    if h == h_padded:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, h_padded - h)])


def _pallas_call(kernel, *, grid, in_specs, out_specs, out_shape,
                 scratch_shapes, interpret, name):
    kwargs = dict(grid=grid, in_specs=in_specs, out_specs=out_specs,
                  out_shape=out_shape, scratch_shapes=scratch_shapes,
                  interpret=interpret)
    if _COMPILER_PARAMS is not None:
        kwargs["compiler_params"] = _COMPILER_PARAMS
    try:
        return pl.pallas_call(kernel, name=name, **kwargs)
    except TypeError:   # older pallas without the name kwarg
        return pl.pallas_call(kernel, **kwargs)


def _ln_fwd_launch(y2, bias, res2, gamma, beta, eps, h, out_dtype,
                   sum_dtype, interpret):
    """[N, H] row-flattened launcher.  Pads H to a lane multiple (the
    kernel masks pad lanes out of the statistics) and tiles rows."""
    n = y2.shape[0]
    hp = -(-h // 128) * 128
    blk = _tuned_row_block("fused_ln", n, hp, out_dtype)
    args = [_pad_lanes(y2, hp), _pad_lanes(bias[None], hp),
            _pad_lanes(res2, hp), _pad_lanes(gamma[None], hp),
            _pad_lanes(beta[None], hp)]
    row_spec = pl.BlockSpec((blk, hp), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, hp), lambda i: (0, 0))
    out, s = _pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps, h_valid=h),
        grid=(n // blk,),
        in_specs=[row_spec, vec_spec, row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((n, hp), out_dtype),
                   jax.ShapeDtypeStruct((n, hp), sum_dtype)],
        scratch_shapes=[], interpret=interpret,
        name="fused_bias_residual_layernorm_fwd")(*args)
    return out[:, :h], s[:, :h]


def _ln_bwd_launch(s2, gamma, dout2, dsum2, eps, h, in_dtype,
                   param_dtype, interpret):
    n = s2.shape[0]
    hp = -(-h // 128) * 128
    blk = _tuned_row_block("fused_ln", n, hp, in_dtype)
    has_dsum = dsum2 is not None
    args = [_pad_lanes(s2, hp), _pad_lanes(gamma[None], hp),
            _pad_lanes(dout2, hp)]
    row_spec = pl.BlockSpec((blk, hp), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, hp), lambda i: (0, 0))
    in_specs = [row_spec, vec_spec, row_spec]
    if has_dsum:
        args.append(_pad_lanes(dsum2, hp))
        in_specs.append(row_spec)
    else:
        args.append(jnp.zeros((1, hp), jnp.float32))
        in_specs.append(vec_spec)
    dx, dbias, dgamma, dbeta = _pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps, h_valid=h,
                          has_dsum=has_dsum),
        grid=(n // blk,),
        in_specs=in_specs,
        out_specs=[row_spec, vec_spec, vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((n, hp), in_dtype),
                   jax.ShapeDtypeStruct((1, hp), param_dtype),
                   jax.ShapeDtypeStruct((1, hp), param_dtype),
                   jax.ShapeDtypeStruct((1, hp), param_dtype)],
        scratch_shapes=[pltpu.VMEM((1, hp), jnp.float32)] * 3,
        interpret=interpret,
        name="fused_bias_residual_layernorm_bwd")(*args)
    return dx[:, :h], dbias[0, :h], dgamma[0, :h], dbeta[0, :h]


def _gelu_fwd_launch(x2, bias, approximate, h, out_dtype, sum_dtype,
                     interpret):
    n = x2.shape[0]
    hp = -(-h // 128) * 128
    blk = _tuned_row_block("fused_gelu", n, hp, out_dtype)
    row_spec = pl.BlockSpec((blk, hp), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, hp), lambda i: (0, 0))
    out, s = _pallas_call(
        functools.partial(_gelu_fwd_kernel, approximate=approximate),
        grid=(n // blk,),
        in_specs=[row_spec, vec_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((n, hp), out_dtype),
                   jax.ShapeDtypeStruct((n, hp), sum_dtype)],
        scratch_shapes=[], interpret=interpret,
        name="fused_bias_gelu_fwd")(
            _pad_lanes(x2, hp), _pad_lanes(bias[None], hp))
    return out[:, :h], s[:, :h]


def _gelu_bwd_launch(s2, dout2, approximate, h, in_dtype, param_dtype,
                     interpret):
    n = s2.shape[0]
    hp = -(-h // 128) * 128
    blk = _tuned_row_block("fused_gelu", n, hp, in_dtype)
    row_spec = pl.BlockSpec((blk, hp), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, hp), lambda i: (0, 0))
    dx, dbias = _pallas_call(
        functools.partial(_gelu_bwd_kernel, approximate=approximate),
        grid=(n // blk,),
        in_specs=[row_spec, row_spec],
        out_specs=[row_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((n, hp), in_dtype),
                   jax.ShapeDtypeStruct((1, hp), param_dtype)],
        scratch_shapes=[pltpu.VMEM((1, hp), jnp.float32)],
        interpret=interpret,
        name="fused_bias_gelu_bwd")(_pad_lanes(s2, hp),
                                    _pad_lanes(dout2, hp))
    return dx[:, :h], dbias[0, :h]


# ----------------------------------------------------------------------
# custom-VJP apply ops (the _flash_apply pattern: identity forward,
# kernel backward off residuals that are named outputs — a
# names-saving remat policy then never re-runs the forward)
# ----------------------------------------------------------------------
def _flat_rows(x):
    return x.reshape(-1, x.shape[-1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _ln_apply(y, bias, residual, gamma, beta, out, s,
              eps, use_pallas, interpret, sum_dtype):
    return out, s


def _ln_apply_fwd(y, bias, residual, gamma, beta, out, s,
                  eps, use_pallas, interpret, sum_dtype):
    del residual
    # zero-size dtype carriers: custom_vjp residuals must be arrays
    return (out, s), (s, gamma, jnp.zeros((0,), y.dtype),
                      jnp.zeros((0,), beta.dtype))


def _ln_apply_bwd(eps, use_pallas, interpret, sum_dtype, res, g):
    s, gamma, in_dt, param_dt = res
    in_dtype, param_dtype = in_dt.dtype, param_dt.dtype
    lead_shape = s.shape[:-1]
    d_out, d_sum = g
    h = s.shape[-1]
    s2 = _flat_rows(s)
    dout2 = _flat_rows(d_out)
    dsum2 = None if d_sum is None else _flat_rows(d_sum)
    if use_pallas:
        dx2, dbias, dgamma, dbeta = _ln_bwd_launch(
            s2, gamma, dout2, dsum2, eps, h, in_dtype, param_dtype,
            interpret)
    else:
        ds, dg_rows, dbeta_rows = _ln_bwd_math(
            s2, gamma, dout2, dsum2, eps, h)
        dx2 = ds.astype(in_dtype)
        dbias = jnp.sum(ds, axis=0).astype(param_dtype)
        dgamma = jnp.sum(dg_rows, axis=0).astype(param_dtype)
        dbeta = jnp.sum(dbeta_rows, axis=0).astype(param_dtype)
    dx = dx2.reshape(lead_shape + (h,))
    # y, bias (row-summed), residual share the chain cotangent; the
    # out/s operands came through the non-differentiable forward kernel
    return (dx, dbias.astype(param_dtype), dx.astype(sum_dtype),
            dgamma.astype(param_dtype), dbeta.astype(param_dtype),
            jnp.zeros_like(s, dtype=in_dtype), jnp.zeros_like(s))


_ln_apply.defvjp(_ln_apply_fwd, _ln_apply_bwd)


# Post-LN form: only the normalized output is returned, so no sum
# cotangent exists AT ALL.  (custom_vjp instantiates concrete zeros for
# an unused output's cotangent, so a two-output op would stream a full
# [N, H] zeros operand through the backward kernel on exactly the
# bytes-bound chain this module exists to shrink — a separate primal
# with one output keeps the d_sum path genuinely absent.)
@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _ln_apply_out(y, bias, residual, gamma, beta, out, s,
                  eps, use_pallas, interpret, sum_dtype):
    return out


def _ln_apply_out_fwd(y, bias, residual, gamma, beta, out, s,
                      eps, use_pallas, interpret, sum_dtype):
    del residual
    return out, (s, gamma, jnp.zeros((0,), y.dtype),
                 jnp.zeros((0,), beta.dtype))


def _ln_apply_out_bwd(eps, use_pallas, interpret, sum_dtype, res, g):
    grads = _ln_apply_bwd(eps, use_pallas, interpret, sum_dtype, res,
                          (g, None))
    return grads


_ln_apply_out.defvjp(_ln_apply_out_fwd, _ln_apply_out_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _gelu_apply(x, bias, out, s, approximate, use_pallas, interpret):
    return out


def _gelu_apply_fwd(x, bias, out, s, approximate, use_pallas, interpret):
    return out, (s, jnp.zeros((0,), x.dtype), jnp.zeros((0,), bias.dtype))


def _gelu_apply_bwd(approximate, use_pallas, interpret, res, g):
    s, in_dt, param_dt = res
    in_dtype, param_dtype = in_dt.dtype, param_dt.dtype
    lead_shape = s.shape[:-1]
    h = s.shape[-1]
    s2 = _flat_rows(s)
    dout2 = _flat_rows(g)
    if use_pallas:
        dx2, dbias = _gelu_bwd_launch(s2, dout2, approximate, h,
                                      in_dtype, param_dtype, interpret)
    else:
        dx2 = _gelu_bwd_math(s2, dout2, approximate)
        dbias = jnp.sum(dx2, axis=0)
        dx2 = dx2.astype(in_dtype)
    dx = dx2.reshape(lead_shape + (h,))
    return (dx, dbias.astype(param_dtype),
            jnp.zeros_like(s, dtype=in_dtype), jnp.zeros_like(s))


_gelu_apply.defvjp(_gelu_apply_fwd, _gelu_apply_bwd)


# ----------------------------------------------------------------------
# public API
# ----------------------------------------------------------------------
def fused_bias_residual_layernorm(y, bias, residual, gamma, beta, *,
                                  eps=1e-5, out_dtype=None,
                                  sum_dtype=None, impl="auto",
                                  return_sum=True):
    """out, resid_sum = LN((y + bias) + residual) * gamma + beta.

    `y` is a bias-less matmul output [..., H]; `bias`/`gamma`/`beta` are
    [H]; `residual` is the incoming stream [..., H].  One kernel launch
    computes the whole chain in fp32 and writes `out` (out_dtype,
    default y.dtype — feeds the next matmul) and `resid_sum` (sum_dtype,
    default residual.dtype — the pre-LN residual stream).  Both outputs
    carry checkpoint_name annotations ("fused_ln_out"/"fused_ln_sum")
    for the save_fused_epilogues remat policy; the backward needs ONLY
    the sum + gamma (mu/rstd are recomputed — cheap reductions), so a
    names-saving remat never re-runs this forward.

    return_sum=False (the post-LN wiring, where the normalized output
    IS the carry) returns just `out` through a single-output primal, so
    no sum cotangent ever exists — a dropped second output would
    otherwise stream a materialized zeros tensor through the backward
    kernel.
    """
    out_dtype = np.dtype(out_dtype) if out_dtype is not None else y.dtype
    sum_dtype = np.dtype(sum_dtype) if sum_dtype is not None \
        else residual.dtype
    use_pallas, interpret = _resolve_impl(impl)
    eps = float(eps)
    h = y.shape[-1]
    with jax.named_scope("fused_bias_residual_layernorm"):
        sg = jax.lax.stop_gradient
        if use_pallas:
            out2, s2 = _ln_fwd_launch(
                _flat_rows(sg(y)), sg(bias), _flat_rows(sg(residual)),
                sg(gamma), sg(beta), eps, h, out_dtype, sum_dtype,
                interpret)
            out = out2.reshape(y.shape)
            s = s2.reshape(y.shape)
        else:
            out_f, s_f = _ln_fwd_math(sg(y), sg(bias), sg(residual),
                                      sg(gamma), sg(beta), eps, h)
            out = out_f.astype(out_dtype)
            s = s_f.astype(sum_dtype)
        out = checkpoint_name(out, FUSED_LN_OUT)
        s = checkpoint_name(s, FUSED_LN_SUM)
        if not return_sum:
            return _ln_apply_out(y, bias, residual, gamma, beta, out, s,
                                 eps, use_pallas, interpret, sum_dtype)
        return _ln_apply(y, bias, residual, gamma, beta, out, s,
                         eps, use_pallas, interpret, sum_dtype)


def fused_bias_gelu(x, bias, *, approximate=False, out_dtype=None,
                    impl="auto"):
    """gelu(x + bias) as one launch; exact-erf by default (the
    reference kernel's form), `approximate=True` for the tanh form
    GPT-2 uses.  The bias+input sum is the only backward residual and
    carries the "fused_gelu_sum" checkpoint name (the save policy keeps
    it and recomputes the 4H-wide output with one transcendental
    pass)."""
    out_dtype = np.dtype(out_dtype) if out_dtype is not None else x.dtype
    use_pallas, interpret = _resolve_impl(impl)
    approximate = bool(approximate)
    h = x.shape[-1]
    with jax.named_scope("fused_bias_gelu"):
        sg = jax.lax.stop_gradient
        if use_pallas:
            out2, s2 = _gelu_fwd_launch(
                _flat_rows(sg(x)), sg(bias), approximate, h, out_dtype,
                x.dtype, interpret)
            out = out2.reshape(x.shape)
            s = s2.reshape(x.shape)
        else:
            out_f, s_f = _gelu_fwd_math(sg(x), sg(bias), approximate)
            out = out_f.astype(out_dtype)
            s = s_f.astype(x.dtype)
        s = checkpoint_name(s, FUSED_GELU_SUM)
        out = checkpoint_name(out, FUSED_GELU_OUT)
        return _gelu_apply(x, bias, out, s, approximate, use_pallas,
                           interpret)


def fused_ops_available():
    """(available, mode) for ds_report: the ops always work — the mode
    says whether they lower to Pallas kernels or the fused XLA form."""
    try:
        mode = "pallas-tpu" if _on_tpu() else "xla-fallback (no TPU)"
        return True, mode
    except Exception as e:  # pragma: no cover  # ds-lint: allow[BROADEXC] availability probe for ds_report: the failure text IS the report row
        return False, f"{type(e).__name__}: {e}"
